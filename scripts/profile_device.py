"""Microprofile of the device query path on the real TPU.

Isolates: (a) pure per-batch compute with pre-staged inputs, (b) plan-array
upload cost, (c) scatter vs top_k split, at two corpus scales.
Run: python scripts/profile_device.py
"""

import time

import numpy as np


def timeit(fn, reps=10):
    fn()  # warmup / compile
    import jax

    t0 = time.monotonic()
    out = None
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp

    print("platform:", jax.devices()[0].platform, flush=True)

    for n_docs in (100_000, 1_000_000):
        print(f"\n===== n_docs={n_docs} =====", flush=True)
        Q = 256  # query batch
        NT = 64  # tiles per query worklist
        TILE = 256
        total_tiles = 4096 if n_docs <= 100_000 else 32768
        rng = np.random.default_rng(0)

        doc_tiles = jnp.asarray(
            rng.integers(0, n_docs, size=(total_tiles, TILE), dtype=np.int32)
        )
        tn_tiles = jnp.asarray(
            rng.random((total_tiles, TILE), dtype=np.float32)
        )
        tile_ids = jnp.asarray(
            rng.integers(0, total_tiles, size=(Q, NT), dtype=np.int32)
        )
        weights = jnp.asarray(rng.random((Q, NT), dtype=np.float32))
        live = jnp.ones(n_docs, dtype=bool)
        jax.block_until_ready((doc_tiles, tn_tiles, tile_ids, weights))

        k = 10

        @jax.jit
        def score_only(tile_ids, weights):
            def one(tids, w):
                docs = doc_tiles[tids]  # [NT, TILE]
                tn = tn_tiles[tids]
                contrib = w[:, None] - w[:, None] / (1.0 + tn)
                scores = (
                    jnp.zeros(n_docs + 1, dtype=jnp.float32)
                    .at[docs]
                    .add(contrib)[:n_docs]
                )
                return scores

            return jax.vmap(one)(tile_ids, weights)

        @jax.jit
        def full(tile_ids, weights):
            scores = score_only(tile_ids, weights)
            s, i = jax.lax.top_k(scores, k)
            return s, i

        @jax.jit
        def topk_only(scores):
            return jax.lax.top_k(scores, k)

        @jax.jit
        def topk_twolevel(scores):
            G = 250
            s2 = scores.reshape(Q, G, -1)
            ls, li = jax.lax.top_k(s2, k)  # [Q, G, k]
            base = (jnp.arange(G, dtype=jnp.int32) * s2.shape[-1])[None, :, None]
            gi = li.astype(jnp.int32) + base
            fs, fi = jax.lax.top_k(ls.reshape(Q, -1), k)
            gi_flat = gi.reshape(Q, -1)
            return fs, jnp.take_along_axis(gi_flat, fi, axis=1)

        scores = score_only(tile_ids, weights)
        jax.block_until_ready(scores)

        t_score = timeit(lambda: score_only(tile_ids, weights))
        print(f"score-only (scatter) per batch of {Q}: {t_score*1e3:.2f} ms", flush=True)
        t_full = timeit(lambda: full(tile_ids, weights))
        print(f"full (score+topk):                    {t_full*1e3:.2f} ms", flush=True)
        t_topk = timeit(lambda: topk_only(scores))
        print(f"topk alone [Q={Q}, N={n_docs}]:        {t_topk*1e3:.2f} ms", flush=True)
        t_topk2 = timeit(lambda: topk_twolevel(scores))
        print(f"topk two-level:                        {t_topk2*1e3:.2f} ms", flush=True)

        # parity of two-level topk
        s1, i1 = topk_only(scores)
        s2_, i2 = topk_twolevel(scores)
        ok = bool(jnp.all(s1 == s2_))
        print("two-level topk score parity:", ok, flush=True)

        # upload cost: fresh numpy -> device of the per-query plan arrays
        def upload():
            a = jax.device_put(
                np.ascontiguousarray(
                    rng.integers(0, total_tiles, size=(Q, NT), dtype=np.int32)
                )
            )
            b = jax.device_put(rng.random((Q, NT), dtype=np.float32))
            return a, b

        t_up = timeit(upload, reps=5)
        print(f"fresh plan upload per batch:           {t_up*1e3:.2f} ms", flush=True)

        t_e2e = timeit(lambda: full(*upload()), reps=5)
        print(f"upload+full e2e:                       {t_e2e*1e3:.2f} ms", flush=True)


if __name__ == "__main__":
    main()
