#!/usr/bin/env python
"""CI smoke gate for the adaptive query-execution subsystem.

Runs the exec parity fuzz suite (planner routing must never change top-10
ids/order/scores) and the micro-batcher scheduling contracts on the CPU
backend — no TPU needed. The same tests ride the tier-1 run via the fast
(`not slow`) marker; this script is the standalone hook for pre-merge /
cron checks:

    python scripts/check_exec_parity.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_exec_parity.py",
        "tests/test_exec_batcher.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
