#!/usr/bin/env python
"""CI smoke gate for the device-resident filter/bitset cache (ISSUE 9).

Runs the filter-cache suite on the CPU backend — no TPU needed: the
64-query cached-vs-uncached parity fuzz (device, block-max conjunction,
and SPMD mesh paths, bit-exact ids/order/fp32 scores/totals including
immediately after refresh/update/delete invalidation), usage-tracking
admission, HBM-budgeted LRU eviction, coalesced-batchmate plane sharing,
and the `_cache/clear` / `_nodes/stats` / `/_metrics` surfaces. The same
tests ride the tier-1 run via the fast (`not slow`) marker; this script
is the standalone hook for pre-merge / cron checks:

    python scripts/check_filter_cache_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_filter_cache.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
