#!/usr/bin/env python
"""CI smoke gate for the conjunction execution stack (ISSUE 5).

Runs the conjunction-kernel parity suite on the CPU backend — no TPU
needed: lead-clause selection follows clause selectivity, the two-phase
block-max prune is exact at tiny k, empty-intersection conjunctions
return zero hits everywhere, and bucketed batched execution is
bit-identical to sequential. The same tests ride the tier-1 run via the
fast (`not slow`) marker; this script is the standalone hook for
pre-merge / cron checks:

    python scripts/check_conj_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_conj_kernel.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
