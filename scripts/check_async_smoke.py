#!/usr/bin/env python
"""CI smoke gate for async search + per-tenant QoS (ISSUE 17).

Runs the stored-progressive-search and weighted-admission suites on the
CPU backend — no TPU needed: completed `_async_search` responses
bit-identical to the synchronous `_search`, order-invariant progressive
reduces across random shard-completion orders, store lifecycle
(keep_alive GC, DELETE cancellation, bounded-store 429s), and the QoS
fairness contracts (hard inflight ceiling, weighted shed-victim choice,
per-lane Retry-After, the in-process flood arc). The same tests ride
the tier-1 run via the fast (`not slow`) marker; this script is the
standalone hook for pre-merge / cron checks:

    python scripts/check_async_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_async_search.py",
        "tests/test_qos.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
