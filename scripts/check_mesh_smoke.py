#!/usr/bin/env python
"""CI smoke gate for one-launch SPMD serving (ISSUE 8).

Runs the sorted/agg/search_after/replicated mesh parity suite on the CPU
backend — no TPU needed: ≥64 fuzzed request shapes must return
bit-identical responses from the SPMD mesh path, the host-loop
coordinator, and the raw-document oracle; replicated indices serve
sorted + aggregating searches with exact values; mesh fallbacks are
counted, never silent. The same tests ride the tier-1 run via the fast
(`not slow`) marker; this script is the standalone hook for pre-merge /
cron checks:

    python scripts/check_mesh_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_mesh_sorted_aggs.py",
        "tests/test_mesh_serving.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
