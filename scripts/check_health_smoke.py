#!/usr/bin/env python
"""CI smoke gate for the cluster health report (ISSUE 15).

Runs, on the CPU backend with no TPU in the loop:

- the rule-based indicator registry (every INDICATORS entry computes a
  reference-shaped status/symptom/details/impacts/diagnosis block; a
  fresh node reports green on every indicator),
- the rolling-window layer (`estpu_*_recent`: record, percentile
  snapshot, aging out of the trailing window),
- the acceptance arcs on BOTH cluster forms: LocalCluster REST front and
  a 2-process ProcCluster — green report → kill a data node →
  `/_health_report` turns non-green with a NAMED per-indicator diagnosis
  within the per-send deadline → restart + heal → green again,
- the seeded retrace defect flipping `device_compile` yellow naming the
  plan class, breaker near-budget/drift rules, the
  `?wait_for_status=green&timeout=` blocking poll (timed_out, never a
  500), and the `GET /_insights/queries` top-N ring.

The same tests ride the tier-1 run via the fast (`not slow`) marker;
this script is the standalone hook for pre-merge / cron checks,
mirroring scripts/check_cluster_obs_smoke.py:

    python scripts/check_health_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_health.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    main_rc = main()
    sys.exit(main_rc)
