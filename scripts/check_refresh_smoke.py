#!/usr/bin/env python
"""CI smoke gate for the delta-scaled refresh (ISSUE 12).

Runs the posting-concatenation merge and segment-granular mesh refresh
suites on the CPU backend — no TPU needed: structural bit-equality of
the concat merge vs the re-analysis oracle (terms, CSR postings,
positions, norms, doc values, vectors, nested/completion/percolator),
search-parity fuzz with deletes purged, the zero-analysis-calls hook
gate (a one-doc write + refresh tokenizes only the delta; merges and
mesh repacks tokenize NOTHING), filter/ANN cache survival across
refresh + merge on the host path, and the mesh half: one-shard repack
per one-doc refresh, field-plane upload skipping, uid-keyed mask ROWS
of unchanged shards hitting across refreshes, all bit-identical to the
host-loop coordinator. The same tests ride the tier-1 run via the fast
(`not slow`) marker; this script is the standalone hook for pre-merge /
cron checks:

    python scripts/check_refresh_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_merge_concat.py",
        "tests/test_mesh_refresh.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
