#!/usr/bin/env python
"""CI smoke gate for the observability subsystem.

Runs the distributed-tracing suite (one connected trace per search over a
replicated multi-shard cluster, fault-tagged spans, Chrome/Perfetto
export, cache-hit honesty, slowlog trace ids) plus the unified-metrics
suite (registry migration parity for `_nodes/stats`, Prometheus
exposition validity, histogram bucket invariants, device launch
instruments), on the CPU backend — no TPU needed, < 30 s. The same tests
ride the tier-1 run via the fast (`not slow`) marker; this script is the
standalone hook for pre-merge / cron checks, mirroring
scripts/check_chaos_smoke.py:

    python scripts/check_obs_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_obs_tracing.py",
        "tests/test_obs_metrics.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
