#!/usr/bin/env python
"""CI smoke gate for IVF-partitioned ANN (ISSUE 10).

Runs the ANN suite on the CPU backend — no TPU needed: the candidate-set
re-rank bit-exactness law (every returned score fp32-equal to the exact
brute-force scorer on the same doc), recall@10 >= 0.95 at the default
nprobe on seeded clustered corpora, filtered-knn pre-rank semantics,
refresh/merge invalidation, brute-force fallback for unpartitionable
segments, the dense_vector ingest 400 contracts, and the script_score
exact path's byte-identity. The same tests ride the tier-1 run via the
fast (`not slow`) marker; this script is the standalone hook for
pre-merge / cron checks:

    python scripts/check_ann_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_ann_ivf.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
