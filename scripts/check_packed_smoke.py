#!/usr/bin/env python
"""CI smoke gate for packed multi-tenant execution (ISSUE 7).

Runs the packed-plane parity suite on the CPU backend — no TPU needed:
per-tenant ids/order/fp32-scores/totals equal the per-index oracle, zero
cross-tenant leakage under adversarial shared-term vocabularies, and the
planner-routed packed/oracle backends return identical responses to solo
execution. The same tests ride the tier-1 run via the fast (`not slow`)
marker; this script is the standalone hook for pre-merge / cron checks:

    python scripts/check_packed_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_packed_multitenant.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
