"""Prototype + profile the candidate-centric (sparse) BM25 kernel.

Instead of scatter-adding into a dense [N] score vector (scatter is ~66M
updates/s on TPU and top_k over [Q, N] scales with corpus size), stably
sort the gathered (doc, contrib) pairs per query by doc and sum each run
with static shifted adds (left-fold in worklist order = the oracle's exact
fp32 accumulation order). Work scales with postings touched, not N.
"""

import sys
import time

import numpy as np


def timeit(fn, reps=10):
    import jax

    jax.block_until_ready(fn())
    t0 = time.monotonic()
    out = None
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / reps


def main():
    import jax
    import jax.numpy as jnp

    print("platform:", jax.devices()[0].platform, flush=True)
    Q, NT, TILE, k = 256, 64, 256, 10
    MAX_RUN = 8  # max entries per doc = #terms in the query (4 here), padded
    n_docs = 1_000_000
    total_tiles = 32768
    rng = np.random.default_rng(0)

    doc_tiles_np = np.sort(
        rng.integers(0, n_docs, size=(total_tiles, TILE), dtype=np.int32), axis=1
    )
    doc_tiles = jnp.asarray(doc_tiles_np)
    tn_tiles = jnp.asarray(rng.random((total_tiles, TILE), dtype=np.float32))
    tile_ids = jnp.asarray(
        rng.integers(0, total_tiles, size=(Q, NT), dtype=np.int32)
    )
    weights = jnp.asarray(rng.random((Q, NT), dtype=np.float32))
    live = jnp.ones(n_docs + 1, dtype=bool)
    jax.block_until_ready((doc_tiles, tn_tiles, tile_ids, weights))

    P = NT * TILE
    SENTINEL = n_docs

    def sparse_one(tids, w):
        docs = doc_tiles[tids].reshape(-1)  # [P]
        tn = tn_tiles[tids]
        contrib = (w[:, None] - w[:, None] / (1.0 + tn)).reshape(-1)
        docs_s, contrib_s = jax.lax.sort(
            (docs, contrib), num_keys=1, is_stable=True
        )
        pad_docs = jnp.full(MAX_RUN, SENTINEL + 1, dtype=docs_s.dtype)
        pad_c = jnp.zeros(MAX_RUN, dtype=contrib_s.dtype)
        docs_ext = jnp.concatenate([docs_s, pad_docs])
        contrib_ext = jnp.concatenate([contrib_s, pad_c])
        run_sum = contrib_s
        for j in range(1, MAX_RUN):
            same = docs_ext[j : j + P] == docs_s
            run_sum = run_sum + jnp.where(same, contrib_ext[j : j + P], 0.0)
        is_start = jnp.concatenate(
            [jnp.ones(1, bool), docs_s[1:] != docs_s[:-1]]
        )
        eligible = is_start & (docs_s != SENTINEL) & live[docs_s]
        key = jnp.where(eligible, run_sum, -jnp.inf)
        top_s, top_i = jax.lax.top_k(key, k)
        top_docs = docs_s[top_i]
        total = jnp.sum(eligible, dtype=jnp.int32)
        return top_s, top_docs, total

    sparse = jax.jit(lambda t, w: jax.vmap(sparse_one)(t, w))

    def dense_one(tids, w):
        docs = doc_tiles[tids]
        tn = tn_tiles[tids]
        contrib = w[:, None] - w[:, None] / (1.0 + tn)
        scores = (
            jnp.zeros(n_docs + 1, dtype=jnp.float32).at[docs].add(contrib)[:n_docs]
        )
        return scores

    dense = jax.jit(lambda t, w: jax.vmap(dense_one)(t, w))
    topk_only = jax.jit(lambda s: jax.lax.top_k(s, k))

    print("compiling sparse...", flush=True)
    t0 = time.monotonic()
    s_s, s_docs, s_tot = jax.device_get(sparse(tile_ids, weights))
    print(f"  compile+run {time.monotonic()-t0:.1f}s", flush=True)
    print("compiling dense...", flush=True)
    d_scores = dense(tile_ids, weights)
    d_s, d_i = jax.device_get(topk_only(d_scores))

    mism = 0
    for q in range(Q):
        if not np.allclose(s_s[q], d_s[q], rtol=1e-5, atol=1e-6):
            mism += 1
        elif sorted(s_docs[q].tolist()) != sorted(d_i[q].tolist()):
            mism += 1
    print(f"parity vs dense: {Q - mism}/{Q} queries match", flush=True)

    t_sparse = timeit(lambda: sparse(tile_ids, weights))
    print(
        f"sparse per batch of {Q}: {t_sparse*1e3:.2f} ms "
        f"({t_sparse/Q*1e6:.0f} us/query)",
        flush=True,
    )

    docs_flat = doc_tiles[tile_ids].reshape(Q, -1)
    contrib_flat = jnp.ones((Q, P), dtype=jnp.float32)
    sort_only = jax.jit(
        lambda d, c: jax.lax.sort((d, c), num_keys=1, is_stable=True)
    )
    t_sort = timeit(lambda: sort_only(docs_flat, contrib_flat))
    print(f"sort alone [Q={Q}, P={P}]: {t_sort*1e3:.2f} ms", flush=True)


if __name__ == "__main__":
    main()
