"""Sweep the reference's YAML REST conformance suites and report.

Usage: python scripts/yaml_conformance.py [test-dir-filter ...]

Runs every section of every .yml under the reference's rest-api-spec test
tree against a fresh in-process node per section, then prints a summary
and writes the per-section outcomes to /tmp/yaml_conformance.json.
Outcomes: pass / fail (assertion or error) / skip (unsupported feature or
API outside the runner's table).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

from yaml_runner import (  # noqa: E402
    REFERENCE_TESTS,
    SkipTest,
    YamlRunner,
    load_suites,
)


def main() -> None:
    from elasticsearch_tpu.rest.server import RestServer

    filters = sys.argv[1:]
    results: dict[str, str] = {}
    counts = {"pass": 0, "fail": 0, "skip": 0}
    by_dir: dict[str, dict[str, int]] = {}
    for path in sorted(REFERENCE_TESTS.rglob("*.yml")):
        rel = str(path.relative_to(REFERENCE_TESTS))
        if filters and not any(rel.startswith(f) for f in filters):
            continue
        try:
            suites = load_suites(path)
        # staticcheck: ignore[broad-except] conformance harness: unparseable-to-us yaml counts as skip and the sweep continues
        except Exception as e:  # malformed-to-us yaml: count as skip
            results[rel] = f"skip (yaml: {e})"
            counts["skip"] += 1
            continue
        for section, steps in suites.items():
            if section in ("setup", "teardown"):
                continue
            key = f"{rel}::{section}"
            try:
                rest = RestServer(data_path=tempfile.mkdtemp())
                runner = YamlRunner(rest)
                if "setup" in suites:
                    runner.run_steps(suites["setup"])
                runner.run_steps(steps)
            except SkipTest as e:
                results[key] = f"skip ({e})"
                outcome = "skip"
            # staticcheck: ignore[broad-except] conformance harness: a failing step is recorded as fail and the sweep continues
            except Exception as e:
                results[key] = f"fail ({type(e).__name__}: {str(e)[:160]})"
                outcome = "fail"
            else:
                results[key] = "pass"
                outcome = "pass"
            counts[outcome] += 1
            top = rel.split("/")[0]
            by_dir.setdefault(top, {"pass": 0, "fail": 0, "skip": 0})
            by_dir[top][outcome] += 1

    with open("/tmp/yaml_conformance.json", "w") as f:
        json.dump({"counts": counts, "results": results}, f, indent=1)
    print(json.dumps(counts))
    for d in sorted(by_dir):
        c = by_dir[d]
        print(f"  {d}: {c['pass']}P/{c['fail']}F/{c['skip']}S")


if __name__ == "__main__":
    main()
