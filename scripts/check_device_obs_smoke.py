#!/usr/bin/env python
"""CI smoke gate for device observability (ISSUE 14).

Runs, on the CPU backend with no TPU in the loop:

- the HBM ledger consistency law: `device.hbm` totals equal the sum of
  each component's own byte stats (engine segments, filter-cache
  planes, ANN tiles, packed planes, mesh snapshots) through refresh /
  evict / `_cache/clear` / delete_index cycles — drift zero, including
  under a threaded eviction burst,
- breaker/ledger no-drift (the breaker writes through),
- per-launch timing histograms (queue/execute split) + the retrace
  census: a seeded shape-polymorphic plan key trips
  `estpu_device_retraces_total`,
- the profiler capture API: start/stop round trip producing a
  Perfetto-loadable trace dir, 409 on double-start, bounded duration,
  capture-window stamp in the obs trace ring, and
- `GET /_cat/hbm` + the `/_cat/segments` device-bytes column.

The same tests ride the tier-1 run via the fast (`not slow`) marker;
this script is the standalone hook for pre-merge / cron checks:

    python scripts/check_device_obs_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_device_obs.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
