#!/usr/bin/env python
"""CI gate for the repo-specific static analyzer (ISSUE 6).

Runs `python -m staticcheck` over the checkout — the four pass families
(trace-hazard, lock-discipline, registry-consistency, hygiene) — and
fails on any non-baselined, non-suppressed finding. Pure stdlib `ast`,
CPU-only, seconds: the same contract the self-run test
(tests/test_staticcheck.py) enforces in tier-1; this script is the
standalone hook for pre-merge / cron checks:

    python scripts/check_static.py

The analyzer prints a per-rule finding-count summary either way, so a
regression is diagnosable from the log alone.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    cmd = [sys.executable, "-m", "staticcheck", "--root", REPO_ROOT]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
