#!/usr/bin/env python
"""CI smoke gate for cluster-scope observability (ISSUE 13).

Runs, on the CPU backend with no TPU in the loop:

- wire-fanned `_nodes/stats` (per-node sections + `_nodes` header, named
  failure entries within the per-send deadline after killing a member,
  hub/tcp response-shape parity),
- the federated `/_metrics` scrape (node-labeled worker series +
  `node="_cluster"` counter folds),
- distributed trace assembly (ONE spliced tree containing remote
  `cluster.shard_search` / `search.segment` spans, chrome export laned
  per node), and
- `GET /_nodes/hot_threads` sampling across real worker processes
  (ProcCluster: each interpreter samples itself).

The same tests ride the tier-1 run via the fast (`not slow`) marker;
this script is the standalone hook for pre-merge / cron checks,
mirroring scripts/check_socket_smoke.py:

    python scripts/check_cluster_obs_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_cluster_obs.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
