import sys; sys.path.insert(0, "/root/repo")
import time, numpy as np
from collections import defaultdict
import jax, jax.numpy as jnp
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.utils.corpus import build_zipf_segment, pick_query_terms

N_DOCS, N_QUERIES, K, REPS = 1_000_000, 256, 10, 5
rng = np.random.default_rng(99)
mappings, segment = build_zipf_segment(N_DOCS, vocab_size=30_000, seed=13)
dev = pack_segment(segment)
seg_tree = bm25_device.segment_tree(dev)
jax.block_until_ready(seg_tree["live"])
compiler = Compiler(dev.fields, dev.doc_values, mappings)
query_terms = pick_query_terms(segment, rng, N_QUERIES)
compiled = [compiler.compile(parse_query({"match": {"body": " ".join(t)}})) for t in query_terms]
groups = defaultdict(list)
for pos, c in enumerate(compiled):
    groups[c.spec].append(pos)
print("groups:", {s[2]: len(p) for s, p in groups.items()})

outs = []
for spec_g, positions in groups.items():
    arrays_b = jax.tree.map(lambda *xs: np.stack(xs), *[compiled[p].arrays for p in positions])
    outs.append(bm25_device.execute_batch_sparse(seg_tree, spec_g, arrays_b, K))
jax.block_until_ready(outs)

t0 = time.monotonic()
for _ in range(REPS):
    for spec_g, positions in groups.items():
        arrays_b = jax.tree.map(lambda *xs: np.stack(xs), *[compiled[p].arrays for p in positions])
print("np.stack staging ms/query:", (time.monotonic() - t0) / (REPS * N_QUERIES) * 1e3)

outs = []
t0 = time.monotonic()
for _ in range(REPS):
    for spec_g, positions in groups.items():
        arrays_b = jax.tree.map(lambda *xs: np.stack(xs), *[compiled[p].arrays for p in positions])
        outs.append(bm25_device.execute_batch_sparse(seg_tree, spec_g, arrays_b, K))
jax.block_until_ready(outs)
print("np.stack full ms/query:", (time.monotonic() - t0) / (REPS * N_QUERIES) * 1e3)

outs = []
t0 = time.monotonic()
for _ in range(REPS):
    for spec_g, positions in groups.items():
        arrays_b = jax.tree.map(lambda *xs: jnp.stack(xs), *[compiled[p].arrays for p in positions])
        outs.append(bm25_device.execute_batch_sparse(seg_tree, spec_g, arrays_b, K))
jax.block_until_ready(outs)
print("jnp.stack full ms/query:", (time.monotonic() - t0) / (REPS * N_QUERIES) * 1e3)
