#!/usr/bin/env python
"""Profile the serving path through the ISSUE-14 capture API.

Replaces the three hand-rolled timer scripts (profile_device.py,
profile_sparse.py, profile_staging.py): instead of re-implementing
timeit loops around raw kernels, this drives the REAL serving stack —
Node.search over a zipf corpus — under an on-demand `jax.profiler`
capture window (the `POST /_profiler/start|stop` surface), then reports
what the always-on instruments measured:

- per-(plan class, backend, phase) launch-ms summaries from the
  `estpu_launch_ms` histograms (queue = dispatch return, execute =
  block_until_ready — the split is honest only on real devices; on
  XLA:CPU the work runs inside dispatch),
- the compile census: real XLA compiles, attributed per plan class, and
  retraces (a compile on an already-seen plan key — the
  shape-polymorphism alarm),
- the HBM ledger (`/_cat/hbm` rows), and
- the Perfetto trace directory (load the .trace.json.gz in
  https://ui.perfetto.dev or chrome://tracing).

Run on the real TPU for the ROADMAP residue rounds (packed win, refresh
p50, MXU matmul-vs-elementwise revisit):

    python scripts/profile_capture.py --docs 1000000 --queries 64 --reps 5
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _hist_summary(snap: dict) -> str:
    count = snap["count"]
    if not count:
        return "n=0"
    mean = snap["sum"] / count
    return f"n={count} mean={mean:.3f}ms sum={snap['sum']:.1f}ms"


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--docs", type=int, default=100_000)
    parser.add_argument("--queries", type=int, default=32)
    parser.add_argument("--reps", type=int, default=5)
    parser.add_argument(
        "--knn", action="store_true",
        help="include a dense_vector field + knn queries in the mix",
    )
    parser.add_argument(
        "--trace-dir", default=None,
        help="capture directory (default: a fresh temp dir)",
    )
    args = parser.parse_args()

    import jax

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.obs import device as device_obs
    from elasticsearch_tpu.utils.corpus import (
        build_zipf_segment,
        pick_query_terms,
    )

    print("platform:", jax.devices()[0].platform, flush=True)
    rng = np.random.default_rng(11)
    t0 = time.monotonic()
    _, seg = build_zipf_segment(
        args.docs, vocab_size=20_000, seed=23, with_sources=True
    )
    seg.doc_values["rank"] = rng.random(args.docs).astype(np.float64)
    d = 16
    if args.knn:
        seg.vectors["vec"] = rng.standard_normal(
            (args.docs, d), dtype=np.float32
        )
    node = Node()
    props = {"body": {"type": "text"}, "rank": {"type": "float"}}
    if args.knn:
        props["vec"] = {
            "type": "dense_vector", "dims": d, "similarity": "l2_norm",
        }
    node.create_index("profile", {"mappings": {"properties": props}})
    engine = node.indices["profile"].engines[0]
    engine.restore_segments([(seg, np.ones(args.docs, dtype=bool))])
    node.refresh("profile")
    print(f"corpus+index build: {time.monotonic() - t0:.1f}s", flush=True)

    term_sets = pick_query_terms(seg, rng, args.queries)
    bodies = []
    for i, terms in enumerate(term_sets):
        lo = float(rng.random() * 0.4)
        bodies.append(
            {
                "query": {
                    "bool": {
                        "must": [{"match": {"body": " ".join(terms[:2])}}],
                        "filter": [
                            {"range": {"rank": {"gte": lo, "lte": lo + 0.5}}}
                        ],
                    }
                },
                "size": 10,
            }
        )
        if args.knn and i % 4 == 0:
            bodies.append(
                {
                    "knn": {
                        "field": "vec",
                        "query_vector": rng.standard_normal(d).tolist(),
                        "k": 10,
                        "num_candidates": 100,
                    }
                }
            )
    for body in bodies:  # warm: every shape compiles outside the capture
        node.search("profile", body)

    census0 = device_obs.process_census()
    start = node.profiler_start(
        {"duration_s": 120, "trace_dir": args.trace_dir}
    )
    t0 = time.monotonic()
    times = []
    for _ in range(args.reps):
        for body in bodies:
            t1 = time.monotonic()
            node.search("profile", body)
            times.append(time.monotonic() - t1)
    elapsed = time.monotonic() - t0
    stop = node.profiler_stop()
    census1 = device_obs.process_census()

    n = len(times)
    print(
        f"\nserved {n} searches in {elapsed:.2f}s "
        f"(p50 {np.median(times) * 1e3:.2f}ms, "
        f"p99 {np.percentile(times, 99) * 1e3:.2f}ms)",
        flush=True,
    )

    print("\n== estpu_launch_ms (plan class / backend / phase) ==")
    family = node.metrics.family("estpu_launch_ms")
    samples = family[2] if family is not None else {}
    for key, snap in sorted(samples.items()):
        labels = dict(key)
        print(
            f"  {labels.get('plan_class', '?'):<22} "
            f"{labels.get('backend', '?'):<16} "
            f"{labels.get('phase', '?'):<8} {_hist_summary(snap)}"
        )

    print("\n== compile census ==")
    compile_section = node.device.compile_census()
    for kind, entry in compile_section["attributed_xla_compiles"].items():
        print(
            f"  {kind:<22} compiles={entry['compiles']} "
            f"compile_ms={entry['compile_ms']} retraces={entry['retraces']}"
        )
    print(
        f"  window: compiles={census1['compiles'] - census0['compiles']} "
        f"retraces={census1['retraces'] - census0['retraces']} "
        f"(a nonzero capture-window retrace means a plan class recompiles "
        f"per query)"
    )

    print("\n== HBM ledger (/_cat/hbm) ==")
    for row in node.cat_hbm():
        print(
            f"  {row['node']:<10} {row['label']:<14} {row['index']:<12} "
            f"{row['bytes']}"
        )

    print(
        f"\nPerfetto trace dir: {stop['trace_dir']} "
        f"(capture {stop['duration_ms']:.0f}ms; load the .trace.json.gz "
        f"at ui.perfetto.dev)"
    )
    print(f"obs trace ring id: {stop['trace_id']} (GET /_traces/<id>)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
