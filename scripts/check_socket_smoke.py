#!/usr/bin/env python
"""CI smoke gate for the real-socket transport / multi-process cluster.

Runs, on the CPU backend with no TPU in the loop:

- the TCP transport contracts (frame codec, handshake refusal, per-send
  deadlines, abrupt-death/partial-frame handling, pooled reconnect,
  interception parity with the in-memory hub), and
- the 2-process loopback cluster scenario (cluster/procs.py): each
  worker an OS process with its own node id + data_path, indexing and
  the search mix served through real sockets, then kill -9 of the
  primary-owning process -> promotion within deadline -> every acked
  write read back, plus a socket-layer partition + heal converging.

The same tests ride the tier-1 run via the fast (`not slow`) marker —
the FULL chaos/replication matrices over TCP run in the `slow` lane of
the transport-parameterized suites. This script is the standalone hook
for pre-merge / cron checks, mirroring scripts/check_chaos_smoke.py:

    python scripts/check_socket_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_tcp_transport.py",
        "tests/test_socket_procs.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
