#!/usr/bin/env python
"""CI smoke gate for the fault-injection / degraded-mode subsystem.

Runs the deterministic-seed chaos suite (seeded fault schedules over a
replicated multi-shard corpus: correct-subset partials, honest shard
accounting, allow_partial_search_results=false → 503, batcher failure
isolation) plus the targeted fault-injection contracts, on the CPU
backend — no TPU needed, < 60 s. The same tests ride the tier-1 run via
the fast (`not slow`) marker; this script is the standalone hook for
pre-merge / cron checks, mirroring scripts/check_exec_parity.py:

    python scripts/check_chaos_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_faults_chaos.py",
        "tests/test_fault_injection.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
