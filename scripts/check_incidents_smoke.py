#!/usr/bin/env python
"""CI smoke gate for the flight recorder + incident autopsy (ISSUE 19).

Runs, on the CPU backend with no TPU in the loop:

- the bounded flight-recorder ring (record/filter/limit semantics, the
  cataloged estpu_recorder_* instruments),
- the auto-capture law on a standalone node and over a LocalCluster REST
  front: any health indicator leaving green freezes an incident capsule
  within one poll, with the named diagnosis, >= 1 recorder frame from
  BEFORE the trigger, spliced exemplar traces, a hot-threads sample, and
  in-window remediation actions; green resolves with a time-to-green,
- manual grabs (`POST /_incidents/_capture`), the ring bound (resolved
  incidents age out first, open ones survive), JSON bundle export, the
  `/_cat/incidents` row surface, `?verbose=false` skipping capsule
  bodies and the cluster fan, the untraced-path law, and the
  `ESTPU_INCIDENTS=0` present-but-inert mode,
- the ProcCluster capsule fan over the never-intercepted `_ctl` path
  (per-member recorder summaries).

The same tests ride the tier-1 run via the fast (`not slow`) marker;
this script is the standalone hook for pre-merge / cron checks,
mirroring scripts/check_health_smoke.py:

    python scripts/check_incidents_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/test_incidents.py",
        "-q",
        "-m",
        "not slow",
        "-p",
        "no:cacheprovider",
    ]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, env=env, cwd=REPO_ROOT)


if __name__ == "__main__":
    main_rc = main()
    sys.exit(main_rc)
