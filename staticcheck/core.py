"""Framework core: findings, suppressions, baseline, project loading.

A *pass* is a function `(Project) -> list[Finding]` registered under a
family name via `@register_pass`. The runner executes every pass (or a
`--only` subset), filters findings through inline suppressions and the
committed baseline, and reports what is left. Everything is stdlib-only
(`ast` + `json`): the gate must run in tier-1 on a CPU box in seconds.

Suppression grammar (same line as the finding, or a comment-only line
immediately above it):

    # staticcheck: ignore[rule-a,rule-b] reason text

The reason is mandatory — a reasonless suppression does not suppress
(the whole point is that every grandfathered hazard carries its "why").

Baseline entries are line-number-free fingerprints
(rule, path, context, message) so unrelated edits to a file do not
invalidate them; `--write-baseline` regenerates the file.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

SEVERITIES = ("error", "warning")

# Rules that never gate (informational hygiene about the tool itself).
ADVISORY_RULES = frozenset({"unused-suppression"})

_SUPPRESS_RE = re.compile(
    r"#\s*staticcheck:\s*ignore\[([A-Za-z0-9_,\- ]+)\]\s*(.*)"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    severity: str = "error"
    # Enclosing def/class qualname — part of the baseline fingerprint so
    # entries survive line drift from unrelated edits.
    context: str = ""

    @property
    def fingerprint(self) -> tuple:
        return (self.rule, self.path, self.context, self.message)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.severity}[{self.rule}] "
            f"{self.message}"
        )


@dataclass
class Suppression:
    path: str
    target: int  # the ONE line this suppression covers
    comment_line: int  # where the comment itself sits (for reporting)
    rules: tuple[str, ...]
    reason: str
    used: bool = False

    def covers(self, finding: Finding) -> bool:
        # Exactly one line: an inline comment covers its own line, a
        # comment-only line covers the next — never a neighbor (a
        # wider window would silently exempt the unannotated hazard one
        # line above a suppression).
        if finding.path != self.path or not self.reason:
            return False
        if finding.line != self.target:
            return False
        return finding.rule in self.rules or "all" in self.rules


class SourceFile:
    """One parsed module: text, AST, suppressions, dotted module name."""

    def __init__(self, root: str, rel: str):
        self.rel = rel.replace(os.sep, "/")
        self.abspath = os.path.join(root, rel)
        with open(self.abspath, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.rel)
        mod = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        self.module = mod.replace("/", ".")
        self.suppressions = self._parse_suppressions()
        # line -> qualname of the innermost def/class starting there (for
        # finding context); filled lazily.
        self._context_spans: list[tuple[int, int, str]] | None = None

    def _parse_suppressions(self) -> list[Suppression]:
        # Real COMMENT tokens only: a suppression example inside a
        # docstring must not register.
        out = []
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.text).readline
            )
            comments = [
                (tok.start[0], tok.string, tok.start[1])
                for tok in tokens
                if tok.type == tokenize.COMMENT
            ]
        except tokenize.TokenError:
            comments = []
        for lineno, comment, col in comments:
            m = _SUPPRESS_RE.search(comment)
            if not m:
                continue
            rules = tuple(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )
            line_text = self.lines[lineno - 1] if lineno <= len(
                self.lines
            ) else ""
            comment_only = line_text.strip().startswith("#")
            out.append(
                Suppression(
                    path=self.rel,
                    # A comment-only line covers the NEXT line; an inline
                    # trailing comment covers its own.
                    target=lineno + 1 if comment_only else lineno,
                    comment_line=lineno,
                    rules=rules,
                    reason=m.group(2).strip(),
                )
            )
        return out

    def context_at(self, line: int) -> str:
        """Qualname of the innermost function/class containing `line`."""
        if self._context_spans is None:
            spans: list[tuple[int, int, str]] = []

            def visit(node, prefix):
                for child in ast.iter_child_nodes(node):
                    if isinstance(
                        child,
                        (
                            ast.FunctionDef,
                            ast.AsyncFunctionDef,
                            ast.ClassDef,
                        ),
                    ):
                        qual = f"{prefix}{child.name}"
                        end = getattr(child, "end_lineno", child.lineno)
                        spans.append((child.lineno, end, qual))
                        visit(child, qual + ".")

            visit(self.tree, "")
            self._context_spans = spans
        best = ""
        best_size = None
        for lo, hi, qual in self._context_spans:
            if lo <= line <= hi and (best_size is None or hi - lo < best_size):
                best, best_size = qual, hi - lo
        return best


# Default scan roots for the real repo layout. Tests are excluded on
# purpose: they exercise hazards (fault injection, deliberate blocking)
# that are the *subject* of the rules, not violations of them.
_REPO_SCAN = ("elasticsearch_tpu", "scripts", "staticcheck")
_REPO_SINGLE_FILES = ("bench.py",)


class Project:
    """The analyzed file set, parsed once and shared by every pass."""

    def __init__(self, root: str, rel_paths: list[str] | None = None):
        self.root = os.path.abspath(root)
        if rel_paths is None:
            rel_paths = self._discover()
        self.files: dict[str, SourceFile] = {}
        errors: list[Finding] = []
        for rel in sorted(rel_paths):
            try:
                sf = SourceFile(self.root, rel)
            except SyntaxError as e:
                errors.append(
                    Finding(
                        rule="parse-error",
                        path=rel.replace(os.sep, "/"),
                        line=e.lineno or 1,
                        message=f"file does not parse: {e.msg}",
                    )
                )
                continue
            self.files[sf.rel] = sf
        self.parse_errors = errors

    def _discover(self) -> list[str]:
        rels: list[str] = []
        scan_dirs = [
            d
            for d in _REPO_SCAN
            if os.path.isdir(os.path.join(self.root, d))
        ]
        if not scan_dirs:
            # Fixture/mini-project layout: everything under root.
            scan_dirs = ["."]
        for d in scan_dirs:
            base = os.path.join(self.root, d)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [
                    n
                    for n in dirnames
                    if n != "__pycache__" and not n.startswith(".")
                ]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        rels.append(
                            os.path.relpath(
                                os.path.join(dirpath, name), self.root
                            )
                        )
        for name in _REPO_SINGLE_FILES:
            if os.path.isfile(os.path.join(self.root, name)):
                rels.append(name)
        return rels

    def get(self, rel: str) -> SourceFile | None:
        return self.files.get(rel)

    def suppressions(self) -> list[Suppression]:
        return [s for sf in self.files.values() for s in sf.suppressions]


# --------------------------------------------------------------- registry

@dataclass
class PassInfo:
    name: str
    fn: object
    rules: dict[str, str] = field(default_factory=dict)  # rule -> rationale


PASSES: dict[str, PassInfo] = {}


def register_pass(name: str, rules: dict[str, str]):
    """Register a pass under a family name with its rule glossary."""

    def deco(fn):
        PASSES[name] = PassInfo(name=name, fn=fn, rules=rules)
        return fn

    return deco


def all_rules() -> dict[str, str]:
    out = {"parse-error": "analyzed file must parse"}
    for info in PASSES.values():
        out.update(info.rules)
    out["unused-suppression"] = (
        "a staticcheck ignore comment that suppresses nothing is stale"
    )
    return out


# ----------------------------------------------------------------- runner

@dataclass
class Report:
    findings: list[Finding]  # post-suppression, post-baseline (the news)
    baselined: list[Finding]
    suppressed: list[Finding]
    unused_suppressions: list[Suppression]
    per_rule: dict[str, int]

    @property
    def failed(self) -> bool:
        return any(f.rule not in ADVISORY_RULES for f in self.findings)

    def summary_lines(self) -> list[str]:
        lines = []
        for rule in sorted(self.per_rule):
            lines.append(f"  {rule:32s} {self.per_rule[rule]}")
        lines.append(
            f"{len(self.findings)} finding(s) "
            f"({len(self.baselined)} baselined, "
            f"{len(self.suppressed)} suppressed)"
        )
        return lines


def load_baseline(path: str) -> set[tuple]:
    if not path or not os.path.isfile(path):
        return set()
    with open(path, "r", encoding="utf-8") as f:
        entries = json.load(f)
    return {
        (e["rule"], e["path"], e.get("context", ""), e["message"])
        for e in entries
    }


def write_baseline(path: str, findings: list[Finding]) -> None:
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "context": f.context,
            "message": f.message,
        }
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=1)
        f.write("\n")


def run_project(
    project: Project,
    baseline: set[tuple] | None = None,
    only: list[str] | None = None,
) -> Report:
    # Import-for-effect: pass modules self-register.
    from . import passes  # noqa: F401

    raw: list[Finding] = list(project.parse_errors)
    active_rules: set[str] = set()
    for name, info in sorted(PASSES.items()):
        if only and name not in only:
            continue
        active_rules.update(info.rules)
        raw.extend(info.fn(project))

    # Attach contexts (cheap, needed for fingerprints).
    fixed: list[Finding] = []
    for f in raw:
        if not f.context:
            sf = project.get(f.path)
            if sf is not None:
                f = Finding(
                    rule=f.rule,
                    path=f.path,
                    line=f.line,
                    message=f.message,
                    severity=f.severity,
                    context=sf.context_at(f.line),
                )
        fixed.append(f)

    sups = project.suppressions()
    by_path: dict[str, list[Suppression]] = {}
    for s in sups:
        by_path.setdefault(s.path, []).append(s)

    kept: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    baseline = baseline or set()
    for f in sorted(fixed, key=lambda f: (f.path, f.line, f.rule)):
        hit = None
        for s in by_path.get(f.path, ()):
            if s.covers(f):
                hit = s
                break
        if hit is not None:
            hit.used = True
            suppressed.append(f)
        elif f.fingerprint in baseline:
            baselined.append(f)
        else:
            kept.append(f)

    # A suppression is only stale if every rule it names actually ran
    # this invocation (a --only subset must not flag the other families'
    # suppressions).
    unused = [
        s
        for s in sups
        if not s.used and all(r in active_rules for r in s.rules)
    ]
    for s in unused:
        kept.append(
            Finding(
                rule="unused-suppression",
                path=s.path,
                line=s.comment_line,
                message=(
                    "suppression "
                    f"ignore[{','.join(s.rules)}] matches no finding"
                    + ("" if s.reason else " (and has no reason text)")
                ),
                severity="warning",
            )
        )

    per_rule: dict[str, int] = {}
    for f in kept:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    return Report(
        findings=kept,
        baselined=baselined,
        suppressed=suppressed,
        unused_suppressions=unused,
        per_rule=per_rule,
    )
