"""Repo-specific static analysis: machine-checked serving invariants.

The codebase's correctness rests on conventions no unit test can see
until they break at runtime: kernels reachable from `jax.jit` /
`shard_map` must stay trace-pure or they silently recompile (or host-
sync) per request; ~30 locks guard the batcher/transport/metrics hot
paths and must never invert or block while held; and each subsystem PR
added a registry (planner BACKENDS, fault sites, metrics catalog, the
arity-7 bool spec) whose producers and consumers are linked only by
convention. `staticcheck` turns those conventions into contracts the
tier-1 gate enforces — the same move as the reference build's
forbidden-APIs / StringFormatting checks (gradle/internal precommit).

Usage:

    python -m staticcheck                  # analyze the repo, exit 1 on
                                           # any non-baselined finding
    python -m staticcheck --rules          # rule glossary
    python -m staticcheck --write-baseline # grandfather current findings

Suppress a single finding at its line (a reason is mandatory):

    something_flagged()  # staticcheck: ignore[rule-name] why it is fine

Passes register themselves in `staticcheck.core.PASSES` on import of
`staticcheck.passes`; everything runs on the stdlib `ast` only.
"""

from .core import Finding, Project, Report, run_project  # noqa: F401
