"""Pass family 1: trace purity of jit/shard_map-reachable code.

Functions reachable from a `jax.jit` / `shard_map` entry point execute
under tracing: a host sync (`.item()`, `np.asarray`, `float()` on a
traced value) stalls the launch pipeline or fails under jit, and a
Python branch on a traced value either fails at trace time or — worse —
silently burns a recompile per distinct value, the exact dispatch
overhead that made BENCH cfg1 lose 12x. The pass:

1. finds jit roots (`@partial(jax.jit, static_argnames=...)` decorators,
   `jax.jit(f)` calls, `shard_map(body, ...)` bodies);
2. walks the project call graph from the roots, propagating which
   parameters are traced (static_argnames and shape-like derivations
   are static; everything else array-ish flows as traced);
3. inside the reachable set, flags host syncs and data-dependent Python
   control flow on traced values;
4. everywhere, flags ephemeral `jax.jit(...)` wrappers (a fresh jit
   cache per call recompiles per request) and unhashable literals
   passed in a jit static parameter position.
"""

from __future__ import annotations

import ast

from ..callgraph import (
    FunctionInfo,
    ProjectIndex,
    dotted_name,
    get_index,
    mentions_traced,
    resolves_to,
)
from ..core import Finding, Project, register_pass

RULES = {
    "host-sync": (
        "host sync (.item()/np.asarray/float()/block_until_ready on a "
        "traced value) inside jit/shard_map-reachable code stalls or "
        "breaks the launch"
    ),
    "traced-branch": (
        "Python if/while/for on a traced value fails at trace time or "
        "recompiles per value"
    ),
    "jit-ephemeral": (
        "jax.jit(...) built and invoked inline creates a fresh compile "
        "cache per call — every request recompiles"
    ),
    "jit-unhashable-static": (
        "list/dict/set literal passed in a jit static parameter position "
        "is unhashable and fails (or defeats) the compile cache"
    ),
}

_HOST_SYNC_ATTRS = frozenset({"item", "tolist", "block_until_ready"})
_HOST_SYNC_BUILTINS = frozenset({"float", "int", "bool", "complex"})
_NUMPY_SYNC_FUNCS = frozenset({"asarray", "array"})
_UNHASHABLE = (
    ast.List,
    ast.Dict,
    ast.Set,
    ast.ListComp,
    ast.DictComp,
    ast.SetComp,
    ast.GeneratorExp,
)


def _is_jax_jit(index: ProjectIndex, sf, node: ast.AST) -> bool:
    return resolves_to(index, sf, node, "jax.jit") or resolves_to(
        index, sf, node, "jax.Jit"
    )


def _is_shard_map(index: ProjectIndex, sf, node: ast.AST) -> bool:
    name = dotted_name(node)
    if name is None:
        return False
    # Any local or jax-qualified shard_map spelling (the repo wraps the
    # 0.4/0.6 API split in parallel/sharded._shard_map).
    return name.split(".")[-1] in ("shard_map", "_shard_map")


def _static_argnames(call: ast.Call) -> set[str]:
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for elt in v.elts:
                    if isinstance(elt, ast.Constant):
                        out.add(str(elt.value))
    return out


class _Roots:
    """jit/shard_map entry points: FunctionInfo -> static param names."""

    def __init__(self, project: Project, index: ProjectIndex):
        self.static: dict[tuple, set[str]] = {}
        self.index = index
        for sf in project.files.values():
            for node in ast.walk(sf.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self._from_decorators(sf, node)
                elif isinstance(node, ast.Call):
                    self._from_call(sf, node)

    def _add(self, info: FunctionInfo | None, static: set[str]) -> None:
        if info is None:
            return
        self.static.setdefault(info.key, set()).update(static)

    def _lookup(self, sf, name: str) -> FunctionInfo | None:
        for key, info in self.index.functions.items():
            if key[0] == sf.rel and (
                info.qualname == name or info.qualname.endswith(f".{name}")
            ):
                return info
        return None

    def _from_decorators(self, sf, fn: ast.AST) -> None:
        for dec in fn.decorator_list:
            static: set[str] | None = None
            if _is_jax_jit(self.index, sf, dec):
                static = set()
            elif isinstance(dec, ast.Call):
                if _is_jax_jit(self.index, sf, dec.func):
                    static = _static_argnames(dec)
                elif (
                    resolves_to(self.index, sf, dec.func, "functools.partial")
                    and dec.args
                    and _is_jax_jit(self.index, sf, dec.args[0])
                ):
                    static = _static_argnames(dec)
            if static is not None:
                self._add(self._lookup(sf, fn.name), static)

    def _from_call(self, sf, call: ast.Call) -> None:
        fn_arg: ast.AST | None = None
        static: set[str] = set()
        if _is_jax_jit(self.index, sf, call.func) and call.args:
            fn_arg = call.args[0]
            static = _static_argnames(call)
        elif _is_shard_map(self.index, sf, call.func) and call.args:
            fn_arg = call.args[0]
        if isinstance(fn_arg, ast.Name):
            self._add(self._lookup(sf, fn_arg.id), static)


def _local_traced(
    info: FunctionInfo, seed: set[str]
) -> set[str]:
    """Names traced inside one function: seeded params/closures plus
    anything assigned from an expression mentioning a traced name (two
    propagation sweeps cover backward references in loops)."""
    traced = set(seed)
    body = info.node.body
    for _ in range(2):
        before = len(traced)
        for node in ast.walk(ast.Module(body=body, type_ignores=[])):
            if isinstance(node, ast.Assign) and mentions_traced(
                node.value, traced
            ):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            traced.add(n.id)
            elif isinstance(node, ast.AugAssign) and mentions_traced(
                node.value, traced
            ):
                if isinstance(node.target, ast.Name):
                    traced.add(node.target.id)
            elif isinstance(node, ast.For) and mentions_traced(
                node.iter, traced
            ):
                _propagate_loop_targets(node, traced)
        if len(traced) == before:
            break
    return traced


def _is_identity_test(test: ast.AST) -> bool:
    """`x is None` / `x is not None` (possibly and/or-joined)."""
    if isinstance(test, ast.BoolOp):
        return all(_is_identity_test(v) for v in test.values)
    return (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], (ast.Is, ast.IsNot))
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    )


def _propagate_loop_targets(node: ast.For, traced: set[str]) -> None:
    """Mark loop targets traced — per position for `zip`/`enumerate`
    (iterating a Python container that MIXES static specs with traced
    pytrees must not poison the static side)."""
    it, tgt = node.iter, node.target
    if (
        isinstance(it, ast.Call)
        and isinstance(it.func, ast.Name)
        and isinstance(tgt, ast.Tuple)
    ):
        if it.func.id == "zip" and len(it.args) == len(tgt.elts):
            for arg, elt in zip(it.args, tgt.elts):
                if mentions_traced(arg, traced):
                    for n in ast.walk(elt):
                        if isinstance(n, ast.Name):
                            traced.add(n.id)
            return
        if it.func.id == "enumerate" and len(tgt.elts) == 2 and it.args:
            if mentions_traced(it.args[0], traced):
                for n in ast.walk(tgt.elts[1]):
                    if isinstance(n, ast.Name):
                        traced.add(n.id)
            return
    for n in ast.walk(tgt):
        if isinstance(n, ast.Name):
            traced.add(n.id)


def _walk_own(info: FunctionInfo):
    """Statements of a function EXCLUDING nested function bodies (those
    are analyzed as their own reachable nodes)."""
    skip: set[int] = set()
    for node in ast.walk(info.node):
        if id(node) in skip:
            continue
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node is not info.node
        ):
            for sub in ast.walk(node):
                skip.add(id(sub))
            continue
        yield node


@register_pass("trace-hazard", RULES)
def run(project: Project) -> list[Finding]:
    index = get_index(project)
    roots = _Roots(project, index)
    findings: list[Finding] = []

    # ---- reachability + traced-parameter propagation (fixpoint)
    traced_params: dict[tuple, set[str]] = {}
    order: list[tuple] = []
    for key, static in roots.static.items():
        info = index.functions[key]
        traced_params[key] = {
            p for p in info.params if p not in static and p != "self"
        }
        order.append(key)

    closure_env: dict[tuple, set[str]] = {k: set() for k in order}
    work = list(order)
    local_cache: dict[tuple, set[str]] = {}
    hops = 0
    while work and hops < 10000:
        hops += 1
        key = work.pop()
        info = index.functions.get(key)
        if info is None:
            continue
        seed = traced_params.get(key, set()) | closure_env.get(key, set())
        traced = _local_traced(info, seed)
        local_cache[key] = traced
        for node in _walk_own(info):
            if not isinstance(node, ast.Call):
                continue
            for callee in index.resolve_call(info, node):
                ck = callee.key
                params = callee.params
                new = traced_params.setdefault(ck, set())
                before = len(new) + len(closure_env.get(ck, set()))
                pos = [p for p in params if p != "self"]
                for i, arg in enumerate(node.args):
                    if i < len(pos) and mentions_traced(arg, traced):
                        new.add(pos[i])
                for kw in node.keywords:
                    if kw.arg in params and mentions_traced(
                        kw.value, traced
                    ):
                        new.add(kw.arg)
                if callee.parent and callee.sf.rel == info.sf.rel:
                    # Nested callee closes over this scope's names.
                    env = closure_env.setdefault(ck, set())
                    env.update(n for n in traced if n not in params)
                after = len(new) + len(closure_env.get(ck, set()))
                if ck not in local_cache or after > before:
                    work.append(ck)
                    if ck not in order:
                        order.append(ck)

    # ---- rules inside the reachable set
    for key in order:
        info = index.functions.get(key)
        if info is None:
            continue
        sf = info.sf
        traced = local_cache.get(key, set())

        def finding(rule: str, node: ast.AST, msg: str) -> None:
            findings.append(
                Finding(
                    rule=rule,
                    path=sf.rel,
                    line=node.lineno,
                    message=msg,
                    context=info.qualname,
                )
            )

        for node in _walk_own(info):
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in _HOST_SYNC_ATTRS
                    and mentions_traced(f.value, traced)
                ):
                    finding(
                        "host-sync",
                        node,
                        f".{f.attr}() on traced value in jit-reachable "
                        f"[{info.qualname}]",
                    )
                elif (
                    isinstance(f, ast.Name)
                    and f.id in _HOST_SYNC_BUILTINS
                    and any(mentions_traced(a, traced) for a in node.args)
                ):
                    finding(
                        "host-sync",
                        node,
                        f"{f.id}() forces a traced value to host in "
                        f"jit-reachable [{info.qualname}]",
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr in _NUMPY_SYNC_FUNCS
                    and isinstance(f.value, ast.Name)
                    and index.imports.get(sf.rel, {}).get(f.value.id)
                    == "numpy"
                    and any(mentions_traced(a, traced) for a in node.args)
                ):
                    finding(
                        "host-sync",
                        node,
                        f"np.{f.attr}() on traced value in jit-reachable "
                        f"[{info.qualname}]",
                    )
            elif isinstance(node, (ast.If, ast.While)):
                if _is_identity_test(node.test):
                    # `x is None` / `x is not None` never reads traced
                    # data — pytree structure is static at trace time.
                    continue
                if mentions_traced(node.test, traced):
                    finding(
                        "traced-branch",
                        node,
                        "Python branch on traced value in "
                        f"[{info.qualname}] (trace error or per-value "
                        "recompile)",
                    )
            elif isinstance(node, ast.For):
                it = node.iter
                hazard = (
                    isinstance(it, ast.Name) and it.id in traced
                ) or (
                    isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id in ("range", "enumerate", "reversed")
                    and any(
                        mentions_traced(a, traced) for a in it.args
                    )
                )
                if hazard:
                    finding(
                        "traced-branch",
                        node,
                        "Python loop over traced value in "
                        f"[{info.qualname}] (length must be static)",
                    )

    # ---- whole-project structural rules
    for sf in project.files.values():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            # jax.jit(...)(args): ephemeral wrapper, recompiles per call.
            if isinstance(node.func, ast.Call) and _is_jax_jit(
                index, sf, node.func.func
            ):
                findings.append(
                    Finding(
                        rule="jit-ephemeral",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            "jax.jit(...) invoked inline — cache the "
                            "jitted callable at module scope"
                        ),
                    )
                )

    # Static positions of known roots must receive hashable literals.
    static_by_key = {
        k: v for k, v in roots.static.items() if v
    }
    for sf in project.files.values():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            for callee in _resolve_any(index, sf, node):
                static = static_by_key.get(callee.key)
                if not static:
                    continue
                pos = [p for p in callee.params if p != "self"]
                bad: list[tuple[str, ast.AST]] = []
                for i, arg in enumerate(node.args):
                    if i < len(pos) and pos[i] in static and isinstance(
                        arg, _UNHASHABLE
                    ):
                        bad.append((pos[i], arg))
                for kw in node.keywords:
                    if kw.arg in static and isinstance(
                        kw.value, _UNHASHABLE
                    ):
                        bad.append((kw.arg, kw.value))
                for pname, arg in bad:
                    findings.append(
                        Finding(
                            rule="jit-unhashable-static",
                            path=sf.rel,
                            line=arg.lineno,
                            message=(
                                f"unhashable literal for static jit "
                                f"arg [{pname}] of "
                                f"[{callee.qualname}]"
                            ),
                        )
                    )
    return findings


def _resolve_any(index: ProjectIndex, sf, call: ast.Call):
    """Resolve a call from arbitrary (possibly module-level) context."""
    f = call.func
    if isinstance(f, ast.Name):
        info = index.functions.get((sf.rel, f.id))
        if info is not None:
            return [info]
        dotted = index.imports.get(sf.rel, {}).get(f.id)
        if dotted and "." in dotted:
            mod, name = dotted.rsplit(".", 1)
            rel = index.module_rel.get(mod)
            if rel:
                info = index.functions.get((rel, name))
                if info is not None:
                    return [info]
    elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        dotted = index.imports.get(sf.rel, {}).get(f.value.id)
        if dotted:
            rel = index.module_rel.get(dotted)
            if rel:
                info = index.functions.get((rel, f.attr))
                if info is not None:
                    return [info]
    return []
