"""Pass family 3: cross-module registry consistency.

Each subsystem PR added a registry whose producers and consumers are
linked only by convention; this pass turns the conventions into checked
contracts:

- **planner backends** (`exec/planner.py` `ExecPlanner.BACKENDS`): every
  backend must have a cost seed mention in `exec/cost.py` (the planner
  calls `seed_ms` for every candidate — an unseeded backend silently
  costs like the device) and at least one execution/surfacing site
  outside the planner itself.
- **fault sites** (`faults/registry.py` `SITES`): every `fault_point()`
  call site in the serving stack must name a registered site pattern
  (an unregistered string is a chaos hook that silently never fires),
  and every registered pattern must have a live call site.
- **metrics catalog** (`obs/metrics.py` `CATALOG`): every `estpu_*`
  instrument created on a registry must be cataloged with a matching
  kind and a `_nodes/stats` section, and every cataloged name must be
  referenced by code — the machine check that `GET /_metrics` and
  `GET /_nodes/stats` stay two views over the same instruments.
- **bool spec** (`query/compile.py` `BOOL_SPEC_FIELDS`): the arity-7
  `("bool", must, should, filter, must_not, msm, lead)` plan tuple is
  constructed only via `make_bool_spec` and destructured with indices
  inside the declared arity, across compile.py / ops/bm25_device.py /
  exec/.
"""

from __future__ import annotations

import ast
import fnmatch

from ..core import Finding, Project, register_pass

RULES = {
    "registry-backend": (
        "planner BACKENDS entry without a cost seed in exec/cost.py or "
        "without any execution/surfacing site"
    ),
    "registry-fault-site": (
        "fault_point() site not declared in faults/registry.py SITES "
        "(or a declared site with no call site)"
    ),
    "registry-metric": (
        "estpu_* instrument not in the obs/metrics.py CATALOG (or "
        "cataloged with the wrong kind / never referenced)"
    ),
    "bool-spec": (
        "arity-7 bool spec constructed outside make_bool_spec or "
        "indexed/destructured beyond the declared field order"
    ),
    "registry-breaker-label": (
        "CircuitBreaker add/add_unchecked/release with a label outside "
        "the HBM ledger's label registry (obs/device.py LEDGER_LABELS)"
    ),
    "registry-indicator": (
        "health INDICATORS entry without an indicator_<name> "
        "implementation in obs/health.py (or an implementation absent "
        "from INDICATORS)"
    ),
    "registry-action": (
        "remediation ACTIONS entry without a plan_<name> implementation "
        "in cluster/remediation.py (or an implementation absent from "
        "ACTIONS)"
    ),
}

_PLANNER = "elasticsearch_tpu/exec/planner.py"
_COST = "elasticsearch_tpu/exec/cost.py"
_FAULTS = "elasticsearch_tpu/faults/registry.py"
_METRICS = "elasticsearch_tpu/obs/metrics.py"
_COMPILE = "elasticsearch_tpu/query/compile.py"
_DEVICE_OBS = "elasticsearch_tpu/obs/device.py"
_HEALTH = "elasticsearch_tpu/obs/health.py"
_REMEDIATION = "elasticsearch_tpu/cluster/remediation.py"

# Files handling raw bool-spec tuples (construction restricted to
# make_bool_spec in compile.py; index bounds checked everywhere below).
_BOOL_SPEC_FILES = (
    _COMPILE,
    "elasticsearch_tpu/ops/bm25_device.py",
    "elasticsearch_tpu/exec/planner.py",
    "elasticsearch_tpu/exec/batcher.py",
    "elasticsearch_tpu/exec/packed.py",
)
_BOOL_SPEC_ARITY = 7


def _const_tuple(node: ast.AST) -> list[str]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _assigned_tuple(tree: ast.AST, name: str) -> tuple[list[str], int]:
    """Find `NAME = ("a", "b", ...)` anywhere (module or class body)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return _const_tuple(node.value), node.lineno
    return [], 0


def _string_literals(tree: ast.AST) -> set[str]:
    return {
        n.value
        for n in ast.walk(tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    }


@register_pass("registry-consistency", RULES)
def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    findings += _check_backends(project)
    findings += _check_fault_sites(project)
    findings += _check_metrics(project)
    findings += _check_bool_spec(project)
    findings += _check_breaker_labels(project)
    findings += _check_indicators(project)
    findings += _check_actions(project)
    return findings


# ----------------------------------------------------------- backends

def _check_backends(project: Project) -> list[Finding]:
    planner = project.get(_PLANNER)
    cost = project.get(_COST)
    if planner is None or cost is None:
        return []
    backends, line = _assigned_tuple(planner.tree, "BACKENDS")
    if not backends:
        return [
            Finding(
                rule="registry-backend",
                path=_PLANNER,
                line=1,
                message="ExecPlanner.BACKENDS tuple not found",
            )
        ]
    cost_literals = _string_literals(cost.tree)
    # Surfacing sites exclude the planner AND the cost model: a backend
    # named only in its cost seed has a price but nothing that ever
    # executes or reports it.
    other_literals: set[str] = set()
    for sf in project.files.values():
        if sf.rel not in (_PLANNER, _COST):
            other_literals |= _string_literals(sf.tree)
    out = []
    for b in backends:
        if b not in cost_literals:
            out.append(
                Finding(
                    rule="registry-backend",
                    path=_PLANNER,
                    line=line,
                    message=(
                        f"backend [{b}] has no cost seed mention in "
                        "exec/cost.py — seed_ms silently misprices it"
                    ),
                )
            )
        if b not in other_literals:
            out.append(
                Finding(
                    rule="registry-backend",
                    path=_PLANNER,
                    line=line,
                    message=(
                        f"backend [{b}] is never referenced outside the "
                        "planner — no execution or surfacing site"
                    ),
                )
            )
    return out


# -------------------------------------------------------- fault sites

def _fault_point_calls(project: Project):
    for sf in project.files.values():
        if sf.rel == _FAULTS:
            continue
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and (
                    (
                        isinstance(node.func, ast.Name)
                        and node.func.id == "fault_point"
                    )
                    or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "fault_point"
                    )
                )
                and node.args
            ):
                yield sf, node


def _site_literal(arg: ast.AST) -> tuple[str, bool]:
    """(site-or-prefix, is_exact). f-strings yield their static prefix."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, True
    if isinstance(arg, ast.JoinedStr):
        prefix = ""
        for part in arg.values:
            if isinstance(part, ast.Constant):
                prefix += str(part.value)
            else:
                break
        return prefix, False
    return "", False


def _check_fault_sites(project: Project) -> list[Finding]:
    reg = project.get(_FAULTS)
    if reg is None:
        return []
    sites, line = _assigned_tuple(reg.tree, "SITES")
    if not sites:
        return [
            Finding(
                rule="registry-fault-site",
                path=_FAULTS,
                line=1,
                message="canonical SITES tuple not found",
            )
        ]
    out = []
    matched: set[str] = set()
    for sf, call in _fault_point_calls(project):
        site, exact = _site_literal(call.args[0])
        if not site:
            continue
        hits = []
        for pat in sites:
            if exact:
                ok = fnmatch.fnmatchcase(site, pat)
            else:
                pat_prefix = pat.split("*")[0]
                ok = site.startswith(pat_prefix) or pat_prefix.startswith(
                    site
                )
            if ok:
                hits.append(pat)
        if hits:
            matched.update(hits)
        else:
            out.append(
                Finding(
                    rule="registry-fault-site",
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        f"fault site [{site}] is not declared in "
                        "faults/registry.py SITES — this chaos hook can "
                        "never be armed by name"
                    ),
                )
            )
    for pat in sites:
        if pat not in matched:
            out.append(
                Finding(
                    rule="registry-fault-site",
                    path=_FAULTS,
                    line=line,
                    message=(
                        f"declared fault site [{pat}] has no fault_point "
                        "call site — dead registry entry"
                    ),
                )
            )
    return out


# ------------------------------------------------------------ metrics

# `windowed_*` are the rolling-window instruments (ISSUE 15): cataloged
# with kind "windowed_histogram"/"windowed_counter", so an uncataloged
# estpu_*_recent / estpu_health_* creation fails the gate like any other.
_INSTRUMENT_METHODS = {
    "counter",
    "gauge",
    "histogram",
    "windowed_histogram",
    "windowed_counter",
}


def _catalog(project: Project) -> tuple[dict[str, str], tuple[int, int]]:
    """CATALOG = {"name": ("kind", "stats section"), ...} -> {name: kind}
    plus the dict's line span (to exclude it from reference counting)."""
    metrics = project.get(_METRICS)
    if metrics is None:
        return {}, (0, 0)
    for node in ast.walk(metrics.tree):
        if isinstance(node, ast.Assign):
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and tgt.id == "CATALOG":
                out = {}
                if isinstance(node.value, ast.Dict):
                    for k, v in zip(node.value.keys, node.value.values):
                        if not (
                            isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                        ):
                            continue
                        kinds = _const_tuple(v)
                        out[k.value] = kinds[0] if kinds else ""
                span = (
                    node.lineno,
                    getattr(node, "end_lineno", node.lineno),
                )
                return out, span
    return {}, (0, 0)


def _check_metrics(project: Project) -> list[Finding]:
    metrics = project.get(_METRICS)
    if metrics is None:
        return []
    catalog, span = _catalog(project)
    if not catalog:
        return [
            Finding(
                rule="registry-metric",
                path=_METRICS,
                line=1,
                message="instrument CATALOG dict not found",
            )
        ]
    out = []
    referenced: set[str] = set()
    for sf in project.files.values():
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value.startswith("estpu_")
            ):
                if sf.rel == _METRICS and span[0] <= node.lineno <= span[1]:
                    continue  # the catalog itself is not a reference
                referenced.add(node.value)
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _INSTRUMENT_METHODS
                and node.args
            ):
                continue
            name_arg = node.args[0]
            if not (
                isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)
                and name_arg.value.startswith("estpu_")
            ):
                continue
            name = name_arg.value
            want = catalog.get(name)
            if want is None:
                out.append(
                    Finding(
                        rule="registry-metric",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"instrument [{name}] is not in the "
                            "obs/metrics.py CATALOG — add it with its "
                            "kind and _nodes/stats section"
                        ),
                    )
                )
            elif want != node.func.attr:
                out.append(
                    Finding(
                        rule="registry-metric",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"instrument [{name}] created as "
                            f"{node.func.attr} but cataloged as {want}"
                        ),
                    )
                )
    for name in sorted(catalog):
        if name not in referenced:
            out.append(
                Finding(
                    rule="registry-metric",
                    path=_METRICS,
                    line=span[0],
                    message=(
                        f"cataloged instrument [{name}] is never "
                        "referenced by code — dead catalog entry"
                    ),
                )
            )
    return out


# ------------------------------------------------------ breaker labels

_BREAKER_METHODS = {"add", "add_unchecked", "release"}


def _check_breaker_labels(project: Project) -> list[Finding]:
    """Every breaker byte must carry a label from the HBM ledger's
    registry (obs/device.py LEDGER_LABELS): the breaker writes through to
    the ledger, so a label allocated outside the registry would mint an
    unbounded/unreconcilable ledger series — the drift the consistency
    law forbids. Checks calls of add/add_unchecked/release carrying a
    LITERAL `label=` keyword (f-strings match by their static prefix,
    like fault-site patterns; non-literal labels pass through — they are
    plumbing, not allocation sites)."""
    device = project.get(_DEVICE_OBS)
    if device is None:
        return []
    labels, line = _assigned_tuple(device.tree, "LEDGER_LABELS")
    if not labels:
        return [
            Finding(
                rule="registry-breaker-label",
                path=_DEVICE_OBS,
                line=1,
                message="LEDGER_LABELS tuple not found",
            )
        ]
    out = []
    for sf in project.files.values():
        if sf.rel == _DEVICE_OBS:
            continue
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BREAKER_METHODS
            ):
                continue
            label_kw = next(
                (kw for kw in node.keywords if kw.arg == "label"), None
            )
            if label_kw is None:
                continue
            label, exact = _site_literal(label_kw.value)
            if not label:
                continue
            if exact:
                ok = any(
                    label == known or label.startswith(known)
                    for known in labels
                )
            else:  # f-string: conservative prefix overlap
                ok = any(
                    label.startswith(known) or known.startswith(label)
                    for known in labels
                )
            if not ok:
                out.append(
                    Finding(
                        rule="registry-breaker-label",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"breaker label [{label}] is not in the HBM "
                            "ledger's LEDGER_LABELS registry "
                            "(obs/device.py) — bytes charged under it "
                            "cannot reconcile with the ledger"
                        ),
                    )
                )
    return out


# --------------------------------------------------------- indicators

def _check_indicators(project: Project) -> list[Finding]:
    """The health-indicator registry (obs/health.py INDICATORS): every
    registered name must have a module-level `indicator_<name>`
    implementation, and every implementation must be registered — an
    indicator that computes but never renders (or renders an entry that
    never computes) would silently hole the health report."""
    health = project.get(_HEALTH)
    if health is None:
        return []
    names, line = _assigned_tuple(health.tree, "INDICATORS")
    if not names:
        return [
            Finding(
                rule="registry-indicator",
                path=_HEALTH,
                line=1,
                message="INDICATORS tuple not found",
            )
        ]
    implemented: dict[str, int] = {}
    for node in health.tree.body:
        if isinstance(node, ast.FunctionDef) and node.name.startswith(
            "indicator_"
        ):
            implemented[node.name[len("indicator_"):]] = node.lineno
    out = []
    for name in names:
        if name not in implemented:
            out.append(
                Finding(
                    rule="registry-indicator",
                    path=_HEALTH,
                    line=line,
                    message=(
                        f"indicator [{name}] is registered in INDICATORS "
                        "but has no indicator_<name> implementation — "
                        "the health report would KeyError computing it"
                    ),
                )
            )
    for name, impl_line in sorted(implemented.items()):
        if name not in names:
            out.append(
                Finding(
                    rule="registry-indicator",
                    path=_HEALTH,
                    line=impl_line,
                    message=(
                        f"indicator_[{name}] is implemented but absent "
                        "from INDICATORS — it never renders in the "
                        "health report"
                    ),
                )
            )
    return out


# ------------------------------------------------------------- actions

def _check_actions(project: Project) -> list[Finding]:
    """The remediation-planner registry (cluster/remediation.py
    ACTIONS): every registered loop must have a pure module-level
    `plan_<name>` implementation, and every implementation must be
    registered — `RemediationService.plan` dispatches by name exactly
    like the health report dispatches INDICATORS, so an unregistered
    planner silently never runs and a registered ghost KeyErrors every
    tick."""
    remediation = project.get(_REMEDIATION)
    if remediation is None:
        return []
    names, line = _assigned_tuple(remediation.tree, "ACTIONS")
    if not names:
        return [
            Finding(
                rule="registry-action",
                path=_REMEDIATION,
                line=1,
                message="ACTIONS tuple not found",
            )
        ]
    implemented: dict[str, int] = {}
    for node in remediation.tree.body:
        if isinstance(node, ast.FunctionDef) and node.name.startswith(
            "plan_"
        ):
            implemented[node.name[len("plan_"):]] = node.lineno
    out = []
    for name in names:
        if name not in implemented:
            out.append(
                Finding(
                    rule="registry-action",
                    path=_REMEDIATION,
                    line=line,
                    message=(
                        f"remediation loop [{name}] is registered in "
                        "ACTIONS but has no plan_<name> implementation "
                        "— every tick would KeyError planning it"
                    ),
                )
            )
    for name, impl_line in sorted(implemented.items()):
        if name not in names:
            out.append(
                Finding(
                    rule="registry-action",
                    path=_REMEDIATION,
                    line=impl_line,
                    message=(
                        f"plan_[{name}] is implemented but absent from "
                        "ACTIONS — the remediation service never "
                        "dispatches it"
                    ),
                )
            )
    return out


# ---------------------------------------------------------- bool spec

def _check_bool_spec(project: Project) -> list[Finding]:
    compile_sf = project.get(_COMPILE)
    if compile_sf is None:
        return []
    fields, _ = _assigned_tuple(compile_sf.tree, "BOOL_SPEC_FIELDS")
    if len(fields) != _BOOL_SPEC_ARITY:
        return [
            Finding(
                rule="bool-spec",
                path=_COMPILE,
                line=1,
                message=(
                    "BOOL_SPEC_FIELDS must declare exactly "
                    f"{_BOOL_SPEC_ARITY} fields (found {len(fields)})"
                ),
            )
        ]
    out = []
    for rel in _BOOL_SPEC_FILES:
        sf = project.get(rel)
        if sf is None:
            continue
        in_ctor = rel == _COMPILE
        for node in ast.walk(sf.tree):
            # Raw construction: a tuple literal ("bool", ...) outside
            # make_bool_spec, in ANY bool-spec-handling file. Star-splat
            # rebuilds count too — their arity is unverifiable here,
            # which is the point of the constructor. (Deliberate
            # non-spec tuples, like the planner's AST signatures, carry
            # inline suppressions.)
            if isinstance(node, ast.Tuple) and node.elts:
                first = node.elts[0]
                if (
                    isinstance(first, ast.Constant)
                    and first.value == "bool"
                    and len(node.elts) > 1
                ):
                    ctor = in_ctor and "make_bool_spec" in sf.context_at(
                        node.lineno
                    )
                    if not ctor:
                        out.append(
                            Finding(
                                rule="bool-spec",
                                path=rel,
                                line=node.lineno,
                                message=(
                                    "raw ('bool', ...) spec tuple — "
                                    "construct via query.compile."
                                    "make_bool_spec so arity stays "
                                    f"{_BOOL_SPEC_ARITY}"
                                ),
                            )
                        )
            # Out-of-range constant index on a bool-spec variable.
            if isinstance(node, ast.Subscript):
                idx = node.slice
                if (
                    isinstance(idx, ast.Constant)
                    and isinstance(idx.value, int)
                    and idx.value >= _BOOL_SPEC_ARITY
                    and isinstance(node.value, ast.Name)
                    and _is_bool_spec_var(sf, node.value.id, node.lineno)
                ):
                    out.append(
                        Finding(
                            rule="bool-spec",
                            path=rel,
                            line=node.lineno,
                            message=(
                                f"index [{idx.value}] beyond bool-spec "
                                f"arity {_BOOL_SPEC_ARITY} on "
                                f"[{node.value.id}]"
                            ),
                        )
                    )
                if (
                    isinstance(idx, ast.Slice)
                    and isinstance(idx.upper, ast.Constant)
                    and isinstance(idx.upper.value, int)
                    and idx.upper.value > _BOOL_SPEC_ARITY
                    and isinstance(node.value, ast.Name)
                    and _is_bool_spec_var(sf, node.value.id, node.lineno)
                ):
                    out.append(
                        Finding(
                            rule="bool-spec",
                            path=rel,
                            line=node.lineno,
                            message=(
                                f"slice bound [{idx.upper.value}] beyond "
                                f"bool-spec arity {_BOOL_SPEC_ARITY} on "
                                f"[{node.value.id}]"
                            ),
                        )
                    )
    return out


def _is_bool_spec_var(sf, name: str, line: int) -> bool:
    """Is `name` treated as a bool spec in the enclosing function? True
    when the function also compares `name[0] == "bool"` (or assigns
    `kind = name[0]` and compares kind)."""
    ctx = sf.context_at(line)
    if not ctx:
        return False
    # Cheap textual scope check: find the enclosing function's span via
    # the context index built by SourceFile.
    for lo, hi, qual in sf._context_spans or ():
        if qual == ctx:
            body = "\n".join(sf.lines[lo - 1 : hi])
            return (
                f'{name}[0] == "bool"' in body
                or f"{name}[0] == 'bool'" in body
                or (f"kind = {name}[0]" in body and '"bool"' in body)
            )
    return False
