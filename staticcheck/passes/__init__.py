"""Pass families. Importing this package registers every pass in
`staticcheck.core.PASSES` (each module calls `register_pass` at import).
"""

from . import hygiene, locks, registries, trace_hazard  # noqa: F401
