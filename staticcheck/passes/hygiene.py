"""Pass family 4: exception and clock hygiene.

- **broad-except**: `except Exception:` (or a bare `except:`) can
  swallow `TaskCancelledError` — turning an instant cancel into a
  completed search — and can mask injected faults the chaos suite
  expects to observe. Handlers that deliberately absorb everything
  (scrape callbacks, best-effort cleanup) carry a suppression naming
  why; degraded-path handlers re-raise cancellation first.
- **wallclock-duration**: `time.time()` measures the wall clock, which
  NTP can step backwards mid-measurement; durations and deadlines use
  `time.monotonic()`. Wall-clock reads that produce user-facing epoch
  timestamps carry a suppression naming why.
"""

from __future__ import annotations

import ast

from ..callgraph import dotted_name
from ..core import Finding, Project, register_pass

RULES = {
    "broad-except": (
        "except Exception can swallow task cancellation and injected "
        "faults — re-raise control-flow errors or narrow the handler"
    ),
    "wallclock-duration": (
        "time.time() is NTP-steppable; durations/deadlines need "
        "time.monotonic() (user-facing epoch timestamps: suppress with "
        "the reason)"
    ),
}

# A broad handler is fine when its body starts by re-raising the
# control-flow exceptions: `except TaskCancelledError: raise` above it,
# or an `if isinstance(e, TaskCancelledError): raise` guard inside.
_CONTROL_FLOW = ("TaskCancelledError",)


def _reraises_control_flow(try_node: ast.Try, handler: ast.ExceptHandler) -> bool:
    idx = try_node.handlers.index(handler)
    # An earlier dedicated handler for the control-flow class that
    # re-raises (or is `raise`-only) protects the broad one below it.
    for prior in try_node.handlers[:idx]:
        names = _handler_names(prior)
        if any(n in _CONTROL_FLOW for n in names) and any(
            isinstance(s, ast.Raise) for s in prior.body
        ):
            return True
    # Or the broad handler itself opens with an isinstance re-raise.
    for stmt in handler.body[:2]:
        if isinstance(stmt, ast.If):
            test_src = ast.dump(stmt.test)
            if any(n in test_src for n in _CONTROL_FLOW) and any(
                isinstance(s, ast.Raise) for s in stmt.body
            ):
                return True
    # Cleanup-and-reraise: a handler whose top level ends in a bare
    # `raise` (release resources, then propagate) cannot swallow
    # anything.
    last = handler.body[-1]
    if isinstance(last, ast.Raise) and last.exc is None:
        return True
    return False


def _handler_names(handler: ast.ExceptHandler) -> list[str]:
    t = handler.type
    if t is None:
        return []
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for n in nodes:
        name = dotted_name(n)
        if name:
            out.append(name.split(".")[-1])
    return out


@register_pass("hygiene", RULES)
def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files.values():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Try):
                for handler in node.handlers:
                    names = _handler_names(handler)
                    broad = handler.type is None or any(
                        n in ("Exception", "BaseException") for n in names
                    )
                    if not broad:
                        continue
                    if _reraises_control_flow(node, handler):
                        continue
                    what = "bare except" if handler.type is None else (
                        "except " + "/".join(names)
                    )
                    findings.append(
                        Finding(
                            rule="broad-except",
                            path=sf.rel,
                            line=handler.lineno,
                            message=(
                                f"{what} can swallow TaskCancelledError/"
                                "injected faults — re-raise control flow "
                                "first, narrow, or suppress with the "
                                "reason"
                            ),
                        )
                    )
            elif isinstance(node, ast.Call):
                # The repo always spells it `import time; time.time()` —
                # no import-table resolution needed.
                if dotted_name(node.func) == "time.time":
                    findings.append(
                        Finding(
                            rule="wallclock-duration",
                            path=sf.rel,
                            line=node.lineno,
                            message=(
                                "time.time() — use time.monotonic() for "
                                "durations/deadlines (epoch timestamps "
                                "reported to users: suppress, naming why)"
                            ),
                        )
                    )
    return findings
