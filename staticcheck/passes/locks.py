"""Pass family 2: lock discipline across the serving hot paths.

~30 `threading.Lock/RLock/Condition` instances guard the batcher,
transport, metrics, engine and cluster layers. Two classes of latent
deadlock/latency bug are machine-checkable:

- **lock-order**: if thread 1 takes A then B while thread 2 takes B
  then A, the process deadlocks under load. The pass names every lock
  `module:Class.attr`, builds an acquisition graph (lexical `with`
  nesting plus acquisitions reachable through calls made while a lock
  is held), and reports every edge participating in a cycle.
- **lock-blocking-call**: sleeping, sending on the transport, launching
  device work, or doing file I/O while holding a lock serializes every
  other thread needing that lock behind an unbounded wait (the
  batcher-holds-lock-across-launch class of bug). `Condition.wait`
  is exempt (it releases the lock); deliberate holds (e.g. translog
  durability ordering) carry inline suppressions naming why.

Locks on different *instances* of the same class share a name, so the
graph over-approximates; cross-instance edges that cannot deadlock are
suppressed or baselined with a written justification.
"""

from __future__ import annotations

import ast

from ..callgraph import (
    FunctionInfo,
    ProjectIndex,
    dotted_name,
    get_index,
)
from ..core import Finding, Project, register_pass

RULES = {
    "lock-order": (
        "two locks are acquired in opposite orders on different paths — "
        "a deadlock waiting for concurrent load"
    ),
    "lock-blocking-call": (
        "blocking call (sleep / transport send / device launch / file "
        "I/O) while holding a lock stalls every waiter"
    ),
    "lock-self-deadlock": (
        "non-reentrant Lock acquired while already held in the same "
        "function — guaranteed deadlock"
    ),
}

_LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}

# Callee attribute names that block the calling thread. Curated for this
# repo: transport sends, device launches/syncs, queue waits.
_BLOCKING_ATTRS = frozenset(
    {
        "send",
        "send_request",
        "block_until_ready",
        "device_put",
        "search",
        "search_many",
        "execute_batch",
        "execute_shards",
    }
)
_BLOCKING_DOTTED = frozenset({"time.sleep", "subprocess.run", "os.fsync"})
# Condition methods that RELEASE the lock while waiting.
_WAIT_ATTRS = frozenset({"wait", "wait_for"})


def _factory_kind(index: ProjectIndex, sf, node: ast.AST) -> str | None:
    """threading.Lock / RLock / Condition constructor -> kind."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    dotted = index.imports.get(sf.rel, {}).get(head, head)
    full = f"{dotted}.{rest}" if rest else dotted
    return _LOCK_FACTORIES.get(full)


class _LockIndex:
    """lock id = "Class.attr" or "<module rel>:name" for globals."""

    def __init__(self, project: Project, index: ProjectIndex):
        self.kinds: dict[str, str] = {}  # lock id -> Lock/RLock/Condition
        # (rel, class, attr) and (rel, global name) -> lock id
        self.attr_ids: dict[tuple[str, str], str] = {}
        self.global_ids: dict[tuple[str, str], str] = {}
        for sf in project.files.values():
            for fn_key, info in index.functions.items():
                if fn_key[0] != sf.rel:
                    continue
                for node in ast.walk(info.node):
                    kind = None
                    target = None
                    if isinstance(node, ast.Assign):
                        kind = _factory_kind(index, sf, node.value)
                        target = node.targets[0] if node.targets else None
                    if kind is None or target is None:
                        continue
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                        and info.cls
                    ):
                        lock_id = f"{info.cls}.{target.attr}"
                        self.attr_ids[(info.cls, target.attr)] = lock_id
                        self.kinds[lock_id] = kind
            for node in sf.tree.body:
                # Module-level locks plus dataclass field defaults.
                if isinstance(node, ast.Assign):
                    kind = _factory_kind(index, sf, node.value)
                    if kind and isinstance(node.targets[0], ast.Name):
                        lock_id = f"{sf.module}:{node.targets[0].id}"
                        self.global_ids[(sf.rel, node.targets[0].id)] = (
                            lock_id
                        )
                        self.kinds[lock_id] = kind
                elif isinstance(node, ast.ClassDef):
                    for stmt in node.body:
                        kind = self._field_default(index, sf, stmt)
                        if kind is None:
                            continue
                        attr = self._ann_target(stmt)
                        if attr:
                            lock_id = f"{node.name}.{attr}"
                            self.attr_ids[(node.name, attr)] = lock_id
                            self.kinds[lock_id] = kind

    @staticmethod
    def _ann_target(stmt) -> str | None:
        if isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ):
            return stmt.target.id
        if isinstance(stmt, ast.Assign) and isinstance(
            stmt.targets[0], ast.Name
        ):
            return stmt.targets[0].id
        return None

    def _field_default(self, index: ProjectIndex, sf, stmt) -> str | None:
        value = getattr(stmt, "value", None)
        if not isinstance(value, ast.Call):
            return None
        if dotted_name(value.func) not in ("field", "dataclasses.field"):
            return None
        for kw in value.keywords:
            if kw.arg == "default_factory":
                name = dotted_name(kw.value)
                if name is None:
                    return None
                head, _, rest = name.partition(".")
                dotted = index.imports.get(sf.rel, {}).get(head, head)
                full = f"{dotted}.{rest}" if rest else dotted
                return _LOCK_FACTORIES.get(full)
        return None

    def resolve(
        self, info: FunctionInfo, expr: ast.AST
    ) -> str | None:
        """`self._lock` / module-global `_lock` / unique attr name."""
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            if expr.value.id == "self" and info.cls:
                hit = self.attr_ids.get((info.cls, expr.attr))
                if hit:
                    return hit
            # Unique-attr fallback: exactly one class defines this attr.
            owners = [
                lock_id
                for (cls, attr), lock_id in self.attr_ids.items()
                if attr == expr.attr
            ]
            if len(owners) == 1:
                return owners[0]
            return None
        if isinstance(expr, ast.Name):
            hit = self.global_ids.get((info.sf.rel, expr.id))
            if hit:
                return hit
        return None


def _with_lock_items(locks: _LockIndex, info: FunctionInfo, node: ast.With):
    out = []
    for item in node.items:
        lock_id = locks.resolve(info, item.context_expr)
        if lock_id is not None:
            out.append(lock_id)
    return out


def _is_blocking(index: ProjectIndex, sf, call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _WAIT_ATTRS:
        # Condition.wait/wait_for RELEASE the held lock while blocked —
        # exempt even if a wait-like attr is ever added to the blocking
        # set for another receiver type.
        return None
    name = dotted_name(f)
    if name is not None:
        head, _, rest = name.partition(".")
        dotted = index.imports.get(sf.rel, {}).get(head, head)
        full = f"{dotted}.{rest}" if rest else dotted
        if full in _BLOCKING_DOTTED:
            return full
    if isinstance(f, ast.Name) and f.id == "open":
        return "open"
    if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS:
        # `re.search(...)`-style module functions are not blocking.
        recv = dotted_name(f.value)
        if recv is not None:
            head = recv.partition(".")[0]
            if index.imports.get(sf.rel, {}).get(head, "") in (
                "re",
                "fnmatch",
            ):
                return None
        return f".{f.attr}"
    return None


@register_pass("lock-discipline", RULES)
def run(project: Project) -> list[Finding]:
    index = get_index(project)
    locks = _LockIndex(project, index)
    findings: list[Finding] = []

    # ---- per-function summaries: locks acquired anywhere inside
    acquires: dict[tuple, set[str]] = {}
    infos = list(index.functions.values())
    for info in infos:
        direct: set[str] = set()
        for node in ast.walk(info.node):
            if isinstance(node, ast.With):
                direct.update(_with_lock_items(locks, info, node))
        acquires[info.key] = direct
    # Transitive closure (bounded fixpoint over the call graph).
    for _ in range(6):
        changed = False
        for info in infos:
            summary = acquires[info.key]
            before = len(summary)
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call):
                    for callee in index.resolve_call(info, node):
                        summary |= acquires.get(callee.key, set())
            if len(summary) != before:
                changed = True
        if not changed:
            break

    # ---- edges + blocking calls under each lexical with-block
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}

    def scan(info: FunctionInfo, node, held: tuple[str, ...]) -> None:
        sf = info.sf
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda),
        ):
            return  # runs later, not under this lock
        if isinstance(node, ast.With):
            got = _with_lock_items(locks, info, node)
            for lock_id in got:
                for h in held:
                    if h == lock_id:
                        if locks.kinds.get(lock_id) == "Lock":
                            findings.append(
                                Finding(
                                    rule="lock-self-deadlock",
                                    path=sf.rel,
                                    line=node.lineno,
                                    message=(
                                        f"[{lock_id}] is a plain Lock "
                                        "already held here"
                                    ),
                                    context=info.qualname,
                                )
                            )
                    else:
                        edges.setdefault(
                            (h, lock_id),
                            (sf.rel, node.lineno, info.qualname),
                        )
            inner = held + tuple(g for g in got if g not in held)
            for child in node.body:
                scan(info, child, inner)
            return
        if isinstance(node, ast.Call) and held:
            blocked = _is_blocking(index, sf, node)
            if blocked is not None:
                findings.append(
                    Finding(
                        rule="lock-blocking-call",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"blocking call [{blocked}] while "
                            f"holding [{held[-1]}]"
                        ),
                        context=info.qualname,
                    )
                )
            # Calls that *transitively* acquire other locks create
            # ordering edges.
            for callee in index.resolve_call(info, node):
                for lock_id in acquires.get(callee.key, set()):
                    for h in held:
                        if h != lock_id:
                            edges.setdefault(
                                (h, lock_id),
                                (sf.rel, node.lineno, info.qualname),
                            )
        for child in ast.iter_child_nodes(node):
            scan(info, child, held)

    for info in infos:
        for stmt in info.node.body:
            scan(info, stmt, ())

    # ---- cycle detection over the ordering edges
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    def reachable(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(graph.get(n, ()))
        return False

    reported: set[frozenset] = set()
    for (a, b), (rel, line, ctx) in sorted(edges.items()):
        if a == b:
            continue
        if reachable(b, a):
            pair = frozenset((a, b))
            if pair in reported:
                continue
            reported.add(pair)
            findings.append(
                Finding(
                    rule="lock-order",
                    path=rel,
                    line=line,
                    message=(
                        f"lock-order inversion: [{a}] -> [{b}] here but "
                        f"[{b}] -> [{a}] elsewhere"
                    ),
                    context=ctx,
                )
            )
    return findings
