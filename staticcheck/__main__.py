"""CLI: `python -m staticcheck` — analyze the repo, gate on findings.

Exit status 0 = clean (every finding suppressed or baselined);
1 = at least one new gating finding; 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

from .core import (
    Project,
    all_rules,
    load_baseline,
    run_project,
    write_baseline,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="staticcheck",
        description="repo-specific AST invariant checks (tier-1 gate)",
    )
    parser.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="repo root to analyze (default: this checkout)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline JSON (default: <root>/staticcheck/baseline.json)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="grandfather every current finding into the baseline file",
    )
    parser.add_argument(
        "--only",
        default=None,
        help="comma-separated pass families to run (default: all)",
    )
    parser.add_argument(
        "--rules", action="store_true", help="print the rule glossary"
    )
    args = parser.parse_args(argv)

    # Pass modules self-register on import.
    from . import passes  # noqa: F401

    if args.rules:
        for rule, why in sorted(all_rules().items()):
            print(f"{rule:28s} {why}")
        return 0

    baseline_path = args.baseline or os.path.join(
        args.root, "staticcheck", "baseline.json"
    )
    only = args.only.split(",") if args.only else None
    if only:
        from .core import PASSES

        unknown = [name for name in only if name not in PASSES]
        if unknown:
            # A typo'd family silently running zero passes would be a
            # false-green gate.
            print(
                f"unknown pass famil{'ies' if len(unknown) > 1 else 'y'} "
                f"{unknown}; available: {sorted(PASSES)}",
                file=sys.stderr,
            )
            return 2
    project = Project(args.root)
    report = run_project(
        project, baseline=load_baseline(baseline_path), only=only
    )

    if args.write_baseline:
        if only:
            # A partial run only holds the executed families' findings;
            # rewriting the baseline from it would silently drop every
            # other family's grandfathered entries.
            print(
                "--write-baseline requires a full run (drop --only)",
                file=sys.stderr,
            )
            return 2
        from .core import ADVISORY_RULES

        # Advisory findings (stale suppressions) must never be
        # grandfathered: baselining one would hide the stale comment —
        # and anything it later starts suppressing — forever.
        entries = [
            f
            for f in report.findings + report.baselined
            if f.rule not in ADVISORY_RULES
        ]
        write_baseline(baseline_path, entries)
        print(f"wrote {len(entries)} entries to {baseline_path}")
        return 0

    for f in report.findings:
        print(f.render())
    print("-- staticcheck summary --")
    for line in report.summary_lines():
        print(line)
    return 1 if report.failed else 0


if __name__ == "__main__":
    sys.exit(main())
