"""Name resolution and a conservative call graph over the project.

Resolution is deliberately simple — this is a repo-specific linter, not
a type checker. A call resolves to project functions via, in order:

1. a bare name defined in the same module (or imported from a project
   module with `from x import f`);
2. `mod.f(...)` where `mod` is an imported project module;
3. `self.f(...)` to a method of the enclosing class (then same-module
   base classes);
4. a unique-name fallback: `obj.f(...)` resolves iff exactly one
   function named `f` exists in the whole project.

Over-approximation is acceptable (passes suppress/baseline the noise);
silent under-approximation of the jit-reachable set is what we must
avoid, because that is where the recompile hazards hide.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Project, SourceFile

# Attribute accesses that yield static (host, hashable) values even when
# the receiver is a traced array — the barrier that keeps `x.shape[0]`
# out of the traced set.
SHAPE_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "nbytes"})
# Builtins whose result is static regardless of argument tracedness.
STATIC_CALLS = frozenset({"len", "isinstance", "type", "hasattr", "getattr"})


@dataclass
class FunctionInfo:
    sf: SourceFile
    qualname: str  # e.g. "ClassName.method" or "fn" or "fn.<locals>.inner"
    node: ast.FunctionDef | ast.AsyncFunctionDef
    cls: str | None  # enclosing class name, if a method
    parent: str | None  # enclosing function qualname, if nested

    @property
    def key(self) -> tuple[str, str]:
        return (self.sf.rel, self.qualname)

    @property
    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


# Stream/file verbs excluded from unique-name resolution: `f.flush()`
# on an untyped receiver must not resolve to, say, Engine.flush.
_FILEISH_METHODS = frozenset(
    {
        "write",
        "read",
        "readline",
        "flush",
        "close",
        "open",
        "seek",
        "tell",
        "fileno",
        "encode",
        "decode",
    }
)


def get_index(project: Project) -> "ProjectIndex":
    """The memoized ProjectIndex for a Project — passes share one index
    instead of re-walking every AST per pass family."""
    cached = getattr(project, "_staticcheck_index", None)
    if cached is None:
        cached = ProjectIndex(project)
        project._staticcheck_index = cached
    return cached


class ProjectIndex:
    """Functions, classes, and import tables for every project file."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: dict[tuple[str, str], FunctionInfo] = {}
        self.by_name: dict[str, list[FunctionInfo]] = {}
        # module rel -> {local name -> dotted target}
        self.imports: dict[str, dict[str, str]] = {}
        # dotted module -> rel path
        self.module_rel: dict[str, str] = {}
        # (rel, class name) -> [base class names]
        self.class_bases: dict[tuple[str, str], list[str]] = {}
        # (rel, class, attr) -> (rel2, class2) | "external" | "unknown":
        # cheap type inference from `self.X = ClassName(...)` assignments.
        self.attr_types: dict[tuple[str, str, str], object] = {}
        for sf in project.files.values():
            self.module_rel[sf.module] = sf.rel
            self.imports[sf.rel] = self._imports(sf)
            self._index_defs(sf)
        for sf in project.files.values():
            self._index_attr_types(sf)

    # ------------------------------------------------------------ indexing

    def _imports(self, sf: SourceFile) -> dict[str, str]:
        table: dict[str, str] = {}
        pkg_parts = sf.module.split(".")
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    table[alias.asname or alias.name.split(".")[0]] = (
                        alias.name
                    )
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # Relative import: strip `level` trailing components
                    # (the module's own name counts as one).
                    base = pkg_parts[: len(pkg_parts) - node.level]
                    prefix = ".".join(base + ([node.module] if node.module else []))
                else:
                    prefix = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    table[alias.asname or alias.name] = (
                        f"{prefix}.{alias.name}" if prefix else alias.name
                    )
        return table

    def _index_defs(self, sf: SourceFile) -> None:
        def visit(node, prefix, cls, parent_fn):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    qual = f"{prefix}{child.name}"
                    self.class_bases[(sf.rel, child.name)] = [
                        b.id
                        for b in child.bases
                        if isinstance(b, ast.Name)
                    ]
                    visit(child, qual + ".", child.name, parent_fn)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = f"{prefix}{child.name}"
                    info = FunctionInfo(
                        sf=sf,
                        qualname=qual,
                        node=child,
                        cls=cls,
                        parent=parent_fn,
                    )
                    self.functions[info.key] = info
                    self.by_name.setdefault(child.name, []).append(info)
                    visit(child, qual + ".<locals>.", None, qual)

        visit(sf.tree, "", None, None)

    def _index_attr_types(self, sf: SourceFile) -> None:
        for info in self.functions.values():
            if info.sf is not sf or not info.cls:
                continue
            for node in ast.walk(info.node):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"
                ):
                    continue
                key = (sf.rel, info.cls, node.targets[0].attr)
                t = self._infer_type(sf, node.value)
                prior = self.attr_types.get(key)
                if prior is None or prior == t:
                    self.attr_types[key] = t
                else:
                    self.attr_types[key] = "unknown"

    def _infer_type(self, sf: SourceFile, value: ast.AST) -> object:
        """(rel, Class) for `ProjectClass(...)`, "external" for library
        constructors/literals, "unknown" otherwise."""
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.Constant)):
            return "external"
        if not isinstance(value, ast.Call):
            return "unknown"
        name = dotted_name(value.func)
        if name is None:
            return "unknown"
        head, _, rest = name.partition(".")
        dotted = self.imports.get(sf.rel, {}).get(head)
        if dotted is None:
            # Same-module class, or a builtin like open()/dict().
            if (sf.rel, head) in self.class_bases and not rest:
                return (sf.rel, head)
            if head in ("open", "dict", "list", "set", "deque", "tuple"):
                return "external"
            return "unknown"
        full = f"{dotted}.{rest}" if rest else dotted
        if "." in full:
            mod, cls = full.rsplit(".", 1)
            rel2 = self.module_rel.get(mod)
            if rel2 is not None and (rel2, cls) in self.class_bases:
                return (rel2, cls)
        if not any(
            m == full or full.startswith(m + ".") or m.startswith(full + ".")
            for m in self.module_rel
        ):
            return "external"
        return "unknown"

    # ---------------------------------------------------------- resolution

    def _module_function(
        self, dotted: str
    ) -> FunctionInfo | None:
        """`pkg.mod.fn` -> FunctionInfo if pkg.mod is a project file."""
        if "." not in dotted:
            return None
        mod, name = dotted.rsplit(".", 1)
        rel = self.module_rel.get(mod)
        if rel is None:
            return None
        return self.functions.get((rel, name))

    def resolve_call(
        self, caller: FunctionInfo, call: ast.Call
    ) -> list[FunctionInfo]:
        func = call.func
        sf = caller.sf
        if isinstance(func, ast.Name):
            # Nested sibling / enclosing-scope function first.
            scope = caller.qualname
            while scope:
                info = self.functions.get(
                    (sf.rel, f"{scope}.<locals>.{func.id}")
                )
                if info is not None:
                    return [info]
                scope = self.functions.get((sf.rel, scope)) and (
                    self.functions[(sf.rel, scope)].parent or ""
                )
            info = self.functions.get((sf.rel, func.id))
            if info is not None:
                return [info]
            dotted = self.imports[sf.rel].get(func.id)
            if dotted:
                info = self._module_function(dotted)
                if info is not None:
                    return [info]
            return []
        if isinstance(func, ast.Attribute):
            attr = func.attr
            recv = func.value
            if isinstance(recv, ast.Name):
                if recv.id in ("self", "cls") and caller.cls:
                    hit = self._method(sf.rel, caller.cls, attr)
                    if hit is not None:
                        return [hit]
                dotted = self.imports[sf.rel].get(recv.id)
                if dotted:
                    rel = self.module_rel.get(dotted)
                    if rel is not None:
                        info = self.functions.get((rel, attr))
                        if info is not None:
                            return [info]
            elif (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id in ("self", "cls")
                and caller.cls
            ):
                # `self.translog.roll()`: use the inferred type of the
                # attribute; an untyped self-chain stays unresolved (a
                # unique-name guess here caused false lock edges through
                # file handles).
                t = self.attr_types.get((sf.rel, caller.cls, recv.attr))
                if isinstance(t, tuple):
                    hit = self._method(t[0], t[1], attr)
                    return [hit] if hit is not None else []
                return []
            if self._external_receiver(sf, recv):
                # `jax.lax.top_k`, `np.argsort`, ...: a library call must
                # never unique-name-resolve onto a same-named project
                # function.
                return []
            if attr in _FILEISH_METHODS:
                # Generic stream/file verbs on an untyped receiver are
                # overwhelmingly stdlib objects, not project methods.
                return []
            # Unique-name fallback (receiver type unknown).
            candidates = self.by_name.get(attr, [])
            if len(candidates) == 1:
                return [candidates[0]]
        return []

    def _external_receiver(self, sf: SourceFile, recv: ast.AST) -> bool:
        """True when the receiver chain is rooted at an imported name
        that does not lead back into the project."""
        name = dotted_name(recv)
        if name is None:
            return False
        dotted = self.imports.get(sf.rel, {}).get(name.split(".")[0])
        if dotted is None:
            return False
        for mod in self.module_rel:
            if (
                mod == dotted
                or mod.startswith(dotted + ".")
                or dotted.startswith(mod + ".")
            ):
                return False
        return True

    def _method(
        self, rel: str, cls: str, attr: str
    ) -> FunctionInfo | None:
        seen = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            info = self.functions.get((rel, f"{c}.{attr}"))
            if info is not None:
                return info
            stack.extend(self.class_bases.get((rel, c), []))
        return None


def dotted_name(node: ast.AST) -> str | None:
    """`a.b.c` expression -> "a.b.c" (None for anything fancier)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolves_to(
    index: ProjectIndex, sf: SourceFile, node: ast.AST, target: str
) -> bool:
    """Does this Name/Attribute expression denote dotted path `target`
    (e.g. "jax.jit", "time.sleep", "numpy.asarray") under the module's
    import table?"""
    name = dotted_name(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    dotted = index.imports.get(sf.rel, {}).get(head, head)
    full = f"{dotted}.{rest}" if rest else dotted
    return full == target


def mentions_traced(node: ast.AST, traced: set[str]) -> bool:
    """Does the expression read any traced name — ignoring reads that
    pass through a static barrier (`.shape`, `len(...)`, etc.)?"""
    if isinstance(node, ast.Attribute) and node.attr in SHAPE_ATTRS:
        return False
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in STATIC_CALLS:
            return False
    if isinstance(node, ast.Name):
        return node.id in traced
    return any(
        mentions_traced(child, traced)
        for child in ast.iter_child_nodes(node)
    )
