"""regexp, boosting, terms_set, and more_like_this queries.

Reference: RegexpQueryBuilder, BoostingQueryBuilder, TermsSetQueryBuilder
(lucene CoveringQuery), MoreLikeThisQueryBuilder (lucene MoreLikeThis).
Each device plan gates against the numpy oracle.
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.oracle import OracleSearcher


@pytest.fixture(scope="module")
def corpus():
    m = Mappings(
        properties={
            "body": {"type": "text"},
            "tag": {"type": "keyword"},
            "required_matches": {"type": "long"},
        }
    )
    b = SegmentBuilder(m)
    docs = [
        {"body": "red apple pie recipe", "tag": "food-101", "required_matches": 2},
        {"body": "green apple tart", "tag": "food-202", "required_matches": 1},
        {"body": "red wine pairing", "tag": "drink-1", "required_matches": 3},
        {"body": "apple wine cider press", "tag": "drink-22", "required_matches": 2},
        {"body": "blueberry pie and apple pie", "tag": "food-303", "required_matches": 1},
        {"body": "unrelated document entirely", "tag": "misc", "required_matches": 1},
    ]
    for i, d in enumerate(docs):
        b.add(d, f"d{i}")
    seg = b.build()
    dev = pack_segment(seg)
    return m, seg, dev


def _both(corpus, query_json, k=6):
    import jax

    m, seg, dev = corpus
    c = Compiler(dev.fields, dev.doc_values, m).compile(parse_query(query_json))
    tree = bm25_device.segment_tree(dev)
    d_s, d_i, d_t = jax.device_get(bm25_device.execute(tree, c.spec, c.arrays, k))
    o_s, o_i, o_t = OracleSearcher(seg, m).search(parse_query(query_json), k)
    n = len(o_i)
    assert list(d_i[:n]) == list(o_i), (query_json, list(d_i[:n]), list(o_i))
    np.testing.assert_allclose(d_s[:n], o_s, rtol=2e-6)
    assert int(d_t) == o_t
    return list(o_i), o_s, o_t


def test_regexp_matches_and_parity(corpus):
    ids, _, total = _both(corpus, {"regexp": {"tag": "food-[0-9]+"}})
    assert total == 3 and set(ids) == {0, 1, 4}
    ids, _, total = _both(
        corpus, {"regexp": {"tag": {"value": "FOOD-.*", "case_insensitive": True}}}
    )
    assert total == 3
    ids, _, total = _both(corpus, {"regexp": {"body": "appl(e|es)"}})
    assert total == 4


def test_regexp_rejects_unsupported_operators(corpus):
    m, seg, dev = corpus
    compiler = Compiler(dev.fields, dev.doc_values, m)
    with pytest.raises(ValueError, match="regexp"):
        compiler.compile(parse_query({"regexp": {"tag": "foo~bar"}}))
    with pytest.raises(ValueError, match="regexp"):
        compiler.compile(parse_query({"regexp": {"tag": "<1-10>"}}))
    # Escaped operators are literal and fine.
    compiler.compile(parse_query({"regexp": {"tag": "a\\~b"}}))


def test_regexp_lucene_semantics():
    """Lucene RegExp: backslash escapes the next char LITERALLY (no \\d
    classes) and ^/$ are literal characters, not anchors."""
    from elasticsearch_tpu.query.compile import regexp_pattern

    assert regexp_pattern("\\d+", False).fullmatch("ddd")
    assert not regexp_pattern("\\d+", False).fullmatch("123")
    assert regexp_pattern("a^b", False).fullmatch("a^b")
    assert not regexp_pattern("a^b", False).fullmatch("ab")
    import pytest as _pytest

    with _pytest.raises(ValueError, match="trailing"):
        regexp_pattern("abc\\", False)


def test_boosting_demotes_not_excludes(corpus):
    ids, scores, total = _both(
        corpus,
        {
            "boosting": {
                "positive": {"match": {"body": "apple"}},
                "negative": {"match": {"body": "wine"}},
                "negative_boost": 0.2,
            }
        },
    )
    assert total == 4  # wine docs still match...
    assert 3 in ids  # ...but the apple+wine doc sinks to the bottom
    assert ids[-1] == 3


def test_terms_set_field_coverage(corpus):
    # required_matches per doc: d0 needs 2 of {red, apple, pie} (has 3 -> hit),
    # d1 needs 1 (has apple -> hit), d2 needs 3 (has red only -> miss),
    # d3 needs 2 (has apple only -> miss), d4 needs 1 (apple+pie -> hit).
    ids, _, total = _both(
        corpus,
        {
            "terms_set": {
                "body": {
                    "terms": ["red", "apple", "pie"],
                    "minimum_should_match_field": "required_matches",
                }
            }
        },
    )
    assert set(ids) == {0, 1, 4} and total == 3


def test_terms_set_script(corpus):
    ids, _, total = _both(
        corpus,
        {
            "terms_set": {
                "body": {
                    "terms": ["red", "apple", "pie"],
                    "minimum_should_match_script": {
                        "source": "Math.min(params.num_terms, doc['required_matches'].value)"
                    },
                }
            }
        },
    )
    assert set(ids) == {0, 1, 4} and total == 3


def test_terms_set_requires_exactly_one_msm():
    with pytest.raises(ValueError, match="terms_set"):
        parse_query({"terms_set": {"body": {"terms": ["a"]}}})


def test_more_like_this(corpus):
    ids, _, total = _both(
        corpus,
        {
            "more_like_this": {
                "fields": ["body"],
                "like": ["apple pie apple pie baking"],
                "min_term_freq": 2,
                "min_doc_freq": 1,
                "minimum_should_match": "30%",
            }
        },
    )
    # Selected terms: apple, pie (tf 2, present in corpus); docs with either.
    assert 0 in ids and 4 in ids and total >= 3


def test_more_like_this_requires_text():
    with pytest.raises(ValueError, match="more_like_this"):
        parse_query({"more_like_this": {"fields": ["body"], "like": [{"_id": "1"}]}})
    with pytest.raises(ValueError, match="more_like_this"):
        parse_query({"more_like_this": {"like": ["x"]}})


def test_new_queries_through_bool_composition(corpus):
    _both(
        corpus,
        {
            "bool": {
                "must": [
                    {
                        "boosting": {
                            "positive": {"match": {"body": "apple"}},
                            "negative": {"regexp": {"tag": "drink-.*"}},
                            "negative_boost": 0.5,
                        }
                    }
                ],
                "filter": [{"regexp": {"tag": "[a-z]+-[0-9]+"}}],
            }
        },
    )
