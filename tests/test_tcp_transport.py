"""TCP transport (cluster/tcp_transport.py): frame codec, handshake
refusal, per-send deadlines, abrupt-death/partial-frame handling, pooled
reconnect, interception parity with the in-memory hub — plus the trimmed
tier-1 socket smoke: a LocalCluster over real loopback sockets surviving
primary kill and partition with zero acked-write loss. (The FULL chaos
and replication matrices run over TCP in the `slow` lane via the
transport-parameterized suites.)"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu.cluster import (
    ConnectTransportError,
    LocalCluster,
    RemoteActionError,
    TcpTransport,
    TcpTransportHub,
    TransportHub,
)
from elasticsearch_tpu.cluster.tcp_transport import (
    InMemoryAddressBook,
    encode_frame,
    read_frame,
)
from elasticsearch_tpu.faults import REGISTRY, FaultSpec

MAPPINGS = {"properties": {"body": {"type": "text"}}}


@pytest.fixture(autouse=True)
def _clean_registry():
    REGISTRY.clear()
    yield
    REGISTRY.clear()


def _echo(from_id, action, payload):
    return {"echo": action, "from": from_id, "payload": payload}


@pytest.fixture
def pair():
    """Two live endpoints (a, b) sharing one in-memory address book."""
    book = InMemoryAddressBook()
    a = TcpTransport("a", book, cluster_name="t")
    b = TcpTransport("b", book, cluster_name="t")
    a.register("a", _echo)
    b.register("b", _echo)
    yield a, b
    a.close()
    b.close()


class TestFrameCodec:
    def test_roundtrip_via_socket_pair(self):
        left, right = socket.socketpair()
        try:
            obj = {"x": 1, "nested": {"y": [1, 2, 3]}, "s": "héllo"}
            left.sendall(encode_frame(obj))
            got, nbytes = read_frame(right)
            assert got == obj
            assert nbytes == len(encode_frame(obj))
        finally:
            left.close()
            right.close()

    def test_numpy_payloads_serialize(self):
        left, right = socket.socketpair()
        try:
            obj = {
                "score": np.float32(1.5),
                "count": np.int64(7),
                "arr": np.array([1.0, 2.0]),
                "ids": {"b", "a"},
            }
            left.sendall(encode_frame(obj))
            got, _ = read_frame(right)
            assert got == {
                "score": 1.5,
                "count": 7,
                "arr": [1.0, 2.0],
                "ids": ["a", "b"],
            }
        finally:
            left.close()
            right.close()

    def test_oversized_inbound_frame_refused(self):
        left, right = socket.socketpair()
        try:
            left.sendall(struct.pack(">I", 1 << 30))
            with pytest.raises(ConnectTransportError, match="exceeds"):
                read_frame(right)
        finally:
            left.close()
            right.close()


class TestEndpoint:
    def test_request_response(self, pair):
        a, b = pair
        out = a.send("a", "b", "ping", {"n": 1})
        assert out == {"echo": "ping", "from": "a", "payload": {"n": 1}}

    def test_remote_error_carries_type(self, pair):
        a, b = pair

        def boom(from_id, action, payload):
            raise KeyError("nope")

        b.register("b", boom)
        with pytest.raises(RemoteActionError) as err:
            a.send("a", "b", "x", {})
        assert err.value.remote_type == "KeyError"

    def test_remote_connect_error_crosses_as_connect(self, pair):
        a, b = pair

        def closed(from_id, action, payload):
            raise ConnectTransportError("[b] closed")

        b.register("b", closed)
        with pytest.raises(ConnectTransportError, match="closed"):
            a.send("a", "b", "x", {})

    def test_unknown_peer_unreachable(self, pair):
        a, _ = pair
        with pytest.raises(ConnectTransportError, match="no published"):
            a.send("a", "ghost", "ping", {})

    def test_dead_peer_connection_refused_fast(self, pair):
        a, b = pair
        a.send("a", "b", "ping", {})  # warm pool
        b.close(abrupt=True)  # process death: address stays, port dead
        t0 = time.monotonic()
        with pytest.raises(ConnectTransportError):
            a.send("a", "b", "ping", {})
        assert time.monotonic() - t0 < 5.0  # bounded, not hung

    def test_slow_handler_hits_send_deadline(self, pair):
        a, b = pair

        def slow(from_id, action, payload):
            time.sleep(2.0)
            return {}

        b.register("b", slow)
        t0 = time.monotonic()
        with pytest.raises(ConnectTransportError, match="timed out"):
            a.send("a", "b", "x", {}, timeout_s=0.2)
        assert time.monotonic() - t0 < 1.5
        assert (
            a.metrics.value(
                "estpu_transport_send_timeouts_total",
                transport="tcp",
                node="a",
            )
            >= 1
        )

    def test_handshake_refuses_wrong_cluster(self, pair):
        a, b = pair
        book = a.book
        rogue = TcpTransport("rogue", book, cluster_name="OTHER")
        rogue.register("rogue", _echo)
        try:
            with pytest.raises(ConnectTransportError, match="refused"):
                rogue.send("rogue", "b", "ping", {})
            assert (
                b.metrics.value(
                    "estpu_transport_handshake_rejects_total", node="b"
                )
                >= 1
            )
        finally:
            rogue.close()

    def test_partial_frame_then_close_does_not_wedge_server(self, pair):
        a, b = pair
        # A client that dies mid-frame (half a length prefix + garbage).
        raw = socket.create_connection(b.address)
        raw.sendall(encode_frame({"_handshake": {
            "cluster": "t", "version": 1, "node": "raw"}}))
        read_frame(raw)  # handshake ok
        raw.sendall(struct.pack(">I", 100) + b"half")
        raw.close()
        # The endpoint keeps serving everyone else.
        assert a.send("a", "b", "ping", {})["echo"] == "ping"

    def test_stale_pooled_connection_retries_fresh(self, pair):
        a, b = pair
        book = a.book
        a.send("a", "b", "ping", {})  # pool a connection to b's OLD port
        b.close(abrupt=True)
        b2 = TcpTransport("b", book, cluster_name="t")  # restarted process
        b2.register("b", _echo)
        try:
            # The pooled conn is dead; the send must fall back to a fresh
            # dial against the re-published address and succeed.
            assert a.send("a", "b", "ping", {})["echo"] == "ping"
        finally:
            b2.close()

    def test_frames_counted_both_directions(self, pair):
        a, b = pair
        a.send("a", "b", "ping", {})
        sent = a.metrics.value(
            "estpu_transport_frames_total", node="a", dir="sent"
        )
        received = b.metrics.value(
            "estpu_transport_frames_total", node="b", dir="received"
        )
        assert sent >= 1 and received >= 1


class TestInterceptionParity:
    """The MockTransportService surface behaves identically over sockets."""

    def test_drop_action(self, pair):
        a, b = pair
        a.intercepts.drop_action("a", "b", "ping")
        with pytest.raises(ConnectTransportError, match="dropped"):
            a.send("a", "b", "ping", {})
        assert a.send("a", "b", "other", {})["echo"] == "other"
        a.intercepts.clear_drops()
        assert a.send("a", "b", "ping", {})["echo"] == "ping"

    def test_partition_and_heal(self, pair):
        a, b = pair
        a.intercepts.partition({"a"}, {"b"})
        with pytest.raises(ConnectTransportError, match="unreachable"):
            a.send("a", "b", "ping", {})
        a.intercepts.heal_partition()
        assert a.send("a", "b", "ping", {})["echo"] == "ping"

    def test_injected_delay_respects_deadline(self, pair):
        a, b = pair
        a.intercepts.set_delay(5.0)
        t0 = time.monotonic()
        with pytest.raises(ConnectTransportError, match="timed out"):
            a.send("a", "b", "ping", {}, timeout_s=0.2)
        assert time.monotonic() - t0 < 1.5
        a.intercepts.set_delay(0.0)

    def test_generic_transport_send_fault_site_fires_over_tcp(self, pair):
        a, b = pair
        REGISTRY.put(
            FaultSpec(
                site="transport.send.ping", error="transport", seed=1
            )
        )
        with pytest.raises(ConnectTransportError, match="injected"):
            a.send("a", "b", "ping", {})
        REGISTRY.clear()
        assert a.send("a", "b", "ping", {})["echo"] == "ping"

    def test_tcp_frame_fault_resets_connection(self, pair):
        a, b = pair
        REGISTRY.put(
            FaultSpec(site="transport.tcp.frame", error="transport", seed=2)
        )
        # The receiver tears the connection down mid-exchange; the sender
        # observes it as a transport failure, never a hang.
        with pytest.raises(ConnectTransportError):
            a.send("a", "b", "ping", {}, timeout_s=2.0)
        REGISTRY.clear()
        assert a.send("a", "b", "ping", {})["echo"] == "ping"


class TestHubDeadline:
    """Satellite: the in-memory hub honors the same per-send deadline."""

    def test_slow_handler_times_out(self):
        hub = TransportHub(default_timeout_s=0.2)
        hub.register("n", lambda f, a, p: time.sleep(5.0))
        t0 = time.monotonic()
        with pytest.raises(ConnectTransportError, match="timed out"):
            hub.send("m", "n", "x", {})
        assert time.monotonic() - t0 < 2.0
        assert hub.stats()["send_timeouts"] == 1

    def test_injected_delay_times_out(self):
        hub = TransportHub(default_timeout_s=0.2)
        hub.register("n", lambda f, a, p: {"ok": True})
        hub.set_delay(5.0)
        t0 = time.monotonic()
        with pytest.raises(ConnectTransportError, match="timed out"):
            hub.send("m", "n", "x", {})
        assert time.monotonic() - t0 < 2.0

    def test_fast_handler_unaffected(self):
        hub = TransportHub(default_timeout_s=5.0)
        hub.register("n", lambda f, a, p: {"got": p})
        assert hub.send("m", "n", "x", {"v": 1}) == {"got": {"v": 1}}

    def test_gateway_clamp_applies_to_live_tcp_sends(self):
        """The gateway clamps the HUB's default; TCP sends must resolve
        against that live value, not the default each endpoint copied at
        registration time — otherwise one wedged send outlives the
        gateway's whole retry budget."""
        from elasticsearch_tpu.cluster import ReplicationGateway

        cluster = LocalCluster(2, transport="tcp")
        try:
            ReplicationGateway(cluster, timeout_s=0.3)
            assert cluster.hub.default_timeout_s == 0.3
            cluster.hub._endpoints["node-1"].register(
                "node-1", lambda f, a, p: time.sleep(5.0)
            )
            t0 = time.monotonic()
            with pytest.raises(ConnectTransportError, match="timed out"):
                cluster.hub.send("node-0", "node-1", "ping", {})
            assert time.monotonic() - t0 < 2.0
        finally:
            cluster.close()


class TestTraceOverTheWire:
    def test_trace_context_survives_tcp(self):
        from elasticsearch_tpu.obs.tracing import TRACER

        cluster = LocalCluster(2, transport="tcp")
        try:
            cluster.create_index(
                "tr", n_shards=1, n_replicas=1, mappings=MAPPINGS
            )
            cluster.any_node().execute_write("tr", "d1", {"body": "x"})
            with TRACER.start_trace("test-root") as root:
                trace_id = root.trace_id
                cluster.nodes["node-1"].search(
                    "tr", {"query": {"match_all": {}}}
                )
            spans = TRACER.get(trace_id) or []
            names = {s.name for s in spans}
            # The remote shard execution parented into the caller's trace
            # via the `_trace` payload field riding the JSON frame.
            assert any(n.startswith("transport.") for n in names), names
            assert "cluster.shard_search" in names, names
        finally:
            cluster.close()


class TestStatsSurface:
    """Satellite contracts: swallowed stepper errors and the transport
    layer are VISIBLE in `_nodes/stats`, never silent."""

    def test_step_errors_and_transport_surface_in_nodes_stats(
        self, monkeypatch
    ):
        import json as _json

        from elasticsearch_tpu.rest.server import RestServer

        monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
        monkeypatch.setenv("ESTPU_CLUSTER_TRANSPORT", "tcp")
        server = RestServer(replication_nodes=2)
        try:
            # Wedge one node's control-plane step: the background stepper
            # must keep running AND count every swallowed error.
            def boom():
                raise RuntimeError("wedged control plane")

            monkeypatch.setattr(
                server.cluster.nodes["node-1"], "check_recoveries", boom
            )
            deadline = time.monotonic() + 5.0
            rep = None
            while time.monotonic() < deadline:
                status, stats = server.dispatch(
                    "GET", "/_nodes/stats", {}, ""
                )
                assert status == 200
                rep = next(iter(stats["nodes"].values()))["replication"]
                if rep["step_errors"] >= 1:
                    break
                time.sleep(0.05)
            assert rep is not None and rep["step_errors"] >= 1, rep
            # Transport instruments ride the same stats surface.
            transport = rep["transport"]
            assert transport["kind"] == "tcp"
            assert transport["connections"] >= 1
            assert transport["frames"]["sent"] >= 1
            # The cluster still serves through the wedged stepper.
            status, _ = server.dispatch(
                "PUT",
                "/alive",
                {},
                _json.dumps({"mappings": MAPPINGS}),
            )
            assert status == 200
        finally:
            server.close()


class TestTcpClusterSmoke:
    """Trimmed tier-1 slice of the chaos contract over real sockets:
    kill the primary-owning node, partition the master away — promotion
    within the control rounds, zero acked-write loss."""

    def test_kill_primary_promotion_no_acked_loss(self):
        cluster = LocalCluster(3, transport="tcp")
        try:
            cluster.create_index(
                "kp", n_shards=1, n_replicas=2, mappings=MAPPINGS
            )
            acked = []
            for i in range(30):
                resp = cluster.any_node().execute_write(
                    "kp", f"k{i}", {"body": f"payload {i}"}
                )
                assert resp["result"] == "created"
                acked.append(f"k{i}")
            routing = cluster.any_node().state.indices["kp"].shards[0]
            old_primary, old_term = routing.primary, routing.primary_term
            cluster.kill(old_primary)
            cluster.step()
            survivor = cluster.any_node()
            new_routing = survivor.state.indices["kp"].shards[0]
            assert new_routing.primary not in (None, old_primary)
            assert new_routing.primary_term == old_term + 1
            for doc_id in acked:
                assert survivor.get_doc("kp", doc_id) is not None, doc_id
            out = survivor.search(
                "kp", {"query": {"match_all": {}}, "size": 50}
            )
            assert out["hits"]["total"]["value"] == len(acked)
            # Writes continue through the promoted primary.
            resp = survivor.execute_write("kp", "after", {"body": "after"})
            assert resp["result"] == "created"
        finally:
            cluster.close()

    def test_partition_master_steps_down_and_heals(self):
        cluster = LocalCluster(3, transport="tcp")
        try:
            cluster.create_index(
                "pt", n_shards=1, n_replicas=2, mappings=MAPPINGS
            )
            acked = []
            for i in range(10):
                cluster.any_node().execute_write(
                    "pt", f"p{i}", {"body": "x"}
                )
                acked.append(f"p{i}")
            master = cluster.master()
            others = {n for n in cluster.seeds if n != master.node_id}
            cluster.hub.partition({master.node_id}, others)
            master.health_round()  # loses quorum -> steps down
            assert master.state.master is None
            for n in others:
                cluster.nodes[n].try_elect()
            new_master = cluster.master()
            assert new_master is not None
            assert new_master.node_id in others
            # Majority side serves every acked write through the split.
            majority = cluster.nodes[sorted(others)[0]]
            for doc_id in acked:
                assert majority.get_doc("pt", doc_id) is not None
            cluster.hub.heal_partition()
            cluster.step()
            cluster.step()
            # Convergence: every node agrees on ONE elected master (the
            # lowest-id candidate may legitimately retake mastership
            # after healing).
            masters = {
                n.state.master
                for n in cluster.nodes.values()
                if not n.closed
            }
            assert len(masters) == 1 and None not in masters, masters
        finally:
            cluster.close()

    def test_socket_unreachable_replica_failed_out_then_heals(self):
        cluster = LocalCluster(3, transport="tcp")
        try:
            cluster.create_index(
                "fo", n_shards=1, n_replicas=1, mappings=MAPPINGS
            )
            routing = cluster.any_node().state.indices["fo"].shards[0]
            replica, primary = routing.replicas[0], routing.primary
            cluster.hub.drop_action(primary, replica, "replica_op")
            resp = cluster.any_node().execute_write(
                "fo", "x1", {"body": "x"}
            )
            assert resp["result"] == "created"
            routing = cluster.any_node().state.indices["fo"].shards[0]
            assert replica not in routing.in_sync
            cluster.hub.clear_drops()
            cluster.step()
            cluster.step()
            routing = cluster.any_node().state.indices["fo"].shards[0]
            assert replica in routing.in_sync
        finally:
            cluster.close()


class TestHandshakeAuth:
    """Shared-key HMAC wire authn (satellite of the socketed-topology
    PR): a peer without the cluster's transport key cannot complete a
    handshake, and the refusal feeds the SAME observables (reject
    counter + windowed event) the `transport` health indicator reads."""

    def _pair(self, key_a, key_b):
        book = InMemoryAddressBook()
        a = TcpTransport("a", book, cluster_name="t", auth_key=key_a)
        b = TcpTransport("b", book, cluster_name="t", auth_key=key_b)
        a.register("a", _echo)
        b.register("b", _echo)
        return a, b

    def test_matching_keys_serve(self):
        a, b = self._pair("sesame", "sesame")
        try:
            out = a.send("a", "b", "ping", {"n": 1})
            assert out["echo"] == "ping"
            assert b.stats()["handshake_rejects"] == 0
        finally:
            a.close()
            b.close()

    def test_mismatched_key_rejected_and_counted(self):
        a, b = self._pair("wrong", "sesame")
        try:
            with pytest.raises(ConnectTransportError) as err:
                a.send("a", "b", "ping", {}, timeout_s=3.0)
            text = str(err.value)
            assert "auth" in text
            assert "sesame" not in text  # never echo key material
            assert "wrong" not in text
            assert b.stats()["handshake_rejects"] >= 1
            assert b.recent_events().get("handshake_reject", 0) >= 1
        finally:
            a.close()
            b.close()

    def test_missing_key_rejected(self):
        # Dialer has no key at all (env empty -> authn disabled on its
        # side); the keyed server still refuses it.
        a, b = self._pair("", "sesame")
        try:
            with pytest.raises(ConnectTransportError):
                a.send("a", "b", "ping", {}, timeout_s=3.0)
            assert b.stats()["handshake_rejects"] >= 1
        finally:
            a.close()
            b.close()

    def test_env_key_picked_up(self, monkeypatch):
        from elasticsearch_tpu.cluster.tcp_transport import (
            TRANSPORT_KEY_ENV,
        )

        monkeypatch.setenv(TRANSPORT_KEY_ENV, "from-env")
        book = InMemoryAddressBook()
        a = TcpTransport("a", book, cluster_name="t")
        b = TcpTransport("b", book, cluster_name="t")
        a.register("a", _echo)
        b.register("b", _echo)
        try:
            assert a.auth_key == "from-env"
            out = a.send("a", "b", "ping", {})
            assert out["echo"] == "ping"
        finally:
            a.close()
            b.close()


class TestDrainBarrier:
    """Graceful-shutdown drain (satellite of the SIGTERM-drain arc): a
    worker about to exit waits out its in-flight requests — they answer
    instead of dying as connection resets."""

    def test_drain_waits_for_inflight_request(self):
        book = InMemoryAddressBook()
        a = TcpTransport("a", book, cluster_name="t")
        b = TcpTransport("b", book, cluster_name="t")
        entered = threading.Event()
        release = threading.Event()

        def slow(from_id, action, payload):
            entered.set()
            release.wait(timeout=10.0)
            return {"done": True}

        a.register("a", _echo)
        b.register("b", slow)
        result: dict = {}

        def send():
            result["out"] = a.send("a", "b", "work", {}, timeout_s=10.0)

        sender = threading.Thread(target=send, daemon=True)
        try:
            sender.start()
            assert entered.wait(timeout=5.0)
            # In-flight: a bounded drain reports stragglers honestly.
            assert b.drain(timeout_s=0.2) is False
            release.set()
            assert b.drain(timeout_s=5.0) is True
            sender.join(timeout=5.0)
            assert result["out"] == {"done": True}
            assert b.stats()["drains"] >= 2
        finally:
            release.set()
            a.close()
            b.close()

    def test_drain_idle_is_immediate(self):
        book = InMemoryAddressBook()
        b = TcpTransport("b", book, cluster_name="t")
        b.register("b", _echo)
        try:
            t0 = time.monotonic()
            assert b.drain(timeout_s=5.0) is True
            assert time.monotonic() - t0 < 1.0
        finally:
            b.close()
