"""Cluster-scope observability (ISSUE 13): wire-fanned `_nodes/stats`,
federated `/_metrics`, distributed trace assembly, and hot-threads
sampling.

Three surfaces, three topologies:

- standalone Node: same `_nodes` header shape with total=1;
- in-memory LocalCluster behind the REST server (hub AND tcp transports:
  one response shape across both — the PR-11 interception-parity rule
  applied to observability);
- ProcCluster (2 spawned OS worker processes + tiebreaker): the
  acceptance topology — per-node sections cross real sockets, remote
  span bodies live in worker rings until trace assembly splices them,
  and `kill -9` of a worker yields a NAMED failure entry within the
  per-send deadline, never a hang.
"""

import json
import os
import tempfile
import time

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.obs.hot_threads import hot_threads_text
from elasticsearch_tpu.obs.tracing import chrome_trace, splice_spans
from elasticsearch_tpu.rest.server import RestServer

REPLICATED_INDEX = json.dumps(
    {
        "settings": {
            "index": {"number_of_shards": 2, "number_of_replicas": 1}
        },
        "mappings": {"properties": {"b": {"type": "text"}}},
    }
)

# Sections every ClusterNode's node_stats wire payload must carry — the
# one-shape-across-transports contract.
MEMBER_SECTIONS = {
    "name",
    "roles",
    "master",
    "process",
    "indices",
    "search_resilience",
    "cluster_state",
    "step_errors",
    "transport",
    # Per-node device.hbm section (ISSUE 14): computed from component
    # stats on workers (no write-through ledger there), fanned so the
    # coordinating front's /_cat/hbm shows every member's residency.
    "device",
}


def _member_sections(stats: dict, node_id: str) -> set:
    return set(stats["nodes"][node_id]) & MEMBER_SECTIONS


class TestStandaloneShape:
    def test_nodes_header_present_single_node(self):
        node = Node()
        stats = node.nodes_stats()
        assert stats["_nodes"] == {
            "total": 1,
            "successful": 1,
            "failed": 0,
        }
        assert node.node_name in stats["nodes"]
        # Pre-PR consumers keep working: the local sections are intact.
        assert "device" in stats["nodes"][node.node_name]
        assert "obs" in stats["nodes"][node.node_name]

    def test_cluster_obs_section_shape(self):
        node = Node()
        obs = node.nodes_stats()["nodes"][node.node_name]["obs"]["cluster"]
        for key in (
            "fanouts",
            "fan_failures",
            "fan_latency_ms",
            "trace_fragments_collected",
            "hot_threads_samples",
        ):
            assert key in obs

    def test_cat_nodes_single_row(self):
        node = Node()
        rows = node.cat_nodes()
        assert len(rows) == 1
        assert rows[0]["name"] == node.node_name
        assert rows[0]["master"] == "*"
        assert rows[0]["node.role"] == "dim"

    def test_hot_threads_samples_own_process(self):
        node = Node()
        text = node.hot_threads(interval_s=0.05, snapshots=2)
        assert f"::: {{{node.node_name}}} pid[{os.getpid()}]" in text
        assert "busiestThreads=3" in text
        obs = node.nodes_stats()["nodes"][node.node_name]["obs"]["cluster"]
        assert obs["hot_threads_samples"] >= 2


class TestLocalClusterFanIn:
    @pytest.fixture(scope="class")
    def rest(self):
        mesh = os.environ.get("ESTPU_MESH_SERVING")
        os.environ["ESTPU_MESH_SERVING"] = "0"
        server = RestServer(replication_nodes=3)
        yield server
        server.close()
        if mesh is None:
            os.environ.pop("ESTPU_MESH_SERVING", None)
        else:
            os.environ["ESTPU_MESH_SERVING"] = mesh

    def test_header_and_per_node_sections(self, rest):
        status, stats = rest.dispatch("GET", "/_nodes/stats", {}, "")
        assert status == 200
        assert stats["_nodes"]["total"] == 4  # 3 members + coordinator
        assert stats["_nodes"]["successful"] == 4
        assert stats["_nodes"]["failed"] == 0
        for node_id in ("node-1", "node-2"):
            assert _member_sections(stats, node_id) == MEMBER_SECTIONS
            assert stats["nodes"][node_id]["roles"] == ["data", "master"]
        # The coordinator entry (name-shared with member node-0) carries
        # BOTH the local sections and the grafted member sections.
        merged = stats["nodes"]["node-0"]
        assert "replication" in merged and "roles" in merged
        # Exactly one elected master across the members.
        masters = [
            node_id
            for node_id, section in stats["nodes"].items()
            if section.get("master") is True
        ]
        assert len(masters) == 1

    def test_trace_assembly_one_spliced_tree(self, rest):
        rest.dispatch("PUT", "/obsx", {}, REPLICATED_INDEX)
        rest.dispatch(
            "PUT", "/obsx/_doc/1", {}, json.dumps({"b": "alpha"})
        )
        rest.dispatch("POST", "/obsx/_refresh", {}, "")
        status, _ = rest.dispatch(
            "POST",
            "/obsx/_search",
            {},
            json.dumps({"query": {"match": {"b": "alpha"}}}),
        )
        assert status == 200
        trace_id = rest._tl.response_headers["X-Trace-Id"]
        status, tree = rest.dispatch(
            "GET", f"/_traces/{trace_id}", {}, ""
        )
        assert status == 200
        assert tree["_nodes"]["failed"] == 0
        spans = tree["spans"]
        roots = [s for s in spans if s["parent_id"] is None]
        assert len(roots) == 1  # ONE spliced tree, no duplicate spans
        assert len({s["span_id"] for s in spans}) == len(spans)
        names = [s["name"] for s in spans]
        assert "cluster.shard_search" in names
        assert "search.segment" in names

    def test_unknown_trace_404_with_fan(self, rest):
        status, resp = rest.dispatch(
            "GET", "/_traces/deadbeefdeadbeef", {}, ""
        )
        assert status == 404
        assert resp["error"]["type"] == "resource_not_found_exception"

    def test_metrics_node_labeled_with_cluster_fold(self, rest):
        status, payload = rest.dispatch("GET", "/_metrics", {}, "")
        assert status == 200
        text = payload.text
        for node_id in ("node-0", "node-1", "node-2"):
            assert f'node="{node_id}"' in text
        # Counters without a per-node label fold into cluster totals.
        assert 'node="_cluster"' in text
        # The fold never double-counts series that are ALREADY per-node:
        # the coordinator degraded-search counter keeps its 3 node
        # samples and gains no _cluster twin.
        lines = [
            line
            for line in text.splitlines()
            if line.startswith("estpu_cluster_search_resilience_total")
        ]
        assert lines and not any('node="_cluster"' in line for line in lines)

    def test_cat_nodes_roles_master_load(self, rest):
        status, rows = rest.dispatch("GET", "/_cat/nodes", {}, "")
        assert status == 200
        by_name = {r["name"]: r for r in rows}
        assert set(by_name) == {"node-0", "node-1", "node-2"}
        assert all(r["node.role"] == "dm" for r in rows)
        assert sum(r["master"] == "*" for r in rows) == 1
        for row in rows:
            int(row["load"]), int(row["docs"]), int(row["step_errors"])

    def test_hot_threads_fans_over_members(self, rest):
        status, payload = rest.dispatch(
            "GET",
            "/_nodes/hot_threads",
            {"interval": "50ms", "snapshots": "2", "threads": "2"},
            "",
        )
        assert status == 200
        text = payload.text
        for node_id in ("node-0", "node-1", "node-2"):
            assert f"::: {{{node_id}}}" in text
        # The member sharing the coordinating front's name reports ONCE
        # (same interpreter — the nodes_stats merge rule).
        assert text.count("::: {node-0}") == 1

    def test_hot_threads_bad_interval_400(self, rest):
        status, resp = rest.dispatch(
            "GET", "/_nodes/hot_threads", {"interval": "bogus"}, ""
        )
        assert status == 400
        assert resp["error"]["type"] == "illegal_argument_exception"

    def test_killed_member_named_failure_within_deadline(self, rest):
        rest.cluster.kill("node-2")
        try:
            t0 = time.monotonic()
            status, stats = rest.dispatch("GET", "/_nodes/stats", {}, "")
            elapsed = time.monotonic() - t0
            assert status == 200
            from elasticsearch_tpu.node import NODES_FAN_TIMEOUT_S

            assert elapsed < NODES_FAN_TIMEOUT_S + 3.0
            assert stats["_nodes"]["failed"] == 1
            failure = stats["_nodes"]["failures"][0]
            assert failure["node"] == "node-2"
            assert failure["reason"]
            # Survivors still ship full sections.
            assert _member_sections(stats, "node-1") == MEMBER_SECTIONS
            assert "node-2" not in stats["nodes"]
            # The fan failure is counted (estpu_nodes_stats_fan_failures).
            obs = next(iter(stats["nodes"].values()))["obs"]["cluster"]
            assert obs["fan_failures"].get("node_stats", 0) >= 1
        finally:
            rest.cluster.restart("node-2")


def test_fan_in_parity_hub_vs_tcp():
    """One response shape across transports: the per-member sections of
    `_nodes/stats` are identical over the in-memory hub and real loopback
    sockets (and both carry the `_nodes` header)."""
    sections = {}
    for transport in ("hub", "tcp"):
        server = RestServer(
            replication_nodes=2, cluster_transport=transport
        )
        try:
            status, stats = server.dispatch(
                "GET", "/_nodes/stats", {}, ""
            )
            assert status == 200
            assert stats["_nodes"]["failed"] == 0
            sections[transport] = _member_sections(stats, "node-1")
        finally:
            server.close()
    assert sections["hub"] == sections["tcp"] == MEMBER_SECTIONS


class TestSpliceAndRender:
    def test_splice_dedups_and_prefers_finished(self):
        frag_a = [
            {
                "trace_id": "t",
                "span_id": "s1",
                "parent_id": None,
                "name": "root",
                "start_time_in_millis": 10,
                "duration_ms": 5.0,
                "in_progress": True,
            }
        ]
        frag_b = [
            dict(frag_a[0], in_progress=False),
            {
                "trace_id": "t",
                "span_id": "s2",
                "parent_id": "s1",
                "name": "child",
                "start_time_in_millis": 11,
                "duration_ms": 1.0,
            },
        ]
        spans = splice_spans([frag_a, frag_b, frag_b])
        assert [s["span_id"] for s in spans] == ["s1", "s2"]
        assert not spans[0].get("in_progress", False)

    def test_chrome_lanes_by_node_tag(self):
        spans = [
            {
                "span_id": "a",
                "parent_id": None,
                "name": "root",
                "start_time_in_millis": 1,
                "duration_ms": 2.0,
            },
            {
                "span_id": "b",
                "parent_id": "a",
                "name": "remote",
                "start_time_in_millis": 2,
                "duration_ms": 1.0,
                "tags": {"node": "node-1"},
            },
        ]
        chrome = chrome_trace(spans)
        events = chrome["traceEvents"]
        assert len(events) == 2
        assert events[0]["tid"] != events[1]["tid"]
        assert all(e["ph"] == "X" and e["dur"] >= 1.0 for e in events)

    def test_hot_threads_text_renders_stacks(self):
        import threading

        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(range(100))

        worker = threading.Thread(target=spin, daemon=True, name="spinner")
        worker.start()
        try:
            text = hot_threads_text(
                node_name="n", threads=2, interval_s=0.05, snapshots=2
            )
        finally:
            stop.set()
            worker.join(timeout=2)
        assert text.startswith("::: {n} pid[")
        assert "snapshots sharing following" in text
        assert "busy in thread 'spinner'" in text


@pytest.fixture(scope="module")
def procs():
    from elasticsearch_tpu.cluster.procs import ProcCluster

    cluster = ProcCluster(
        2, data_path=tempfile.mkdtemp(prefix="estpu-obs-procs-")
    )
    yield cluster
    cluster.close()


class TestProcClusterObservability:
    """The acceptance topology: 2 spawned OS data processes + a
    voting-only tiebreaker, all collection over the `_ctl` socket path.
    One cluster boot for the whole class (workers pay a full JAX import);
    the kill -9 scenario runs LAST."""

    def test_nodes_stats_sections_cross_real_sockets(self, procs):
        procs.create_index(
            "obs",
            n_shards=1,
            n_replicas=1,
            mappings={"properties": {"b": {"type": "text"}}},
        )
        for i in range(8):
            procs.write("obs", f"d{i}", {"b": f"alpha term{i % 3}"})
        # The primary refreshes serving this (num_docs counts searchable
        # docs, not the unrefreshed buffer).
        out = procs.search("obs", {"query": {"match_all": {}}, "size": 1})
        assert out["hits"]["total"]["value"] == 8
        stats = procs.nodes_stats()
        assert stats["_nodes"] == {
            "total": 3,
            "successful": 3,
            "failed": 0,
        }
        supervisor_pid = os.getpid()
        for worker in procs.workers:
            section = stats["nodes"][worker]
            assert set(section) & MEMBER_SECTIONS == MEMBER_SECTIONS
            # A REAL worker process, not an in-process stand-in.
            assert section["process"]["pid"] != supervisor_pid
            assert section["roles"] == ["data", "master"]
            assert section["transport"]["kind"] == "tcp"
        tiebreaker = stats["nodes"]["tiebreaker"]
        assert tiebreaker["roles"] == ["master", "voting_only"]
        assert tiebreaker["indices"]["shards"]["count"] == 0
        # Docs live in the worker-owned copies, never the tiebreaker
        # (the searched primary has refreshed them searchable).
        docs = sum(
            stats["nodes"][w]["indices"]["docs"]["count"]
            for w in procs.workers
        )
        assert docs >= 8

    def test_trace_assembly_splices_remote_worker_spans(self, procs):
        out, trace_id = procs.search_traced(
            "obs", {"query": {"match": {"b": "alpha"}}, "size": 5}
        )
        assert out["_shards"]["failed"] == 0
        tree = procs.trace(trace_id)
        assert tree is not None and tree["_nodes"]["failed"] == 0
        spans = tree["spans"]
        roots = [s for s in spans if s["parent_id"] is None]
        assert len(roots) == 1 and roots[0]["name"] == "procs.search"
        names = [s["name"] for s in spans]
        # Remote execution spans whose BODIES lived in a worker's ring
        # until assembly: the shard search and its per-segment launch.
        assert "cluster.shard_search" in names
        assert "search.segment" in names
        remote_nodes = {
            (s.get("tags") or {}).get("node")
            for s in spans
            if s["name"] == "cluster.shard_search"
        }
        assert remote_nodes & set(procs.workers)
        chrome = procs.trace(trace_id, fmt="chrome")
        assert chrome["traceEvents"]
        # Worker spans render on their own Perfetto track.
        assert len({e["tid"] for e in chrome["traceEvents"]}) >= 2
        assert procs.trace("0" * 32) is None

    def test_metrics_federated_with_node_labels(self, procs):
        text = procs.metrics_text(max_age_s=0.0)
        for worker in procs.workers:
            assert f'node="{worker}"' in text
        assert 'node="tiebreaker"' in text
        assert 'node="_cluster"' in text
        # Worker-process transport counters crossed the wire.
        assert "estpu_transport_frames_total" in text
        # Scrape cache: an immediate re-scrape inside the TTL is the
        # cached text (no second fan).
        fanouts_before = procs._ctl.metrics.value(
            "estpu_nodes_stats_fanouts_total", action="metrics_wire"
        )
        procs.metrics_text(max_age_s=60.0)
        assert (
            procs._ctl.metrics.value(
                "estpu_nodes_stats_fanouts_total", action="metrics_wire"
            )
            == fanouts_before
        )

    def test_hot_threads_samples_worker_interpreters(self, procs):
        text = procs.hot_threads(interval_s=0.2, snapshots=4)
        pids = set()
        for line in text.splitlines():
            if line.startswith("::: {"):
                pids.add(int(line.split("pid[", 1)[1].rstrip("]")))
        assert "::: {tiebreaker}" in text
        for worker in procs.workers:
            assert f"::: {{{worker}}}" in text
        # Three distinct interpreters sampled themselves.
        assert len(pids) == 3

    def test_kill9_named_failure_within_deadline(self, procs):
        """The acceptance scenario: SIGKILL one data process mid-flight;
        `_nodes/stats` answers within the transport deadline with
        `_nodes.failed == 1` (named, with reason) and full sections from
        every survivor."""
        victim = procs.workers[1]
        procs.kill_9(victim)
        t0 = time.monotonic()
        stats = procs.nodes_stats()
        elapsed = time.monotonic() - t0
        assert elapsed < (procs.send_timeout_s or 5.0) + 3.0
        assert stats["_nodes"]["failed"] == 1
        failure = stats["_nodes"]["failures"][0]
        assert failure["node"] == victim
        assert failure["reason"]
        survivor = procs.workers[0]
        assert (
            set(stats["nodes"][survivor]) & MEMBER_SECTIONS
            == MEMBER_SECTIONS
        )
        assert "tiebreaker" in stats["nodes"]
        # The federated scrape degrades the same way: survivors' series
        # still present, no hang.
        text = procs.metrics_text(max_age_s=0.0)
        assert f'node="{survivor}"' in text
