"""Span query family: span_term, span_or, span_near, span_first, span_not.

Reference: SpanTermQueryBuilder, SpanOrQueryBuilder, SpanNearQueryBuilder
(lucene NearSpansOrdered/Unordered), SpanFirstQueryBuilder,
SpanNotQueryBuilder. Matching sets over unit spans are exact; scoring
uses freq = chain-end count with the summed-idf weight (the sloppy-freq
1/(1+stretch) weighting is a noted divergence — see _eval_span_near).
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.oracle import OracleSearcher


@pytest.fixture(scope="module")
def corpus():
    m = Mappings(properties={"body": {"type": "text"}})
    docs = [
        "the quick brown fox jumps over the lazy dog",      # 0
        "quick fox",                                        # 1
        "the fox was quick and brown",                      # 2
        "lazy quick brown dog fox",                         # 3
        "a dog and a fox walked home",                      # 4
        "quick brown quick fox",                            # 5
        "brown dog",                                        # 6
    ]
    b = SegmentBuilder(m)
    for i, text in enumerate(docs):
        b.add({"body": text}, f"d{i}")
    seg = b.build()
    dev = pack_segment(seg)
    return m, seg, dev


def _both(corpus, query_json, k=7):
    import jax

    m, seg, dev = corpus
    q = parse_query(query_json)
    c = Compiler(dev.fields, dev.doc_values, m).compile(q)
    tree = bm25_device.segment_tree(dev)
    d_s, d_i, d_t = jax.device_get(bm25_device.execute(tree, c.spec, c.arrays, k))
    o_s, o_i, o_t = OracleSearcher(seg, m).search(q, k)
    n = len(o_i)
    assert list(d_i[:n]) == list(o_i), (query_json, list(d_i[:n]), list(o_i))
    np.testing.assert_allclose(d_s[:n], o_s, rtol=2e-6)
    assert int(d_t) == o_t, query_json
    return list(o_i), o_s, o_t


def test_span_term_scores_like_term(corpus):
    ids, scores, total = _both(corpus, {"span_term": {"body": "fox"}})
    assert total == 6
    ids2, scores2, total2 = _both(corpus, {"term": {"body": "fox"}})
    assert ids == ids2 and total == total2
    np.testing.assert_array_equal(scores, scores2)


def test_span_near_ordered(corpus):
    # "quick ... fox" within slop 0 (adjacent, ordered): docs 1 and 5.
    ids, _, total = _both(
        corpus,
        {
            "span_near": {
                "clauses": [
                    {"span_term": {"body": "quick"}},
                    {"span_term": {"body": "fox"}},
                ],
                "slop": 0,
                "in_order": True,
            }
        },
    )
    assert set(ids) == {1, 5} and total == 2
    # slop 2 adds docs 0 (quick brown fox) and 3 (quick brown dog fox).
    ids, _, total = _both(
        corpus,
        {
            "span_near": {
                "clauses": [
                    {"span_term": {"body": "quick"}},
                    {"span_term": {"body": "fox"}},
                ],
                "slop": 2,
                "in_order": True,
            }
        },
    )
    assert set(ids) == {0, 1, 3, 5} and total == 4


def test_span_near_unordered(corpus):
    # unordered: "fox ... quick" in doc 2 now matches at slop 1.
    ids, _, total = _both(
        corpus,
        {
            "span_near": {
                "clauses": [
                    {"span_term": {"body": "quick"}},
                    {"span_term": {"body": "fox"}},
                ],
                "slop": 1,
                "in_order": False,
            }
        },
    )
    assert 2 in ids and 1 in ids


def test_span_near_three_clauses(corpus):
    ids, _, total = _both(
        corpus,
        {
            "span_near": {
                "clauses": [
                    {"span_term": {"body": "quick"}},
                    {"span_term": {"body": "brown"}},
                    {"span_term": {"body": "fox"}},
                ],
                "slop": 0,
                "in_order": True,
            }
        },
    )
    assert set(ids) == {0}  # only "quick brown fox" adjacent in order
    ids, _, total = _both(
        corpus,
        {
            "span_near": {
                "clauses": [
                    {"span_term": {"body": "quick"}},
                    {"span_term": {"body": "brown"}},
                    {"span_term": {"body": "fox"}},
                ],
                "slop": 1,
                "in_order": True,
            }
        },
    )
    assert set(ids) == {0, 3, 5}


def test_span_or_and_nested_in_near(corpus):
    ids, _, total = _both(
        corpus,
        {
            "span_or": {
                "clauses": [
                    {"span_term": {"body": "lazy"}},
                    {"span_term": {"body": "walked"}},
                ]
            }
        },
    )
    assert set(ids) == {0, 3, 4}
    ids, _, total = _both(
        corpus,
        {
            "span_near": {
                "clauses": [
                    {
                        "span_or": {
                            "clauses": [
                                {"span_term": {"body": "quick"}},
                                {"span_term": {"body": "lazy"}},
                            ]
                        }
                    },
                    {"span_term": {"body": "dog"}},
                ],
                "slop": 0,
                "in_order": True,
            }
        },
    )
    assert set(ids) == {0}  # only "lazy dog" is adjacent
    ids, _, total = _both(
        corpus,
        {
            "span_near": {
                "clauses": [
                    {
                        "span_or": {
                            "clauses": [
                                {"span_term": {"body": "quick"}},
                                {"span_term": {"body": "lazy"}},
                            ]
                        }
                    },
                    {"span_term": {"body": "dog"}},
                ],
                "slop": 1,
                "in_order": True,
            }
        },
    )
    assert set(ids) == {0, 3}  # doc 3: quick(1) .. dog(3), stretch 1


def test_span_first(corpus):
    ids, _, total = _both(
        corpus,
        {"span_first": {"match": {"span_term": {"body": "quick"}}, "end": 1}},
    )
    assert set(ids) == {1, 5}  # "quick" as the first token
    ids, _, total = _both(
        corpus,
        {"span_first": {"match": {"span_term": {"body": "quick"}}, "end": 2}},
    )
    assert set(ids) == {0, 1, 3, 5}  # "quick" within the first two tokens


def test_span_not(corpus):
    # fox not immediately preceded by quick (pre=1): docs 0,2,3,4 keep
    # foxes; doc 1 and 5's foxes follow quick directly.
    ids, _, total = _both(
        corpus,
        {
            "span_not": {
                "include": {"span_term": {"body": "fox"}},
                "exclude": {"span_term": {"body": "quick"}},
                "pre": 1,
            }
        },
    )
    assert 1 not in ids and 5 not in ids
    assert {0, 2, 3, 4} <= set(ids)


def test_span_parse_errors():
    with pytest.raises(ValueError, match="span"):
        parse_query({"span_near": {"clauses": [{"term": {"body": "x"}}]}})
    with pytest.raises(ValueError, match="in_order"):
        parse_query(
            {
                "span_near": {
                    "clauses": [
                        {"span_term": {"body": "a"}},
                        {"span_term": {"body": "b"}},
                        {"span_term": {"body": "c"}},
                    ],
                    "in_order": False,
                }
            }
        )
    with pytest.raises(ValueError, match="span_first"):
        parse_query({"span_first": {"match": {"span_term": {"body": "a"}}}})


def test_span_in_bool_filter(corpus):
    ids, _, total = _both(
        corpus,
        {
            "bool": {
                "must": [{"match": {"body": "dog"}}],
                "filter": [
                    {
                        "span_near": {
                            "clauses": [
                                {"span_term": {"body": "quick"}},
                                {"span_term": {"body": "fox"}},
                            ],
                            "slop": 2,
                            "in_order": True,
                        }
                    }
                ],
            }
        },
    )
    assert set(ids) == {0, 3}
