"""Completion suggester + completion field type.

Reference: search/suggest/completion/CompletionSuggester.java:30
(NRTSuggester FSTs), CompletionFieldMapper (input/weight docs),
FuzzyCompletionQuery (fuzzy prefix).
"""

import pytest

from elasticsearch_tpu.node import ApiError, Node


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path))
    n.create_index(
        "music",
        {
            "mappings": {
                "properties": {
                    "title": {"type": "text"},
                    "suggest": {"type": "completion"},
                }
            }
        },
    )
    docs = [
        ("1", {"title": "a", "suggest": {"input": ["Hotel California", "California Dreamin"], "weight": 10}}),
        ("2", {"title": "b", "suggest": {"input": "Hotel Costa Rica", "weight": 5}}),
        ("3", {"title": "c", "suggest": ["Hot Chocolate", "Chocolate Rain"]}),
        ("4", {"title": "d", "suggest": {"input": "Hotline Bling", "weight": 20}}),
    ]
    for doc_id, src in docs:
        n.index_doc("music", src, doc_id)
    n.refresh("music")
    return n


def _options(node, body):
    out = node.search("music", {"suggest": {"s": body}, "size": 0})
    return out["suggest"]["s"][0]["options"]


def test_prefix_weight_ranking(node):
    opts = _options(node, {"prefix": "hot", "completion": {"field": "suggest"}})
    texts = [o["text"] for o in opts]
    # Weight-desc: Hotline Bling (20) > Hotel California (10) > Hotel
    # Costa Rica (5) > Hot Chocolate (1).
    assert texts == [
        "Hotline Bling",
        "Hotel California",
        "Hotel Costa Rica",
        "Hot Chocolate",
    ]
    assert opts[0]["_id"] == "4" and opts[0]["_score"] == 20.0


def test_prefix_case_insensitive_and_size(node):
    opts = _options(
        node, {"prefix": "HOTEL", "completion": {"field": "suggest", "size": 1}}
    )
    assert [o["text"] for o in opts] == ["Hotel California"]


def test_fuzzy_prefix(node):
    opts = _options(
        node,
        {"prefix": "hotl", "completion": {"field": "suggest", "fuzzy": {}}},
    )
    texts = [o["text"] for o in opts]
    assert "Hotline Bling" in texts and "Hotel California" in texts


def test_skip_duplicates(node):
    node.index_doc(
        "music", {"title": "e", "suggest": {"input": "Hotel California", "weight": 3}}, "5"
    )
    node.refresh("music")
    with_dups = _options(
        node, {"prefix": "hotel cal", "completion": {"field": "suggest"}}
    )
    assert len(with_dups) == 2
    deduped = _options(
        node,
        {
            "prefix": "hotel cal",
            "completion": {"field": "suggest", "skip_duplicates": True},
        },
    )
    assert [o["text"] for o in deduped] == ["Hotel California"]


def test_deleted_docs_stop_suggesting(node):
    node.delete_doc("music", "4", refresh=True)
    opts = _options(node, {"prefix": "hotline", "completion": {"field": "suggest"}})
    assert opts == []


def test_completion_survives_restart(node, tmp_path):
    node.flush("music")
    n2 = Node(data_path=str(tmp_path))
    out = n2.search(
        "music",
        {
            "suggest": {
                "s": {"prefix": "hot choc", "completion": {"field": "suggest"}}
            },
            "size": 0,
        },
    )
    assert [o["text"] for o in out["suggest"]["s"][0]["options"]] == [
        "Hot Chocolate"
    ]


def test_completion_requires_field(node):
    with pytest.raises(ApiError):
        node.search(
            "music",
            {"suggest": {"s": {"prefix": "x", "completion": {}}}, "size": 0},
        )


def test_completion_regex(node):
    opts = _options(
        node, {"regex": "hot.l", "completion": {"field": "suggest"}}
    )
    texts = [o["text"] for o in opts]
    assert "Hotel California" in texts and "Hot Chocolate" not in texts


def test_completion_requires_prefix_or_regex(node):
    with pytest.raises(ApiError):
        node.search(
            "music",
            {"suggest": {"s": {"completion": {"field": "suggest"}}}, "size": 0},
        )


def test_completion_wrong_field_type(node):
    with pytest.raises(ApiError):
        node.search(
            "music",
            {
                "suggest": {
                    "s": {"prefix": "x", "completion": {"field": "title"}}
                },
                "size": 0,
            },
        )


def test_stored_script_ref_404_without_any_scripts(node):
    with pytest.raises(ApiError) as e:
        node.search(
            "music",
            {
                "query": {
                    "script_score": {
                        "query": {"match_all": {}},
                        "script": {"id": "does-not-exist"},
                    }
                }
            },
        )
    assert "unable to find script" in str(e.value)
