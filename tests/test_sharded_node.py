"""number_of_shards wired through Node/REST: routing, coordinator merge,
parity vs a single-shard index, persistence, and the mesh snapshot.

Matches VERDICT item 4: an 8-shard index created over HTTP serves searches
with parity vs 1-shard (reference: OperationRouting.java:245 routing,
SearchPhaseController.java:398 coordinator merge).
"""

import json

import numpy as np
import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.server import RestServer

MAPPINGS = {
    "properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "rank": {"type": "long"},
    }
}

WORDS = ["ant", "bee", "cat", "dog", "elk", "fox", "gnu", "hen"]


def make_docs(n=120, seed=3):
    rng = np.random.default_rng(seed)
    docs = []
    for i in range(n):
        docs.append(
            (
                f"doc{i}",
                {
                    "body": " ".join(rng.choice(WORDS, rng.integers(2, 9))),
                    "tag": str(rng.choice(["x", "y", "z"])),
                    "rank": int(rng.integers(0, 500)),
                },
            )
        )
    return docs


def load(node, index, docs, n_shards):
    node.create_index(
        index,
        {
            "settings": {"index": {"number_of_shards": n_shards}},
            "mappings": MAPPINGS,
        },
    )
    for doc_id, src in docs:
        node.index_doc(index, src, doc_id)
    node.refresh(index)


@pytest.fixture(scope="module")
def nodes():
    docs = make_docs()
    node = Node()
    load(node, "one", docs, 1)
    load(node, "eight", docs, 8)
    return node, docs


def test_shards_receive_disjoint_docs(nodes):
    node, docs = nodes
    svc = node.get_index("eight")
    assert svc.n_shards == 8
    per_shard = [e.num_docs for e in svc.engines]
    assert sum(per_shard) == len(docs)
    assert sum(1 for c in per_shard if c > 0) > 4  # murmur3 spreads


def test_search_parity_one_vs_eight_shards(nodes):
    node, docs = nodes
    for body in [
        {"query": {"match": {"body": "ant bee"}}, "size": 15},
        {"query": {"bool": {"must": [{"match": {"body": "cat"}}],
                            "filter": [{"term": {"tag": "x"}}]}}, "size": 10},
        {"query": {"match_phrase": {"body": "fox gnu"}}, "size": 10},
        {"query": {"range": {"rank": {"gte": 100, "lt": 300}}}, "size": 10},
        {"query": {"match": {"body": "dog"}}, "size": 7,
         "sort": [{"rank": "desc"}]},
        {"query": {"match_all": {}}, "size": 5, "from": 10,
         "sort": [{"rank": "asc"}]},
    ]:
        r1 = node.search("one", body)
        r8 = node.search("eight", body)
        assert r8["hits"]["total"]["value"] == r1["hits"]["total"]["value"]
        s1 = [h["_score"] for h in r1["hits"]["hits"]]
        s8 = [h["_score"] for h in r8["hits"]["hits"]]
        assert s8 == s1  # global (DFS) stats: scores routing-independent
        if "sort" in body:
            assert [h["sort"] for h in r8["hits"]["hits"]] == [
                h["sort"] for h in r1["hits"]["hits"]
            ]
        # id parity modulo tie order: equal-key groups can legitimately
        # truncate to different members at the k boundary (the tie-break is
        # (key, shard, doc) and shard structure differs), so compare ids of
        # every NON-boundary key group.
        def keyed(hits):
            out = {}
            for h in hits:
                key = tuple(h.get("sort") or []) or h["_score"]
                out.setdefault(key, set()).add(h["_id"])
            return out

        h1, h8 = r1["hits"]["hits"], r8["hits"]["hits"]
        k1, k8 = keyed(h1), keyed(h8)
        if h1:
            last1 = tuple(h1[-1].get("sort") or []) or h1[-1]["_score"]
            last8 = tuple(h8[-1].get("sort") or []) or h8[-1]["_score"]
            for key in set(k1) & set(k8) - {last1, last8}:
                assert k1[key] == k8[key]


def test_aggregations_across_shards(nodes):
    node, docs = nodes
    body = {
        "size": 0,
        "aggs": {
            "tags": {"terms": {"field": "tag"}},
            "ranks": {"histogram": {"field": "rank", "interval": 100}},
            "avg_rank": {"avg": {"field": "rank"}},
        },
    }
    r1 = node.search("one", body)
    r8 = node.search("eight", body)
    assert r8["aggregations"]["tags"] == r1["aggregations"]["tags"]
    assert r8["aggregations"]["ranks"] == r1["aggregations"]["ranks"]
    assert r8["aggregations"]["avg_rank"]["value"] == pytest.approx(
        r1["aggregations"]["avg_rank"]["value"], rel=1e-6
    )


def test_document_apis_route_correctly(nodes):
    node, docs = nodes
    # realtime get before and after refresh
    resp = node.get_doc("eight", "doc5")
    assert resp["found"] and resp["_source"] == dict(docs[5][1])
    upd = node.update_doc("eight", "doc5", {"doc": {"rank": 9999}})
    assert upd["result"] == "updated"
    assert node.get_doc("eight", "doc5")["_source"]["rank"] == 9999
    # restore for other tests
    node.update_doc("eight", "doc5", {"doc": docs[5][1]})
    resp = node.index_doc("eight", {"body": "zzz"},
                          None)  # auto-id routes
    assert resp["result"] == "created"
    got = node.get_doc("eight", resp["_id"])
    assert got["found"]
    node.delete_doc("eight", resp["_id"])


def test_rest_multi_shard_end_to_end():
    rest = RestServer()
    docs = make_docs(40, seed=9)
    status, _ = rest.dispatch(
        "PUT",
        "/r8",
        {},
        json.dumps(
            {
                "settings": {"index": {"number_of_shards": 8}},
                "mappings": MAPPINGS,
            }
        ),
    )
    assert status == 200
    lines = []
    for doc_id, src in docs:
        lines.append(json.dumps({"index": {"_id": doc_id}}))
        lines.append(json.dumps(src))
    status, resp = rest.dispatch(
        "POST", "/r8/_bulk", {"refresh": "true"}, "\n".join(lines)
    )
    assert status == 200 and not resp["errors"]
    status, resp = rest.dispatch(
        "POST", "/r8/_search", {}, json.dumps({"query": {"match": {"body": "ant"}}})
    )
    assert status == 200
    assert resp["_shards"]["total"] == 8
    expected = len(
        [1 for _, s in docs if "ant" in s["body"].split()]
    )
    assert resp["hits"]["total"]["value"] == expected
    status, cat = rest.dispatch("GET", "/_cat/indices", {}, "")
    assert any(row["index"] == "r8" and row["pri"] == "8" for row in cat)


def test_sharded_persistence_and_recovery(tmp_path):
    docs = make_docs(30, seed=21)
    node = Node(data_path=str(tmp_path))
    load(node, "p4", docs, 4)
    node.flush("p4")
    node.close()

    node2 = Node(data_path=str(tmp_path))
    svc = node2.get_index("p4")
    assert svc.n_shards == 4
    assert svc.num_docs == len(docs)
    r = node2.search("p4", {"query": {"match_all": {}}, "size": 0})
    assert r["hits"]["total"]["value"] == len(docs)
    got = node2.get_doc("p4", "doc3")
    assert got["found"]
    node2.close()


def test_invalid_shard_count_rejected():
    node = Node()
    from elasticsearch_tpu.node import ApiError

    with pytest.raises(ApiError):
        node.create_index(
            "bad", {"settings": {"index": {"number_of_shards": 0}}}
        )
    with pytest.raises(ApiError):
        node.create_index(
            "bad2", {"settings": {"index": {"number_of_shards": "nope"}}}
        )


def test_mesh_snapshot_matches_coordinator():
    import jax
    from jax.sharding import Mesh

    # Fresh index: the snapshot rebuilds segments from live docs, so its
    # term statistics exclude tombstones while the engine path keeps them
    # until merge (both are legitimate Lucene states — parity needs a
    # tombstone-free index).
    node = Node()
    docs = make_docs(80, seed=31)
    load(node, "mesh8", docs, 8)
    svc = node.get_index("mesh8")
    mesh = Mesh(np.array(jax.devices()[:8]), ("shard",))
    snap = svc.mesh_snapshot(mesh)
    from elasticsearch_tpu.query.dsl import parse_query

    body = {"match": {"body": "bee cat"}}
    scores, gids, total = snap.search(parse_query(body), k=12)
    host = node.search("mesh8", {"query": body, "size": 12})
    assert total == host["hits"]["total"]["value"]
    mesh_ids = {
        snap.segments[s].ids[l] for s, l in (snap.locate(g) for g in gids)
    }
    assert mesh_ids == {h["_id"] for h in host["hits"]["hits"]}
    np.testing.assert_array_equal(
        scores, np.array([h["_score"] for h in host["hits"]["hits"]],
                         dtype=np.float32),
    )
