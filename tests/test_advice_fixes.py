"""Regression tests for the round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.rest.server import RestServer
from elasticsearch_tpu.script import compile_script


class TestSandboxEscape:
    """painless-lite must reject every attribute-walk escape route."""

    @pytest.mark.parametrize(
        "src",
        [
            "sigmoid.__globals__['__builtins__']['__import__']('os')",
            "(1.0).__class__.__base__.__subclasses__()",
            "_score.__class__",
            "params.__dict__",
            "doc['f'].__class__",
            "Math.__subclasshook__",
            "doc['f'].value.__class__",
            "params['x'].__class__.__mro__",
            "params['__class__']",
            "params['__getattribute__']('_values')",
            "params['__setattr__']('_values', 0)",
            "doc['__class__']",
        ],
    )
    def test_dunder_walks_rejected(self, src):
        with pytest.raises(ValueError):
            compile_script(src)

    @pytest.mark.parametrize(
        "src",
        [
            "doc[_score]",  # non-constant subscript key
            "doc[doc]",
            "params[1]",  # non-string key
            "Math.hypot(1, 2)",  # unknown Math member
            "_score.real",  # attribute on a bare value
        ],
    )
    def test_nonwhitelisted_access_rejected(self, src):
        with pytest.raises(ValueError):
            compile_script(src)

    def test_legit_scripts_still_compile(self):
        for src in [
            "_score * 2.0",
            "Math.log(1 + _score)",
            "params.w * doc['price'].value",
            "params['w'] * doc['price'].value + Math.PI",
            "doc['f'].empty ? 0.0 : doc['f'].value",
            "cosineSimilarity(params.qv, 'vec') + 1.0",
            "saturation(doc['pagerank'].value, 10)",
        ]:
            compile_script(src)

    def test_legit_script_evaluates(self):
        s = compile_script("params.w * doc['price'].value + _score")
        out = s.evaluate(
            np,
            np.array([1.0, 2.0], dtype=np.float32),
            {"price": np.array([10.0, 20.0], dtype=np.float32)},
            {},
            {"w": 2.0},
        )
        np.testing.assert_allclose(out, [21.0, 42.0])


class TestShardedMergeFill:
    """Merged top-k must fill min(size, total), not min(size, docs/shard)."""

    def test_k_exceeding_per_shard_docs(self):
        import jax
        from jax.sharding import Mesh

        from elasticsearch_tpu.parallel.sharded import ShardedIndex
        from elasticsearch_tpu.query.dsl import MatchAllQuery

        mesh = Mesh(np.array(jax.devices()[:4]), ("shard",))
        mappings = Mappings.from_json(
            {"properties": {"body": {"type": "text"}}}
        )
        docs = [(str(i), {"body": f"doc number {i}"}) for i in range(40)]
        idx = ShardedIndex.from_docs(docs, mappings, mesh)
        scores, ids, total = idx.search(MatchAllQuery(), k=30)
        assert total == 40
        assert len(ids) == 30  # was 18 before the fix
        assert len(set(int(i) for i in ids)) == 30


class TestUpdateUpsert:
    def test_upsert_indexes_as_is_when_missing(self):
        node = Node()
        node.create_index("i")
        node.update_doc(
            "i", "1", {"doc": {"a": 2}, "upsert": {"a": 1, "b": 9}}
        )
        got = node.get_doc("i", "1")
        # ES indexes the upsert doc as-is; `doc` is NOT applied.
        assert got["_source"] == {"a": 1, "b": 9}

    def test_doc_applied_when_existing(self):
        node = Node()
        node.create_index("i")
        node.index_doc("i", {"a": 1, "b": 9}, "1")
        node.update_doc("i", "1", {"doc": {"a": 2}, "upsert": {"a": 0}})
        assert node.get_doc("i", "1")["_source"] == {"a": 2, "b": 9}


class TestRestDispatch:
    def test_unknown_route_is_400(self):
        rest = RestServer()
        status, payload = rest.dispatch("GET", "/_nope/zzz/yyy", {}, "")
        assert status == 400
        assert payload["error"]["type"] == "invalid_request"

    def test_wrong_method_is_405(self):
        rest = RestServer()
        status, _ = rest.dispatch("DELETE", "/_cluster/health", {}, "")
        assert status == 405

    def test_head_routes_like_get(self):
        rest = RestServer()
        status, payload = rest.dispatch("HEAD", "/", {}, "")
        assert status == 200
        assert "tagline" in payload
