"""Regression tests for the round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.rest.server import RestServer
from elasticsearch_tpu.script import compile_script


class TestSandboxEscape:
    """painless-lite must reject every attribute-walk escape route."""

    @pytest.mark.parametrize(
        "src",
        [
            "sigmoid.__globals__['__builtins__']['__import__']('os')",
            "(1.0).__class__.__base__.__subclasses__()",
            "_score.__class__",
            "params.__dict__",
            "doc['f'].__class__",
            "Math.__subclasshook__",
            "doc['f'].value.__class__",
            "params['x'].__class__.__mro__",
            "params['__class__']",
            "params['__getattribute__']('_values')",
            "params['__setattr__']('_values', 0)",
            "doc['__class__']",
        ],
    )
    def test_dunder_walks_rejected(self, src):
        with pytest.raises(ValueError):
            compile_script(src)

    @pytest.mark.parametrize(
        "src",
        [
            "doc[_score]",  # non-constant subscript key
            "doc[doc]",
            "params[1]",  # non-string key
            "Math.hypot(1, 2)",  # unknown Math member
            "_score.real",  # attribute on a bare value
        ],
    )
    def test_nonwhitelisted_access_rejected(self, src):
        with pytest.raises(ValueError):
            compile_script(src)

    def test_legit_scripts_still_compile(self):
        for src in [
            "_score * 2.0",
            "Math.log(1 + _score)",
            "params.w * doc['price'].value",
            "params['w'] * doc['price'].value + Math.PI",
            "doc['f'].empty ? 0.0 : doc['f'].value",
            "cosineSimilarity(params.qv, 'vec') + 1.0",
            "saturation(doc['pagerank'].value, 10)",
        ]:
            compile_script(src)

    def test_legit_script_evaluates(self):
        s = compile_script("params.w * doc['price'].value + _score")
        out = s.evaluate(
            np,
            np.array([1.0, 2.0], dtype=np.float32),
            {"price": np.array([10.0, 20.0], dtype=np.float32)},
            {},
            {"w": 2.0},
        )
        np.testing.assert_allclose(out, [21.0, 42.0])


class TestShardedMergeFill:
    """Merged top-k must fill min(size, total), not min(size, docs/shard)."""

    def test_k_exceeding_per_shard_docs(self):
        import jax
        from jax.sharding import Mesh

        from elasticsearch_tpu.parallel.sharded import ShardedIndex
        from elasticsearch_tpu.query.dsl import MatchAllQuery

        mesh = Mesh(np.array(jax.devices()[:4]), ("shard",))
        mappings = Mappings.from_json(
            {"properties": {"body": {"type": "text"}}}
        )
        docs = [(str(i), {"body": f"doc number {i}"}) for i in range(40)]
        idx = ShardedIndex.from_docs(docs, mappings, mesh)
        scores, ids, total = idx.search(MatchAllQuery(), k=30)
        assert total == 40
        assert len(ids) == 30  # was 18 before the fix
        assert len(set(int(i) for i in ids)) == 30


class TestUpdateUpsert:
    def test_upsert_indexes_as_is_when_missing(self):
        node = Node()
        node.create_index("i")
        node.update_doc(
            "i", "1", {"doc": {"a": 2}, "upsert": {"a": 1, "b": 9}}
        )
        got = node.get_doc("i", "1")
        # ES indexes the upsert doc as-is; `doc` is NOT applied.
        assert got["_source"] == {"a": 1, "b": 9}

    def test_doc_applied_when_existing(self):
        node = Node()
        node.create_index("i")
        node.index_doc("i", {"a": 1, "b": 9}, "1")
        node.update_doc("i", "1", {"doc": {"a": 2}, "upsert": {"a": 0}})
        assert node.get_doc("i", "1")["_source"] == {"a": 2, "b": 9}


class TestRestDispatch:
    def test_unknown_route_is_400(self):
        rest = RestServer()
        status, payload = rest.dispatch("GET", "/_nope/zzz/yyy", {}, "")
        assert status == 400
        assert payload["error"]["type"] == "invalid_request"

    def test_wrong_method_is_405(self):
        rest = RestServer()
        status, _ = rest.dispatch("DELETE", "/_cluster/health", {}, "")
        assert status == 405

    def test_head_routes_like_get(self):
        rest = RestServer()
        status, payload = rest.dispatch("HEAD", "/", {}, "")
        assert status == 200
        assert "tagline" in payload


class TestTranslogRound2Advice:
    """Round-2 advisor findings: in-place torn-tail repair, locking,
    mid-log corruption detection, orphan generation sweep."""

    def _tl(self, tmp_path):
        from elasticsearch_tpu.index.translog import Translog

        return Translog(str(tmp_path / "translog"))

    def test_torn_tail_truncated_in_place(self, tmp_path):
        import os

        from elasticsearch_tpu.index.translog import Translog

        tl = self._tl(tmp_path)
        for s in range(3):
            tl.add({"seqno": s, "op": "index", "id": f"d{s}", "source": {}})
        tl.sync()
        tl.close()
        gen_path = tl._gen_path(tl.generation)
        with open(gen_path, "ab") as f:
            f.write(b'{"seqno": 3, "op": "ind')  # torn mid-record
        inode_before = os.stat(gen_path).st_ino
        tl2 = Translog(str(tmp_path / "translog"))
        # Same inode: the repair truncated in place — it never rewrote the
        # file (a rewrite would zero every fsynced op first).
        assert os.stat(gen_path).st_ino == inode_before
        assert [op["seqno"] for op in tl2.replay()] == [0, 1, 2]
        tl2.close()

    def test_midlog_corruption_raises(self, tmp_path):
        from elasticsearch_tpu.index.translog import (
            Translog,
            TranslogCorruptedError,
        )

        tl = self._tl(tmp_path)
        for s in range(3):
            tl.add({"seqno": s, "op": "index", "id": f"d{s}", "source": {}})
        tl.sync()
        tl.close()
        gen_path = tl._gen_path(tl.generation)
        with open(gen_path, "rb") as f:
            lines = f.readlines()
        lines[1] = b"\x00garbage\x00\n"  # corrupt a NON-final record
        with open(gen_path, "wb") as f:
            f.writelines(lines)
        tl2 = Translog.__new__(Translog)  # bypass open-time tail repair
        tl2.path = str(tmp_path / "translog")
        tl2._ckp_path = tl._ckp_path
        with pytest.raises(TranslogCorruptedError):
            list(tl2.replay())

    def test_orphan_generations_swept_on_open(self, tmp_path):
        import os

        from elasticsearch_tpu.index.translog import Translog

        tl = self._tl(tmp_path)
        tl.add({"seqno": 0, "op": "index", "id": "a", "source": {}})
        tl.roll(persisted_seqno=0)  # now on generation 2, min_gen 2
        tl.close()
        # Simulate a crash between checkpoint write and file removal:
        orphan = tl._gen_path(1)
        with open(orphan, "wb") as f:
            f.write(b'{"seqno": 0, "op": "delete", "id": "a"}\n')
        tl2 = Translog(str(tmp_path / "translog"))
        assert not os.path.exists(orphan)
        tl2.close()

    def test_concurrent_adds_never_tear_records(self, tmp_path):
        import threading

        from elasticsearch_tpu.index.translog import Translog

        tl = self._tl(tmp_path)
        n_threads, per_thread = 8, 200

        def writer(t):
            for i in range(per_thread):
                tl.add(
                    {
                        "seqno": t * per_thread + i,
                        "op": "index",
                        "id": f"t{t}-{i}",
                        "source": {"pad": "x" * 64},
                    }
                )

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(n_threads)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        tl.sync()
        tl.close()
        tl2 = Translog(str(tmp_path / "translog"))
        seqnos = sorted(op["seqno"] for op in tl2.replay())
        assert seqnos == list(range(n_threads * per_thread))
        tl2.close()


class TestSparseTpadFallback:
    """Wide disjunctions must not unroll a ~1000-step sparse fold."""

    def test_wide_disjunction_routes_to_dense(self):
        from elasticsearch_tpu.ops import bm25_device

        assert bm25_device.supports_sparse(("terms", "body", 64, 8))
        assert bm25_device.supports_sparse(("terms", "body", 64, 32))
        assert not bm25_device.supports_sparse(("terms", "body", 64, 64))
        assert not bm25_device.supports_sparse(("terms", "body", 4096, 1024))

    def test_wide_disjunction_results_match_oracle(self):
        from elasticsearch_tpu.index.engine import Engine
        from elasticsearch_tpu.index.mapping import Mappings
        from elasticsearch_tpu.ops import bm25_device
        from elasticsearch_tpu.ops.bm25 import search_field
        from elasticsearch_tpu.query.dsl import parse_query

        rng = np.random.default_rng(7)
        vocab = [f"w{i}" for i in range(80)]
        engine = Engine(Mappings(properties={"body": {"type": "text"}}))
        for i in range(300):
            engine.index(
                {"body": " ".join(rng.choice(vocab, rng.integers(3, 20)))},
                f"d{i}",
            )
        engine.refresh()
        handle = engine.segments[0]
        # 40 query terms -> t_pad 64 > SPARSE_TPAD_MAX: auto path must use
        # the dense kernel and still match the oracle.
        terms = [f"w{i}" for i in range(40)]
        compiled = engine.compiler_for(handle).compile(
            parse_query({"match": {"body": " ".join(terms)}})
        )
        assert not bm25_device.supports_sparse(compiled.spec)
        seg_tree = bm25_device.segment_tree(handle.device)
        scores, ids, total = bm25_device.execute_auto(
            seg_tree, compiled.spec, compiled.arrays, 10
        )
        o_scores, o_ids = search_field(
            handle.segment.fields["body"], terms, 300, 10
        )
        n = len(o_ids)
        assert list(np.asarray(ids)[:n]) == list(o_ids)
        np.testing.assert_array_equal(np.asarray(scores)[:n], o_scores)
