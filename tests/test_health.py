"""Cluster health report (ISSUE 15): rule-based indicators over rolling
windows, `GET /_health_report`, the `wait_for_status` blocking poll, and
the query-insights ring.

The acceptance arc runs on BOTH cluster forms: a LocalCluster REST front
and a 2-process ProcCluster — green report → kill a data node →
`/_health_report` turns non-green with a NAMED per-indicator diagnosis
within the per-send deadline (never a hang) → restart + heal → green
again. Indicator rules are additionally unit-tested over synthetic
HealthContexts (the pure-function contract), and the PR-14 seeded
retrace defect must flip `device_compile` to yellow NAMING the plan
class.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

import pytest

from elasticsearch_tpu.cluster.state import ClusterState, IndexMeta, ShardRouting
from elasticsearch_tpu.node import NODES_FAN_TIMEOUT_S, Node
from elasticsearch_tpu.obs.health import (
    INDICATORS,
    HealthContext,
    HealthService,
    indicator_device_memory,
    indicator_exec_saturation,
    indicator_master_stability,
    indicator_shards_availability,
    indicator_transport,
    shard_summary,
    worst,
)
from elasticsearch_tpu.obs.insights import QueryInsights
from elasticsearch_tpu.obs.metrics import (
    MetricsRegistry,
    WindowedCounter,
    WindowedHistogram,
)
from elasticsearch_tpu.rest.server import RestServer

REPLICATED_INDEX = json.dumps(
    {
        "settings": {"number_of_shards": 1, "number_of_replicas": 1},
        "mappings": {"properties": {"b": {"type": "text"}}},
    }
)


def _mappings():
    return {"mappings": {"properties": {"body": {"type": "text"}}}}


# ------------------------------------------------------- rolling windows


class TestRollingWindows:
    def test_windowed_histogram_percentiles_and_rate(self):
        wh = WindowedHistogram(window_s=60.0, interval_s=5.0)
        for v in range(1, 101):
            wh.record(float(v))
        snap = wh.snapshot()
        assert snap["count"] == 100
        assert 45 <= snap["p50"] <= 55
        assert snap["p99"] >= 95
        assert snap["max"] == 100.0
        assert snap["rate_per_s"] == pytest.approx(100 / 60.0, rel=1e-3)

    def test_windowed_counter_ages_out(self):
        wc = WindowedCounter(window_s=0.2, interval_s=0.05)
        wc.inc(3)
        assert wc.count() == 3
        time.sleep(0.45)
        assert wc.count() == 0  # outside the trailing window

    def test_windowed_histogram_ages_out(self):
        wh = WindowedHistogram(window_s=0.2, interval_s=0.05)
        wh.record(7.0)
        assert wh.snapshot()["count"] == 1
        time.sleep(0.45)
        assert wh.snapshot()["count"] == 0

    def test_registry_windows_expose_stat_gauges(self):
        registry = MetricsRegistry()
        wh = registry.windowed_histogram(
            "estpu_rest_latency_recent_ms", "t", endpoint="search"
        )
        wh.record(10.0)
        # Same (name, labels) returns the same window.
        again = registry.windowed_histogram(
            "estpu_rest_latency_recent_ms", "t", endpoint="search"
        )
        assert again is wh
        text = registry.exposition()
        assert 'estpu_rest_latency_recent_ms{endpoint="search",stat="p50"}' in text
        assert registry.window(
            "estpu_rest_latency_recent_ms", endpoint="search"
        ) is wh
        wc = registry.windowed_counter(
            "estpu_transport_events_recent", "t", event="reconnect"
        )
        wc.inc(4)
        assert registry.window_counts(
            "estpu_transport_events_recent", "event"
        ) == {"reconnect": 4.0}


# --------------------------------------------------------- indicator rules


def _state(term=3, master="node-0", unassigned=False, under_replicated=False):
    routing = ShardRouting(
        primary=None if unassigned else "node-0",
        replicas=[] if (unassigned or under_replicated) else ["node-1"],
        in_sync={"node-0", "node-1"},
    )
    meta = IndexMeta(
        name="h", mappings={}, n_shards=1, n_replicas=1,
        shards={0: routing},
    )
    return ClusterState(
        term=term,
        version=7,
        master=master,
        nodes={"node-0", "node-1"},
        seed_nodes=("node-0", "node-1", "node-2"),
        indices={"h": meta},
    )


def _ctx(state=None, **kw):
    defaults = dict(
        standalone=state is None,
        state=state,
        node_inputs={"node-0": {}},
        fanned=state is not None,
        expected_nodes=("node-0", "node-1", "node-2") if state else (),
    )
    defaults.update(kw)
    return HealthContext(**defaults)


class TestIndicatorRules:
    def test_every_indicator_registered_and_callable(self):
        from elasticsearch_tpu.obs import health

        for name in INDICATORS:
            assert callable(getattr(health, f"indicator_{name}"))

    def test_worst_ordering(self):
        assert worst(["green", "yellow"]) == "yellow"
        assert worst(["yellow", "red", "green"]) == "red"
        assert worst([]) == "green"

    def test_shard_summary_matches_cluster_health_semantics(self):
        assert shard_summary(None)["status"] == "red"
        assert shard_summary(_state())["status"] == "green"
        assert shard_summary(_state(unassigned=True))["status"] == "red"
        yellow = shard_summary(_state(under_replicated=True))
        assert yellow["status"] == "yellow"
        assert yellow["active_shards"] < yellow["desired_shards"]

    def test_shards_availability_names_dead_node(self):
        ctx = _ctx(
            _state(),
            fan_failures=[
                {"node": "node-1", "type": "ConnectTransportError",
                 "reason": "refused"}
            ],
        )
        out = indicator_shards_availability(ctx)
        assert out["status"] == "yellow"
        assert any("node-1" in d["cause"] for d in out["diagnosis"])
        assert any("restart" in d["action"] for d in out["diagnosis"])

    def test_shards_availability_red_names_indices(self):
        out = indicator_shards_availability(_ctx(_state(unassigned=True)))
        assert out["status"] == "red"
        assert any("['h']" in d["cause"] for d in out["diagnosis"])

    def test_master_stability_red_without_master(self):
        out = indicator_master_stability(_ctx(_state(master=None)))
        assert out["status"] == "red"
        assert out["impacts"] and out["diagnosis"]

    def test_master_stability_red_below_quorum(self):
        # 1 answering node of 3 seeds: below the quorum of 2.
        ctx = _ctx(
            _state(),
            node_inputs={"node-0": {}},
            fan_failures=[
                {"node": n, "type": "ConnectTransportError", "reason": "x"}
                for n in ("node-1", "node-2")
            ],
        )
        out = indicator_master_stability(ctx)
        assert out["status"] == "red"
        assert any("quorum" in d["cause"] for d in out["diagnosis"])

    def test_master_stability_yellow_on_reelection_churn(self):
        service = HealthService()
        inputs = {n: {} for n in ("node-0", "node-1", "node-2")}
        for term in (1, 2, 3):
            report = service.report(
                _ctx(_state(term=term), node_inputs=dict(inputs))
            )
        out = report["indicators"]["master_stability"]
        assert out["status"] == "yellow"
        assert any("term changed" in d["cause"] for d in out["diagnosis"])

    def test_device_memory_rules(self):
        # Near budget -> yellow.
        ctx = _ctx(node_inputs={"n": {
            "breaker": {
                "limit_size_in_bytes": 1000,
                "estimated_size_in_bytes": 950,
            },
            "hbm": {"breaker_drift_bytes": 0},
        }})
        assert indicator_device_memory(ctx)["status"] == "yellow"
        # Drift is ALWAYS red.
        ctx = _ctx(node_inputs={"n": {
            "breaker": {
                "limit_size_in_bytes": 1000,
                "estimated_size_in_bytes": 10,
            },
            "hbm": {"breaker_drift_bytes": 64},
        }})
        out = indicator_device_memory(ctx)
        assert out["status"] == "red"
        assert any("drift" in s for s in [out["symptom"]])
        # Recent trips -> yellow.
        ctx = _ctx(node_inputs={"n": {
            "breaker": {
                "limit_size_in_bytes": 1000,
                "estimated_size_in_bytes": 10,
            },
            "hbm": {"breaker_drift_bytes": 0},
            "breaker_trips_recent": 2,
        }})
        assert indicator_device_memory(ctx)["status"] == "yellow"
        # Eviction burst -> yellow.
        ctx = _ctx(node_inputs={"n": {
            "breaker": {
                "limit_size_in_bytes": 1000,
                "estimated_size_in_bytes": 10,
            },
            "hbm": {"breaker_drift_bytes": 0},
            "evictions_recent": {"filter": 200},
        }})
        out = indicator_device_memory(ctx)
        assert out["status"] == "yellow"
        assert "eviction burst" in out["symptom"]

    def test_device_memory_breaker_fuzz(self):
        """Near-budget fuzz: random fills on a real breaker flip the
        indicator exactly when usage crosses the yellow fraction."""
        import numpy as np

        from elasticsearch_tpu.common.breaker import (
            BreakerError,
            CircuitBreaker,
        )
        from elasticsearch_tpu.obs.health import HBM_YELLOW_FRACTION

        rng = np.random.default_rng(5)
        for _ in range(12):
            breaker = CircuitBreaker(10_000)
            target = int(rng.integers(1000, 10_000))
            filled = 0
            while filled < target:
                n = min(int(rng.integers(1, 2000)), target - filled)
                breaker.add(n, label="segment")
                filled += n
            ctx = _ctx(node_inputs={"n": {
                "breaker": breaker.stats(),
                "hbm": {"breaker_drift_bytes": 0},
                "breaker_trips_recent": breaker.trips_recent(),
            }})
            out = indicator_device_memory(ctx)
            expect = (
                "yellow"
                if filled >= 10_000 * HBM_YELLOW_FRACTION
                else "green"
            )
            assert out["status"] == expect, (filled, out["symptom"])
            # Overfill trips the breaker -> yellow regardless of level.
            with pytest.raises(BreakerError):
                breaker.add(20_000, label="segment")
            ctx = _ctx(node_inputs={"n": {
                "breaker": breaker.stats(),
                "hbm": {"breaker_drift_bytes": 0},
                "breaker_trips_recent": breaker.trips_recent(),
            }})
            assert indicator_device_memory(ctx)["status"] == "yellow"

    def test_exec_saturation_rules(self):
        base = {"batcher": {"quarantined_now": 0, "queued": 0}}
        assert (
            indicator_exec_saturation(_ctx(node_inputs={"n": dict(base)}))[
                "status"
            ]
            == "green"
        )
        ctx = _ctx(node_inputs={"n": {**base, "shed_recent": 3}})
        out = indicator_exec_saturation(ctx)
        assert out["status"] == "yellow" and "shed" in out["symptom"]
        ctx = _ctx(node_inputs={"n": {**base, "shed_recent": 500}})
        assert indicator_exec_saturation(ctx)["status"] == "red"
        ctx = _ctx(node_inputs={"n": {
            **base,
            "queue_wait_recent": {"p99": 400.0, "count": 9},
        }})
        out = indicator_exec_saturation(ctx)
        assert out["status"] == "yellow" and "p99" in out["symptom"]
        ctx = _ctx(node_inputs={"n": {
            "batcher": {"quarantined_now": 2, "queued": 0},
        }})
        out = indicator_exec_saturation(ctx)
        assert out["status"] == "yellow" and "quarantined" in out["symptom"]

    def test_device_compile_yellow_on_recent_launch_errors(self):
        from elasticsearch_tpu.obs.health import indicator_device_compile

        ctx = _ctx(node_inputs={"n": {
            "device_compile": {
                "compiles_by_plan_class": {"solo": 2},
                "retraced_plan_classes": {},
            },
            "launch_outcomes_recent": {"device": {"ok": 1, "error": 3}},
        }})
        out = indicator_device_compile(ctx)
        assert out["status"] == "yellow"
        assert "failed" in out["symptom"]
        assert out["details"]["launch_errors_recent"] == 3
        assert any("raising" in d["cause"] for d in out["diagnosis"])

    def test_transport_rules(self):
        ctx = _ctx(node_inputs={"n": {
            "transport": {"kind": "tcp"},
            "transport_events_recent": {"send_timeout": 2},
        }})
        out = indicator_transport(ctx)
        assert out["status"] == "yellow" and "timeout" in out["symptom"]
        ctx = _ctx(node_inputs={"n": {
            "transport_events_recent": {"handshake_reject": 1},
        }})
        assert indicator_transport(ctx)["status"] == "yellow"
        ctx = _ctx(node_inputs={"n": {
            "transport_events_recent": {"reconnect": 500},
        }})
        out = indicator_transport(ctx)
        assert out["status"] == "yellow" and "churn" in out["symptom"]
        # A kill blip's dozen-odd dials stays green (shards_availability
        # owns single-death findings, not the wire indicator).
        ctx = _ctx(node_inputs={"n": {
            "transport_events_recent": {"reconnect": 16},
        }})
        assert indicator_transport(ctx)["status"] == "green"
        ctx = _ctx(node_inputs={"n": {
            "mesh_breakers": {"idx": "open"},
        }})
        out = indicator_transport(ctx)
        assert out["status"] == "yellow"
        assert "mesh circuit breaker" in out["symptom"]


# ------------------------------------------------------- standalone node


class TestStandaloneReport:
    @pytest.fixture(scope="class")
    def rest(self):
        server = RestServer()
        server.node.create_index("hx", _mappings())
        server.node.index_doc(
            "hx", {"body": "alpha beta"}, "1", refresh=True
        )
        yield server
        server.close()

    def test_fresh_node_every_indicator_green_shape(self, rest):
        status, rep = rest.dispatch("GET", "/_health_report", {}, "")
        assert status == 200
        assert rep["status"] == "green"
        assert set(rep["indicators"]) == set(INDICATORS)
        for name, ind in rep["indicators"].items():
            assert ind["status"] == "green", name
            assert ind["symptom"]
            # Reference-shaped blocks present (empty when green).
            assert set(ind) == {
                "status", "symptom", "details", "impacts", "diagnosis",
            }
            assert ind["impacts"] == [] and ind["diagnosis"] == []
        assert "_nodes" not in rep  # standalone: nothing fanned

    def test_verbose_false_skips_detail_blocks(self, rest):
        status, rep = rest.dispatch(
            "GET", "/_health_report", {"verbose": "false"}, ""
        )
        assert status == 200
        for ind in rep["indicators"].values():
            assert set(ind) == {"status", "symptom"}

    def test_single_indicator_route_and_unknown_400(self, rest):
        status, rep = rest.dispatch(
            "GET", "/_health_report/device_memory", {}, ""
        )
        assert status == 200
        assert list(rep["indicators"]) == ["device_memory"]
        status, err = rest.dispatch(
            "GET", "/_health_report/bogus", {}, ""
        )
        assert status == 400
        assert err["error"]["type"] == "illegal_argument_exception"
        status, err = rest.dispatch(
            "GET", "/_health_report", {"verbose": "maybe"}, ""
        )
        assert status == 400

    def test_health_polling_does_not_churn_trace_ring(self, rest):
        def newest_ids():
            # Newest-first trace ids: the ring may already be at
            # capacity (process-global), so compare identities, not
            # counts.
            return [
                t["trace_id"]
                for t in rest.node.get_traces(limit=5)["traces"]
            ]

        before = newest_ids()
        for _ in range(5):
            status, _rep = rest.dispatch("GET", "/_health_report", {}, "")
            assert status == 200
        assert newest_ids() == before  # polls buffered NO traces
        # ... while an ordinary request DOES trace.
        rest.dispatch(
            "POST",
            "/hx/_search",
            {},
            json.dumps({"query": {"match": {"body": "alpha"}}}),
        )
        after = newest_ids()
        assert after != before
        assert after[0] not in before

    def test_endpoint_classes_split_reads_from_writes(self):
        from elasticsearch_tpu.rest.server import _endpoint_class

        assert _endpoint_class("/idx/_search", "POST") == "search"
        assert _endpoint_class("/idx/_knn_search", "GET") == "search"
        assert _endpoint_class("/idx/_doc/1", "GET") == "read"
        assert _endpoint_class("/idx/_doc/1", "HEAD") == "read"
        assert _endpoint_class("/idx/_mget", "POST") == "read"
        assert _endpoint_class("/idx/_doc/1", "PUT") == "write"
        assert _endpoint_class("/_bulk", "POST") == "write"
        assert _endpoint_class("/idx/_update/1", "POST") == "write"
        assert _endpoint_class("/_health_report", "GET") == "admin"
        assert _endpoint_class("/idx", "PUT") == "other"

    def test_rest_latency_window_records_by_endpoint_class(self, rest):
        rest.dispatch(
            "POST",
            "/hx/_search",
            {},
            json.dumps({"query": {"match": {"body": "alpha"}}}),
        )
        window = rest.node.metrics.window(
            "estpu_rest_latency_recent_ms", endpoint="search"
        )
        assert window is not None and window.snapshot()["count"] >= 1

    def test_seeded_retrace_defect_flips_device_compile(self, rest):
        """The PR-14 seeded shape-polymorphism defect: the SAME plan key
        launches a NEW shape — device_compile goes yellow NAMING the
        plan class."""
        import jax
        import jax.numpy as jnp

        node = rest.node
        f = jax.jit(lambda x: x * 3 + 1)
        with node.device.timed("healthpoly", ("healthpoly", 1), "device") as t:
            t.dispatched(f(jnp.ones(3)))
        assert t.first
        with node.device.timed("healthpoly", ("healthpoly", 1), "device") as t:
            t.dispatched(f(jnp.ones(9)))  # same key, new shape: retrace
        status, rep = rest.dispatch("GET", "/_health_report", {}, "")
        assert status == 200
        ind = rep["indicators"]["device_compile"]
        assert ind["status"] == "yellow"
        assert "healthpoly" in ind["symptom"]
        assert "healthpoly" in ind["details"]["retraced_plan_classes"]
        assert any("plan key" in d["cause"] for d in ind["diagnosis"])
        assert rep["status"] == "yellow"

    def test_health_section_and_metrics_exposed(self, rest):
        rest.dispatch("GET", "/_health_report", {}, "")
        stats = rest.node.nodes_stats()["nodes"][rest.node.node_name]
        section = stats["health"]
        assert section["reports_total"] >= 1
        assert set(section["indicators"]) == set(INDICATORS)
        status, payload = rest.dispatch("GET", "/_metrics", {}, "")
        assert status == 200
        assert "estpu_health_reports_total" in payload.text
        assert 'estpu_health_status{indicator="device_memory"}' in payload.text

    def test_cluster_health_is_view_of_shard_summary(self, rest):
        status, health = rest.dispatch("GET", "/_cluster/health", {}, "")
        assert status == 200
        assert health["status"] == "green"
        assert health["timed_out"] is False
        status, rows = rest.dispatch("GET", "/_cat/health", {}, "")
        assert rows[0]["status"] == health["status"]
        assert rows[0]["unassign"] == str(health["unassigned_shards"])

    def test_wait_for_status_satisfied_immediately(self, rest):
        status, health = rest.dispatch(
            "GET",
            "/_cluster/health",
            {"wait_for_status": "yellow", "timeout": "5s"},
            "",
        )
        assert status == 200
        assert health["timed_out"] is False  # green satisfies yellow

    def test_wait_for_status_rejects_bogus_value(self, rest):
        status, err = rest.dispatch(
            "GET", "/_cluster/health", {"wait_for_status": "purple"}, ""
        )
        assert status == 400
        assert err["error"]["type"] == "illegal_argument_exception"


class TestExecSaturationEndToEnd:
    def test_windowed_shed_flips_indicator(self):
        node = Node()
        try:
            # The batcher registered its windows at construction; drive
            # them the way a 429 storm would.
            shed = node.metrics.window("estpu_exec_batcher_shed_recent")
            assert shed is not None
            shed.inc(3)
            rep = node.health_report()
            ind = rep["indicators"]["exec_saturation"]
            assert ind["status"] == "yellow"
            assert "shed" in ind["symptom"]
        finally:
            node.close()

    def test_device_memory_drift_red_end_to_end(self):
        node = Node(breaker_limit_bytes=1_000_000)
        try:
            node.breaker.used += 123  # forge accounting drift
            rep = node.health_report()
            ind = rep["indicators"]["device_memory"]
            assert ind["status"] == "red"
            assert rep["status"] == "red"
            assert any(
                "bypassed the write-through ledger" in d["cause"]
                for d in ind["diagnosis"]
            )
        finally:
            node.close()


# ---------------------------------------------------------- query insights


class TestQueryInsights:
    def test_top_n_bound_and_ordering(self):
        ring = QueryInsights(capacity=3)
        for took in (5, 50, 10, 80, 1, 30):
            ring.record(index="i", took_ms=took)
        out = ring.queries()
        assert [e["took_ms"] for e in out] == [80, 50, 30]
        # A fast query cannot wash a slow exemplar out.
        ring.record(index="i", took_ms=2)
        assert [e["took_ms"] for e in ring.queries()] == [80, 50, 30]
        stats = ring.stats()
        assert stats["entries"] == 3 and stats["capacity"] == 3
        assert stats["min_retained_took_ms"] == 30

    def test_entry_shape_from_real_search(self):
        node = Node()
        try:
            node.create_index("qi", _mappings())
            node.index_doc("qi", {"body": "alpha beta"}, "1", refresh=True)
            node.search("qi", {"query": {"match": {"body": "alpha"}}})
            entries = node.query_insights()["queries"]
            assert entries
            entry = entries[0]
            assert entry["index"] == "qi"
            assert "took_ms" in entry and "timestamp_ms" in entry
            assert entry["shards"]["total"] == 1
            assert entry["trace_id"]  # the exemplar join key
            # Chosen backend(s) ride the phases hook.
            assert entry["backends"]
            assert "phases" in entry and "execute_ms" in entry["phases"]
            assert "alpha" in entry["source"]
        finally:
            node.close()

    def test_rest_route_and_stats_section(self):
        server = RestServer()
        try:
            server.node.create_index("qi2", _mappings())
            server.dispatch(
                "PUT", "/qi2/_doc/1", {},
                json.dumps({"body": "alpha"}),
            )
            server.dispatch("POST", "/qi2/_refresh", {}, "")
            server.dispatch(
                "POST", "/qi2/_search", {},
                json.dumps({"query": {"match": {"body": "alpha"}}}),
            )
            status, out = server.dispatch(
                "GET", "/_insights/queries", {"size": "1"}, ""
            )
            assert status == 200
            assert len(out["queries"]) == 1
            assert out["queries"][0]["index"] == "qi2"
            stats = server.node.nodes_stats()["nodes"][
                server.node.node_name
            ]
            assert stats["obs"]["insights"]["entries"] >= 1
        finally:
            server.close()


# ------------------------------------------------- LocalCluster REST front


class TestLocalClusterHealthArc:
    @pytest.fixture(scope="class")
    def rest(self):
        mesh = os.environ.get("ESTPU_MESH_SERVING")
        os.environ["ESTPU_MESH_SERVING"] = "0"
        server = RestServer(replication_nodes=3)
        server.dispatch("PUT", "/hobs", {}, REPLICATED_INDEX)
        server.dispatch(
            "PUT", "/hobs/_doc/1", {}, json.dumps({"b": "alpha"})
        )
        yield server
        server.close()
        if mesh is None:
            os.environ.pop("ESTPU_MESH_SERVING", None)
        else:
            os.environ["ESTPU_MESH_SERVING"] = mesh

    def _wait_green(self, rest, timeout_s=30.0):
        deadline = time.monotonic() + timeout_s
        while True:
            status, rep = rest.dispatch("GET", "/_health_report", {}, "")
            assert status == 200
            if rep["status"] == "green":
                return rep
            if time.monotonic() >= deadline:
                raise AssertionError(f"never green: {rep}")
            time.sleep(0.2)

    def test_green_report_carries_nodes_header(self, rest):
        rep = self._wait_green(rest)
        assert rep["_nodes"]["failed"] == 0
        assert set(rep["indicators"]) == set(INDICATORS)

    def test_kill_heal_arc_named_diagnosis_within_deadline(self, rest):
        self._wait_green(rest)
        rest.cluster.kill("node-2")
        try:
            t0 = time.monotonic()
            status, rep = rest.dispatch("GET", "/_health_report", {}, "")
            elapsed = time.monotonic() - t0
            assert status == 200
            assert elapsed < NODES_FAN_TIMEOUT_S + 3.0
            assert rep["status"] != "green"
            assert rep["_nodes"]["failed"] == 1
            assert rep["_nodes"]["failures"][0]["node"] == "node-2"
            sa = rep["indicators"]["shards_availability"]
            assert sa["status"] != "green"
            assert any("node-2" in d["cause"] for d in sa["diagnosis"])
            assert any("restart" in d["action"] for d in sa["diagnosis"])
            # wait_for_status=green times out HONESTLY (200 +
            # timed_out: true, never a 500) while the cluster is degraded
            # ... unless the stepper heals it within the wait, in which
            # case the poll returns green (both are correct; what the
            # contract forbids is an error).
            status, health = rest.dispatch(
                "GET",
                "/_cluster/health",
                {"wait_for_status": "green", "timeout": "200ms"},
                "",
            )
            assert status == 200
            assert health["timed_out"] or health["status"] == "green"
        finally:
            rest.cluster.restart("node-2")
        rep = self._wait_green(rest)
        assert rep["_nodes"]["failed"] == 0
        status, health = rest.dispatch(
            "GET",
            "/_cluster/health",
            {"wait_for_status": "green", "timeout": "30s"},
            "",
        )
        assert status == 200
        assert health["status"] == "green" and not health["timed_out"]


# ------------------------------------------------------ ProcCluster (2 OS
# processes + tiebreaker): the acceptance arc over real sockets.


@pytest.fixture(scope="module")
def procs():
    from elasticsearch_tpu.cluster.procs import ProcCluster

    cluster = ProcCluster(
        2, data_path=tempfile.mkdtemp(prefix="estpu-health-procs-")
    )
    yield cluster
    cluster.close()


class TestProcClusterHealthArc:
    def test_full_arc_green_kill9_named_diagnosis_heal_green(self, procs):
        procs.create_index(
            "h",
            n_shards=1,
            n_replicas=1,
            mappings={"properties": {"b": {"type": "text"}}},
        )
        procs.write("h", "d1", {"b": "alpha"})
        procs.wait_for(
            lambda: procs.health_report()["status"] == "green",
            timeout_s=30,
            what="green report",
        )
        rep = procs.health_report()
        assert rep["_nodes"] == {"total": 3, "successful": 3, "failed": 0}

        victim = procs.workers[1]
        procs.kill_9(victim)
        t0 = time.monotonic()
        rep = procs.health_report()
        elapsed = time.monotonic() - t0
        # Within the per-send deadline: a SIGKILL'd process degrades the
        # report with a named diagnosis, never a hang.
        assert elapsed < (procs.send_timeout_s or 5.0) + 3.0
        assert rep["status"] != "green"
        assert rep["_nodes"]["failed"] == 1
        assert rep["_nodes"]["failures"][0]["node"] == victim
        sa = rep["indicators"]["shards_availability"]
        assert sa["status"] != "green"
        assert any(victim in d["cause"] for d in sa["diagnosis"])

        # The cheap probe (verbose=false) skips the worker fan entirely:
        # statuses + symptoms only, still instant.
        terse = procs.health_report(verbose=False)
        assert set(terse["indicators"]) == set(INDICATORS)
        for ind in terse["indicators"].values():
            assert set(ind) == {"status", "symptom"}

        procs.restart(victim)
        procs.wait_for(
            lambda: procs.health_report()["status"] == "green",
            timeout_s=60,
            what="healed green report",
        )
        rep = procs.health_report()
        assert rep["status"] == "green"
        assert rep["_nodes"]["failed"] == 0


class TestProcClusterNoTiebreaker:
    def test_terse_probe_adopts_worker_state(self):
        """Without a supervisor-resident tiebreaker the probe has no
        local state; BOTH modes must adopt an answering worker's
        published state — a healthy cluster must never read red just
        because the cheap probe skipped the fan."""
        from elasticsearch_tpu.cluster.procs import ProcCluster

        procs = ProcCluster(
            2,
            data_path=tempfile.mkdtemp(prefix="estpu-health-notb-"),
            tiebreaker=False,
        )
        try:
            procs.create_index(
                "nt",
                n_shards=1,
                n_replicas=1,
                mappings={"properties": {"b": {"type": "text"}}},
            )
            procs.write("nt", "d1", {"b": "alpha"})
            procs.wait_for(
                lambda: procs.health_report()["status"] == "green",
                timeout_s=30,
                what="green report (no tiebreaker)",
            )
            terse = procs.health_report(verbose=False)
            assert terse["status"] == "green"
            assert (
                terse["indicators"]["master_stability"]["status"]
                == "green"
            )
        finally:
            procs.close()
