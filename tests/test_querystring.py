"""query_string / simple_query_string: parser semantics + device/oracle
parity.

Reference: index/query/QueryStringQueryBuilder, SimpleQueryStringBuilder.
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler, aggregate_field_stats
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.oracle import OracleSearcher

MAPPINGS = Mappings.from_json(
    {
        "properties": {
            "title": {"type": "text"},
            "body": {"type": "text"},
        }
    }
)


@pytest.fixture(scope="module")
def node():
    node = Node()
    node.create_index(
        "q",
        {
            "mappings": {
                "properties": {
                    "title": {"type": "text"},
                    "body": {"type": "text"},
                }
            }
        },
    )
    docs = [
        {"title": "quick brown fox", "body": "jumps over the lazy dog"},
        {"title": "lazy dog", "body": "sleeps all day long"},
        {"title": "brown bear", "body": "quick to anger"},
        {"title": "red fox", "body": "clever and quick"},
    ]
    for i, d in enumerate(docs):
        node.index_doc("q", d, f"d{i}")
    node.refresh("q")
    return node


def ids(r):
    return sorted(h["_id"] for h in r["hits"]["hits"])


def test_default_or_and_operators(node):
    r = node.search("q", {"query": {"query_string": {"query": "fox bear"}}})
    assert ids(r) == ["d0", "d2", "d3"]
    r = node.search(
        "q",
        {"query": {"query_string": {"query": "quick AND fox"}}},
    )
    assert ids(r) == ["d0", "d3"]
    r = node.search(
        "q",
        {
            "query": {
                "query_string": {
                    "query": "quick fox",
                    "default_operator": "AND",
                }
            }
        },
    )
    assert ids(r) == ["d0", "d3"]
    r = node.search(
        "q", {"query": {"query_string": {"query": "quick NOT fox"}}}
    )
    assert ids(r) == ["d2"]


def test_field_syntax_phrase_prefix_group(node):
    r = node.search(
        "q", {"query": {"query_string": {"query": "title:lazy"}}}
    )
    assert ids(r) == ["d1"]
    r = node.search(
        "q", {"query": {"query_string": {"query": '"lazy dog"'}}}
    )
    assert ids(r) == ["d0", "d1"]
    r = node.search(
        "q", {"query": {"query_string": {"query": "bro*"}}}
    )
    assert ids(r) == ["d0", "d2"]
    r = node.search(
        "q",
        {"query": {"query_string": {"query": "(bear OR sleeps) AND NOT red"}}},
    )
    assert ids(r) == ["d1", "d2"]


def test_fields_param_and_boost(node):
    r = node.search(
        "q",
        {
            "query": {
                "query_string": {"query": "quick", "fields": ["title"]}
            }
        },
    )
    assert ids(r) == ["d0"]
    r = node.search(
        "q",
        {
            "query": {
                "query_string": {
                    "query": "quick",
                    "fields": ["title^3", "body"],
                }
            }
        },
    )
    assert ids(r) == ["d0", "d2", "d3"]
    assert r["hits"]["hits"][0]["_id"] == "d0"  # title boost wins


def test_simple_query_string(node):
    r = node.search(
        "q",
        {
            "query": {
                "simple_query_string": {
                    "query": "quick -fox",
                    "fields": ["title", "body"],
                }
            }
        },
    )
    assert ids(r) == ["d2"]
    # ':' is literal text in the simple dialect (no field syntax): the
    # analyzer splits "title:lazy" into [title, lazy] and "lazy" matches
    r = node.search(
        "q",
        {"query": {"simple_query_string": {"query": "title:lazy"}}},
    )
    assert ids(r) == ["d0", "d1"]


def test_parse_errors(node):
    for bad in ["(unclosed", "[1 TO 5]", "AND"]:
        with pytest.raises(ApiError):
            node.search("q", {"query": {"query_string": {"query": bad}}})


def test_hyphenated_terms_are_not_exclusions(node):
    node.index_doc("q", {"title": "wi fi router"}, "hy", refresh=True)
    r = node.search(
        "q", {"query": {"query_string": {"query": "wi-fi",
                                         "fields": ["title"]}}}
    )
    assert "hy" in ids(r)  # analyzed to [wi, fi], OR-matched — not -fi
    # a -prefix AFTER whitespace still prohibits
    r = node.search(
        "q",
        {"query": {"query_string": {"query": "router -quick",
                                    "fields": ["title"]}}},
    )
    assert ids(r) == ["hy"]
    node.delete_doc("q", "hy", refresh=True)


def test_simple_dialect_never_raises(node):
    for garbage in ["foo(", 'un"closed', "AND", "a^", "(((", "[1 TO 2]"]:
        r = node.search(
            "q",
            {"query": {"simple_query_string": {"query": garbage,
                                               "fields": ["title"]}}},
        )
        assert "hits" in r  # degraded to plain text, no 400


def test_empty_fields_list_matches_nothing(node):
    r = node.search(
        "q", {"query": {"query_string": {"query": "fox", "fields": []}}}
    )
    assert r["hits"]["total"]["value"] == 0


def test_profile_agg_only_on_sharded_index():
    n2 = Node()
    n2.create_index(
        "pr", {"settings": {"index": {"number_of_shards": 2}},
               "mappings": {"properties": {"n": {"type": "long"}}}}
    )
    for i in range(8):
        n2.index_doc("pr", {"n": i}, f"d{i}")
    n2.refresh("pr")
    r1 = n2.search(
        "pr", {"size": 0, "profile": True,
               "aggs": {"m": {"max": {"field": "n"}}}}
    )
    assert r1["aggregations"]["m"]["value"] == 7.0  # no 500, no stale data
    assert "profile" not in r1 or r1["profile"]["shards"] is not None
    r2 = n2.search("pr", {"query": {"match_all": {}}, "profile": True})
    r3 = n2.search(
        "pr", {"size": 0, "profile": True,
               "aggs": {"m": {"max": {"field": "n"}}}}
    )
    assert r3.get("profile") != r2["profile"]  # never replays stale profiles


def test_device_oracle_parity():
    rng = np.random.default_rng(5)
    builder = SegmentBuilder(MAPPINGS)
    words = ["ant", "bee", "cow", "dog", "elk"]
    for i in range(100):
        builder.add(
            {
                "title": " ".join(rng.choice(words, rng.integers(1, 5))),
                "body": " ".join(rng.choice(words, rng.integers(1, 8))),
            },
            f"d{i}",
        )
    segment = builder.build()
    device = pack_segment(segment)
    stats = aggregate_field_stats([segment])
    compiler = Compiler(
        fields=device.fields,
        doc_values=device.doc_values,
        mappings=MAPPINGS,
        stats=stats,
    )
    oracle = OracleSearcher(segment, MAPPINGS)
    tree = bm25_device.segment_tree(device)
    for q_json in [
        {"query_string": {"query": "ant bee"}},
        {"query_string": {"query": "ant AND bee"}},
        {"query_string": {"query": "title:cow OR body:dog"}},
        {"query_string": {"query": '"ant bee" OR elk'}},
        {"simple_query_string": {"query": "ant +bee -cow",
                                 "fields": ["title", "body"]}},
    ]:
        query = parse_query(q_json)
        o_scores, o_ids, o_total = oracle.search(query, 20)
        compiled = compiler.compile(query)
        d_scores, d_ids, d_total = (
            np.asarray(x)
            for x in bm25_device.execute(tree, compiled.spec, compiled.arrays, 20)
        )
        n = min(20, o_total)
        assert int(d_total) == o_total, q_json
        np.testing.assert_array_equal(d_ids[:n], o_ids[:n], err_msg=str(q_json))
        np.testing.assert_array_equal(d_scores[:n], o_scores[:n])
