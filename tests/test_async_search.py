"""Async search (ISSUE 17): stored progressive searches.

Contracts under test:
- envelope shape: POST /{index}/_async_search returns
  `{id?, is_partial, is_running, response}` after
  wait_for_completion_timeout; completed-within-wait without
  keep_on_completion behaves like a synchronous search (no id left to
  GET);
- progressive partials: while running, `response` is the exact answer
  over the shards reduced so far (honest `_shards.successful`), and the
  COMPLETED response is bit-identical to the synchronous `_search`
  (ids, order, scores, agg values, shard math — `took` excluded, it
  measures a different execution);
- store lifecycle: keep_alive expiry GC, DELETE cancellation, the
  bounded store 429ing only when full of still-running entries;
- order-invariance fuzz: ProgressiveShardReduce renders bit-identically
  under every shard-completion order, at every prefix, across
  metric/percentile/terms agg families and field-sorted hits;
- chaos: an armed `async.reduce` fault degrades one shard into an
  honest failures[] entry instead of poisoning the stored search.
"""

import json
import os
import random
import time

import pytest

from elasticsearch_tpu.cluster import LocalCluster
from elasticsearch_tpu.exec.async_search import ProgressiveShardReduce
from elasticsearch_tpu.faults import REGISTRY, FaultSpec
from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.search.service import SearchRequest

N_DOCS = 48


def _fill(node, index, n_shards):
    node.create_index(
        index,
        {
            "settings": {"index": {"number_of_shards": n_shards}},
            "mappings": {
                "properties": {
                    "f": {"type": "keyword"},
                    "v": {"type": "integer"},
                    # Dyadic-safe floats: per-shard metric folds associate
                    # exactly, so the fuzz parity below is bit-exact.
                    "x": {"type": "float"},
                    "body": {"type": "text"},
                }
            },
        },
    )
    for i in range(N_DOCS):
        node.index_doc(
            index,
            {
                "f": f"k{i % 5}",
                "v": i,
                "x": i * 0.25,
                "body": f"word{i % 7} common text",
            },
            str(i),
        )
    node.refresh(index)


def _drain_async(n):
    """Wait for any still-running async runner threads before close."""
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if not n.tasks.list("indices:data/read/search[async]"):
            return
        time.sleep(0.05)


@pytest.fixture(scope="module")
def node():
    # The progressive sharded tier is the host-coordinator path; under
    # the conftest 8-device mesh a multi-shard index would otherwise be
    # mesh-served at create time and take the solo fallback.
    prev = os.environ.get("ESTPU_MESH_SERVING")
    os.environ["ESTPU_MESH_SERVING"] = "0"
    try:
        n = Node(data_path=None)
        _fill(n, "sh", 3)
        _fill(n, "solo", 1)
    finally:
        if prev is None:
            os.environ.pop("ESTPU_MESH_SERVING", None)
        else:
            os.environ["ESTPU_MESH_SERVING"] = prev
    yield n
    _drain_async(n)
    n.close()


def strip_took(resp: dict) -> dict:
    out = dict(resp)
    out.pop("took", None)
    return out


BODIES = [
    pytest.param(
        {"query": {"match_all": {}}, "size": 10, "sort": [{"v": "desc"}]},
        id="field-sorted",
    ),
    pytest.param(
        {"query": {"match": {"body": "word3"}}, "size": 8},
        id="relevance",
    ),
    pytest.param(
        {
            "size": 0,
            "aggs": {
                "byf": {"terms": {"field": "f"}},
                "sx": {"sum": {"field": "x"}},
                "mx": {"max": {"field": "v"}},
                "pv": {"percentiles": {"field": "v"}},
            },
        },
        id="agg-only",
    ),
    pytest.param(
        {
            "query": {"match": {"body": "common"}},
            "size": 5,
            "from": 3,
            "sort": [{"v": "asc"}],
            "aggs": {
                "byf": {
                    "terms": {"field": "f"},
                    "aggs": {"ax": {"avg": {"field": "x"}}},
                },
            },
        },
        id="paged-sorted-nested-aggs",
    ),
]


class TestEnvelope:
    def test_completed_within_wait_is_sync_shaped(self, node):
        body = {"query": {"match_all": {}}, "size": 5}
        sync = node.search("sh", dict(body))
        out = node.async_search_submit(
            "sh", dict(body), params={"wait_for_completion_timeout": "10s"}
        )
        # Completed inside the wait without keep_on_completion: nothing
        # stored, no id — the sync-search degenerate case.
        assert "id" not in out
        assert out["is_running"] is False
        assert out["is_partial"] is False
        assert out["start_time_in_millis"] <= out["completion_time_in_millis"]
        assert strip_took(out["response"]) == strip_took(sync)

    def test_running_envelope_and_blocking_get(self, node, monkeypatch):
        monkeypatch.setenv("ESTPU_ASYNC_PART_DELAY_MS", "250")
        body = {"query": {"match_all": {}}, "size": 6, "sort": [{"v": "asc"}]}
        sync = node.search("sh", dict(body))
        out = node.async_search_submit(
            "sh", dict(body), params={"wait_for_completion_timeout": "1ms"}
        )
        assert out["is_running"] is True
        assert out["is_partial"] is True
        assert "id" in out and "expiration_time_in_millis" in out
        # The blocking poll returns the completed search.
        got = node.async_search_get(
            out["id"], params={"wait_for_completion_timeout": "30s"}
        )
        assert got["is_running"] is False
        assert got["is_partial"] is False
        assert strip_took(got["response"]) == strip_took(sync)
        node.async_search_delete(out["id"])

    def test_partials_are_honest_prefixes(self, node, monkeypatch):
        monkeypatch.setenv("ESTPU_ASYNC_PART_DELAY_MS", "400")
        body = {"query": {"match_all": {}}, "size": 6}
        out = node.async_search_submit(
            "sh", dict(body), params={"wait_for_completion_timeout": "60ms"}
        )
        assert out["is_running"] is True
        shards = out["response"]["_shards"]
        # A partial names how many shards it actually covers.
        assert shards["total"] == 3
        assert 0 <= shards["successful"] < 3
        got = node.async_search_get(
            out["id"], params={"wait_for_completion_timeout": "30s"}
        )
        assert got["response"]["_shards"]["successful"] == 3
        node.async_search_delete(out["id"])

    def test_keep_on_completion_stores_the_result(self, node):
        body = {"query": {"match_all": {}}, "size": 3}
        out = node.async_search_submit(
            "sh",
            dict(body),
            params={
                "wait_for_completion_timeout": "10s",
                "keep_on_completion": "true",
            },
        )
        assert "id" in out and out["is_running"] is False
        got = node.async_search_get(out["id"])
        assert strip_took(got["response"]) == strip_took(out["response"])
        assert node.async_search_delete(out["id"]) == {"acknowledged": True}
        with pytest.raises(ApiError) as err:
            node.async_search_get(out["id"])
        assert err.value.status == 404

    def test_submit_errors_are_synchronous_400s(self, node):
        with pytest.raises(ApiError) as err:
            node.async_search_submit("sh", {"bogus_key": 1})
        assert err.value.status == 400
        with pytest.raises(ApiError) as err:
            node.async_search_submit("missing-index", {})
        assert err.value.status == 404


class TestParity:
    @pytest.mark.parametrize("body", BODIES)
    def test_sharded_completion_bit_identical_to_sync(self, node, body):
        sync = node.search("sh", dict(body))
        out = node.async_search_submit(
            "sh", dict(body), params={"wait_for_completion_timeout": "30s"}
        )
        assert out["is_running"] is False
        assert strip_took(out["response"]) == strip_took(sync)

    def test_solo_fallback_parity(self, node):
        # highlight is outside the progressive tier: the solo fallback
        # still serves it, one final part, bit-identical.
        body = {
            "query": {"match": {"body": "word2"}},
            "size": 5,
            "highlight": {"fields": {"body": {}}},
        }
        sync = node.search("solo", dict(body))
        out = node.async_search_submit(
            "solo", dict(body), params={"wait_for_completion_timeout": "30s"}
        )
        assert strip_took(out["response"]) == strip_took(sync)


class TestStoreLifecycle:
    def test_keep_alive_expiry_gc(self, node):
        body = {"query": {"match_all": {}}, "size": 1}
        out = node.async_search_submit(
            "sh",
            dict(body),
            params={
                "wait_for_completion_timeout": "10s",
                "keep_on_completion": "true",
                "keep_alive": "150ms",
            },
        )
        assert "id" in out
        time.sleep(0.3)
        with pytest.raises(ApiError) as err:
            node.async_search_get(out["id"])
        assert err.value.status == 404

    def test_get_extends_keep_alive(self, node):
        body = {"query": {"match_all": {}}, "size": 1}
        out = node.async_search_submit(
            "sh",
            dict(body),
            params={
                "wait_for_completion_timeout": "10s",
                "keep_on_completion": "true",
                "keep_alive": "200ms",
            },
        )
        got = node.async_search_get(out["id"], params={"keep_alive": "1h"})
        assert (
            got["expiration_time_in_millis"]
            > out["expiration_time_in_millis"]
        )
        time.sleep(0.3)  # would have expired under the original keep_alive
        assert node.async_search_get(out["id"])["is_running"] is False
        node.async_search_delete(out["id"])

    def test_delete_cancels_a_running_search(self, node, monkeypatch):
        monkeypatch.setenv("ESTPU_ASYNC_PART_DELAY_MS", "400")
        out = node.async_search_submit(
            "sh",
            {"query": {"match_all": {}}, "size": 1},
            params={"wait_for_completion_timeout": "40ms"},
        )
        assert out["is_running"] is True
        running = node.tasks.list("indices:data/read/search[async]")
        assert running, "the async runner must be a registered task"
        assert node.async_search_delete(out["id"]) == {"acknowledged": True}
        # The cancelled runner unregisters its task promptly.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if not node.tasks.list("indices:data/read/search[async]"):
                break
            time.sleep(0.05)
        assert not node.tasks.list("indices:data/read/search[async]")

    def test_store_full_of_running_429s(self, node, monkeypatch):
        monkeypatch.setenv("ESTPU_ASYNC_PART_DELAY_MS", "500")
        svc = node.async_search
        monkeypatch.setattr(svc, "max_stored", 2)
        ids = []
        try:
            for _ in range(2):
                out = node.async_search_submit(
                    "sh",
                    {"query": {"match_all": {}}, "size": 1},
                    params={"wait_for_completion_timeout": "1ms"},
                )
                ids.append(out["id"])
            with pytest.raises(ApiError) as err:
                node.async_search_submit(
                    "sh",
                    {"query": {"match_all": {}}, "size": 1},
                    params={"wait_for_completion_timeout": "1ms"},
                )
            assert err.value.status == 429
            assert (err.value.headers or {}).get("Retry-After")
        finally:
            for id_ in ids:
                try:
                    node.async_search_delete(id_)
                except ApiError:
                    pass

    def test_full_store_evicts_oldest_completed(self, node, monkeypatch):
        svc = node.async_search
        monkeypatch.setattr(svc, "max_stored", 2)
        params = {
            "wait_for_completion_timeout": "10s",
            "keep_on_completion": "true",
        }
        body = {"query": {"match_all": {}}, "size": 1}
        first = node.async_search_submit("sh", dict(body), params=params)
        second = node.async_search_submit("sh", dict(body), params=params)
        third = node.async_search_submit("sh", dict(body), params=params)
        # The oldest COMPLETED entry made room; the newest two remain.
        with pytest.raises(ApiError):
            node.async_search_get(first["id"])
        for out in (second, third):
            assert node.async_search_get(out["id"])["is_running"] is False
            node.async_search_delete(out["id"])


class TestReduceFuzz:
    """Order-invariance: ProgressiveShardReduce must render bit-exactly
    under EVERY shard-completion order, at every prefix."""

    def _captured_parts(self, node, body):
        """Run the real async runner and steal its reducer's per-shard
        parts — the same keyed hits + agg wires production the serving
        path uses."""
        out = node.async_search_submit(
            "sh",
            dict(body),
            params={
                "wait_for_completion_timeout": "30s",
                "keep_on_completion": "true",
            },
        )
        assert out["is_running"] is False
        entry = node.async_search._store[out["id"]]
        reduce = entry.reduce
        assert reduce is not None
        parts = dict(reduce._parts)
        skipped = dict(reduce._skipped)
        node.async_search_delete(out["id"])
        return out["response"], parts, skipped

    def _fresh_reduce(self, node, body):
        svc = node.indices["sh"]
        request = SearchRequest.from_json(dict(body))
        return ProgressiveShardReduce(
            request,
            from_=request.from_,
            size=request.size,
            n_shards=3,
            index_name="sh",
            mappings=svc.mappings,
            style="coordinator",
        )

    def _feed(self, reduce, parts, skipped, order):
        for sid in order:
            if sid in parts:
                total, max_score, keyed, wires, timed_out = parts[sid]
                reduce.add_part(
                    sid, total, max_score, keyed,
                    agg_wires=wires, timed_out=timed_out,
                )
            else:
                s_total, s_wires = skipped[sid]
                reduce.add_skipped(sid, total=s_total, agg_wires=s_wires)

    @pytest.mark.parametrize("body", BODIES)
    def test_random_orders_and_prefixes_converge(self, node, body):
        sync = node.search("sh", dict(body))
        final, parts, skipped = self._captured_parts(node, body)
        assert strip_took(final) == strip_took(sync)
        shard_ids = sorted(set(parts) | set(skipped))
        rng = random.Random(17)
        for _trial in range(6):
            order = list(shard_ids)
            rng.shuffle(order)
            reduce = self._fresh_reduce(node, body)
            for i, sid in enumerate(order):
                self._feed(reduce, parts, skipped, [sid])
                # Every prefix must render identically to an ascending-
                # order fold over the same subset: completion order can
                # never leak into the partial.
                ref = self._fresh_reduce(node, body)
                self._feed(ref, parts, skipped, sorted(order[: i + 1]))
                assert strip_took(reduce.render()) == strip_took(
                    ref.render()
                ), f"prefix {i + 1} of order {order} diverged"
            assert strip_took(reduce.render()) == strip_took(sync)

    def test_retried_shard_overwrites_its_slot(self, node):
        body = {"query": {"match_all": {}}, "size": 10}
        sync = node.search("sh", dict(body))
        _final, parts, skipped = self._captured_parts(node, body)
        reduce = self._fresh_reduce(node, body)
        order = sorted(set(parts) | set(skipped))
        self._feed(reduce, parts, skipped, order)
        # A gateway retry re-delivers shard 0: idempotent overwrite.
        self._feed(reduce, parts, skipped, [order[0]])
        assert strip_took(reduce.render()) == strip_took(sync)


class TestFaultDegradation:
    def test_armed_reduce_fault_degrades_one_shard(self, node):
        REGISTRY.put(FaultSpec(site="async.reduce", error_rate=1.0, count=1))
        try:
            out = node.async_search_submit(
                "sh",
                {"query": {"match_all": {}}, "size": 5},
                params={"wait_for_completion_timeout": "30s"},
            )
        finally:
            REGISTRY.clear()
        shards = out["response"]["_shards"]
        assert shards["failed"] == 1
        assert shards["successful"] == 2
        assert shards["failures"][0]["reason"]["type"] == "InjectedFaultError"

    def test_all_shards_failed_is_an_error_envelope(self, node):
        REGISTRY.put(FaultSpec(site="async.reduce", error_rate=1.0))
        try:
            out = node.async_search_submit(
                "sh",
                {"query": {"match_all": {}}, "size": 5},
                params={"wait_for_completion_timeout": "30s"},
            )
        finally:
            REGISTRY.clear()
        assert out["is_partial"] is True
        assert out["is_running"] is False
        assert out["error"]["status"] == 503
        assert out["error"]["type"] == "search_phase_execution_exception"


class TestReplicatedTier:
    @pytest.fixture(scope="class")
    def rnode(self):
        n = Node(data_path=None, replication=LocalCluster(3))
        n.create_index(
            "rep",
            {
                "settings": {
                    "index": {
                        "number_of_shards": 3,
                        "number_of_replicas": 1,
                    }
                },
                "mappings": {
                    "properties": {
                        "f": {"type": "keyword"},
                        "v": {"type": "integer"},
                    }
                },
            },
        )
        for i in range(30):
            n.index_doc("rep", {"f": f"k{i % 4}", "v": i}, str(i))
        n.refresh("rep")
        yield n
        n.close()

    def test_replicated_completion_parity(self, rnode):
        body = {
            "query": {"match_all": {}},
            "size": 7,
            "sort": [{"v": "asc"}],
            "aggs": {
                "byf": {"terms": {"field": "f"}},
                "mv": {"max": {"field": "v"}},
            },
        }
        sync = rnode.search("rep", dict(body))
        out = rnode.async_search_submit(
            "rep", dict(body), params={"wait_for_completion_timeout": "30s"}
        )
        assert out["is_running"] is False
        assert strip_took(out["response"]) == strip_took(sync)

    def test_replicated_progressive_partials(self, rnode, monkeypatch):
        monkeypatch.setenv("ESTPU_ASYNC_PART_DELAY_MS", "300")
        body = {"query": {"match_all": {}}, "size": 5}
        sync = rnode.search("rep", dict(body))
        out = rnode.async_search_submit(
            "rep", dict(body), params={"wait_for_completion_timeout": "50ms"}
        )
        assert out["is_running"] is True
        assert out["response"]["_shards"]["total"] == 3
        assert out["response"]["_shards"]["successful"] < 3
        got = rnode.async_search_get(
            out["id"], params={"wait_for_completion_timeout": "30s"}
        )
        assert strip_took(got["response"]) == strip_took(sync)
        rnode.async_search_delete(out["id"])


class TestRestApi:
    @pytest.fixture(scope="class")
    def rest(self):
        from elasticsearch_tpu.rest.server import RestServer

        rest = RestServer()
        status, _ = rest.dispatch(
            "PUT",
            "/ridx",
            {},
            json.dumps(
                {
                    "settings": {"index": {"number_of_shards": 2}},
                    "mappings": {
                        "properties": {"v": {"type": "integer"}}
                    },
                }
            ),
        )
        assert status == 200
        for i in range(12):
            rest.dispatch(
                "PUT", f"/ridx/_doc/{i}", {}, json.dumps({"v": i})
            )
        rest.dispatch("POST", "/ridx/_refresh", {}, "")
        yield rest
        rest.close()

    def test_rest_round_trip(self, rest):
        body = json.dumps(
            {"query": {"match_all": {}}, "size": 4, "sort": [{"v": "desc"}]}
        )
        status, sync = rest.dispatch("POST", "/ridx/_search", {}, body)
        assert status == 200
        status, out = rest.dispatch(
            "POST",
            "/ridx/_async_search",
            {
                "wait_for_completion_timeout": "30s",
                "keep_on_completion": "true",
            },
            body,
        )
        assert status == 200
        assert out["is_running"] is False
        assert strip_took(out["response"]) == strip_took(sync)
        status, got = rest.dispatch(
            "GET", f"/_async_search/{out['id']}", {}, ""
        )
        assert status == 200
        assert strip_took(got["response"]) == strip_took(sync)
        status, deleted = rest.dispatch(
            "DELETE", f"/_async_search/{out['id']}", {}, ""
        )
        assert status == 200 and deleted == {"acknowledged": True}
        status, _ = rest.dispatch(
            "GET", f"/_async_search/{out['id']}", {}, ""
        )
        assert status == 404
