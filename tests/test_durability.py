"""Durability: translog WAL, flush/commit, restart recovery.

The verdict's acceptance test: index, kill the process, restart, get
identical search results. Simulated both in-process (fresh Engine/Node over
the same data dir) and across real processes (subprocess kill -9).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.translog import Translog
from elasticsearch_tpu.node import Node


@pytest.fixture
def mappings():
    return Mappings.from_json(
        {
            "properties": {
                "body": {"type": "text"},
                "n": {"type": "long"},
            }
        }
    )


class TestTranslog:
    def test_append_sync_replay(self, tmp_path):
        tl = Translog(str(tmp_path / "tl"))
        for i in range(5):
            tl.add({"seqno": i, "op": "index", "id": str(i), "source": {"a": i}})
        tl.sync()
        tl.close()
        tl2 = Translog(str(tmp_path / "tl"))
        ops = list(tl2.replay(above_seqno=1))
        assert [op["seqno"] for op in ops] == [2, 3, 4]

    def test_roll_trims_generations(self, tmp_path):
        tl = Translog(str(tmp_path / "tl"))
        for i in range(3):
            tl.add({"seqno": i, "op": "index", "id": str(i), "source": {}})
        tl.roll(persisted_seqno=2)
        tl.add({"seqno": 3, "op": "index", "id": "3", "source": {}})
        tl.sync()
        assert [op["seqno"] for op in tl.replay(above_seqno=2)] == [3]
        # old generation file deleted
        assert not os.path.exists(str(tmp_path / "tl" / "translog-1.log"))
        tl.close()

    def test_torn_tail_skipped(self, tmp_path):
        tl = Translog(str(tmp_path / "tl"))
        tl.add({"seqno": 0, "op": "index", "id": "0", "source": {}})
        tl.sync()
        tl.close()
        # simulate a torn write: partial JSON at the tail
        gen = str(tmp_path / "tl" / "translog-1.log")
        with open(gen, "ab") as f:
            f.write(b'{"seqno": 1, "op": "in')
        tl2 = Translog(str(tmp_path / "tl"))
        assert [op["seqno"] for op in tl2.replay()] == [0]
        tl2.close()


class TestEngineRecovery:
    def test_unflushed_ops_replay_from_translog(self, tmp_path, mappings):
        path = str(tmp_path / "idx")
        e1 = Engine(mappings, data_path=path)
        e1.index({"body": "hello world", "n": 1}, "a")
        e1.index({"body": "hello there", "n": 2}, "b")
        e1.sync_translog()
        # no flush, no refresh — crash now
        e2 = Engine(mappings, data_path=path)
        assert e2.get("a") == {"body": "hello world", "n": 1}
        assert e2.get("b") == {"body": "hello there", "n": 2}
        assert e2.num_docs == 2  # replay ends with a refresh
        assert e2.max_seqno == e1.max_seqno

    def test_flush_then_restart(self, tmp_path, mappings):
        path = str(tmp_path / "idx")
        e1 = Engine(mappings, data_path=path)
        for i in range(20):
            e1.index({"body": f"doc number {i} common", "n": i}, f"d{i}")
        e1.flush()
        e1.delete("d3")
        e1.index({"body": "updated doc common", "n": 99}, "d4")
        e1.sync_translog()

        e2 = Engine(mappings, data_path=path)
        assert e2.get("d3") is None
        assert e2.get("d4") == {"body": "updated doc common", "n": 99}
        assert e2.num_docs == 19
        # search parity across restart
        from elasticsearch_tpu.search.service import SearchRequest, SearchService

        req = SearchRequest.from_json({"query": {"match": {"body": "common"}}, "size": 25})
        e1.refresh()
        h1 = SearchService(e1).search(req)
        h2 = SearchService(e2).search(req)
        assert [h.doc_id for h in h1.hits] == [h.doc_id for h in h2.hits]
        assert [h.score for h in h1.hits] == pytest.approx(
            [h.score for h in h2.hits]
        )

    def test_flush_is_idempotent_and_gc_safe(self, tmp_path, mappings):
        path = str(tmp_path / "idx")
        e1 = Engine(mappings, data_path=path)
        e1.index({"body": "one"}, "1")
        e1.flush()
        e1.flush()
        e1.index({"body": "two"}, "2")
        e1.flush()
        e2 = Engine(mappings, data_path=path)
        assert e2.num_docs == 2
        assert len(e2.segments) == 2

    def test_auto_id_counter_recovers(self, tmp_path, mappings):
        path = str(tmp_path / "idx")
        e1 = Engine(mappings, data_path=path)
        r1 = e1.index({"body": "x"})
        e1.sync_translog()
        e2 = Engine(mappings, data_path=path)
        r2 = e2.index({"body": "y"})
        assert r2["_id"] != r1["_id"]


class TestNodeRecovery:
    def test_node_restart_in_process(self, tmp_path):
        data = str(tmp_path / "data")
        n1 = Node(data_path=data)
        n1.create_index(
            "logs",
            {"mappings": {"properties": {"msg": {"type": "text"}}}},
        )
        for i in range(10):
            n1.index_doc("logs", {"msg": f"event {i} alpha"}, f"e{i}")
        n1.flush("logs")
        n1.index_doc("logs", {"msg": "late event alpha"}, "late")
        n1.close()

        n2 = Node(data_path=data)
        assert "logs" in n2.indices
        r = n2.search("logs", {"query": {"match": {"msg": "alpha"}}, "size": 20})
        ids = {h["_id"] for h in r["hits"]["hits"]}
        assert ids == {f"e{i}" for i in range(10)} | {"late"}

    def test_node_restart_subprocess_kill9(self, tmp_path):
        """The real thing: a REST node killed with SIGKILL mid-life."""
        data = str(tmp_path / "data")
        script = f"""
import sys
sys.path.insert(0, {json.dumps(os.getcwd())})
import jax
jax.config.update("jax_platforms", "cpu")
from elasticsearch_tpu.node import Node
node = Node(data_path={json.dumps(data)})
node.create_index("k", {{"mappings": {{"properties": {{"t": {{"type": "text"}}}}}}}})
for i in range(8):
    node.index_doc("k", {{"t": f"word {{i}}"}}, f"w{{i}}")
node.flush("k")
node.index_doc("k", {{"t": "word unflushed"}}, "w8")
print("READY", flush=True)
import time
time.sleep(30)
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", script],
            stdout=subprocess.PIPE,
            env=env,
        )
        try:
            line = proc.stdout.readline().decode()
            assert "READY" in line, line
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()

        n2 = Node(data_path=data)
        r = n2.search("k", {"query": {"match": {"t": "word"}}, "size": 20})
        ids = {h["_id"] for h in r["hits"]["hits"]}
        assert ids == {f"w{i}" for i in range(9)}
