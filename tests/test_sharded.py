"""Sharded (multi-device) search over the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.parallel.routing import murmur3_hash, shard_for_id
from elasticsearch_tpu.parallel.sharded import ShardedIndex
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.service import SearchRequest, SearchService

VOCAB = [
    "alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
    "hotel", "india", "juliet", "kilo", "lima",
]


def make_docs(n=200, seed=11):
    rng = np.random.default_rng(seed)
    mappings = Mappings(
        properties={
            "body": {"type": "text"},
            "tag": {"type": "keyword"},
            "rank": {"type": "long"},
        }
    )
    docs = []
    for i in range(n):
        docs.append(
            (
                f"doc{i}",
                {
                    "body": " ".join(rng.choice(VOCAB, rng.integers(3, 30))),
                    "tag": str(rng.choice(["red", "green", "blue"])),
                    "rank": int(rng.integers(0, 100)),
                },
            )
        )
    return mappings, docs


@pytest.fixture(scope="module")
def mesh():
    devices = np.array(jax.devices()[:8])
    return Mesh(devices, ("shard",))


@pytest.fixture(scope="module")
def sharded(mesh):
    mappings, docs = make_docs()
    return ShardedIndex.from_docs(docs, mappings, mesh), mappings, docs


def single_engine_reference(mappings, docs, query_json, k):
    """Single-shard reference via the engine/service path."""
    engine = Engine(mappings)
    for doc_id, src in docs:
        engine.index(src, doc_id)
    engine.refresh()
    service = SearchService(engine)
    resp = service.search(SearchRequest.from_json({"query": query_json, "size": k}))
    return resp


QUERIES = [
    {"match": {"body": "alpha"}},
    {"match": {"body": "alpha bravo charlie"}},
    {"bool": {"must": [{"match": {"body": "delta"}}], "filter": [{"term": {"tag": "red"}}]}},
    {"bool": {"must": [{"match": {"body": "echo foxtrot"}}], "must_not": [{"range": {"rank": {"lt": 50}}}]}},
    {"match_all": {}},
]


@pytest.mark.parametrize("query_json", QUERIES)
def test_sharded_matches_single_shard(sharded, query_json):
    """8-way sharded search must agree with the single-shard engine on
    totals, scores, and hit ids (global DFS stats make scores identical)."""
    index, mappings, docs = sharded
    k = 10
    scores, gids, total = index.search(parse_query(query_json), k)
    ref = single_engine_reference(mappings, docs, query_json, k)
    assert total == ref.total
    got_ids = []
    for g in gids:
        shard, local = index.locate(g)
        got_ids.append(index.segments[shard].ids[local])
    ref_ids = [h.doc_id for h in ref.hits]
    ref_scores = [h.score for h in ref.hits]
    # Scores must match to fp32 tolerance; ids must match except where equal
    # scores allow different (but valid) tie orders across shard layouts.
    np.testing.assert_allclose(scores, ref_scores[: len(scores)], rtol=1e-5, atol=1e-6)
    for got, want, s_got, s_want in zip(got_ids, ref_ids, scores, ref_scores):
        if got != want:
            assert s_got == pytest.approx(s_want, rel=1e-5), (
                f"different doc {got} vs {want} without a score tie"
            )


def test_sharded_total_and_k_trim(sharded):
    index, mappings, docs = sharded
    scores, gids, total = index.search(parse_query({"match": {"body": "zzz"}}), 10)
    assert total == 0 and len(scores) == 0


def test_murmur3_known_values():
    """Murmur3 x86_32 reference vectors (public algorithm test vectors)."""
    from elasticsearch_tpu.parallel.routing import murmur3_32

    assert murmur3_32(b"") == 0
    assert murmur3_32(b"hello") == 613153351
    # String routing hashes UTF-16-LE bytes, matching the reference's
    # Murmur3HashFunction two-bytes-per-char layout.
    assert murmur3_hash("") == 0
    assert murmur3_hash("hello") == murmur3_32("hello".encode("utf-16-le"))
    # Distribution sanity + floorMod semantics for negative hashes.
    shards = [shard_for_id(f"doc{i}", 8) for i in range(1000)]
    counts = np.bincount(shards, minlength=8)
    assert counts.min() > 60  # roughly uniform
    assert all(0 <= s < 8 for s in shards)


def test_routing_is_stable(sharded):
    index, mappings, docs = sharded
    for doc_id, _ in docs[:20]:
        s = shard_for_id(doc_id, index.n_shards)
        assert doc_id in index.segments[s].ids
