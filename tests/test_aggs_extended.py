"""percentiles / percentile_ranks / extended_stats / top_hits / composite
aggregations + f64-exact metric accumulation (VERDICT r4 items 6 and 8).

References: search/aggregations/metrics/PercentilesAggregationBuilder.java:62,
TopHitsAggregationBuilder.java:51, bucket/composite/
CompositeAggregationBuilder.java:35, metrics/InternalSum.java:22 (double
accumulation).
"""

import json

import numpy as np
import pytest

from elasticsearch_tpu.rest.server import RestServer

MAPPINGS = {
    "properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "rank": {"type": "long"},
        "price": {"type": "double"},
    }
}


@pytest.fixture(scope="module")
def rest():
    rest = RestServer()
    status, _ = rest.dispatch(
        "PUT", "/agx", {}, json.dumps({"mappings": MAPPINGS})
    )
    assert status == 200
    rng = np.random.default_rng(5)
    lines = []
    rows = []
    for i in range(500):
        r = int(rng.integers(0, 1000))
        p = round(float(rng.uniform(0, 100)), 2)
        t = ["x", "y", "z"][i % 3]
        rows.append((r, p, t))
        lines.append(json.dumps({"index": {"_id": f"d{i}"}}))
        lines.append(
            json.dumps({"body": "alpha beta", "tag": t, "rank": r, "price": p})
        )
    status, resp = rest.dispatch(
        "POST", "/agx/_bulk", {"refresh": "true"}, "\n".join(lines)
    )
    assert status == 200 and not resp["errors"]
    rest.rows = rows
    return rest


def search(rest, body, index="agx"):
    status, resp = rest.dispatch(
        "POST", f"/{index}/_search", {}, json.dumps(body)
    )
    assert status == 200, resp
    return resp


class TestF64Metrics:
    def test_sum_matches_numpy_f64_exactly(self, rest):
        prices = np.array([p for _, p, _ in rest.rows], dtype=np.float64)
        resp = search(rest, {"size": 0, "aggs": {"s": {"sum": {"field": "price"}}}})
        got = resp["aggregations"]["s"]["value"]
        expect = float(np.sum(prices))
        assert got == pytest.approx(expect, abs=np.spacing(expect))

    def test_f32_would_drift_f64_does_not(self):
        """Accumulating many small values: the old f32 device sum drifts
        user-visibly; the f64 host reduce matches numpy exactly."""
        rest = RestServer()
        rest.dispatch(
            "PUT", "/drift", {},
            json.dumps({"mappings": {"properties": {"v": {"type": "double"}}}}),
        )
        lines = []
        for i in range(20000):
            lines.append(json.dumps({"index": {"_id": f"v{i}"}}))
            lines.append(json.dumps({"v": 0.1}))
        status, resp = rest.dispatch(
            "POST", "/drift/_bulk", {"refresh": "true"}, "\n".join(lines)
        )
        assert status == 200 and not resp["errors"]
        resp = search(rest, {"size": 0, "aggs": {"s": {"sum": {"field": "v"}}}}, "drift")
        expect = float(np.sum(np.full(20000, 0.1, dtype=np.float64)))
        got = resp["aggregations"]["s"]["value"]
        assert got == pytest.approx(expect, abs=2 * np.spacing(expect))
        # And the f32 running total would NOT be this close:
        f32 = float(np.sum(np.full(20000, np.float32(0.1), dtype=np.float32)))
        assert abs(f32 - expect) > 1e-4

    def test_extended_stats(self, rest):
        prices = np.array([p for _, p, _ in rest.rows], dtype=np.float64)
        resp = search(
            rest,
            {"size": 0, "aggs": {"es": {"extended_stats": {"field": "price"}}}},
        )
        es = resp["aggregations"]["es"]
        assert es["count"] == len(prices)
        assert es["avg"] == pytest.approx(float(np.mean(prices)))
        assert es["variance"] == pytest.approx(float(np.var(prices)), rel=1e-9)
        assert es["std_deviation"] == pytest.approx(float(np.std(prices)), rel=1e-9)
        assert es["std_deviation_bounds"]["upper"] == pytest.approx(
            float(np.mean(prices) + 2 * np.std(prices)), rel=1e-9
        )


class TestPercentiles:
    def test_default_percents_match_numpy(self, rest):
        ranks = np.array([r for r, _, _ in rest.rows], dtype=np.float64)
        resp = search(
            rest, {"size": 0, "aggs": {"p": {"percentiles": {"field": "rank"}}}}
        )
        got = resp["aggregations"]["p"]["values"]
        for q in (1, 5, 25, 50, 75, 95, 99):
            assert got[f"{q}.0"] == pytest.approx(
                float(np.percentile(ranks, q)), rel=1e-12
            )

    def test_custom_percents_and_unkeyed(self, rest):
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "p": {
                        "percentiles": {
                            "field": "rank",
                            "percents": [50, 99.9],
                            "keyed": False,
                        }
                    }
                },
            },
        )
        vals = resp["aggregations"]["p"]["values"]
        assert [v["key"] for v in vals] == [50.0, 99.9]

    def test_under_filter_agg(self, rest):
        ranks = np.array(
            [r for r, _, t in rest.rows if t == "x"], dtype=np.float64
        )
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "only_x": {
                        "filter": {"term": {"tag": "x"}},
                        "aggs": {"p": {"percentiles": {"field": "rank"}}},
                    }
                },
            },
        )
        got = resp["aggregations"]["only_x"]["p"]["values"]
        assert got["50.0"] == pytest.approx(float(np.percentile(ranks, 50)))

    def test_percentile_ranks(self, rest):
        ranks = np.sort([r for r, _, _ in rest.rows])
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "pr": {
                        "percentile_ranks": {
                            "field": "rank",
                            "values": [250, 750],
                        }
                    }
                },
            },
        )
        got = resp["aggregations"]["pr"]["values"]
        expect = np.searchsorted(ranks, 250, side="right") / len(ranks) * 100
        assert got["250.0"] == pytest.approx(float(expect))

    def test_requires_values(self, rest):
        status, resp = rest.dispatch(
            "POST",
            "/agx/_search",
            {},
            json.dumps(
                {"size": 0, "aggs": {"pr": {"percentile_ranks": {"field": "rank"}}}}
            ),
        )
        assert status == 400


class TestTopHits:
    def test_top_level(self, rest):
        resp = search(
            rest,
            {
                "size": 0,
                "query": {"match": {"body": "alpha"}},
                "aggs": {"th": {"top_hits": {"size": 3}}},
            },
        )
        th = resp["aggregations"]["th"]["hits"]
        assert th["total"]["value"] == 500
        assert len(th["hits"]) == 3
        assert th["hits"][0]["_score"] == pytest.approx(th["max_score"])
        assert th["hits"][0]["_index"] == "agx"

    def test_under_terms_with_source_filter(self, rest):
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "tags": {
                        "terms": {"field": "tag"},
                        "aggs": {
                            "best": {
                                "top_hits": {"size": 2, "_source": ["rank"]}
                            }
                        },
                    }
                },
            },
        )
        for b in resp["aggregations"]["tags"]["buckets"]:
            th = b["best"]["hits"]
            assert th["total"]["value"] == b["doc_count"]
            assert len(th["hits"]) == 2
            for h in th["hits"]:
                assert set(h["_source"]) <= {"rank"}
                # Member docs really carry this bucket's tag.
                tag = next(
                    t for i, (_, _, t) in enumerate(rest.rows)
                    if f"d{i}" == h["_id"]
                )
                assert tag == b["key"]

    def test_under_range(self, rest):
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "bands": {
                        "range": {
                            "field": "rank",
                            "ranges": [{"to": 500}, {"from": 500}],
                        },
                        "aggs": {"top": {"top_hits": {"size": 1}}},
                    }
                },
            },
        )
        lo, hi = resp["aggregations"]["bands"]["buckets"]
        for b, pred in ((lo, lambda r: r < 500), (hi, lambda r: r >= 500)):
            assert b["top"]["hits"]["total"]["value"] == b["doc_count"]
            hit = b["top"]["hits"]["hits"][0]
            rank = next(
                r for i, (r, _, _) in enumerate(rest.rows)
                if f"d{i}" == hit["_id"]
            )
            assert pred(rank)

    def test_under_histogram(self, rest):
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "h": {
                        "histogram": {"field": "rank", "interval": 250},
                        "aggs": {"top": {"top_hits": {"size": 1}}},
                    }
                },
            },
        )
        for b in resp["aggregations"]["h"]["buckets"]:
            assert b["top"]["hits"]["total"]["value"] == b["doc_count"]


class TestComposite:
    def test_pagination_covers_everything_exactly_once(self, rest):
        import collections

        expect = collections.Counter()
        for r, _, t in rest.rows:
            expect[(t, (r // 250) * 250)] += 1
        seen = {}
        after = None
        pages = 0
        while True:
            comp = {
                "size": 3,
                "sources": [
                    {"t": {"terms": {"field": "tag"}}},
                    {"h": {"histogram": {"field": "rank", "interval": 250}}},
                ],
            }
            if after:
                comp["after"] = after
            resp = search(
                rest,
                {
                    "size": 0,
                    "aggs": {
                        "c": {
                            "composite": comp,
                            "aggs": {"ap": {"avg": {"field": "price"}}},
                        }
                    },
                },
            )
            agg = resp["aggregations"]["c"]
            if not agg["buckets"]:
                break
            pages += 1
            for b in agg["buckets"]:
                key = (b["key"]["t"], b["key"]["h"])
                assert key not in seen, "bucket repeated across pages"
                seen[key] = b["doc_count"]
                assert b["ap"]["value"] is not None
            after = agg.get("after_key")
            if after is None:
                break
        assert pages > 1
        assert seen == {(t, h): c for (t, h), c in expect.items()}

    def test_desc_order(self, rest):
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "c": {
                        "composite": {
                            "size": 100,
                            "sources": [
                                {"t": {"terms": {"field": "tag", "order": "desc"}}}
                            ],
                        }
                    }
                },
            },
        )
        keys = [b["key"]["t"] for b in resp["aggregations"]["c"]["buckets"]]
        assert keys == sorted(keys, reverse=True)

    def test_rejected_under_parent(self, rest):
        status, resp = rest.dispatch(
            "POST",
            "/agx/_search",
            {},
            json.dumps(
                {
                    "size": 0,
                    "aggs": {
                        "f": {
                            "filter": {"term": {"tag": "x"}},
                            "aggs": {
                                "c": {
                                    "composite": {
                                        "sources": [
                                            {"t": {"terms": {"field": "tag"}}}
                                        ]
                                    }
                                }
                            },
                        }
                    },
                }
            ),
        )
        assert status == 400

    def test_date_histogram_source(self, rest):
        rest2 = RestServer()
        rest2.dispatch(
            "PUT", "/dh", {},
            json.dumps(
                {"mappings": {"properties": {"ts": {"type": "date"}}}}
            ),
        )
        lines = []
        day = 86400000
        for i in range(6):
            lines.append(json.dumps({"index": {"_id": f"t{i}"}}))
            lines.append(json.dumps({"ts": (i % 3) * day}))
        status, resp = rest2.dispatch(
            "POST", "/dh/_bulk", {"refresh": "true"}, "\n".join(lines)
        )
        assert status == 200 and not resp["errors"]
        resp = search(
            rest2,
            {
                "size": 0,
                "aggs": {
                    "c": {
                        "composite": {
                            "sources": [
                                {
                                    "d": {
                                        "date_histogram": {
                                            "field": "ts",
                                            "fixed_interval": "1d",
                                        }
                                    }
                                }
                            ]
                        }
                    }
                },
            },
            "dh",
        )
        buckets = resp["aggregations"]["c"]["buckets"]
        assert [b["doc_count"] for b in buckets] == [2, 2, 2]
        assert [b["key"]["d"] for b in buckets] == [0, day, 2 * day]


class TestMultiShard:
    def test_new_aggs_across_shards(self):
        rest = RestServer()
        rest.dispatch(
            "PUT", "/m", {},
            json.dumps(
                {
                    "settings": {"index": {"number_of_shards": 4}},
                    "mappings": MAPPINGS,
                }
            ),
        )
        rng = np.random.default_rng(9)
        lines = []
        ranks = []
        for i in range(200):
            r = int(rng.integers(0, 100))
            ranks.append(r)
            lines.append(json.dumps({"index": {"_id": f"s{i}"}}))
            lines.append(
                json.dumps(
                    {"body": "w", "tag": ["a", "b"][i % 2], "rank": r,
                     "price": 1.5}
                )
            )
        status, resp = rest.dispatch(
            "POST", "/m/_bulk", {"refresh": "true"}, "\n".join(lines)
        )
        assert status == 200 and not resp["errors"]
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "p": {"percentiles": {"field": "rank", "percents": [50]}},
                    "s": {"sum": {"field": "price"}},
                    "th": {"top_hits": {"size": 2}},
                    "c": {
                        "composite": {
                            "size": 100,
                            "sources": [{"t": {"terms": {"field": "tag"}}}],
                        }
                    },
                },
            },
            "m",
        )
        aggs = resp["aggregations"]
        assert aggs["s"]["value"] == pytest.approx(300.0)
        assert aggs["p"]["values"]["50.0"] == pytest.approx(
            float(np.percentile(np.asarray(ranks, dtype=np.float64), 50))
        )
        assert aggs["th"]["hits"]["total"]["value"] == 200
        assert [b["doc_count"] for b in aggs["c"]["buckets"]] == [100, 100]


class TestContextMasks:
    def test_top_hits_under_terms_inside_filter_respects_context(self, rest):
        """Regression: the bucket top_hits of a terms agg nested in a
        filter parent must only see docs matching the filter."""
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "only_x": {
                        "filter": {"term": {"tag": "x"}},
                        "aggs": {
                            "bands": {
                                "range": {
                                    "field": "rank",
                                    "ranges": [{"to": 500}, {"from": 500}],
                                },
                                "aggs": {"th": {"top_hits": {"size": 3}}},
                            }
                        },
                    }
                },
            },
        )
        bands = resp["aggregations"]["only_x"]["bands"]["buckets"]
        for b in bands:
            th = b["th"]["hits"]
            assert th["total"]["value"] == b["doc_count"]
            for h in th["hits"]:
                i = int(h["_id"][1:])
                assert rest.rows[i][2] == "x", "doc outside filter context"

    def test_top_hits_under_calendar_date_histogram(self):
        rest = RestServer()
        rest.dispatch(
            "PUT", "/cal", {},
            json.dumps({"mappings": {"properties": {"ts": {"type": "date"}}}}),
        )
        lines = []
        month = 32 * 86400000
        for i in range(6):
            lines.append(json.dumps({"index": {"_id": f"c{i}"}}))
            lines.append(json.dumps({"ts": (i % 3) * month}))
        status, resp = rest.dispatch(
            "POST", "/cal/_bulk", {"refresh": "true"}, "\n".join(lines)
        )
        assert status == 200 and not resp["errors"]
        resp = search(
            rest,
            {
                "size": 0,
                "aggs": {
                    "m": {
                        "date_histogram": {
                            "field": "ts",
                            "calendar_interval": "month",
                        },
                        "aggs": {"th": {"top_hits": {"size": 1}}},
                    }
                },
            },
            "cal",
        )
        for b in resp["aggregations"]["m"]["buckets"]:
            assert b["th"]["hits"]["total"]["value"] == b["doc_count"]

    def test_malformed_decay_body_400(self, rest):
        status, resp = rest.dispatch(
            "POST",
            "/agx/_search",
            {},
            json.dumps(
                {"query": {"function_score": {"gauss": {"rank": 5}}}}
            ),
        )
        assert status == 400
