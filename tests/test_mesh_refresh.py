"""Segment-granular mesh refresh (ISSUE 12, the mesh half).

A one-doc write + refresh on a multi-shard mesh index must cost the
delta, not the index:

- only the OWNING shard re-merges and re-packs (the other shards'
  buffers are reused — `estpu_mesh_segments_reused_total`);
- within the re-packed shard, device planes of untouched fields are
  shared with the previous snapshot (`pack_segment_delta` — counted
  as `estpu_mesh_field_planes_reused_total`);
- the merge itself never tokenizes (posting concatenation with per-
  handle piece caching, hook-counted via estpu_analysis_calls_total);
- filter-cache mask ROWS of unchanged shards keep hitting across the
  refresh (keyed by (handle uid, live epoch) signatures; the old
  generation-sum key killed every stacked plane on any refresh);
- and results stay bit-identical to the host-loop coordinator.
"""

import json

import numpy as np
import pytest

from elasticsearch_tpu.analysis.analyzers import analysis_calls_total
from elasticsearch_tpu.rest.server import RestServer

WORDS = ["ant", "bee", "cat", "dog", "elk", "fox", "gnu", "hen"]

MAPPINGS = {
    "properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "rank": {"type": "long"},
    }
}


@pytest.fixture()
def rest(monkeypatch):
    monkeypatch.setenv("ESTPU_FILTER_CACHE_MIN_FREQ", "1")
    rest = RestServer()
    status, _ = rest.dispatch(
        "PUT",
        "/mesh",
        {},
        json.dumps(
            {
                "settings": {"index": {"number_of_shards": 8}},
                "mappings": MAPPINGS,
            }
        ),
    )
    assert status == 200
    rng = np.random.default_rng(23)
    lines = []
    for i in range(160):
        lines.append(json.dumps({"index": {"_id": f"d{i}"}}))
        lines.append(
            json.dumps(
                {
                    "body": " ".join(rng.choice(WORDS, rng.integers(2, 9))),
                    "tag": str(rng.choice(["x", "y", "z"])),
                    "rank": int(rng.integers(0, 500)),
                }
            )
        )
    status, resp = rest.dispatch(
        "POST", "/mesh/_bulk", {"refresh": "true"}, "\n".join(lines)
    )
    assert status == 200 and not resp["errors"]
    return rest


def mesh_view(rest):
    mv = rest.node.get_index("mesh").search.mesh_view
    assert mv is not None, "8-device CPU mesh should enable SPMD serving"
    return mv


def serve(rest, body):
    status, resp = rest.dispatch(
        "POST", "/mesh/_search", {}, json.dumps(body)
    )
    assert status == 200, resp
    rest.node.request_cache.clear()
    return resp


def host_answer(rest, body):
    """The same request through the host-loop coordinator."""
    svc = rest.node.get_index("mesh")
    mv = svc.search.mesh_view
    svc.search.mesh_view = None
    try:
        return serve(rest, body)
    finally:
        svc.search.mesh_view = mv


def hits_sig(resp):
    return (
        resp["hits"]["total"]["value"],
        [
            (h["_id"], h.get("_score"), tuple(h.get("sort", ())))
            for h in resp["hits"]["hits"]
        ],
    )


MATCH = {"query": {"match": {"body": "bee cat"}}, "size": 20}
FILTERED = {
    "query": {
        "bool": {
            "must": [{"match": {"body": "ant"}}],
            "filter": [
                {"term": {"tag": "x"}},
                {"range": {"rank": {"lt": 100000}}},
            ],
        }
    },
    "size": 20,
}


def test_one_doc_refresh_repacks_one_shard_and_reuses_planes(rest):
    mv = mesh_view(rest)
    serve(rest, MATCH)  # builds the snapshot (8 packs)
    assert mv.served >= 1
    packs0, reuses0 = mv.packs, mv.seg_reuses
    # One-doc write + refresh: exactly one shard owns the doc. The doc
    # carries ONLY `body`, so the owning shard's tag/rank planes are
    # byte-identical after the merge and their uploads are skipped.
    rest.dispatch(
        "PUT",
        "/mesh/_doc/delta1",
        {"refresh": "true"},
        json.dumps({"body": "bee delta"}),
    )
    resp = serve(rest, MATCH)
    assert mv.packs == packs0 + 1, "only the owning shard re-packs"
    assert mv.seg_reuses == reuses0 + 7, "the other 7 shards reuse buffers"
    # Within the re-packed shard, untouched planes (other fields) were
    # shared with the previous snapshot, not re-uploaded.
    reused = mv.metrics.value("estpu_mesh_field_planes_reused_total")
    assert reused > 0
    # Bit-identical to the host loop after the delta refresh.
    assert hits_sig(resp) == hits_sig(host_answer(rest, MATCH))


def test_mesh_refresh_and_serve_do_zero_analysis(rest):
    mv = mesh_view(rest)
    serve(rest, MATCH)  # initial snapshot built
    rest.dispatch(
        "PUT",
        "/mesh/_doc/delta2",
        {"refresh": "true"},
        json.dumps({"body": "cat delta", "tag": "y", "rank": 9}),
    )
    served0 = mv.served
    before = analysis_calls_total()
    # A term query analyzes nothing; the mesh re-merge + repack of the
    # delta shard must add ZERO analysis calls (pure posting concat).
    resp = serve(rest, {"query": {"term": {"tag": "y"}}, "size": 5})
    assert mv.served == served0 + 1
    assert analysis_calls_total() == before
    assert resp["hits"]["total"]["value"] > 0


def test_filter_rows_of_unchanged_shards_survive_refresh(rest):
    mv = mesh_view(rest)
    cache = rest.node.filter_cache
    assert cache is not None
    # Admission (sighting 1) + build/store (sighting 2 hits min_freq=1
    # immediately; the second serve substitutes cached rows).
    cold = serve(rest, FILTERED)
    warm = serve(rest, FILTERED)
    assert hits_sig(cold) == hits_sig(warm)
    row_keys0 = {
        k for k in cache.keys()
        if isinstance(k[1], tuple) and k[1][0] == "row"
    }
    assert len(row_keys0) >= 8, "one mask row per shard should be cached"
    # One-doc write + refresh: exactly one shard's signature moves.
    rest.dispatch(
        "PUT",
        "/mesh/_doc/delta3",
        {"refresh": "true"},
        json.dumps({"body": "ant delta", "tag": "x", "rank": 3}),
    )
    hits0 = cache.stats()["hit_count"]
    after = serve(rest, FILTERED)
    row_keys1 = {
        k for k in cache.keys()
        if isinstance(k[1], tuple) and k[1][0] == "row"
    }
    # Per cached filter: 7 of the 8 rows survived the refresh (same
    # (uid, epoch) sigs); the delta shard minted a fresh row; the dead
    # row purged eagerly on the snapshot change.
    n_filters = len(row_keys0) // 8
    assert len(row_keys0 & row_keys1) == 7 * n_filters
    assert len(row_keys1 - row_keys0) == n_filters
    assert cache.stats()["hit_count"] > hits0
    # Parity after the delta, cached rows substituted.
    assert hits_sig(after) == hits_sig(host_answer(rest, FILTERED))


def test_filtered_parity_fuzz_across_refreshes(rest):
    """Ingest-while-serving in miniature: interleave writes/refreshes
    with filtered searches; every mesh answer must equal the host loop
    bit-exactly while warm rows keep serving."""
    rng = np.random.default_rng(5)
    mv = mesh_view(rest)
    for round_ in range(6):
        doc_id = f"ingest{round_}"
        rest.dispatch(
            "PUT",
            f"/mesh/_doc/{doc_id}",
            {"refresh": "true"},
            json.dumps(
                {
                    "body": " ".join(rng.choice(WORDS, rng.integers(2, 9))),
                    "tag": str(rng.choice(["x", "y", "z"])),
                    "rank": int(rng.integers(0, 500)),
                }
            ),
        )
        for body in (MATCH, FILTERED):
            got = serve(rest, body)
            want = host_answer(rest, body)
            assert hits_sig(got) == hits_sig(want), (round_, body)
    assert mv.served >= 12
    stats = rest.node.filter_cache.stats()
    assert stats["hit_count"] > 0


def test_deletes_flow_through_row_cache(rest):
    """A delete + refresh bumps the owning handle's live epoch: its
    shard re-packs, rows re-key, and results stay host-identical."""
    mv = mesh_view(rest)
    serve(rest, FILTERED)
    serve(rest, FILTERED)
    packs0 = mv.packs
    rest.dispatch("DELETE", "/mesh/_doc/d3", {"refresh": "true"}, "")
    got = serve(rest, FILTERED)
    assert mv.packs > packs0
    assert all(h["_id"] != "d3" for h in got["hits"]["hits"])
    assert hits_sig(got) == hits_sig(host_answer(rest, FILTERED))
    match_all = {"query": {"match_all": {}}, "size": 0}
    assert (
        serve(rest, match_all)["hits"]["total"]["value"]
        == host_answer(rest, match_all)["hits"]["total"]["value"]
    )
