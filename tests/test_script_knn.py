"""script_score (painless-lite), brute-force kNN, and rescore."""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.script import compile_script
from elasticsearch_tpu.search.oracle import OracleSearcher
from elasticsearch_tpu.search.service import SearchRequest, SearchService


def test_painless_lite_basics():
    s = compile_script("params.w1 * _score + params.w2")
    out = s.evaluate(
        np, np.array([1.0, 2.0], np.float32), {}, {}, {"w1": 2.0, "w2": 0.5}
    )
    np.testing.assert_allclose(out, [2.5, 4.5])


def test_painless_lite_doc_access_and_math():
    s = compile_script("Math.log(doc['pop'].value + 1) * _score")
    out = s.evaluate(
        np,
        np.array([1.0, 1.0], np.float32),
        {"pop": np.array([0.0, np.e - 1], np.float32)},
        {},
        {},
    )
    np.testing.assert_allclose(out, [0.0, 1.0], atol=1e-6)


def test_painless_lite_ternary_vectorized():
    s = compile_script("doc['x'].value > 1 ? _score * 2 : _score")
    out = s.evaluate(
        np,
        np.array([1.0, 1.0], np.float32),
        {"x": np.array([0.0, 5.0], np.float32)},
        {},
        {},
    )
    np.testing.assert_allclose(out, [1.0, 2.0])


def test_painless_lite_rejects_malicious():
    with pytest.raises(ValueError):
        compile_script("__import__('os').system('x')")
    with pytest.raises(ValueError):
        compile_script("[x for x in range(10)]")
    with pytest.raises(ValueError):
        compile_script("lambda: 1")


def test_painless_lite_return_form():
    s = compile_script("return _score + 1;")
    np.testing.assert_allclose(
        s.evaluate(np, np.array([1.0], np.float32), {}, {}, {}), [2.0]
    )


@pytest.fixture(scope="module")
def vector_corpus():
    rng = np.random.default_rng(3)
    mappings = Mappings(
        properties={
            "title": {"type": "text"},
            "vec": {"type": "dense_vector", "dims": 16},
            "pop": {"type": "double"},
        }
    )
    builder = SegmentBuilder(mappings)
    words = ["apple", "banana", "cherry", "date", "elder"]
    for i in range(200):
        builder.add(
            {
                "title": " ".join(rng.choice(words, 4)),
                "vec": rng.normal(size=16).astype(np.float32).tolist(),
                "pop": float(rng.random()),
            },
            f"d{i}",
        )
    segment = builder.build()
    dev = pack_segment(segment)
    return (
        mappings,
        segment,
        bm25_device.segment_tree(dev),
        Compiler(dev.fields, dev.doc_values, mappings),
        OracleSearcher(segment, mappings),
    )


def run_parity(vector_corpus, query_json, k=10, rtol=1e-5):
    _, _, seg_tree, compiler, oracle = vector_corpus
    q = parse_query(query_json)
    c = compiler.compile(q)
    ds, di, dt = bm25_device.execute(seg_tree, c.spec, c.arrays, k)
    os_, oi, ot = oracle.search(q, k)
    n = min(k, int(dt))
    assert int(dt) == ot
    np.testing.assert_array_equal(np.asarray(di)[:n], oi)
    np.testing.assert_allclose(np.asarray(ds)[:n], os_, rtol=rtol, atol=1e-5)


def test_knn_cosine_script_score(vector_corpus):
    _, segment, *_ = vector_corpus
    qv = segment.vectors["vec"][7].tolist()  # query with a known doc's vector
    run_parity(
        vector_corpus,
        {
            "script_score": {
                "query": {"match_all": {}},
                "script": {
                    "source": "cosineSimilarity(params.qv, 'vec') + 1.0",
                    "params": {"qv": qv},
                },
            }
        },
    )


def test_knn_exact_self_match(vector_corpus):
    """The doc whose vector equals the query must rank first (cos = 1)."""
    _, segment, seg_tree, compiler, _ = vector_corpus
    qv = segment.vectors["vec"][7].tolist()
    q = parse_query(
        {
            "script_score": {
                "query": {"match_all": {}},
                "script": {
                    "source": "cosineSimilarity(params.qv, 'vec') + 1.0",
                    "params": {"qv": qv},
                },
            }
        }
    )
    c = compiler.compile(q)
    ds, di, dt = bm25_device.execute(seg_tree, c.spec, c.arrays, 3)
    assert int(np.asarray(di)[0]) == 7
    assert np.asarray(ds)[0] == pytest.approx(2.0, rel=1e-5)


def test_knn_dot_and_l2(vector_corpus):
    _, segment, *_ = vector_corpus
    qv = segment.vectors["vec"][0].tolist()
    run_parity(
        vector_corpus,
        {
            "script_score": {
                "query": {"match_all": {}},
                "script": {
                    "source": "dotProduct(params.qv, 'vec')",
                    "params": {"qv": qv},
                },
                "min_score": 0.0,
            }
        },
    )
    run_parity(
        vector_corpus,
        {
            "script_score": {
                "query": {"match_all": {}},
                "script": {
                    "source": "1 / (1 + l2norm(params.qv, 'vec'))",
                    "params": {"qv": qv},
                },
            }
        },
    )


def test_script_score_over_bm25_subquery(vector_corpus):
    """BASELINE config 4 shape: linear re-rank of BM25 scores."""
    run_parity(
        vector_corpus,
        {
            "script_score": {
                "query": {"match": {"title": "apple banana"}},
                "script": {
                    "source": "params.w1 * _score + params.w2 * doc['pop'].value",
                    "params": {"w1": 0.8, "w2": 2.0},
                },
            }
        },
    )


def make_service():
    mappings = Mappings(
        properties={"title": {"type": "text"}, "pop": {"type": "double"}}
    )
    engine = Engine(mappings)
    docs = [
        ("a", "red fox", 0.9),
        ("b", "red red fox", 0.1),
        ("c", "red dog", 0.5),
        ("d", "blue fish", 0.99),
    ]
    for doc_id, title, pop in docs:
        engine.index({"title": title, "pop": pop}, doc_id)
    engine.refresh()
    return SearchService(engine)


def test_rescore_total_mode():
    svc = make_service()
    base = svc.search(SearchRequest.from_json({"query": {"match": {"title": "red"}}}))
    resp = svc.search(
        SearchRequest.from_json(
            {
                "query": {"match": {"title": "red"}},
                "rescore": {
                    "window_size": 10,
                    "query": {
                        "rescore_query": {
                            "script_score": {
                                "query": {"match_all": {}},
                                "script": {"source": "doc['pop'].value * 10"},
                            }
                        },
                        "query_weight": 0.0,
                        "rescore_query_weight": 1.0,
                    },
                },
            }
        )
    )
    assert {h.doc_id for h in resp.hits} == {h.doc_id for h in base.hits}
    # With query_weight 0 the order is purely by pop desc.
    assert [h.doc_id for h in resp.hits] == ["a", "c", "b"]
    assert resp.hits[0].score == pytest.approx(9.0)


def test_rescore_window_limits_reordering():
    svc = make_service()
    resp = svc.search(
        SearchRequest.from_json(
            {
                "query": {"match": {"title": "red"}},
                "rescore": {
                    "window_size": 2,
                    "query": {
                        "rescore_query": {
                            "script_score": {
                                "query": {"match_all": {}},
                                "script": {"source": "doc['pop'].value * 10"},
                            }
                        },
                        "query_weight": 0.0,
                    },
                },
            }
        )
    )
    base = svc.search(SearchRequest.from_json({"query": {"match": {"title": "red"}}}))
    # Only the top-2 of the original ranking were eligible to reorder; the
    # third hit stays third.
    assert resp.hits[2].doc_id == base.hits[2].doc_id
