"""Ingest pipelines: processors, CRUD, write-path wiring, simulate.

Reference: ingest/IngestService.java, modules/ingest-common processors.
"""

import json

import pytest

from elasticsearch_tpu.ingest import Pipeline, PipelineError
from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.rest.server import RestServer


def run(processors, doc):
    return Pipeline("t", {"processors": processors}).run(doc)


def test_basic_processors():
    assert run([{"set": {"field": "a", "value": 5}}], {}) == {"a": 5}
    assert run([{"remove": {"field": "a"}}], {"a": 1, "b": 2}) == {"b": 2}
    assert run(
        [{"rename": {"field": "a", "target_field": "b"}}], {"a": 1}
    ) == {"b": 1}
    assert run([{"lowercase": {"field": "s"}}], {"s": "ABC"}) == {"s": "abc"}
    assert run([{"uppercase": {"field": "s"}}], {"s": "abc"}) == {"s": "ABC"}
    assert run([{"trim": {"field": "s"}}], {"s": " x "}) == {"s": "x"}
    assert run(
        [{"convert": {"field": "n", "type": "integer"}}], {"n": "42"}
    ) == {"n": 42}
    assert run(
        [{"split": {"field": "s", "separator": ","}}], {"s": "a,b,c"}
    ) == {"s": ["a", "b", "c"]}
    assert run(
        [{"join": {"field": "s", "separator": "-"}}], {"s": ["a", "b"]}
    ) == {"s": "a-b"}
    assert run(
        [{"append": {"field": "tags", "value": "new"}}], {"tags": ["old"]}
    ) == {"tags": ["old", "new"]}
    assert run(
        [{"gsub": {"field": "s", "pattern": r"\d+", "replacement": "#"}}],
        {"s": "a1b22c"},
    ) == {"s": "a#b#c"}


def test_templates_dots_missing_and_failures():
    # {{field}} templates and dotted paths
    assert run(
        [{"set": {"field": "greeting", "value": "hi {{user.name}}"}}],
        {"user": {"name": "ada"}},
    ) == {"user": {"name": "ada"}, "greeting": "hi ada"}
    assert run(
        [{"lowercase": {"field": "user.name"}}], {"user": {"name": "ADA"}}
    ) == {"user": {"name": "ada"}}
    with pytest.raises(PipelineError):
        run([{"lowercase": {"field": "nope"}}], {})
    assert run(
        [{"lowercase": {"field": "nope", "ignore_missing": True}}], {"a": 1}
    ) == {"a": 1}
    assert run(
        [{"fail": {"message": "boom", "ignore_failure": True}}], {"a": 1}
    ) == {"a": 1}
    with pytest.raises(PipelineError):
        run([{"fail": {"message": "bad doc {{a}}"}}], {"a": 7})
    with pytest.raises(PipelineError):
        run([{"convert": {"field": "n", "type": "integer"}}], {"n": "xx"})
    with pytest.raises(PipelineError):
        Pipeline("p", {"processors": [{"nope_proc": {}}]})
    with pytest.raises(PipelineError):
        Pipeline("p", {"processors": []})


def test_run_never_mutates_nested_source():
    src = {"user": {"name": "ADA"}, "tags": ["old"]}
    out = run(
        [
            {"lowercase": {"field": "user.name"}},
            {"append": {"field": "tags", "value": "new"}},
        ],
        src,
    )
    assert out == {"user": {"name": "ada"}, "tags": ["old", "new"]}
    assert src == {"user": {"name": "ADA"}, "tags": ["old"]}


def test_bad_regex_rejected_at_put_time():
    with pytest.raises(PipelineError):
        Pipeline("p", {"processors": [{"split": {"field": "s", "separator": "("}}]})
    with pytest.raises(PipelineError):
        Pipeline(
            "p",
            {"processors": [{"gsub": {"field": "s", "pattern": "[",
                                      "replacement": "x"}}]},
        )


def test_convert_leading_zeros_and_hex():
    assert run(
        [{"convert": {"field": "n", "type": "integer"}}], {"n": "042"}
    ) == {"n": 42}
    with pytest.raises(PipelineError):
        run([{"convert": {"field": "n", "type": "integer"}}], {"n": "0x10"})


def test_drop_and_set_override():
    assert run([{"drop": {}}], {"a": 1}) is None
    assert run(
        [{"set": {"field": "a", "value": 9, "override": False}}], {"a": 1}
    ) == {"a": 1}
    # original dict untouched (run works on a copy)
    src = {"a": 1}
    run([{"set": {"field": "b", "value": 2}}], src)
    assert src == {"a": 1}


def test_pipeline_on_write_paths():
    node = Node()
    node.create_index(
        "p", {"mappings": {"properties": {"msg": {"type": "text"}}}}
    )
    node.put_pipeline(
        "clean",
        {
            "processors": [
                {"lowercase": {"field": "msg"}},
                {"set": {"field": "via", "value": "clean"}},
            ]
        },
    )
    node.index_doc("p", {"msg": "HELLO World"}, "1", pipeline="clean")
    assert node.get_doc("p", "1")["_source"] == {
        "msg": "hello world",
        "via": "clean",
    }
    with pytest.raises(ApiError):
        node.index_doc("p", {"msg": "x"}, "2", pipeline="missing_pipe")


def test_default_pipeline_and_drop():
    node = Node()
    node.put_pipeline(
        "gate",
        {
            "processors": [
                {"drop": {}},
            ]
        },
    )
    node.create_index(
        "d",
        {
            "settings": {"index": {"default_pipeline": "gate"}},
            "mappings": {"properties": {"x": {"type": "long"}}},
        },
    )
    resp = node.index_doc("d", {"x": 1}, "1")
    assert resp["result"] == "noop"
    node.refresh("d")
    assert node.get_index("d").num_docs == 0
    # _none bypasses the default pipeline
    resp = node.index_doc("d", {"x": 2}, "2", pipeline="_none")
    assert resp["result"] == "created"


def test_bulk_with_pipeline_param_and_meta_override():
    node = Node()
    node.create_index("b", {})
    node.put_pipeline(
        "tag", {"processors": [{"set": {"field": "tagged", "value": True}}]}
    )
    node.put_pipeline(
        "other", {"processors": [{"set": {"field": "other", "value": 1}}]}
    )
    lines = [
        json.dumps({"index": {"_id": "1"}}),
        json.dumps({"v": 1}),
        json.dumps({"index": {"_id": "2", "pipeline": "other"}}),
        json.dumps({"v": 2}),
    ]
    resp = node.bulk("\n".join(lines), default_index="b", pipeline="tag")
    assert not resp["errors"]
    assert node.get_doc("b", "1")["_source"] == {"v": 1, "tagged": True}
    assert node.get_doc("b", "2")["_source"] == {"v": 2, "other": 1}


def test_ingest_rest_crud_and_simulate(tmp_path):
    node = Node(data_path=str(tmp_path))
    rest = RestServer(node=node)
    status, r = rest.dispatch(
        "PUT",
        "/_ingest/pipeline/norm",
        {},
        json.dumps(
            {
                "description": "normalize",
                "processors": [{"trim": {"field": "name"}}],
            }
        ),
    )
    assert status == 200
    status, r = rest.dispatch("GET", "/_ingest/pipeline/norm", {}, "")
    assert status == 200 and r["norm"]["description"] == "normalize"
    status, r = rest.dispatch(
        "POST",
        "/_ingest/pipeline/norm/_simulate",
        {},
        json.dumps({"docs": [{"_source": {"name": "  ada  "}}]}),
    )
    assert status == 200
    assert r["docs"][0]["doc"]["_source"] == {"name": "ada"}
    # ad-hoc simulate without a stored pipeline
    status, r = rest.dispatch(
        "POST",
        "/_ingest/pipeline/_simulate",
        {},
        json.dumps(
            {
                "pipeline": {"processors": [{"drop": {}}]},
                "docs": [{"_source": {"a": 1}}],
            }
        ),
    )
    assert r["docs"][0]["doc"] is None
    node.close()

    # pipelines survive restart
    node2 = Node(data_path=str(tmp_path))
    assert "norm" in node2.pipelines
    node2.close()
    status, r = rest.dispatch("DELETE", "/_ingest/pipeline/norm", {}, "")
    assert status == 200
    status, r = rest.dispatch("GET", "/_ingest/pipeline/norm", {}, "")
    assert status == 404
