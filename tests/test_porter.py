"""Porter stemmer + the full english analyzer chain.

Reference: Lucene PorterStemFilter / EnglishAnalyzer via analysis-common.
"""

from elasticsearch_tpu.analysis.analyzers import get_analyzer
from elasticsearch_tpu.analysis.porter import stem
from elasticsearch_tpu.node import Node

# Canonical (Porter 1980) full-pipeline outputs.
VECTORS = {
    "caresses": "caress", "ponies": "poni", "ties": "ti", "cats": "cat",
    "feed": "feed", "agreed": "agre", "plastered": "plaster", "bled": "bled",
    "motoring": "motor", "sing": "sing", "conflated": "conflat",
    "sized": "size", "hopping": "hop", "tanned": "tan", "falling": "fall",
    "hissing": "hiss", "failing": "fail", "filing": "file", "happy": "happi",
    "sky": "sky", "relational": "relat", "conditional": "condit",
    "rational": "ration", "digitizer": "digit", "operator": "oper",
    "feudalism": "feudal", "decisiveness": "decis", "hopefulness": "hope",
    "formaliti": "formal", "formative": "form", "formalize": "formal",
    "electriciti": "electr", "electrical": "electr", "hopeful": "hope",
    "goodness": "good", "revival": "reviv", "allowance": "allow",
    "inference": "infer", "airliner": "airlin", "adjustable": "adjust",
    "defensible": "defens", "irritant": "irrit", "replacement": "replac",
    "adjustment": "adjust", "dependent": "depend", "adoption": "adopt",
    "communism": "commun", "activate": "activ", "effective": "effect",
    "rate": "rate", "cease": "ceas", "roll": "roll",
    "generalization": "gener", "oscillators": "oscil",
    "differentli": "differ",
}


def test_canonical_vectors():
    for word, expected in VECTORS.items():
        assert stem(word) == expected, (word, stem(word), expected)


def test_english_analyzer_chain():
    a = get_analyzer("english")
    # stopwords drop, stems apply; the word-run tokenizer splits "runner's"
    assert a.analyze("The runner's shoes are running quickly") == [
        "runner", "s", "shoe", "run", "quickli",
    ]


def test_stemmed_search_recall():
    node = Node()
    node.create_index(
        "en",
        {
            "mappings": {
                "properties": {"t": {"type": "text", "analyzer": "english"}}
            }
        },
    )
    node.index_doc("en", {"t": "the connected engines"}, "1")
    node.index_doc("en", {"t": "a connection of engineering"}, "2")
    node.refresh("en")
    # "connect"/"connection"/"connected" all stem to connect
    r = node.search("en", {"query": {"match": {"t": "connections"}}})
    assert {h["_id"] for h in r["hits"]["hits"]} == {"1", "2"}
    # phrase matching works through stems + stopword gaps
    r = node.search("en", {"query": {"match_phrase": {"t": "connected engine"}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]


def test_custom_analyzer_with_stemmer():
    node = Node()
    node.create_index(
        "cu",
        {
            "settings": {
                "analysis": {
                    "analyzer": {
                        "my_stem": {
                            "tokenizer": "standard",
                            "filter": ["lowercase", "porter_stem"],
                        }
                    }
                }
            },
            "mappings": {
                "properties": {"t": {"type": "text", "analyzer": "my_stem"}}
            },
        },
    )
    node.index_doc("cu", {"t": "Jumping Wildly"}, "1", refresh=True)
    r = node.search("cu", {"query": {"match": {"t": "jumps"}}})
    assert r["hits"]["total"]["value"] == 1
