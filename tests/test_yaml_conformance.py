"""Pinned-green subset of the reference's YAML REST conformance suites.

tests/yaml_green.json lists every (file::section) of the reference's
rest-api-spec executable tests that this node currently passes verbatim
through tests/yaml_runner.py (sweep the full tree with
scripts/yaml_conformance.py). This test keeps the green set green —
a regression here means an API-compatibility break the reference's own
conformance suite would catch.
"""

import json
import tempfile
from pathlib import Path

import pytest

from yaml_runner import REFERENCE_TESTS, SkipTest, YamlRunner, load_suites

GREEN = json.loads(
    (Path(__file__).parent / "yaml_green.json").read_text()
)


@pytest.mark.parametrize("case", GREEN)
def test_yaml_green(case):
    if not REFERENCE_TESTS.exists():
        pytest.skip("reference YAML suites not mounted")
    rel, section = case.split("::", 1)
    from elasticsearch_tpu.rest.server import RestServer

    suites = load_suites(REFERENCE_TESTS / rel)
    rest = RestServer(data_path=tempfile.mkdtemp())
    runner = YamlRunner(rest)
    try:
        if "setup" in suites:
            runner.run_steps(suites["setup"])
        runner.run_steps(suites[section])
    except SkipTest as e:
        pytest.skip(str(e))
