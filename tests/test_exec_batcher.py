"""Micro-batcher scheduling contracts: coalescing, deadlines, queued
cancellation, load shedding.

The deadline contract (ISSUE satellite): a query never waits in the batch
queue past the batcher's max-wait or its own `?timeout=` — whichever is
stricter. The cancellation contract: `POST /_tasks/{id}/_cancel` on a
search still WAITING in the queue removes it immediately (it never rides
the launch), via tasks.Task cancel listeners.
"""

import threading
import time

import pytest

from elasticsearch_tpu.common.indexing_pressure import IndexingPressureRejected
from elasticsearch_tpu.common.tasks import TaskCancelledError, TaskManager
from elasticsearch_tpu.exec.batcher import MicroBatcher
from elasticsearch_tpu.node import ApiError, Node


class StubSearcher:
    """A search_many endpoint recording batch sizes, optionally slow."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self.calls: list[list] = []
        self.lock = threading.Lock()

    def search_many(self, requests, tasks=None):
        with self.lock:
            self.calls.append(list(requests))
        if self.delay_s:
            time.sleep(self.delay_s)
        return [f"r:{r}" for r in requests]


def test_idle_group_launches_immediately():
    """No idle tax: a lone request must not wait out max_wait."""
    batcher = MicroBatcher(max_wait_s=5.0)
    stub = StubSearcher()
    t0 = time.monotonic()
    out = batcher.execute(stub, "q1")
    elapsed = time.monotonic() - t0
    assert out == "r:q1"
    assert elapsed < 1.0, f"idle request waited {elapsed:.3f}s"
    assert [len(c) for c in stub.calls] == [1]
    batcher.close()


def test_concurrent_arrivals_coalesce():
    """Requests arriving while a batch is in flight ride ONE next launch."""
    batcher = MicroBatcher(max_wait_s=0.25)
    stub = StubSearcher(delay_s=0.3)
    results: dict = {}

    def go(name, delay):
        time.sleep(delay)
        results[name] = batcher.execute(stub, name)

    threads = [threading.Thread(target=go, args=("a", 0.0))]
    # b/c/d arrive while a's batch is executing: they coalesce.
    threads += [
        threading.Thread(target=go, args=(n, 0.1)) for n in ("b", "c", "d")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert results == {n: f"r:{n}" for n in "abcd"}
    sizes = sorted(len(c) for c in stub.calls)
    assert sizes == [1, 3], f"expected [1, 3] got {sizes}"
    stats = batcher.stats()
    assert stats["batches"] == 2
    assert stats["coalesced_requests"] == 3
    assert stats["occupancy_histogram"].get("4") == 1  # pow-2 bucket of 3
    batcher.close()


def test_queued_wait_capped_by_timeout():
    """Deadline-aware max-wait: a queued query with `?timeout=` launches
    by its own deadline even when max_wait is much longer."""
    batcher = MicroBatcher(max_wait_s=10.0)
    stub = StubSearcher()
    tm = TaskManager()
    # Occupy the group so the second arrival gets the batching window.
    slow = StubSearcher(delay_s=0.25)

    def first():
        batcher.execute(slow, "warm")

    t1 = threading.Thread(target=first)
    t1.start()
    time.sleep(0.05)  # let the first batch take flight
    task = tm.register("indices:data/read/search", timeout_s=0.4)
    t0 = time.monotonic()
    out = batcher.execute(slow, "deadline", task=task)
    elapsed = time.monotonic() - t0
    t1.join(timeout=5)
    assert out == "r:deadline"
    # Bounded by its own timeout (plus the in-flight batch draining),
    # never by the 10s max_wait.
    assert elapsed < 2.0, f"queued request waited {elapsed:.3f}s"
    assert batcher.stats()["queue_wait_p99_ms"] < 2000
    batcher.close()


def test_cancel_while_queued_returns_immediately():
    """A queued search cancelled via its task unwinds at once — it is
    removed from the queue and never launches."""
    batcher = MicroBatcher(max_wait_s=30.0)
    slow = StubSearcher(delay_s=0.6)
    tm = TaskManager()

    def first():
        batcher.execute(slow, "blocker")

    t1 = threading.Thread(target=first)
    t1.start()
    time.sleep(0.1)  # blocker's batch is now in flight
    task = tm.register("indices:data/read/search")
    err: dict = {}

    def second():
        t0 = time.monotonic()
        try:
            batcher.execute(slow, "victim", task=task)
        except TaskCancelledError as e:
            err["e"] = e
            err["elapsed"] = time.monotonic() - t0

    t2 = threading.Thread(target=second)
    t2.start()
    time.sleep(0.1)  # victim is queued behind the in-flight batch
    task.cancel("test cancel")
    t2.join(timeout=5)
    t1.join(timeout=5)
    assert "e" in err, "queued search was not cancelled"
    assert err["elapsed"] < 0.45, (
        f"cancel took {err['elapsed']:.3f}s — it waited for the launch"
    )
    assert all("victim" not in c for c in slow.calls)
    assert batcher.stats()["queue_cancellations"] == 1
    batcher.close()


def test_rest_cancel_of_queued_search(monkeypatch):
    """End-to-end satellite: POST /_tasks/{id}/_cancel on a search still
    waiting in the batch queue returns it immediately with 400
    task_cancelled_exception, without waiting for the batch to launch."""
    node = Node()
    node.exec_planner = None  # pin device lanes (keep kernels patchable)
    node.packed_exec = None  # pin the per-index group (patched kernel below)
    node.exec_batcher = MicroBatcher(max_wait_s=30.0)
    node.create_index(
        "cq", {"mappings": {"properties": {"b": {"type": "text"}}}}
    )
    for i in range(12):
        node.index_doc("cq", {"b": f"alpha common w{i % 3}"}, f"d{i}")
    node.refresh("cq")

    from elasticsearch_tpu.ops import bm25_device

    started = threading.Event()
    release = threading.Event()
    orig = bm25_device.execute_batch_sparse

    def slow(*args, **kwargs):
        started.set()
        release.wait(timeout=5)
        return orig(*args, **kwargs)

    monkeypatch.setattr(bm25_device, "execute_batch_sparse", slow)
    body = {"query": {"match": {"b": "alpha"}}}
    outcomes: dict = {}

    def blocker():
        outcomes["blocker"] = node.search("cq", dict(body))

    def victim():
        t0 = time.monotonic()
        try:
            node.search("cq", dict(body))
            outcomes["victim"] = "completed"
        except ApiError as e:
            outcomes["victim"] = e.err_type
        outcomes["victim_s"] = time.monotonic() - t0

    t1 = threading.Thread(target=blocker)
    t1.start()
    assert started.wait(timeout=5), "first batch never launched"
    t2 = threading.Thread(target=victim)
    t2.start()
    deadline = time.monotonic() + 5
    victim_task = None
    while victim_task is None and time.monotonic() < deadline:
        tasks = node.list_tasks("indices:data/read/search")
        running = tasks["nodes"][node.node_name]["tasks"]
        if len(running) == 2:
            victim_task = sorted(
                running, key=lambda t: int(t.split(":")[1])
            )[-1]
        else:
            time.sleep(0.01)
    assert victim_task is not None
    time.sleep(0.05)  # let the victim reach the queue
    node.cancel_task(victim_task)
    t2.join(timeout=5)
    assert outcomes["victim"] == "task_cancelled_exception"
    assert outcomes["victim_s"] < 2.0
    release.set()
    t1.join(timeout=10)
    assert "hits" in outcomes["blocker"]
    node.close()


def test_load_shedding_rejects_when_queue_full():
    batcher = MicroBatcher(max_wait_s=30.0, queue_limit=2)
    slow = StubSearcher(delay_s=0.5)
    threads = [
        threading.Thread(target=lambda: batcher.execute(slow, "a"))
    ]
    threads[0].start()
    time.sleep(0.1)  # in flight
    for name in ("b", "c"):
        threads.append(
            threading.Thread(
                target=lambda n=name: batcher.execute(slow, n)
            )
        )
        threads[-1].start()
    time.sleep(0.1)  # queue now holds b and c
    with pytest.raises(IndexingPressureRejected):
        batcher.execute(slow, "overflow")
    assert batcher.stats()["rejected"] == 1
    for t in threads:
        t.join(timeout=5)
    batcher.close()


def test_node_serves_concurrent_searches_coalesced():
    """Through the Node: concurrent identical-shape searches coalesce
    (occupancy histogram shows a multi-request batch) and return correct
    independent results."""
    node = Node()
    node.exec_planner = None  # keep lanes on the batched device kernel
    node.create_index(
        "co", {"mappings": {"properties": {"b": {"type": "text"}}}}
    )
    for i in range(40):
        node.index_doc("co", {"b": f"alpha w{i % 7} common"}, f"d{i}")
    node.refresh("co")
    terms = ["w0", "w1", "w2", "w3", "w4", "w5"]
    results: dict = {}

    def go(term):
        results[term] = node.search(
            "co", {"query": {"match": {"b": f"alpha {term}"}}, "size": 3}
        )

    # Warm the compile cache so the coalescing window isn't dominated by
    # first-compile time.
    go("w6")
    threads = [threading.Thread(target=go, args=(t,)) for t in terms]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for term in terms:
        hits = results[term]["hits"]["hits"]
        assert hits, f"no hits for {term}"
        assert all(term in h["_source"]["b"] for h in hits[:1]) or hits
    stats = node.nodes_stats()["nodes"][node.node_name]["exec"]["batcher"]
    assert stats["requests"] >= 7
    node.close()
