"""Chaos suite: seeded randomized fault schedules over a replicated
multi-shard corpus, asserting the degraded-mode invariants.

Invariants (the ISSUE's acceptance contract):

- returned hits are always a CORRECT SUBSET of the fault-free result —
  identical per-doc scores, non-increasing order, never wrong docs; a
  response with zero failed shards is bit-identical to the baseline;
- `successful + failed + skipped == total` on every `_shards` object;
- `allow_partial_search_results=false` never yields a silently-partial
  200: every response is either a complete 200 or a 503;
- a batcher-site fault on one sub-request never fails a coalesced
  batchmate;
- with faults disabled the identical workload returns bit-identical
  top-10 hits.

Everything runs on the CPU backend with deterministic seeds; the same
schedule replays identically (FaultRegistry is seeded per spec).
"""

import json
import threading

import pytest

from elasticsearch_tpu.faults import REGISTRY
from elasticsearch_tpu.rest.server import RestServer

QUERIES = [
    {"query": {"match": {"body": "findme"}}, "size": 20},
    {"query": {"match": {"body": "alpha beta"}}, "size": 10},
    {"query": {"term": {"tag": "red"}}, "size": 20},
    {
        "query": {
            "bool": {
                "must": [{"match": {"body": "findme"}}],
                "should": [{"match": {"body": "gamma"}}],
            }
        },
        "size": 15,
    },
]

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon"]
TAGS = ["red", "blue"]


def _seed_corpus(rest, index, n=48):
    lines = []
    for i in range(n):
        lines.append(json.dumps({"index": {"_index": index, "_id": f"d{i}"}}))
        lines.append(
            json.dumps(
                {
                    "body": f"findme {WORDS[i % 5]} {WORDS[(i * 3) % 5]} "
                    f"filler{i}",
                    "tag": TAGS[i % 2],
                }
            )
        )
    status, resp = rest.dispatch("POST", "/_bulk", {}, "\n".join(lines))
    assert status == 200 and not resp["errors"], resp
    status, _ = rest.dispatch("POST", f"/{index}/_refresh", {}, "")
    assert status == 200


def _search(rest, index, body, query=None):
    return rest.dispatch(
        "POST", f"/{index}/_search", query or {}, json.dumps(body)
    )


def _assert_shard_math(resp):
    sh = resp["_shards"]
    assert (
        sh["successful"] + sh["failed"] + sh["skipped"] == sh["total"]
    ), sh
    return sh


def _assert_correct_subset(resp, full_baseline):
    """Hits carry fault-free scores, in non-increasing score order.
    `full_baseline` must page over the ENTIRE match set: a partial
    merge over fewer shards can legitimately surface equal-scored docs
    the full top-k page truncated away."""
    scores = {h["_id"]: h["_score"] for h in full_baseline["hits"]["hits"]}
    prev = None
    for hit in resp["hits"]["hits"]:
        assert hit["_id"] in scores, f"unknown hit {hit['_id']}"
        assert scores[hit["_id"]] == hit["_score"], hit["_id"]
        if prev is not None:
            assert hit["_score"] <= prev
        prev = hit["_score"]


def _assert_bit_identical(resp, baseline):
    got = [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]
    want = [(h["_id"], h["_score"]) for h in baseline["hits"]["hits"]]
    assert got == want


@pytest.fixture(autouse=True)
def _clean_registry():
    REGISTRY.clear()
    yield
    REGISTRY.clear()


@pytest.fixture(
    params=["hub", pytest.param("tcp", marks=pytest.mark.slow)]
)
def replicated(request, monkeypatch):
    """Replicated REST cluster, parameterized over both transports: the
    in-memory hub every run, real TCP loopback sockets in the `slow`
    lane — the identical seeded chaos schedules replay over the wire."""
    monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
    monkeypatch.setenv("ESTPU_CLUSTER_TRANSPORT", request.param)
    server = RestServer(replication_nodes=3)
    status, _ = server.dispatch(
        "PUT",
        "/chaos",
        {},
        json.dumps(
            {
                "settings": {
                    "index": {
                        "number_of_shards": 2,
                        "number_of_replicas": 2,
                    }
                },
                "mappings": {
                    "properties": {
                        "body": {"type": "text"},
                        "tag": {"type": "keyword"},
                    }
                },
            }
        ),
    )
    assert status == 200
    _seed_corpus(server, "chaos")
    yield server
    server.close()


class TestReplicatedChaos:
    def _baselines(self, rest):
        """(page baseline, full-match-set baseline) per query."""
        out = []
        for body in QUERIES:
            status, page = _search(rest, "chaos", body)
            assert status == 200
            assert _assert_shard_math(page)["failed"] == 0
            status, full = _search(rest, "chaos", dict(body, size=60))
            assert status == 200
            out.append((page, full))
        return out

    def test_seeded_schedule_partial_results_are_correct_subsets(
        self, replicated
    ):
        """30% per-send transport failure on the query phase: every
        response is a 200 whose hits are a correct subset; the shard
        accounting always adds up; partials report honest failures[]."""
        rest = replicated
        baselines = self._baselines(rest)
        status, _ = rest.dispatch(
            "POST",
            "/_fault",
            {},
            json.dumps(
                {
                    "site": "transport.send.shard_search",
                    "error_rate": 0.9,
                    "error": "transport",
                    "seed": 1234,
                }
            ),
        )
        assert status == 200
        partials = 0
        for round_i in range(10):
            for body, (page, full) in zip(QUERIES, baselines):
                status, resp = _search(rest, "chaos", body)
                # Copy retry (2 rounds x 3 copies) absorbs most injected
                # failures; an all-copies-dead shard degrades to partial,
                # an all-shards-dead search is an honest 503.
                if status == 503:
                    continue
                assert status == 200, resp
                sh = _assert_shard_math(resp)
                if sh["failed"]:
                    partials += 1
                    assert sh["failures"], sh
                    for entry in sh["failures"]:
                        assert entry["index"] == "chaos"
                        assert entry["reason"]["reason"]
                _assert_correct_subset(resp, full)
                if sh["failed"] == 0:
                    _assert_bit_identical(resp, page)
        assert partials > 0, "chaos schedule never produced a partial"
        # Degradation is visible in the stats surface.
        status, stats = rest.dispatch("GET", "/_nodes/stats", {}, "")
        assert status == 200
        node = next(iter(stats["nodes"].values()))
        resilience = node["replication"]["search_resilience"]
        assert resilience["shard_failures"] > 0
        assert resilience["partial_results"] > 0
        assert resilience["copy_retries"] > 0
        assert node["replication"]["adaptive_replica_selection"]

    def test_partial_disallowed_never_silently_partial(self, replicated):
        """allow_partial_search_results=false under the same schedule:
        every response is a complete 200 or a 503 — never a 200 with
        failed shards."""
        rest = replicated
        status, _ = rest.dispatch(
            "POST",
            "/_fault",
            {},
            json.dumps(
                {
                    "site": "transport.send.shard_search",
                    "error_rate": 0.9,
                    "error": "transport",
                    "seed": 1234,
                }
            ),
        )
        assert status == 200
        saw_503 = False
        for round_i in range(10):
            for body in QUERIES:
                status, resp = _search(
                    rest,
                    "chaos",
                    body,
                    query={"allow_partial_search_results": "false"},
                )
                if status == 503:
                    saw_503 = True
                    assert (
                        resp["error"]["type"]
                        == "search_phase_execution_exception"
                    )
                    continue
                assert status == 200, resp
                assert _assert_shard_math(resp)["failed"] == 0
        assert saw_503, "schedule never exhausted a shard's copies"

    def test_faults_disabled_restores_bit_identical_top10(self, replicated):
        rest = replicated
        baselines = self._baselines(rest)
        status, _ = rest.dispatch(
            "POST",
            "/_fault",
            {},
            json.dumps(
                {
                    "site": "transport.send.shard_search",
                    "error_rate": 0.9,
                    "error": "transport",
                    "seed": 77,
                }
            ),
        )
        assert status == 200
        for body in QUERIES:
            _search(rest, "chaos", body)  # chaos traffic
        status, resp = rest.dispatch("DELETE", "/_fault", {}, "")
        assert status == 200 and resp["cleared"] == 1
        for body, (page, _full) in zip(QUERIES, baselines):
            status, resp = _search(rest, "chaos", dict(body, size=10))
            assert status == 200
            assert _assert_shard_math(resp)["failed"] == 0
            want = [
                (h["_id"], h["_score"])
                for h in page["hits"]["hits"][:10]
            ]
            got = [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]
            assert got == want


@pytest.fixture
def local(monkeypatch):
    monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
    server = RestServer()
    status, _ = server.dispatch(
        "PUT",
        "/chaos",
        {},
        json.dumps(
            {
                "settings": {"index": {"number_of_shards": 3}},
                "mappings": {
                    "properties": {
                        "body": {"type": "text"},
                        "tag": {"type": "keyword"},
                    }
                },
            }
        ),
    )
    assert status == 200
    _seed_corpus(server, "chaos")
    yield server
    server.close()


class TestLocalCoordinatorChaos:
    def test_concurrent_chaos_with_batcher_isolation(self, local):
        """Randomized faults at every local site under concurrent batched
        traffic: every request ends in a correct-subset 200 or an honest
        503; no injected batcher fault ever fails a batchmate with a
        non-search error."""
        rest = local
        baselines = {}
        for i, body in enumerate(QUERIES):
            status, resp = _search(rest, "chaos", dict(body, size=60))
            assert status == 200
            baselines[i] = resp
        status, _ = rest.dispatch(
            "POST",
            "/_fault",
            {},
            json.dumps(
                {
                    "faults": [
                        {
                            "site": "coordinator.shard",
                            "error_rate": 0.15,
                            "seed": 42,
                        },
                        {
                            "site": "batcher.launch",
                            "error_rate": 0.2,
                            "seed": 43,
                        },
                        {
                            "site": "search.kernel",
                            "error_rate": 0.05,
                            "seed": 44,
                            "delay_ms": 1,
                        },
                    ]
                }
            ),
        )
        assert status == 200
        outcomes = []
        lock = threading.Lock()

        def worker(worker_id):
            for round_i in range(6):
                qi = (worker_id + round_i) % len(QUERIES)
                status, resp = _search(rest, "chaos", QUERIES[qi])
                with lock:
                    outcomes.append((qi, status, resp))

        threads = [
            threading.Thread(target=worker, args=(w,)) for w in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(outcomes) == 24
        ok = 0
        for qi, status, resp in outcomes:
            if status == 503:
                assert (
                    resp["error"]["type"]
                    == "search_phase_execution_exception"
                )
                continue
            assert status == 200, resp
            ok += 1
            _assert_shard_math(resp)
            _assert_correct_subset(resp, baselines[qi])
        assert ok > 0
        stats = rest.node.exec_batcher.stats()
        # Injected batcher faults were isolated and retried individually.
        assert stats["retried_individually"] > 0
