"""significant_terms, matrix_stats aggregations, and can_match pruning.

Reference: SignificantTermsAggregationBuilder + JLHScore/ChiSquare
heuristics, modules/aggs-matrix-stats (RunningStats/MatrixStatsResults),
action/search/CanMatchPreFilterSearchPhase.java.
"""

import numpy as np
import pytest

from elasticsearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path))
    n.create_index(
        "crimes",
        {
            "mappings": {
                "properties": {
                    "desc": {"type": "text"},
                    "type": {"type": "keyword"},
                    "x": {"type": "double"},
                    "y": {"type": "double"},
                }
            }
        },
    )
    rng = np.random.default_rng(5)
    # "bicycle theft" reports are dominated by type=theft; background has
    # many other types.
    types = ["burglary", "assault", "fraud", "theft", "vandalism"]
    for i in range(200):
        t = types[i % 5]
        desc = "bicycle stolen" if (t == "theft" and i % 10 < 8) else "incident report"
        x = float(rng.normal(0, 1))
        n.index_doc(
            "crimes",
            {"desc": desc, "type": t, "x": x, "y": 2.0 * x + float(rng.normal(0, 0.1))},
            str(i),
        )
    n.refresh("crimes")
    return n


def test_significant_terms_jlh(node):
    out = node.search(
        "crimes",
        {
            "size": 0,
            "query": {"match": {"desc": "bicycle"}},
            "aggs": {
                "sig": {
                    "significant_terms": {"field": "type", "min_doc_count": 3}
                }
            },
        },
    )
    agg = out["aggregations"]["sig"]
    assert agg["doc_count"] == out["hits"]["total"]["value"]
    assert agg["bg_count"] == 200
    buckets = agg["buckets"]
    assert buckets and buckets[0]["key"] == "theft"
    b = buckets[0]
    assert b["doc_count"] > 0 and b["bg_count"] == 40 and b["score"] > 0
    # "theft" is overrepresented in the foreground; others score 0 (jlh).
    assert all(x["key"] == "theft" for x in buckets)


def test_significant_terms_chi_square(node):
    out = node.search(
        "crimes",
        {
            "size": 0,
            "query": {"match": {"desc": "bicycle"}},
            "aggs": {
                "sig": {
                    "significant_terms": {
                        "field": "type",
                        "chi_square": {},
                        "min_doc_count": 3,
                    }
                }
            },
        },
    )
    buckets = out["aggregations"]["sig"]["buckets"]
    assert buckets and buckets[0]["key"] == "theft"


def test_matrix_stats(node):
    out = node.search(
        "crimes",
        {
            "size": 0,
            "aggs": {"m": {"matrix_stats": {"fields": ["x", "y"]}}},
        },
    )
    agg = out["aggregations"]["m"]
    assert agg["doc_count"] == 200
    by_name = {f["name"]: f for f in agg["fields"]}
    fx, fy = by_name["x"], by_name["y"]
    # y = 2x + noise: correlation ~1, covariance(y,x) ~ 2*var(x).
    assert fx["correlation"]["y"] > 0.99
    assert abs(fy["covariance"]["x"] - 2.0 * fx["variance"]) < 0.1
    # Cross-check moments against numpy.
    xs = np.array(
        [
            node.get_doc("crimes", str(i))["_source"]["x"]
            for i in range(200)
        ]
    )
    assert abs(fx["mean"] - xs.mean()) < 1e-9
    assert abs(fx["variance"] - xs.var(ddof=1)) < 1e-9


def test_matrix_stats_requires_fields(node):
    from elasticsearch_tpu.node import ApiError

    with pytest.raises(ApiError):
        node.search(
            "crimes", {"size": 0, "aggs": {"m": {"matrix_stats": {}}}}
        )


@pytest.fixture()
def sharded(tmp_path, monkeypatch):
    # can_match belongs to the host-loop scatter/gather; the SPMD mesh
    # path is one fused program with no per-shard skip decision.
    monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
    n = Node(data_path=str(tmp_path))
    n.create_index(
        "logs",
        {
            "settings": {"index": {"number_of_shards": 4}},
            "mappings": {
                "properties": {
                    "ts": {"type": "long"},
                    "msg": {"type": "text"},
                }
            },
        },
    )
    for i in range(80):
        n.index_doc("logs", {"ts": i, "msg": f"event {i}"}, str(i))
    n.refresh("logs")
    return n


def test_can_match_skips_shards(sharded):
    # A range beyond every shard's bounds: all shards skip, zero hits.
    out = sharded.search(
        "logs", {"query": {"range": {"ts": {"gte": 1000}}}}
    )
    assert out["hits"]["total"]["value"] == 0
    assert out["_shards"]["skipped"] == 4
    # A matching range: results correct, and a bool filter carries the
    # pruning decision too.
    out = sharded.search(
        "logs",
        {
            "query": {
                "bool": {
                    "must": [{"match": {"msg": "event"}}],
                    "filter": [{"range": {"ts": {"gte": 0, "lte": 79}}}],
                }
            },
            "size": 100,
        },
    )
    assert out["hits"]["total"]["value"] == 80
    assert out["_shards"]["skipped"] == 0


def test_can_match_never_skips_matching_shards(sharded):
    # Point lookup: only shards whose bounds contain ts=5 run, but the
    # answer stays exact.
    out = sharded.search(
        "logs", {"query": {"term": {"ts": 5}}, "size": 10}
    )
    assert out["hits"]["total"]["value"] == 1
    assert [h["_id"] for h in out["hits"]["hits"]] == ["5"]


def test_can_match_msm_zero_does_not_skip(sharded):
    out = sharded.search(
        "logs",
        {
            "query": {
                "bool": {
                    "should": [{"range": {"ts": {"gte": 1000}}}],
                    "minimum_should_match": 0,
                }
            },
            "size": 0,
        },
    )
    assert out["hits"]["total"]["value"] == 80
    assert out["_shards"]["skipped"] == 0


def test_can_match_scroll_snapshot_isolation(sharded):
    # Bounds must follow the pinned snapshot, not the live engine: after
    # new out-of-range docs arrive, a fresh search must still see them.
    for i in range(4):
        sharded.index_doc("logs", {"ts": 5000 + i, "msg": "late"}, f"n{i}")
    sharded.refresh("logs")
    out = sharded.search(
        "logs", {"query": {"range": {"ts": {"gte": 4000}}}, "size": 10}
    )
    assert out["hits"]["total"]["value"] == 4


def test_matrix_stats_large_offset_stability(node):
    # Epoch-millis-scale values: raw power sums would cancel
    # catastrophically; pivoted moments must stay accurate.
    base = 1.7e12
    for i in range(50):
        node.index_doc(
            "crimes",
            {"x": base + float(i), "y": 3.0 * i + 0.001 * (i % 7)},
            f"big{i}",
        )
    node.refresh("crimes")
    out = node.search(
        "crimes",
        {
            "size": 0,
            "query": {"ids": {"values": [f"big{i}" for i in range(50)]}},
            "aggs": {"m": {"matrix_stats": {"fields": ["x", "y"]}}},
        },
    )
    by_name = {f["name"]: f for f in out["aggregations"]["m"]["fields"]}
    fx = by_name["x"]
    xs = base + np.arange(50, dtype=np.float64)
    assert fx["variance"] >= 0
    assert abs(fx["variance"] - xs.var(ddof=1)) / xs.var(ddof=1) < 1e-6
    assert fx["correlation"]["y"] > 0.999
