"""function_score: device ↔ oracle parity, ES semantics, REST shapes.

Reference: index/query/functionscore/FunctionScoreQueryBuilder.java:45 and
the function implementations in common/lucene/search/function/.
"""

import json

import numpy as np
import pytest

from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.rest.server import RestServer

from test_device_parity import assert_parity, build_corpus, run_both


@pytest.fixture(scope="module")
def corpus():
    from elasticsearch_tpu.index.tiles import pack_segment
    from elasticsearch_tpu.ops import bm25_device
    from elasticsearch_tpu.query.compile import Compiler
    from elasticsearch_tpu.search.oracle import OracleSearcher

    rng = np.random.default_rng(23)
    mappings, segment = build_corpus(rng, 400, seed_fields=False)
    dev = pack_segment(segment)
    seg_tree = bm25_device.segment_tree(dev)
    compiler = Compiler(dev.fields, dev.doc_values, mappings)
    oracle = OracleSearcher(segment, mappings)
    return mappings, segment, dev, seg_tree, compiler, oracle


def fs(body):
    return {"function_score": body}


class TestParity:
    def test_weight_only(self, corpus):
        assert_parity(
            corpus,
            fs({"query": {"match": {"body": "alpha bravo"}}, "weight": 2.5}),
        )

    def test_field_value_factor(self, corpus):
        assert_parity(
            corpus,
            fs(
                {
                    "query": {"match": {"body": "alpha"}},
                    "field_value_factor": {
                        "field": "rank",
                        "factor": 1.2,
                        "modifier": "log1p",
                        "missing": 1,
                    },
                }
            ),
        )

    @pytest.mark.parametrize(
        "modifier",
        ["none", "log1p", "log2p", "ln1p", "ln2p", "square", "sqrt"],
    )
    def test_fvf_modifiers(self, corpus, modifier):
        assert_parity(
            corpus,
            fs(
                {
                    "query": {"match": {"title": "charlie"}},
                    "field_value_factor": {
                        "field": "rank",
                        "modifier": modifier,
                        "missing": 2,
                    },
                    "boost_mode": "sum",
                }
            ),
        )

    @pytest.mark.parametrize(
        "score_mode", ["multiply", "sum", "avg", "first", "max", "min"]
    )
    def test_score_modes_with_filters(self, corpus, score_mode):
        assert_parity(
            corpus,
            fs(
                {
                    "query": {"match": {"body": "alpha bravo charlie"}},
                    "functions": [
                        {
                            "filter": {"term": {"tag": "red"}},
                            "weight": 3.0,
                        },
                        {
                            "filter": {"range": {"rank": {"gte": 500}}},
                            "field_value_factor": {
                                "field": "rank",
                                "modifier": "sqrt",
                                "missing": 1,
                            },
                            "weight": 0.5,
                        },
                        {"weight": 1.7},
                    ],
                    "score_mode": score_mode,
                }
            ),
        )

    @pytest.mark.parametrize(
        "boost_mode", ["multiply", "replace", "sum", "avg", "max", "min"]
    )
    def test_boost_modes(self, corpus, boost_mode):
        assert_parity(
            corpus,
            fs(
                {
                    "query": {"match": {"body": "delta echo"}},
                    "field_value_factor": {
                        "field": "rank",
                        "modifier": "ln2p",
                        "missing": 1,
                    },
                    "boost_mode": boost_mode,
                }
            ),
        )

    @pytest.mark.parametrize("kind", ["gauss", "exp", "linear"])
    def test_decay_functions(self, corpus, kind):
        assert_parity(
            corpus,
            fs(
                {
                    "query": {"match": {"body": "alpha"}},
                    kind: {
                        "rank": {
                            "origin": 500,
                            "scale": 200,
                            "offset": 50,
                            "decay": 0.33,
                        }
                    },
                    "boost_mode": "multiply",
                }
            ),
        )

    def test_random_score_deterministic_and_uniform(self, corpus):
        body = fs(
            {
                "query": {"match_all": {}},
                "random_score": {"seed": 42},
                "boost_mode": "replace",
            }
        )
        (d_scores, d_ids, _), (o_scores, o_ids, _) = run_both(corpus, body)
        np.testing.assert_array_equal(d_ids, o_ids)
        np.testing.assert_allclose(d_scores, o_scores, rtol=1e-6)
        assert 0.0 <= float(d_scores.max()) < 1.0
        # Different seed -> different ordering.
        body2 = fs(
            {
                "query": {"match_all": {}},
                "random_score": {"seed": 7},
                "boost_mode": "replace",
            }
        )
        (_, d_ids2, _), _ = run_both(corpus, body2)
        assert list(d_ids2) != list(d_ids)

    def test_max_boost_and_min_score(self, corpus):
        assert_parity(
            corpus,
            fs(
                {
                    "query": {"match": {"body": "alpha bravo"}},
                    "field_value_factor": {
                        "field": "rank",
                        "missing": 1,
                    },
                    "max_boost": 10.0,
                    "min_score": 5.0,
                    "boost_mode": "multiply",
                }
            ),
        )

    def test_script_score_function(self, corpus):
        assert_parity(
            corpus,
            fs(
                {
                    "query": {"match": {"body": "alpha"}},
                    "functions": [
                        {
                            "script_score": {
                                "script": {
                                    "source": "_score * 2.0 + params.bump",
                                    "params": {"bump": 3.0},
                                }
                            }
                        }
                    ],
                    "boost_mode": "replace",
                }
            ),
        )

    def test_no_functions_neutral(self, corpus):
        # No functions: factor 1, score unchanged (modulo boost).
        assert_parity(
            corpus, fs({"query": {"match": {"body": "alpha"}}, "boost": 2.0})
        )

    def test_nested_inside_bool(self, corpus):
        assert_parity(
            corpus,
            {
                "bool": {
                    "must": [
                        fs(
                            {
                                "query": {"match": {"body": "alpha"}},
                                "weight": 2.0,
                            }
                        )
                    ],
                    "filter": [{"exists": {"field": "rank"}}],
                }
            },
        )


class TestParseErrors:
    def test_two_functions_in_one_entry(self):
        with pytest.raises(ValueError, match="at most one score function"):
            parse_query(
                fs(
                    {
                        "functions": [
                            {
                                "weight": 1,
                                "field_value_factor": {"field": "r"},
                                "random_score": {},
                            }
                        ]
                    }
                )
            )

    def test_bad_modifier(self):
        with pytest.raises(ValueError, match="modifier"):
            parse_query(
                fs(
                    {
                        "field_value_factor": {
                            "field": "rank",
                            "modifier": "cube",
                        }
                    }
                )
            )

    def test_bad_score_mode(self):
        with pytest.raises(ValueError, match="score_mode"):
            parse_query(fs({"weight": 2, "score_mode": "median"}))

    def test_decay_requires_scale(self):
        with pytest.raises(ValueError, match="scale"):
            parse_query(fs({"gauss": {"rank": {"origin": 0}}}))

    def test_empty_function_entry(self):
        with pytest.raises(ValueError, match="function or a weight"):
            parse_query(fs({"functions": [{}]}))


class TestRest:
    def test_end_to_end_and_error_shape(self):
        rest = RestServer()
        status, _ = rest.dispatch(
            "PUT",
            "/fsx",
            {},
            json.dumps(
                {
                    "mappings": {
                        "properties": {
                            "body": {"type": "text"},
                            "rank": {"type": "long"},
                        }
                    }
                }
            ),
        )
        assert status == 200
        lines = []
        for i in range(30):
            lines.append(json.dumps({"index": {"_id": f"f{i}"}}))
            lines.append(
                json.dumps({"body": "quick brown fox", "rank": i * 10})
            )
        status, resp = rest.dispatch(
            "POST", "/fsx/_bulk", {"refresh": "true"}, "\n".join(lines)
        )
        assert status == 200 and not resp["errors"]
        status, resp = rest.dispatch(
            "POST",
            "/fsx/_search",
            {},
            json.dumps(
                {
                    "query": fs(
                        {
                            "query": {"match": {"body": "fox"}},
                            "field_value_factor": {
                                "field": "rank",
                                "missing": 0,
                            },
                            "boost_mode": "replace",
                        }
                    ),
                    "size": 3,
                }
            ),
        )
        assert status == 200
        ids = [h["_id"] for h in resp["hits"]["hits"]]
        assert ids == ["f29", "f28", "f27"]  # highest rank wins
        # ES-shaped 400 on a bad body.
        status, resp = rest.dispatch(
            "POST",
            "/fsx/_search",
            {},
            json.dumps(
                {"query": fs({"weight": 1, "boost_mode": "sideways"})}
            ),
        )
        assert status == 400
        # The node wraps search-body errors the way ES does: a 400 whose
        # top-level type is the search wrapper exception.
        assert resp["error"]["type"] == "search_phase_execution_exception"
        assert "boost_mode" in resp["error"]["reason"]


def test_fvf_requires_field():
    with pytest.raises(ValueError, match="field"):
        parse_query(fs({"field_value_factor": {"factor": 2.0}}))
