"""REST layer: ES-compatible endpoints over the in-process dispatcher plus
one live-socket round trip."""

import json
import threading
import urllib.request

import pytest

from elasticsearch_tpu.rest.server import RestServer


@pytest.fixture()
def rest():
    return RestServer()


def call(rest, method, path, body=None, query=None):
    payload = (
        body
        if isinstance(body, str)
        else (json.dumps(body) if body is not None else "")
    )
    return rest.dispatch(method, path, query or {}, payload)


def test_root_banner(rest):
    status, body = call(rest, "GET", "/")
    assert status == 200
    assert body["version"]["number"].startswith("8.")


def test_index_lifecycle(rest):
    status, body = call(
        rest,
        "PUT",
        "/books",
        {"mappings": {"properties": {"title": {"type": "text"}}}},
    )
    assert status == 200 and body["acknowledged"]
    status, body = call(rest, "PUT", "/books")
    assert status == 400 and body["error"]["type"] == "resource_already_exists_exception"
    status, body = call(rest, "GET", "/books/_mapping")
    assert body["books"]["mappings"]["properties"]["title"]["type"] == "text"
    status, body = call(rest, "DELETE", "/books")
    assert body["acknowledged"]
    status, body = call(rest, "GET", "/books/_mapping")
    assert status == 404 and body["error"]["type"] == "index_not_found_exception"


def test_document_crud_and_search(rest):
    call(rest, "PUT", "/lib", {"mappings": {"properties": {"t": {"type": "text"}}}})
    status, body = call(
        rest, "PUT", "/lib/_doc/1", {"t": "quick brown fox"}, {"refresh": "true"}
    )
    assert status == 200 and body["result"] == "created"
    call(rest, "PUT", "/lib/_doc/2", {"t": "lazy dog"}, {"refresh": "true"})

    status, body = call(rest, "GET", "/lib/_doc/1")
    assert body["found"] and body["_source"]["t"] == "quick brown fox"

    status, body = call(
        rest, "POST", "/lib/_search", {"query": {"match": {"t": "fox"}}}
    )
    assert body["hits"]["total"]["value"] == 1
    assert body["hits"]["hits"][0]["_id"] == "1"

    status, body = call(rest, "DELETE", "/lib/_doc/1", None, {"refresh": "true"})
    assert body["result"] == "deleted"
    status, body = call(
        rest, "POST", "/lib/_search", {"query": {"match": {"t": "fox"}}}
    )
    assert body["hits"]["total"]["value"] == 0


def test_update_and_upsert(rest):
    call(rest, "PUT", "/u")
    call(rest, "PUT", "/u/_doc/1", {"a": 1, "b": "x"}, {"refresh": "true"})
    status, body = call(rest, "POST", "/u/_update/1", {"doc": {"a": 2}})
    assert body["result"] == "updated"
    status, body = call(rest, "GET", "/u/_doc/1")
    assert body["_source"] == {"a": 2, "b": "x"}
    status, body = call(rest, "POST", "/u/_update/9", {"doc": {"a": 1}})
    assert status == 404
    status, body = call(
        rest, "POST", "/u/_update/9", {"doc": {"a": 5}, "doc_as_upsert": True}
    )
    assert body["result"] == "created"


def test_bulk_ndjson(rest):
    lines = [
        {"index": {"_index": "bk", "_id": "1"}},
        {"t": "alpha bravo"},
        {"index": {"_index": "bk", "_id": "2"}},
        {"t": "alpha charlie"},
        {"delete": {"_index": "bk", "_id": "2"}},
        {"index": {"_index": "missing-CAPS", "_id": "3"}},  # invalid name
        {"t": "x"},
    ]
    body = "\n".join(json.dumps(l) for l in lines) + "\n"
    status, resp = call(rest, "POST", "/_bulk", body, {"refresh": "true"})
    assert status == 200
    assert resp["errors"] is True
    assert resp["items"][0]["index"]["status"] == 201
    assert resp["items"][2]["delete"]["status"] == 200
    assert resp["items"][3]["index"]["status"] == 400
    status, resp = call(rest, "POST", "/bk/_search", {"query": {"match": {"t": "alpha"}}})
    assert resp["hits"]["total"]["value"] == 1


def test_create_conflict(rest):
    call(rest, "PUT", "/c")
    status, _ = call(rest, "PUT", "/c/_create/1", {"x": 1}, {"refresh": "true"})
    assert status == 200
    status, body = call(rest, "PUT", "/c/_create/1", {"x": 2})
    assert status == 409
    assert body["error"]["type"] == "version_conflict_engine_exception"


def test_count_and_cat_and_health(rest):
    call(rest, "PUT", "/k")
    call(rest, "PUT", "/k/_doc/1", {"n": 5}, {"refresh": "true"})
    call(rest, "PUT", "/k/_doc/2", {"n": 15}, {"refresh": "true"})
    status, body = call(
        rest, "POST", "/k/_count", {"query": {"range": {"n": {"gte": 10}}}}
    )
    assert body["count"] == 1
    status, body = call(rest, "GET", "/_cluster/health")
    assert body["status"] == "green"
    status, body = call(rest, "GET", "/_cat/indices")
    assert body[0]["index"] == "k" and body[0]["docs.count"] == "2"


def test_analyze(rest):
    call(rest, "PUT", "/a")
    status, body = call(
        rest, "POST", "/a/_analyze", {"analyzer": "standard", "text": "The QUICK fox"}
    )
    assert [t["token"] for t in body["tokens"]] == ["the", "quick", "fox"]


def test_rank_eval(rest):
    call(rest, "PUT", "/r")
    for i, text in enumerate(["apple pie", "apple juice", "banana split"]):
        call(rest, "PUT", f"/r/_doc/{i}", {"t": text}, {"refresh": "true"})
    body = {
        "requests": [
            {
                "id": "apple_query",
                "request": {"query": {"match": {"t": "apple"}}},
                "ratings": [
                    {"_id": "0", "rating": 1},
                    {"_id": "1", "rating": 1},
                    {"_id": "2", "rating": 0},
                ],
            }
        ],
        "metric": {"recall": {"k": 10}},
    }
    status, resp = call(rest, "POST", "/r/_rank_eval", body)
    assert status == 200
    assert resp["metric_score"] == 1.0


def test_error_shapes(rest):
    status, body = call(rest, "GET", "/nope/_search")
    assert status == 404 and body["status"] == 404
    call(rest, "PUT", "/x")
    status, body = call(rest, "POST", "/x/_search", "{bad json")
    assert status == 400 and body["error"]["type"] == "parsing_exception"
    status, body = call(
        rest, "POST", "/x/_search", {"query": {"wibble": {}}}
    )
    assert status == 400


def test_live_http_socket():
    """Full socket round trip on an ephemeral port."""
    rest = RestServer()
    server = rest.serve("127.0.0.1", 0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        def http(method, path, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=data,
                method=method,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read())

        status, body = http("GET", "/")
        assert status == 200 and "tagline" in body
        http("PUT", "/live")
        http("PUT", "/live/_doc/1?refresh=true" if False else "/live/_doc/1", {"t": "hello world"})
        http("POST", "/live/_refresh")
        status, body = http("POST", "/live/_search", {"query": {"match": {"t": "hello"}}})
        assert body["hits"]["total"]["value"] == 1
    finally:
        server.shutdown()
