"""geo_point fields + geo queries, intervals, rare_terms, MAD, and the
new mapper types (ip/binary/date_nanos).

Reference: GeoDistanceQueryBuilder, GeoBoundingBoxQueryBuilder,
IntervalQueryBuilder, RareTermsAggregationBuilder,
MedianAbsoluteDeviationAggregationBuilder, IpFieldMapper,
BinaryFieldMapper.
"""

import numpy as np
import pytest

from elasticsearch_tpu.node import ApiError, Node


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path))
    n.create_index(
        "places",
        {
            "mappings": {
                "properties": {
                    "name": {"type": "text"},
                    "loc": {"type": "geo_point"},
                    "tag": {"type": "keyword"},
                    "v": {"type": "double"},
                    "addr": {"type": "ip"},
                    "blob": {"type": "binary"},
                    "ts": {"type": "date_nanos"},
                }
            }
        },
    )
    cities = [
        ("berlin", 52.52, 13.40),
        ("paris", 48.85, 2.35),
        ("london", 51.50, -0.12),
        ("nyc", 40.71, -74.00),
    ]
    for i, (name, lat, lon) in enumerate(cities):
        n.index_doc(
            "places",
            {
                "name": f"{name} quick brown fox jumps", "loc": [lon, lat],
                "tag": name, "v": float(i),
                "addr": f"10.0.0.{i}", "blob": "aGVsbG8=",
                "ts": "2020-01-01T00:00:00.123456789Z",
            },
            name,
        )
    n.refresh("places")
    return n


def test_geo_distance(node):
    out = node.search(
        "places",
        {
            "query": {
                "geo_distance": {
                    "distance": "200km",
                    "loc": {"lat": 49.0, "lon": 4.0},
                }
            },
            "size": 10,
        },
    )
    # Reims-ish center: only Paris (~130km) is in range.
    ids = {h["_id"] for h in out["hits"]["hits"]}
    assert ids == {"paris"}
    out = node.search(
        "places",
        {
            "query": {
                "geo_distance": {"distance": "7000km", "loc": [8.0, 50.0]}
            },
            "size": 10,
        },
    )
    assert {h["_id"] for h in out["hits"]["hits"]} == {
        "berlin", "paris", "london", "nyc",
    }


def test_geo_bounding_box(node):
    out = node.search(
        "places",
        {
            "query": {
                "geo_bounding_box": {
                    "loc": {
                        "top_left": {"lat": 55.0, "lon": -1.0},
                        "bottom_right": {"lat": 45.0, "lon": 15.0},
                    }
                }
            },
            "size": 10,
        },
    )
    assert {h["_id"] for h in out["hits"]["hits"]} == {
        "berlin", "paris", "london",
    }


def test_intervals_ordered_and_gaps(node):
    out = node.search(
        "places",
        {
            "query": {
                "intervals": {
                    "name": {
                        "match": {
                            "query": "quick fox",
                            "max_gaps": 1,
                            "ordered": True,
                        }
                    }
                }
            },
            "size": 10,
        },
    )
    assert len(out["hits"]["hits"]) == 4  # quick [brown] fox everywhere
    out = node.search(
        "places",
        {
            "query": {
                "intervals": {
                    "name": {
                        "match": {
                            "query": "quick fox",
                            "max_gaps": 0,
                            "ordered": True,
                        }
                    }
                }
            },
        },
    )
    assert out["hits"]["hits"] == []
    out = node.search(
        "places",
        {
            "query": {
                "intervals": {
                    "name": {
                        "all_of": {
                            "ordered": True,
                            "intervals": [
                                {"match": {"query": "berlin"}},
                                {"prefix": {"prefix": "qui"}},
                            ],
                        }
                    }
                }
            },
        },
    )
    assert [h["_id"] for h in out["hits"]["hits"]] == ["berlin"]


def test_ip_and_binary_and_date_nanos(node):
    out = node.search(
        "places", {"query": {"term": {"addr": "10.0.0.2"}}}
    )
    assert [h["_id"] for h in out["hits"]["hits"]] == ["london"]
    doc = node.get_doc("places", "berlin")
    assert doc["_source"]["blob"] == "aGVsbG8="
    out = node.search(
        "places",
        {"query": {"range": {"ts": {"gte": "2020-01-01"}}}, "size": 10},
    )
    assert len(out["hits"]["hits"]) == 4


def test_rare_terms_and_mad(node):
    node.index_doc("places", {"tag": "berlin", "v": 100.0}, "extra")
    node.refresh("places")
    out = node.search(
        "places",
        {
            "size": 0,
            "aggs": {
                "rare": {"rare_terms": {"field": "tag"}},
                "mad": {"median_absolute_deviation": {"field": "v"}},
            },
        },
    )
    rare = out["aggregations"]["rare"]["buckets"]
    # berlin now occurs twice -> not rare; the others are singletons.
    assert [b["key"] for b in rare] == ["london", "nyc", "paris"]
    vals = np.array([0.0, 1.0, 2.0, 3.0, 100.0])
    med = np.median(vals)
    assert out["aggregations"]["mad"]["value"] == pytest.approx(
        float(np.median(np.abs(vals - med)))
    )


def test_index_less_apis(node):
    out = node.search("_all", {"query": {"match_all": {}}, "size": 0})
    assert out["hits"]["total"]["value"] == 4
    assert node.refresh_all()["_shards"]["failed"] == 0
    assert set(node.get_mapping_all()) == {"places"}
    assert node.expand_index_patterns("pla*") == ["places"]
