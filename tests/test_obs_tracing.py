"""End-to-end distributed tracing (obs/tracing.py).

The acceptance contract: one `_search` against a replicated multi-shard
cluster yields ONE connected trace — root REST span → gateway → per-shard
(remote, via transport payload propagation) → per-segment launch spans —
including under injected faults and copy-retry reroutes; the trace
exports as valid Chrome trace-event JSON; `profile: true` inlines the
request's own span tree; cache hits are tagged and report an honest
nonzero took; slowlog lines carry trace_id + took_breakdown.
"""

import json
import logging
import threading

import pytest

from elasticsearch_tpu.faults import REGISTRY, FaultSpec
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.obs.tracing import (
    TRACER,
    format_traceparent,
    parse_traceparent,
)
from elasticsearch_tpu.rest.server import RestServer


@pytest.fixture(autouse=True)
def _clean_obs():
    REGISTRY.clear()
    TRACER.clear()
    yield
    REGISTRY.clear()
    TRACER.clear()


def _assert_connected(spans):
    """Every span parents (transitively) to the single root."""
    by_id = {s["span_id"]: s for s in spans}
    roots = [s for s in spans if s["parent_id"] is None]
    assert len(roots) == 1, [s["name"] for s in roots]
    root_id = roots[0]["span_id"]
    for s in spans:
        seen = set()
        cur = s
        while cur["parent_id"] is not None:
            assert cur["parent_id"] in by_id, (
                f"span [{s['name']}] dangles at [{cur['name']}]"
            )
            assert cur["span_id"] not in seen, "parent cycle"
            seen.add(cur["span_id"])
            cur = by_id[cur["parent_id"]]
        assert cur["span_id"] == root_id
    return roots[0]


def _seed(rest, index="obs", shards=2, replicas=2, n=16):
    status, _ = rest.dispatch(
        "PUT",
        f"/{index}",
        {},
        json.dumps(
            {
                "settings": {
                    "index": {
                        "number_of_shards": shards,
                        "number_of_replicas": replicas,
                    }
                },
                "mappings": {"properties": {"b": {"type": "text"}}},
            }
        ),
    )
    assert status == 200
    lines = []
    for i in range(n):
        lines.append(json.dumps({"index": {"_index": index, "_id": f"d{i}"}}))
        lines.append(json.dumps({"b": f"alpha w{i % 3} filler{i}"}))
    status, resp = rest.dispatch("POST", "/_bulk", {}, "\n".join(lines))
    assert status == 200 and not resp["errors"]
    rest.dispatch("POST", f"/{index}/_refresh", {}, "")


@pytest.fixture
def replicated(monkeypatch):
    monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
    rest = RestServer(replication_nodes=3)
    _seed(rest)
    yield rest
    rest.close()


class TestReplicatedTrace:
    def _search_trace(self, rest, body=None, headers=None):
        status, resp = rest.dispatch(
            "POST",
            "/obs/_search",
            {},
            json.dumps(body or {"query": {"match": {"b": "alpha"}}}),
            headers=headers,
        )
        trace_id = rest._tl.response_headers.get("X-Trace-Id")
        assert trace_id, "dispatch must return X-Trace-Id"
        return status, resp, trace_id

    def test_single_search_yields_one_connected_trace(self, replicated):
        """3 nodes, 2 shards, 2 replicas: root REST span → search →
        gateway → per-shard → remote execution → per-segment launches,
        every span parenting to the root."""
        status, resp, trace_id = self._search_trace(replicated)
        assert status == 200
        assert resp["_shards"]["failed"] == 0
        status, tree = replicated.dispatch(
            "GET", f"/_traces/{trace_id}", {}, ""
        )
        assert status == 200
        spans = tree["spans"]
        root = _assert_connected(spans)
        assert root["name"] == "rest.request"
        names = [s["name"] for s in spans]
        assert "search" in names
        assert "gateway.search" in names
        # Per-shard scatter on the cluster coordinator.
        assert names.count("cluster.shard") == 2
        # The wire hop (payload-propagated context)...
        assert any(n == "transport.shard_search" for n in names)
        # ...and the REMOTE node's execution parenting through it, down
        # to per-segment kernel launches.
        assert any(n == "cluster.shard_search" for n in names)
        assert any(n == "search.segment" for n in names)

    def test_trace_listed_in_ring(self, replicated):
        _status, _resp, trace_id = self._search_trace(replicated)
        status, listing = replicated.dispatch("GET", "/_traces", {}, "")
        assert status == 200
        assert any(t["trace_id"] == trace_id for t in listing["traces"])
        entry = next(
            t for t in listing["traces"] if t["trace_id"] == trace_id
        )
        assert entry["root"] == "rest.request"
        assert entry["spans"] >= 5

    def test_unknown_trace_404(self, replicated):
        status, resp = replicated.dispatch(
            "GET", "/_traces/deadbeef", {}, ""
        )
        assert status == 404
        assert resp["error"]["type"] == "resource_not_found_exception"

    def test_connected_under_faults_and_copy_retries(self, replicated):
        """An armed transport fault: the trace stays ONE connected tree,
        faulted spans are tagged injected_fault, and copy retries show as
        events on the shard spans."""
        status, _ = replicated.dispatch(
            "POST",
            "/_fault",
            {},
            json.dumps(
                {
                    "site": "transport.send.shard_search",
                    "error_rate": 0.6,
                    "error": "transport",
                    "seed": 11,
                }
            ),
        )
        assert status == 200
        saw_injected = saw_retry = False
        for _ in range(8):
            status, _resp, trace_id = self._search_trace(replicated)
            if status != 200:
                continue  # all-copies-dead 503: no result to trace-check
            spans = TRACER.export(trace_id)["spans"]
            _assert_connected(spans)
            for s in spans:
                if s.get("tags", {}).get("injected_fault"):
                    assert s["status"] == "error"
                    saw_injected = True
                for ev in s.get("events", []):
                    if ev["name"] == "search.copy_retry":
                        saw_retry = True
            if saw_injected and saw_retry:
                break
        assert saw_injected, "no span carried the injected_fault tag"
        assert saw_retry, "no copy_retry event reached the trace"

    def test_chrome_export_is_valid_trace_event_json(self, replicated):
        _status, _resp, trace_id = self._search_trace(replicated)
        status, chrome = replicated.dispatch(
            "GET", f"/_traces/{trace_id}", {"format": "chrome"}, ""
        )
        assert status == 200
        # Round-trips as JSON and carries the trace-event shape Perfetto
        # loads: complete events with microsecond ts/dur.
        blob = json.loads(json.dumps(chrome))
        events = blob["traceEvents"]
        assert events
        for ev in events:
            assert ev["ph"] == "X"
            assert isinstance(ev["name"], str) and ev["name"]
            assert ev["ts"] > 0 and ev["dur"] > 0
            assert "pid" in ev and "tid" in ev
            assert "span_id" in ev["args"]

    def test_traceparent_header_continues_callers_trace(self, replicated):
        parent = format_traceparent("ab" * 16, "cd" * 8)
        assert parse_traceparent(parent) == ("ab" * 16, "cd" * 8)
        _status, _resp, trace_id = self._search_trace(
            replicated, headers={"traceparent": parent}
        )
        assert trace_id == "ab" * 16
        spans = TRACER.export(trace_id)["spans"]
        root = next(s for s in spans if s["name"] == "rest.request")
        assert root["parent_id"] == "cd" * 8

    def test_opaque_id_tags_root(self, replicated):
        _status, _resp, trace_id = self._search_trace(
            replicated, headers={"X-Opaque-Id": "req-42"}
        )
        spans = TRACER.export(trace_id)["spans"]
        root = next(s for s in spans if s["name"] == "rest.request")
        assert root["tags"]["opaque_id"] == "req-42"


class TestLocalTrace:
    @pytest.fixture
    def node(self, monkeypatch):
        monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
        node = Node()
        node.create_index(
            "t",
            {
                "mappings": {"properties": {"b": {"type": "text"}}},
                "settings": {"index": {"number_of_shards": 2}},
            },
        )
        for i in range(12):
            node.index_doc("t", {"b": f"alpha w{i % 3}"}, f"d{i}")
        node.refresh("t")
        return node

    def _last_trace(self):
        traces = TRACER.traces()
        assert traces
        return TRACER.export(traces[0]["trace_id"])["spans"]

    def test_coordinator_shard_fault_tags_span(self, node):
        """An injected coordinator.shard fault: the search degrades to a
        partial 200, the trace stays connected, and the failed shard's
        span is error + injected_fault."""
        REGISTRY.put(
            FaultSpec(site="coordinator.shard", error_rate=1.0, count=1)
        )
        out = node.search(
            "t", {"query": {"match": {"b": "alpha"}}, "profile": True}
        )
        assert out["_shards"]["failed"] == 1
        spans = self._last_trace()
        _assert_connected(spans)
        failed = [
            s
            for s in spans
            if s["name"] == "coordinator.shard" and s["status"] == "error"
        ]
        assert len(failed) == 1
        assert failed[0]["tags"]["injected_fault"] is True
        # The surviving shard still bottomed out in segment launches.
        assert any(s["name"] == "search.segment" for s in spans)

    def test_batcher_queue_and_launch_spans(self, node):
        """A batchable search rides the micro-batcher: its trace carries
        the queue-wait span and the coalesced-launch span."""
        out = node.search("t", {"query": {"match": {"b": "alpha"}}})
        assert out["hits"]["hits"]
        spans = self._last_trace()
        _assert_connected(spans)
        names = [s["name"] for s in spans]
        assert "batcher.queue" in names
        launch = next(s for s in spans if s["name"] == "batcher.launch")
        assert launch["tags"]["batch_size"] >= 1
        assert "launch_id" in launch["tags"]

    def test_coalesced_launch_span_shared_across_batchmates(self, node):
        """Concurrent same-shape searches that coalesce share ONE launch:
        their traces carry batcher.launch spans with the same launch_id."""
        barrier = threading.Barrier(3)
        trace_ids = []
        lock = threading.Lock()

        def one():
            with TRACER.start_trace("test.client") as root:
                with lock:
                    trace_ids.append(root.trace_id)
                barrier.wait()
                node.search("t", {"query": {"match": {"b": "alpha"}}})

        threads = [threading.Thread(target=one) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        launch_ids = {}
        for tid in trace_ids:
            spans = TRACER.export(tid)["spans"]
            _assert_connected(spans)
            for s in spans:
                if s["name"] == "batcher.launch":
                    launch_ids.setdefault(
                        s["tags"]["launch_id"], 0
                    )
                    launch_ids[s["tags"]["launch_id"]] += 1
        # Every rider got a launch span; coalesced riders share an id
        # (timing may split them across 1-3 launches, never more).
        assert sum(launch_ids.values()) == 3
        assert len(launch_ids) <= 3

    def test_profile_inlines_own_span_tree(self, node):
        out = node.search(
            "t", {"query": {"match": {"b": "alpha"}}, "profile": True}
        )
        tree = out["profile"]["trace"]
        assert tree["spans"]
        names = [s["name"] for s in tree["spans"]]
        assert "search" in names and "search.segment" in names
        # The root search span is still open at inline time.
        search_span = next(s for s in tree["spans"] if s["name"] == "search")
        assert search_span.get("in_progress") is True

    def test_cache_hit_honest_took_and_tag(self, node):
        body = {"query": {"match": {"b": "alpha"}}, "size": 0}
        first = node.search("t", dict(body))
        assert first["hits"]["total"]["value"] > 0
        hit = node.search("t", dict(body))
        # Honest nonzero took measured on THIS request, not a replay of
        # the cached execution's timing.
        assert hit["took"] >= 1
        assert node.request_cache.stats()["hit_count"] == 1
        spans = self._last_trace()
        search_span = next(s for s in spans if s["name"] == "search")
        assert search_span["tags"]["cache_hit"] is True
        # The hit's trace has no kernel work under the search span.
        assert not any(s["name"] == "search.segment" for s in spans)

    def test_slowlog_line_has_trace_id_and_breakdown(
        self, monkeypatch, caplog
    ):
        monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
        monkeypatch.setenv("ESTPU_EXEC_BATCHER", "0")  # unbatched: phases
        node = Node()
        node.create_index(
            "s",
            {
                "mappings": {"properties": {"b": {"type": "text"}}},
                "settings": {
                    "index": {
                        "search": {
                            "slowlog": {
                                "threshold": {"query": {"warn": "0ms"}}
                            }
                        }
                    }
                },
            },
        )
        node.index_doc("s", {"b": "alpha"}, "d0")
        node.refresh("s")
        with caplog.at_level(
            logging.WARNING, logger="elasticsearch_tpu.slowlog.search"
        ):
            node.search("s", {"query": {"match": {"b": "alpha"}}})
        assert caplog.records
        msg = caplog.records[0].getMessage()
        assert "trace_id[" in msg and "trace_id[-]" not in msg
        assert "took_breakdown[" in msg
        assert "execute_ms" in msg

    def test_indexing_slowlog_fires(self, monkeypatch, caplog):
        node = Node()
        node.create_index(
            "w",
            {
                "mappings": {"properties": {"b": {"type": "text"}}},
                "settings": {
                    "index": {
                        "indexing": {
                            "slowlog": {
                                "threshold": {"index": {"warn": "0ms"}}
                            }
                        }
                    }
                },
            },
        )
        with caplog.at_level(
            logging.WARNING, logger="elasticsearch_tpu.slowlog.index"
        ):
            node.index_doc("w", {"b": "alpha"}, "d0")
        assert caplog.records
        msg = caplog.records[0].getMessage()
        assert "id[d0]" in msg and "took[" in msg

    def test_indexing_slowlog_threshold_dynamic(self, caplog):
        node = Node()
        node.create_index(
            "w2", {"mappings": {"properties": {"b": {"type": "text"}}}}
        )
        node.put_settings(
            "w2",
            {
                "index": {
                    "indexing": {
                        "slowlog": {"threshold": {"index": {"warn": "0ms"}}}
                    }
                }
            },
        )
        with caplog.at_level(
            logging.WARNING, logger="elasticsearch_tpu.slowlog.index"
        ):
            node.index_doc("w2", {"b": "x"}, "d1")
        assert caplog.records


class TestTasksApi:
    def test_running_time_is_monotonic_based(self):
        node = Node()
        task = node.tasks.register("indices:data/read/search", "test")
        # Wall-clock poisoning start_ms must not affect the runtime (the
        # old implementation derived nanos from it).
        task.start_ms -= 3_600_000.0
        j = task.to_json()
        assert 0 <= j["running_time_in_nanos"] < int(60e9)
        node.tasks.unregister(task)

    def test_list_tasks_detailed_reports_span(self):
        node = Node()
        task = node.tasks.register("indices:data/read/search", "probing")
        task.span_name = "search.segment"
        out = node.list_tasks(detailed=True)
        entry = out["nodes"][node.node_name]["tasks"][task.id]
        assert entry["span"] == "search.segment"
        assert entry["description"] == "probing"
        plain = node.list_tasks()["nodes"][node.node_name]["tasks"][task.id]
        assert "description" not in plain
        assert plain["running_time_in_nanos"] >= 0
        node.tasks.unregister(task)

    def test_cat_tasks_route(self):
        rest = RestServer()
        task = rest.node.tasks.register("indices:data/read/search", "x")
        task.span_name = "batcher.queue"
        status, rows = rest.dispatch("GET", "/_cat/tasks", {}, "")
        assert status == 200
        assert any(
            r["task_id"] == task.id and r["span"] == "batcher.queue"
            for r in rows
        )
        status, detailed = rest.dispatch(
            "GET", "/_tasks", {"detailed": "true"}, ""
        )
        assert status == 200
        assert task.id in detailed["nodes"][rest.node.node_name]["tasks"]
        rest.node.tasks.unregister(task)
