"""Test environment: force an 8-device virtual CPU mesh before jax imports.

Multi-chip sharding paths are tested on virtual CPU devices (the driver
separately dry-runs __graft_entry__.dryrun_multichip); real-TPU benchmarking
happens via bench.py only.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
