"""Test environment: force an 8-device virtual CPU mesh.

Multi-chip sharding paths are tested on virtual CPU devices (the driver
separately dry-runs __graft_entry__.dryrun_multichip); real-TPU benchmarking
happens via bench.py only.

NOTE: setting the JAX_PLATFORMS env var is NOT enough in this image — the
axon TPU plugin registers itself from sitecustomize at interpreter startup
and calls jax.config.update("jax_platforms", "axon,cpu"), overriding the
environment. We must update the config (and clear any initialized backends)
after importing jax.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb

if _xb.backends_are_initialized():  # pragma: no cover - defensive
    from jax.extend.backend import clear_backends

    clear_backends()

assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}"
)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
