"""Test environment: force an 8-device virtual CPU mesh.

Multi-chip sharding paths are tested on virtual CPU devices (the driver
separately dry-runs __graft_entry__.dryrun_multichip); real-TPU benchmarking
happens via bench.py only.

NOTE: setting the JAX_PLATFORMS env var is NOT enough in this image — the
axon TPU plugin registers itself from sitecustomize at interpreter startup
and calls jax.config.update("jax_platforms", "axon,cpu"), overriding the
environment. We must update the config (and clear any initialized backends)
after importing jax.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb

if _xb.backends_are_initialized():  # pragma: no cover - defensive
    from jax.extend.backend import clear_backends

    clear_backends()

assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}"
)

import numpy as np
import pytest


# --------------------------------------------------------------------------
# VM-mapping pressure guard.
#
# Every XLA:CPU compile mmaps JIT code regions that stay mapped for the
# executable's lifetime. One full tier-1 run compiles thousands of distinct
# programs in ONE process, and the kernel caps a process's mappings at
# vm.max_map_count (65530 default). At the cliff the next mmap inside
# LLVM's JIT fails and XLA SEGFAULTS (observed deterministically at ~65.5k
# maps, two-thirds through the suite) instead of raising. jax.clear_caches()
# drops compiled executables (and their mappings); later tests simply
# recompile. Clearing is keyed on MEASURED pressure, not a test count, so
# small runs never pay a recompile and full runs stay far from the cliff.
# --------------------------------------------------------------------------

_MAPS_CHECK_EVERY = 20  # tests between /proc/self/maps size probes
_MAPS_SOFT_LIMIT = 40_000  # clear compiled-program caches beyond this
_tests_done = 0


def pytest_runtest_teardown(item, nextitem):
    global _tests_done
    _tests_done += 1
    if _tests_done % _MAPS_CHECK_EVERY:
        return
    try:
        with open("/proc/self/maps", "rb") as f:
            n_maps = sum(1 for _ in f)
    except OSError:  # non-Linux: no map cap to defend against
        return
    if n_maps >= _MAPS_SOFT_LIMIT:
        jax.clear_caches()


@pytest.fixture
def rng():
    return np.random.default_rng(42)
