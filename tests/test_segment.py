import numpy as np

from elasticsearch_tpu.index import Mappings, SegmentBuilder
from elasticsearch_tpu.utils import smallfloat


def build_books():
    mappings = Mappings.from_json(
        {
            "properties": {
                "title": {"type": "text"},
                "tag": {"type": "keyword"},
                "year": {"type": "long"},
            }
        }
    )
    b = SegmentBuilder(mappings)
    b.add({"title": "the quick brown fox", "tag": "animals", "year": 2001}, "a")
    b.add({"title": "the lazy dog", "tag": "animals", "year": 2002}, "b")
    b.add({"title": "quick quick fox", "year": 2003}, "c")
    b.add({"tag": "other"}, "d")
    return b.build()


def test_postings_and_stats():
    seg = build_books()
    title = seg.fields["title"]
    assert seg.num_docs == 4
    assert title.doc_count == 3  # doc d has no title
    assert title.sum_total_tf == 4 + 3 + 3
    docs, tfs = title.postings("quick")
    np.testing.assert_array_equal(docs, [0, 2])
    np.testing.assert_array_equal(tfs, [1.0, 2.0])
    docs, _ = title.postings("missing")
    assert len(docs) == 0
    assert int(title.df[title.terms["the"]]) == 2


def test_norms_quantized():
    seg = build_books()
    title = seg.fields["title"]
    expect = [4, 3, 3, 0]
    for doc, ln in enumerate(expect):
        assert title.norm_bytes[doc] == smallfloat.int_to_byte4(ln)
    np.testing.assert_array_equal(title.quantized_lengths(), np.float32(expect))


def test_keyword_field_untokenized():
    seg = build_books()
    tag = seg.fields["tag"]
    docs, _ = tag.postings("animals")
    np.testing.assert_array_equal(docs, [0, 1])
    assert tag.doc_count == 3


def test_doc_values_with_missing():
    seg = build_books()
    year = seg.doc_values["year"]
    np.testing.assert_array_equal(year[:3], [2001.0, 2002.0, 2003.0])
    assert np.isnan(year[3])


def test_dynamic_mapping():
    m = Mappings()
    b = SegmentBuilder(m)
    b.add({"msg": "hello world", "n": 7, "x": 1.5, "flag": True})
    seg = b.build()
    assert m.fields["msg"].type == "text"
    assert m.fields["n"].type == "long"
    assert m.fields["x"].type == "double"
    assert m.fields["flag"].type == "boolean"
    assert seg.doc_values["flag"][0] == 1.0


def test_dense_vector():
    m = Mappings.from_json(
        {"properties": {"emb": {"type": "dense_vector", "dims": 4}}}
    )
    b = SegmentBuilder(m)
    b.add({"emb": [1.0, 2.0, 3.0, 4.0]})
    b.add({})
    seg = b.build()
    assert seg.vectors["emb"].shape == (2, 4)
    np.testing.assert_array_equal(seg.vectors["emb"][1], 0.0)


def test_multivalue_text():
    m = Mappings()
    b = SegmentBuilder(m)
    b.add({"t": ["red fox", "red dog"]})
    seg = b.build()
    t = seg.fields["t"]
    docs, tfs = t.postings("red")
    np.testing.assert_array_equal(docs, [0])
    np.testing.assert_array_equal(tfs, [2.0])
    assert t.sum_total_tf == 4


def test_keyword_norms_disabled():
    seg = build_books()
    assert seg.fields["tag"].has_norms is False
    assert seg.fields["title"].has_norms is True


def test_keyword_scoring_ignores_length():
    from elasticsearch_tpu.ops import bm25

    m = Mappings.from_json({"properties": {"tag": {"type": "keyword"}}})
    b = SegmentBuilder(m)
    b.add({"tag": ["a", "b", "c"]})  # dl=3
    b.add({"tag": ["a"]})  # dl=1
    seg = b.build()
    s = bm25.score_terms_dense(seg.fields["tag"], ["a"], 2)
    assert s[0] == s[1] != 0.0


def test_index_false_numeric_keeps_doc_values():
    m = Mappings.from_json(
        {"properties": {"year": {"type": "long", "index": False}}}
    )
    b = SegmentBuilder(m)
    b.add({"year": 1999})
    seg = b.build()
    assert seg.doc_values["year"][0] == 1999.0


def test_mappings_roundtrip_lossless():
    m = Mappings.from_json(
        {
            "properties": {
                "year": {"type": "long", "index": False},
                "t": {"type": "text", "analyzer": "english", "search_analyzer": "standard"},
                "k": {"type": "keyword"},
                "nt": {"type": "text", "norms": False},
            }
        }
    )
    m2 = Mappings.from_json(m.to_json())
    for name in m.fields:
        a, b2 = m.fields[name], m2.fields[name]
        assert (a.type, a.index, a.norms, a.analyzer, a.search_analyzer, a.dims) == (
            b2.type, b2.index, b2.norms, b2.analyzer, b2.search_analyzer, b2.dims
        )


def test_zero_token_doc_not_in_doc_count():
    m = Mappings.from_json(
        {"properties": {"t": {"type": "text", "analyzer": "english"}}}
    )
    b = SegmentBuilder(m)
    b.add({"t": "the of and"})  # all stopwords -> 0 tokens
    b.add({"t": "fox"})
    seg = b.build()
    t = seg.fields["t"]
    assert t.doc_count == 1
    assert t.sum_total_tf == 1


def test_builder_reuse_does_not_mutate_built_segment():
    b = SegmentBuilder(Mappings())
    b.add({"t": "one"}, "a")
    seg = b.build()
    b.add({"t": "two"}, "b")
    assert len(seg.sources) == 1 and len(seg.ids) == 1
