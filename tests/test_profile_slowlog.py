"""Profile API + search slow log (SURVEY §5.1 tracing/profiling).

Reference: search/profile/ (the "profile": true response section),
index/SearchSlowLog.java.
"""

import logging

import pytest

from elasticsearch_tpu.node import Node

MAPPINGS = {"properties": {"t": {"type": "text"}, "n": {"type": "long"}}}


def seed(node, index="p", n=20, segments=2, **extra):
    node.create_index(index, {"mappings": MAPPINGS, **extra})
    per = n // segments
    for i in range(n):
        node.index_doc(index, {"t": f"w{i % 3}", "n": i}, f"d{i}")
        if (i + 1) % per == 0:
            node.refresh(index)
    node.refresh(index)


@pytest.mark.parametrize("n_shards", [1, 2])
def test_profile_reports_per_segment_timing(n_shards):
    node = Node()
    seed(node, settings={"index": {"number_of_shards": n_shards}})
    r = node.search(
        "p", {"query": {"match": {"t": "w1"}}, "profile": True}
    )
    shards = r["profile"]["shards"]
    assert len(shards) >= 1
    q = shards[0]["searches"][0]["query"][0]
    assert q["time_in_nanos"] > 0
    assert q["breakdown"]["segments"]
    assert all(s["time_in_nanos"] >= 0 for s in q["breakdown"]["segments"])
    # no profile key without the flag
    r = node.search("p", {"query": {"match": {"t": "w1"}}})
    assert "profile" not in r


def test_slowlog_fires_on_threshold(caplog):
    node = Node()
    seed(
        node,
        settings={
            "index": {
                "search": {
                    "slowlog": {"threshold": {"query": {"warn": "0ms"}}}
                }
            }
        },
    )
    with caplog.at_level(
        logging.WARNING, logger="elasticsearch_tpu.slowlog.search"
    ):
        node.search("p", {"query": {"match": {"t": "w0"}}})
    assert any("took[" in rec.message for rec in caplog.records)


def test_slowlog_silent_below_threshold(caplog):
    node = Node()
    seed(
        node,
        settings={
            "index": {
                "search": {
                    "slowlog": {"threshold": {"query": {"warn": "1h"}}}
                }
            }
        },
    )
    with caplog.at_level(
        logging.DEBUG, logger="elasticsearch_tpu.slowlog.search"
    ):
        node.search("p", {"query": {"match": {"t": "w0"}}})
    assert not caplog.records


def test_slowlog_threshold_settable_dynamically(caplog):
    node = Node()
    seed(node)
    node.put_settings(
        "p",
        {"index": {"search": {"slowlog": {"threshold": {"query": {"warn": "0ms"}}}}}},
    )
    with caplog.at_level(
        logging.WARNING, logger="elasticsearch_tpu.slowlog.search"
    ):
        node.search("p", {"query": {"match_all": {}}})
    assert caplog.records
