"""ISSUE 8 parity fuzz: one-launch SPMD serving of sorted, aggregating,
and replicated searches.

Gate: ≥64 randomized request shapes (single-field sorts asc/desc with
missing first/last and `_doc` tiebreaks, search_after cursors, the
mesh-eligible agg family, size:0 agg-only, track_total_hits variants)
must return BIT-IDENTICAL responses (ids + order + fp32 scores/sort keys
+ agg values + totals + shard math) from:

- the SPMD mesh path (ONE shard_map launch, asserted via `served`),
- the host-loop coordinator (mesh disabled), and
- an independent numpy oracle computed from the raw documents.

A replicated 2-node cluster additionally serves the same sorted/agg
shapes with exact agg values and the documented (key, shard, insertion)
hit order. Fallbacks for still-ineligible shapes are counted, never
silent.
"""

import json

import numpy as np
import pytest

from elasticsearch_tpu.parallel.routing import shard_for_id
from elasticsearch_tpu.rest.server import RestServer

WORDS = ["ant", "bee", "cat", "dog", "elk", "fox"]
TAGS = ["x", "y", "z"]
N_DOCS = 260
N_SHARDS = 4
DAY = 86_400_000

MAPPINGS = {
    "properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "price": {"type": "long"},
        "qty": {"type": "integer"},
        "ts": {"type": "date"},
    }
}


def build_docs():
    rng = np.random.default_rng(1234)
    docs = {}
    for i in range(N_DOCS):
        doc = {
            "body": " ".join(rng.choice(WORDS, rng.integers(2, 7))),
            "tag": str(rng.choice(TAGS)),
            "qty": int(rng.integers(0, 4)),
            "ts": int(1_700_000_000_000 + int(rng.integers(0, 20)) * DAY),
        }
        if rng.random() > 0.15:  # ~15% missing price
            doc["price"] = int(rng.integers(0, 40))
        docs[f"d{i}"] = doc
    return docs


DOCS = build_docs()


@pytest.fixture(scope="module")
def rest():
    rest = RestServer()
    status, _ = rest.dispatch(
        "PUT",
        "/fz",
        {},
        json.dumps(
            {
                "settings": {"index": {"number_of_shards": N_SHARDS}},
                "mappings": MAPPINGS,
            }
        ),
    )
    assert status == 200
    lines = []
    for doc_id, doc in DOCS.items():
        lines.append(json.dumps({"index": {"_id": doc_id}}))
        lines.append(json.dumps(doc))
    status, resp = rest.dispatch(
        "POST", "/fz/_bulk", {"refresh": "true"}, "\n".join(lines)
    )
    assert status == 200 and not resp["errors"]
    return rest


def mesh_view(rest):
    mv = rest.node.get_index("fz").search.mesh_view
    assert mv is not None
    return mv


def both_paths(rest, body):
    svc = rest.node.get_index("fz")
    mv = mesh_view(rest)
    before = mv.served
    status, via_mesh = rest.dispatch(
        "POST", "/fz/_search", {}, json.dumps(body)
    )
    assert status == 200, via_mesh
    used = mv.served > before
    svc.search.mesh_view = None
    rest.node.request_cache.clear()
    try:
        status, via_host = rest.dispatch(
            "POST", "/fz/_search", {}, json.dumps(body)
        )
    finally:
        svc.search.mesh_view = mv
        rest.node.request_cache.clear()
    assert status == 200, via_host
    return via_mesh, via_host, used


def strip_took(resp):
    return {k: v for k, v in resp.items() if k != "took"}


# ------------------------------------------------------------ the oracle
#
# Independent reference computed from the raw documents: query matching
# for the pooled query shapes, the (sort key, doc) total order, and the
# agg families' exact integer arithmetic.


def matches(doc, query):
    kind, params = next(iter(query.items()))
    if kind == "match_all":
        return True
    if kind == "term":
        ((f, v),) = params.items()
        return doc.get(f) == v
    if kind == "match":
        ((f, text),) = params.items()
        terms = text.split()
        return any(t in doc.get(f, "").split() for t in terms)
    if kind == "bool":
        must = params.get("must", [])
        filt = params.get("filter", [])
        return all(matches(doc, q) for q in must + filt)
    raise AssertionError(f"oracle has no {kind}")


def oracle_sorted_ids(query, field, desc, missing_first, k):
    """Expected hit ids under the documented total order: (key asc after
    transform, shard index, within-shard insertion order)."""
    rows = []
    for seq, (doc_id, doc) in enumerate(DOCS.items()):
        if not matches(doc, query):
            continue
        v = doc.get(field)
        if v is None:
            key = -np.inf if missing_first else np.inf
        else:
            key = -float(v) if desc else float(v)
        rows.append((key, shard_for_id(doc_id, N_SHARDS), seq, doc_id))
    rows.sort(key=lambda r: (r[0], r[1], r[2]))
    return [r[3] for r in rows[:k]], len(rows)


def oracle_matched(query):
    return [doc for doc in DOCS.values() if matches(doc, query)]


# -------------------------------------------------------------- the fuzz

QUERY_POOL = [
    {"match_all": {}},
    {"match": {"body": "bee cat"}},
    {"term": {"tag": "x"}},
    {
        "bool": {
            "must": [{"match": {"body": "ant"}}],
            "filter": [{"term": {"tag": "y"}}],
        }
    },
]

SORT_POOL = [
    None,
    [{"price": "asc"}],
    [{"price": "desc"}],
    [{"price": {"order": "asc", "missing": "_first"}}],
    [{"price": {"order": "desc", "missing": "_first"}}],
    [{"price": "asc"}, "_doc"],
    [{"qty": "asc"}],
]

AGG_POOL = [
    None,
    {
        "p_stats": {"stats": {"field": "price"}},
        "q_avg": {"avg": {"field": "qty"}},
        "p_count": {"value_count": {"field": "price"}},
    },
    {
        "tags": {"terms": {"field": "tag"}},
        "tag_card": {"cardinality": {"field": "tag"}},
        "p_card": {"cardinality": {"field": "price"}},
    },
    {
        "hist": {"histogram": {"field": "price", "interval": 7}},
        "days": {"date_histogram": {"field": "ts", "fixed_interval": "1d"}},
    },
    {
        "r": {
            "range": {
                "field": "price",
                "ranges": [{"to": 10}, {"from": 10, "to": 25}, {"from": 25}],
            }
        },
        "pct": {"percentiles": {"field": "price"}},
    },
    {
        "only_x": {
            "filter": {"term": {"tag": "x"}},
            "aggs": {"s": {"sum": {"field": "price"}}},
        },
        "no_price": {"missing": {"field": "price"}},
        "g": {"global": {}, "aggs": {"mx": {"max": {"field": "qty"}}}},
    },
]

TTH_POOL = [True, 10_000, False, 4]


def fuzz_cases():
    rng = np.random.default_rng(77)
    cases = []
    for _ in range(64):
        body = {"query": dict(QUERY_POOL[rng.integers(len(QUERY_POOL))])}
        sort = SORT_POOL[rng.integers(len(SORT_POOL))]
        if sort is not None:
            body["sort"] = sort
        aggs = AGG_POOL[rng.integers(len(AGG_POOL))]
        if aggs is not None:
            body["aggs"] = aggs
        if aggs is not None and rng.random() < 0.25:
            body["size"] = 0
        else:
            body["size"] = int(rng.choice([8, 13]))
        body["track_total_hits"] = TTH_POOL[rng.integers(len(TTH_POOL))]
        cases.append(body)
    return cases


@pytest.mark.parametrize("body", fuzz_cases())
def test_fuzz_mesh_equals_host_loop_bit_exact(rest, body):
    via_mesh, via_host, used = both_paths(rest, body)
    assert used, (
        f"mesh did not serve eligible {body}: "
        f"{mesh_view(rest).last_fallback_reason}"
    )
    assert strip_took(via_mesh) == strip_took(via_host), (
        json.dumps(strip_took(via_mesh), indent=1),
        json.dumps(strip_took(via_host), indent=1),
    )


def test_fuzz_oracle_sorted_order_and_totals(rest):
    """Mesh-sorted hit order equals the raw-document oracle exactly."""
    checked = 0
    for query in QUERY_POOL:
        for sort in SORT_POOL[1:]:
            ((field, spec),) = sort[0].items()
            desc = (
                spec == "desc"
                or (isinstance(spec, dict) and spec.get("order") == "desc")
            )
            mfirst = (
                isinstance(spec, dict) and spec.get("missing") == "_first"
            )
            body = {"query": query, "sort": sort, "size": 11}
            via_mesh, _via_host, used = both_paths(rest, body)
            assert used
            want_ids, want_total = oracle_sorted_ids(
                query, field, desc, mfirst, 11
            )
            got = [h["_id"] for h in via_mesh["hits"]["hits"]]
            assert got == want_ids, (body, got, want_ids)
            assert via_mesh["hits"]["total"]["value"] == want_total
            # Sort values are the raw f32 field values (missing = null).
            for h in via_mesh["hits"]["hits"]:
                v = DOCS[h["_id"]].get(field)
                assert h["sort"] == [None if v is None else float(v)]
            checked += 1
    assert checked == len(QUERY_POOL) * (len(SORT_POOL) - 1)


def test_fuzz_oracle_agg_values(rest):
    """Mesh agg values equal exact integer arithmetic over raw docs."""
    for query in QUERY_POOL:
        body = {
            "query": query,
            "size": 0,
            "aggs": {**AGG_POOL[1], **AGG_POOL[2], **AGG_POOL[3]},
        }
        via_mesh, via_host, used = both_paths(rest, body)
        assert used
        assert strip_took(via_mesh) == strip_took(via_host)
        matched = oracle_matched(query)
        prices = [d["price"] for d in matched if "price" in d]
        aggs = via_mesh["aggregations"]
        assert aggs["p_count"]["value"] == len(prices)
        assert aggs["p_stats"]["count"] == len(prices)
        assert aggs["p_stats"]["sum"] == float(sum(prices))
        if prices:
            assert aggs["p_stats"]["min"] == float(min(prices))
            assert aggs["p_stats"]["max"] == float(max(prices))
        qtys = [d["qty"] for d in matched]
        if qtys:
            assert aggs["q_avg"]["value"] == sum(qtys) / len(qtys)
        from collections import Counter

        tag_counts = Counter(d["tag"] for d in matched)
        got = {b["key"]: b["doc_count"] for b in aggs["tags"]["buckets"]}
        assert got == dict(tag_counts)
        assert aggs["tag_card"]["value"] == len(tag_counts)
        assert aggs["p_card"]["value"] == len(set(prices))
        hist = Counter((p // 7) * 7 for p in prices)
        got = {b["key"]: b["doc_count"] for b in aggs["hist"]["buckets"]}
        assert {k: v for k, v in got.items() if v} == {
            float(k): v for k, v in hist.items()
        }
        days = Counter((d["ts"] // DAY) * DAY for d in matched)
        got = {b["key"]: b["doc_count"] for b in aggs["days"]["buckets"]}
        assert {k: v for k, v in got.items() if v} == dict(days)


def test_search_after_pagination_chain(rest):
    """Walk a sorted result set page by page via search_after on the mesh
    and via the host loop: identical pages, and their concatenation is
    the oracle's full order."""
    body = {
        "query": {"match_all": {}},
        "sort": [{"price": "asc"}],
        "size": 50,
    }
    mv = mesh_view(rest)
    seen = []
    cursor = None
    for _page in range(4):
        b = dict(body)
        if cursor is not None:
            b["search_after"] = cursor
        via_mesh, via_host, used = both_paths(rest, b)
        assert used, mv.last_fallback_reason
        assert strip_took(via_mesh) == strip_took(via_host)
        hits = via_mesh["hits"]["hits"]
        if not hits:
            break
        seen.extend(h["_id"] for h in hits)
        cursor = hits[-1]["sort"]
    want_ids, total = oracle_sorted_ids(
        {"match_all": {}}, "price", False, False, N_DOCS
    )
    # A key-only cursor resumes STRICTLY past the cursor key, skipping
    # any remaining ties at each page boundary (public search_after
    # semantics without a tiebreak value) — so the walked ids are a
    # subsequence of the oracle order, never a reordering or duplicate.
    assert len(set(seen)) == len(seen)
    seen_set = set(seen)
    assert seen == [i for i in want_ids if i in seen_set]
    assert seen[: 50] == want_ids[: 50]  # page 1 is the exact prefix
    assert len(seen) >= total - 4 * 40  # only tie-groups may be skipped


def test_size0_count_only_serves_on_mesh(rest):
    mv = mesh_view(rest)
    before = mv.served
    via_mesh, via_host, used = both_paths(
        rest, {"query": {"term": {"tag": "x"}}, "size": 0}
    )
    assert used and mv.served == before + 1
    assert strip_took(via_mesh) == strip_took(via_host)
    assert via_mesh["hits"]["hits"] == []
    want = sum(1 for d in DOCS.values() if d["tag"] == "x")
    assert via_mesh["hits"]["total"]["value"] == want


def test_fallbacks_counted_never_silent(rest):
    mv = mesh_view(rest)
    svc = rest.node.get_index("fz")
    total_before = mv.served + sum(mv.fallbacks.values())
    bodies = [
        {"query": {"match_all": {}}, "sort": [{"price": "asc"}, {"qty": "desc"}]},
        {"size": 0, "aggs": {"c": {"composite": {"sources": [
            {"t": {"terms": {"field": "tag"}}}]}}}},
        {"query": {"match": {"body": "bee"}}, "rescore": {
            "window_size": 4,
            "query": {"rescore_query": {"match": {"body": "cat"}}}}},
    ]
    for body in bodies:
        status, _ = rest.dispatch("POST", "/fz/_search", {}, json.dumps(body))
        assert status == 200
        rest.node.request_cache.clear()
    total_after = mv.served + sum(mv.fallbacks.values())
    assert total_after == total_before + len(bodies), (
        "every mesh decline must be counted", mv.fallbacks,
    )
    # The Prometheus exposition carries the reason-labeled counter.
    text = rest.node.metrics.exposition()
    assert "estpu_mesh_fallback_total" in text
    assert 'reason="sort_shape"' in text
    assert svc.search.mesh_view is mv


# ---------------------------------------------------------- replicated


REPL_DOCS = {}


def _build_repl_docs():
    rng = np.random.default_rng(55)
    for i in range(80):
        doc = {
            "body": " ".join(rng.choice(WORDS, rng.integers(2, 5))),
            "tag": str(rng.choice(TAGS)),
            "qty": int(rng.integers(0, 4)),
        }
        if rng.random() > 0.2:
            doc["price"] = int(rng.integers(0, 30))
        REPL_DOCS[f"r{i}"] = doc


_build_repl_docs()


@pytest.fixture(scope="module")
def repl():
    rest = RestServer(replication_nodes=2)
    status, resp = rest.dispatch(
        "PUT",
        "/rp",
        {},
        json.dumps(
            {
                "settings": {
                    "index": {
                        "number_of_shards": 2,
                        "number_of_replicas": 1,
                    }
                },
                "mappings": MAPPINGS,
            }
        ),
    )
    assert status == 200, resp
    for doc_id, doc in REPL_DOCS.items():
        status, resp = rest.dispatch(
            "PUT", f"/rp/_doc/{doc_id}", {}, json.dumps(doc)
        )
        assert status in (200, 201), resp
    rest.dispatch("POST", "/rp/_refresh", {}, None)
    return rest


def test_replicated_sorted_search_order(repl):
    """Replicated sorted searches merge by (sort key, shard, per-shard
    rank) with missing-value placement — previously the cluster merge
    keyed on _score (None for field sorts) and scrambled sorted hits."""
    for sort, desc, mfirst in [
        ([{"price": "asc"}], False, False),
        ([{"price": "desc"}], True, False),
        ([{"price": {"order": "asc", "missing": "_first"}}], False, True),
    ]:
        status, out = repl.dispatch(
            "POST",
            "/rp/_search",
            {},
            json.dumps(
                {"query": {"match_all": {}}, "sort": sort, "size": 15}
            ),
        )
        assert status == 200, out
        rows = []
        for seq, (doc_id, doc) in enumerate(REPL_DOCS.items()):
            v = doc.get("price")
            if v is None:
                key = -np.inf if mfirst else np.inf
            else:
                key = -float(v) if desc else float(v)
            rows.append((key, shard_for_id(doc_id, 2), seq, doc_id))
        rows.sort(key=lambda r: (r[0], r[1], r[2]))
        want = [r[3] for r in rows[:15]]
        got = [h["_id"] for h in out["hits"]["hits"]]
        assert got == want, (sort, got, want)
        for h in out["hits"]["hits"]:
            v = REPL_DOCS[h["_id"]].get("price")
            assert h["sort"] == [None if v is None else float(v)]


def test_replicated_aggs_exact(repl):
    """Aggregations on replicated indices (previously a 400): the shard
    copies return mergeable wire states, the coordinator reduces and
    renders — values exact vs raw-document arithmetic."""
    status, out = repl.dispatch(
        "POST",
        "/rp/_search",
        {},
        json.dumps(
            {
                "size": 0,
                "aggs": {
                    "st": {"stats": {"field": "price"}},
                    "tags": {"terms": {"field": "tag"}},
                    "hist": {"histogram": {"field": "price", "interval": 6}},
                    "r": {"range": {"field": "price", "ranges": [
                        {"to": 10}, {"from": 10}]}},
                    "only_x": {
                        "filter": {"term": {"tag": "x"}},
                        "aggs": {"s": {"sum": {"field": "price"}}},
                    },
                    "t2": {
                        "terms": {"field": "tag"},
                        "aggs": {"mx": {"max": {"field": "price"}}},
                    },
                    "pct": {"percentiles": {"field": "price"}},
                    "card": {"cardinality": {"field": "tag"}},
                },
            }
        ),
    )
    assert status == 200, out
    aggs = out["aggregations"]
    from collections import Counter

    prices = [d["price"] for d in REPL_DOCS.values() if "price" in d]
    assert out["hits"]["total"]["value"] == len(REPL_DOCS)
    assert aggs["st"]["count"] == len(prices)
    assert aggs["st"]["sum"] == float(sum(prices))
    assert aggs["st"]["min"] == float(min(prices))
    assert aggs["st"]["max"] == float(max(prices))
    tag_counts = Counter(d["tag"] for d in REPL_DOCS.values())
    got = {b["key"]: b["doc_count"] for b in aggs["tags"]["buckets"]}
    assert got == dict(tag_counts)
    assert aggs["card"]["value"] == len(tag_counts)
    hist = Counter((p // 6) * 6 for p in prices)
    got = {b["key"]: b["doc_count"] for b in aggs["hist"]["buckets"]}
    assert {k: v for k, v in got.items() if v} == {
        float(k): v for k, v in hist.items()
    }
    assert aggs["r"]["buckets"][0]["doc_count"] == sum(
        1 for p in prices if p < 10
    )
    assert aggs["r"]["buckets"][1]["doc_count"] == sum(
        1 for p in prices if p >= 10
    )
    x_prices = [
        d["price"]
        for d in REPL_DOCS.values()
        if d["tag"] == "x" and "price" in d
    ]
    assert aggs["only_x"]["s"]["value"] == float(sum(x_prices))
    for b in aggs["t2"]["buckets"]:
        t_prices = [
            d["price"]
            for d in REPL_DOCS.values()
            if d["tag"] == b["key"] and "price" in d
        ]
        assert b["mx"]["value"] == float(max(t_prices))
    vals = np.sort(np.asarray(prices, dtype=np.float64))
    got_pct = aggs["pct"]["values"]
    assert got_pct["50.0"] == float(np.percentile(vals, 50, method="linear"))


def test_replicated_agg_only_size0_and_search_after(repl):
    status, out = repl.dispatch(
        "POST",
        "/rp/_search",
        {},
        json.dumps(
            {
                "query": {"term": {"tag": "y"}},
                "size": 0,
                "aggs": {"n": {"value_count": {"field": "qty"}}},
            }
        ),
    )
    assert status == 200, out
    want = sum(1 for d in REPL_DOCS.values() if d["tag"] == "y")
    assert out["hits"]["total"]["value"] == want
    assert out["aggregations"]["n"]["value"] == want
    assert out["hits"]["hits"] == []
    # search_after rides the same per-shard cursor semantics.
    status, p1 = repl.dispatch(
        "POST", "/rp/_search", {},
        json.dumps({"query": {"match_all": {}},
                    "sort": [{"qty": "asc"}], "size": 30}),
    )
    assert status == 200, p1
    cursor = p1["hits"]["hits"][-1]["sort"]
    status, p2 = repl.dispatch(
        "POST", "/rp/_search", {},
        json.dumps({"query": {"match_all": {}}, "sort": [{"qty": "asc"}],
                    "size": 30, "search_after": cursor}),
    )
    assert status == 200, p2
    # Strictly past the cursor key (key-only cursor excludes ties).
    assert all(h["sort"][0] > cursor[0] for h in p2["hits"]["hits"])


def test_replicated_still_unsupported_shapes_400(repl):
    for body in [
        {"size": 0, "aggs": {"th": {"terms": {"field": "tag"}, "aggs": {
            "h": {"top_hits": {"size": 1}}}}}},
        {"size": 0, "aggs": {"m": {"matrix_stats": {"fields": ["price", "qty"]}}}},
    ]:
        status, out = repl.dispatch(
            "POST", "/rp/_search", {}, json.dumps(body)
        )
        assert status == 400, out
        assert "not supported on replicated indices" in json.dumps(out)
