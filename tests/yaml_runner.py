"""Runner for the reference's YAML REST conformance suites.

Executes the executable API specs shipped in the reference repo
(rest-api-spec/src/yamlRestTest/resources/rest-api-spec/test/ — the same
files ESClientYamlSuiteTestCase runs against a live cluster) directly
against an in-process RestServer. Each test is `setup` steps plus named
sections of steps:

    do:      invoke an API (name -> method/path from the API table below)
    match / length / is_true / is_false / gt / gte / lt / lte: assertions
    set:     stash a response value for later $var substitution
    catch:   the do must fail with the given error class/regex

This is the round-4 verdict's "cheapest way to find the next hundred
compatibility gaps": tests/test_yaml_conformance.py pins a curated green
set, and scripts/yaml_conformance.py sweeps everything for a coverage
report.
"""

from __future__ import annotations

import json
import numbers
import re
from pathlib import Path
from typing import Any

import yaml

REFERENCE_TESTS = Path(
    "/root/reference/rest-api-spec/src/yamlRestTest/resources/rest-api-spec/test"
)


class SkipTest(Exception):
    pass


class StepFailure(AssertionError):
    pass


# API name -> (method, path template with {param} placeholders).
# Params not in the template become query-string params; "body" is JSON
# (or NDJSON lines for the bulk/msearch families).
API_TABLE: dict[str, tuple[str, str]] = {
    "indices.create": ("PUT", "/{index}"),
    "indices.delete": ("DELETE", "/{index}"),
    "indices.get": ("GET", "/{index}"),
    "indices.exists": ("HEAD", "/{index}"),
    "indices.refresh": ("POST", "/{index}/_refresh"),
    "indices.flush": ("POST", "/{index}/_flush"),
    "indices.forcemerge": ("POST", "/{index}/_forcemerge"),
    "indices.get_mapping": ("GET", "/{index}/_mapping"),
    "indices.put_mapping": ("PUT", "/{index}/_mapping"),
    "indices.get_settings": ("GET", "/{index}/_settings"),
    "indices.put_settings": ("PUT", "/{index}/_settings"),
    "indices.get_alias": ("GET", "/{index}/_alias"),
    "indices.put_alias": ("PUT", "/{index}/_alias/{name}"),
    "indices.delete_alias": ("DELETE", "/{index}/_alias/{name}"),
    "indices.update_aliases": ("POST", "/_aliases"),
    "indices.put_index_template": ("PUT", "/_index_template/{name}"),
    "indices.get_index_template": ("GET", "/_index_template/{name}"),
    "indices.delete_index_template": ("DELETE", "/_index_template/{name}"),
    "indices.analyze": ("POST", "/{index}/_analyze"),
    "index": ("PUT", "/{index}/_doc/{id}"),
    "create": ("PUT", "/{index}/_create/{id}"),
    "get": ("GET", "/{index}/_doc/{id}"),
    "delete": ("DELETE", "/{index}/_doc/{id}"),
    "update": ("POST", "/{index}/_update/{id}"),
    "bulk": ("POST", "/{index}/_bulk"),
    "mget": ("POST", "/{index}/_mget"),
    "search": ("POST", "/{index}/_search"),
    "count": ("POST", "/{index}/_count"),
    "msearch": ("POST", "/{index}/_msearch"),
    "explain": ("POST", "/{index}/_explain/{id}"),
    "scroll": ("POST", "/_search/scroll"),
    "clear_scroll": ("DELETE", "/_search/scroll"),
    "delete_by_query": ("POST", "/{index}/_delete_by_query"),
    "update_by_query": ("POST", "/{index}/_update_by_query"),
    "reindex": ("POST", "/_reindex"),
    "put_script": ("PUT", "/_scripts/{id}"),
    "get_script": ("GET", "/_scripts/{id}"),
    "delete_script": ("DELETE", "/_scripts/{id}"),
    "render_search_template": ("POST", "/_render/template"),
    "search_template": ("POST", "/{index}/_search/template"),
    "cluster.health": ("GET", "/_cluster/health"),
    "cluster.stats": ("GET", "/_cluster/stats"),
    "nodes.info": ("GET", "/_nodes"),
    "cat.count": ("GET", "/_cat/count/{index}"),
    "cat.indices": ("GET", "/_cat/indices"),
    "ingest.put_pipeline": ("PUT", "/_ingest/pipeline/{id}"),
    "ingest.get_pipeline": ("GET", "/_ingest/pipeline/{id}"),
    "ingest.delete_pipeline": ("DELETE", "/_ingest/pipeline/{id}"),
    "ingest.simulate": ("POST", "/_ingest/pipeline/_simulate"),
    "rank_eval": ("POST", "/{index}/_rank_eval"),
    "tasks.list": ("GET", "/_tasks"),
    "snapshot.create_repository": ("PUT", "/_snapshot/{repository}"),
    "snapshot.create": ("PUT", "/_snapshot/{repository}/{snapshot}"),
    "snapshot.get": ("GET", "/_snapshot/{repository}/{snapshot}"),
    "snapshot.restore": (
        "POST", "/_snapshot/{repository}/{snapshot}/_restore",
    ),
}

_CATCH_STATUS = {
    "bad_request": 400,
    "missing": 404,
    "conflict": 409,
    "forbidden": 403,
    "unauthorized": 401,
    "request_timeout": 408,
}


def load_suites(path: Path) -> dict[str, list[dict]]:
    """{section name: steps}, with 'setup'/'teardown' kept separate."""
    docs = list(yaml.safe_load_all(path.read_text()))
    suites: dict[str, list[dict]] = {}
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        for name, steps in doc.items():
            suites[name] = steps or []
    return suites


class YamlRunner:
    """Executes one test section (plus its file's setup) via dispatch()."""

    def __init__(self, rest):
        self.rest = rest
        self.stash: dict[str, Any] = {}
        self.last: Any = None
        self.last_status: int = 0

    # ---------------------------------------------------------- resolution

    def _sub(self, value):
        if isinstance(value, str):
            if value.startswith("$"):
                key = value[1:]
                if key == "body":
                    return self.last
                if key in self.stash:
                    return self.stash[key]
            return value
        if isinstance(value, dict):
            return {k: self._sub(v) for k, v in value.items()}
        if isinstance(value, list):
            return [self._sub(v) for v in value]
        return value

    def _navigate(self, path: str):
        """Resolve a dotted response path ('hits.total.value', escaped
        dots with backslash, integer list indexes)."""
        if path == "$body":
            return self.last
        cur = self.last
        parts = re.split(r"(?<!\\)\.", path)
        for raw in parts:
            part = raw.replace("\\.", ".")
            if part.startswith("$"):
                part = str(self._sub(part))
            if part == "_arbitrary_key_" and isinstance(cur, dict) and cur:
                key = sorted(cur)[0]
                self.stash["_arbitrary_key_"] = key
                cur = cur[key]
                continue
            if isinstance(cur, list):
                cur = cur[int(part)]
            elif isinstance(cur, dict):
                if part not in cur:
                    raise StepFailure(
                        f"response has no [{path}] (missing [{part}]); "
                        f"got keys {sorted(cur)[:20]}"
                    )
                cur = cur[part]
            else:
                raise StepFailure(
                    f"cannot navigate [{part}] of non-container {cur!r}"
                )
        return cur

    # ------------------------------------------------------------ steps

    def run_steps(self, steps: list[dict]) -> None:
        for step in steps or []:
            ((kind, payload),) = step.items()
            handler = getattr(self, f"_step_{kind}", None)
            if handler is None:
                raise SkipTest(f"unsupported step kind [{kind}]")
            handler(payload)

    def _step_skip(self, payload) -> None:
        # Version ranges target real ES releases; feature flags describe
        # client capabilities. Headers/warnings features are harmless to
        # run without; anything else skips.
        features = payload.get("features") or []
        if isinstance(features, str):
            features = [features]
        harmless = {"headers", "allowed_warnings", "warnings",
                    "contains", "close_to", "arbitrary_key"}
        rest = [f for f in features if f not in harmless]
        if rest:
            raise SkipTest(f"requires features {rest}")

    def _step_do(self, payload) -> None:
        payload = dict(payload)
        payload.pop("headers", None)
        payload.pop("allowed_warnings", None)
        payload.pop("warnings", None)
        catch = payload.pop("catch", None)
        ((api, params),) = payload.items()
        if api not in API_TABLE:
            raise SkipTest(f"API [{api}] not in the runner table")
        params = dict(self._sub(params or {}))
        body = params.pop("body", None)
        method, template = API_TABLE[api]
        if api == "index" and "id" not in params:
            method, template = "POST", "/{index}/_doc"
        path = template
        for name in re.findall(r"\{(\w+)\}", template):
            if name not in params:
                # Optional path params: trim the trailing segment.
                path = path.replace("/{" + name + "}", "")
                continue
            value = params.pop(name)
            if isinstance(value, list):  # multi-index targets join as csv
                value = ",".join(str(v) for v in value)
            path = path.replace("{" + name + "}", str(value))
        query = {
            k: (json.dumps(v) if isinstance(v, bool) else str(v))
            for k, v in params.items()
        }
        # bool query params arrive lowercase like on the wire
        query = {k: v.lower() if v in ("True", "False") else v
                 for k, v in query.items()}
        if isinstance(body, list):  # bulk/msearch NDJSON
            raw = "\n".join(
                line if isinstance(line, str) else json.dumps(line)
                for line in body
            ) + "\n"
        elif body is None:
            raw = ""
        elif isinstance(body, str):
            raw = body
        else:
            raw = json.dumps(body)
        status, response = self.rest.dispatch(method, path, query, raw)
        self.last, self.last_status = response, status
        if catch is not None:
            want = _CATCH_STATUS.get(catch)
            if catch.startswith("/") and catch.endswith("/"):
                if status < 400:
                    raise StepFailure(
                        f"expected an error matching {catch}, got {status}"
                    )
                if not re.search(catch[1:-1], json.dumps(response)):
                    raise StepFailure(
                        f"error {response} does not match {catch}"
                    )
            elif catch in ("request", "param"):
                if status < 400:
                    raise StepFailure(
                        f"expected a request error, got {status}"
                    )
            elif want is not None and status != want:
                raise StepFailure(
                    f"expected catch [{catch}] ({want}), got {status}: "
                    f"{response}"
                )
            return
        if status >= 400:
            raise StepFailure(f"[{api}] failed with {status}: {response}")

    def _step_match(self, payload) -> None:
        for path, expected in payload.items():
            actual = self._navigate(path)
            expected = self._sub(expected)
            if (
                isinstance(expected, str)
                and len(expected) > 1
                and expected.startswith("/")
                and expected.rstrip().endswith("/")
            ):
                pattern = expected.strip().strip("/")
                if not re.search(
                    pattern, str(actual), re.VERBOSE | re.DOTALL
                ):
                    raise StepFailure(
                        f"[{path}]: {actual!r} !~ /{pattern}/"
                    )
                continue
            if (
                isinstance(expected, numbers.Number)
                and isinstance(actual, dict)
                and set(actual) == {"value", "relation"}
            ):
                # Pre-7 suites say `hits.total: N`; modern responses are
                # {value, relation} (the rest_total_hits_as_int shim).
                actual = actual["value"]
            if isinstance(expected, numbers.Number) and isinstance(
                actual, numbers.Number
            ):
                if float(actual) != float(expected):
                    raise StepFailure(
                        f"[{path}]: {actual!r} != {expected!r}"
                    )
                continue
            if actual != expected:
                raise StepFailure(f"[{path}]: {actual!r} != {expected!r}")

    def _step_set(self, payload) -> None:
        for path, var in payload.items():
            self.stash[var] = self._navigate(path)

    def _step_length(self, payload) -> None:
        for path, expected in payload.items():
            actual = self._navigate(path)
            if len(actual) != int(self._sub(expected)):
                raise StepFailure(
                    f"[{path}]: len {len(actual)} != {expected}"
                )

    def _step_is_true(self, payload) -> None:
        value = self._navigate(payload)
        if value in (None, False, "", 0, [], {}):
            raise StepFailure(f"[{payload}] is not true: {value!r}")

    def _step_is_false(self, payload) -> None:
        try:
            value = self._navigate(payload)
        except StepFailure:
            return  # absent counts as false
        if value not in (None, False, "", 0, [], {}):
            raise StepFailure(f"[{payload}] is not false: {value!r}")

    def _cmp(self, payload, op, name) -> None:
        for path, expected in payload.items():
            actual = self._navigate(path)
            if not op(float(actual), float(self._sub(expected))):
                raise StepFailure(f"[{path}]: !({actual} {name} {expected})")

    def _step_gt(self, p) -> None:
        self._cmp(p, lambda a, b: a > b, ">")

    def _step_gte(self, p) -> None:
        self._cmp(p, lambda a, b: a >= b, ">=")

    def _step_lt(self, p) -> None:
        self._cmp(p, lambda a, b: a < b, "<")

    def _step_lte(self, p) -> None:
        self._cmp(p, lambda a, b: a <= b, "<=")


def run_section(rest, path: Path, section: str) -> None:
    """Run one named section (with the file's setup first)."""
    suites = load_suites(path)
    if section not in suites:
        raise KeyError(f"{path} has no section [{section}]")
    runner = YamlRunner(rest)
    if "setup" in suites:
        runner.run_steps(suites["setup"])
    runner.run_steps(suites[section])
