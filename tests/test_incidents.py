"""Flight recorder + incident autopsy (ISSUE 19): the bounded frame
ring, the auto-capture law (any indicator leaving green freezes a
time-correlated evidence capsule within one health poll), manual grabs,
resolution records with time-to-green, the `GET /_incidents` /
`/_cat/incidents` surfaces over both cluster forms, and the
`ESTPU_INCIDENTS=0` present-but-inert mode.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

import pytest

from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.obs.incidents import IncidentService
from elasticsearch_tpu.obs.metrics import MetricsRegistry
from elasticsearch_tpu.obs.recorder import FlightRecorder
from elasticsearch_tpu.rest.server import RestServer

REPLICATED_INDEX = json.dumps(
    {
        "settings": {"number_of_shards": 1, "number_of_replicas": 1},
        "mappings": {"properties": {"b": {"type": "text"}}},
    }
)


def _until(predicate, timeout_s: float, what: str):
    deadline = time.monotonic() + timeout_s
    while True:
        result = predicate()
        if result:
            return result
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.1)


def _wait_enriched(service, incident_id: str, timeout_s: float = 10.0):
    """Enrichment (trace splice + hot threads) runs on a background
    thread; wait for it before asserting capsule anatomy."""

    def done():
        incident = service.get(incident_id)
        state = incident["capsule"]["enrichment"]
        return incident if state != "pending" else None

    return _until(done, timeout_s, f"enrichment of {incident_id}")


# --------------------------------------------------------- the frame ring


class TestFlightRecorder:
    def test_ring_is_bounded_and_ordered(self):
        rec = FlightRecorder(capacity=5)
        for i in range(12):
            rec.record(statuses={"transport": "green"}, extras={"i": i})
        frames = rec.frames()
        assert len(frames) == 5
        assert [f["i"] for f in frames] == [7, 8, 9, 10, 11]
        assert frames[-1] is rec.last()
        stats = rec.stats()
        assert stats == {
            "frames": 5,
            "capacity": 5,
            "recorded_total": 12,
        }

    def test_window_filter_and_limit(self):
        rec = FlightRecorder(capacity=10)
        first = rec.record(extras={"i": 0})
        rec.record(extras={"i": 1})
        assert rec.frames(since_ms=first["at_ms"])[0]["i"] == 0
        assert [f["i"] for f in rec.frames(limit=1)] == [1]
        assert rec.frames(until_ms=first["at_ms"] - 1) == []

    def test_registers_cataloged_instruments(self):
        registry = MetricsRegistry()
        rec = FlightRecorder(capacity=3, metrics=registry)
        rec.record(statuses={"transport": "green"})
        assert registry.value("estpu_recorder_frames_total") == 1


# ------------------------------------------------------------- standalone


@pytest.fixture(scope="module")
def node():
    n = Node(node_name="inc-node")
    n.create_index(
        "inc", {"mappings": {"properties": {"b": {"type": "text"}}}}
    )
    n.index_doc("inc", {"b": "alpha evidence"}, "1")
    n.refresh("inc")
    n.search("inc", {"query": {"match": {"b": "alpha"}}})
    yield n
    n.close()


class TestStandaloneIncidents:
    def test_health_report_records_a_frame(self, node):
        before = node.incidents.recorder.stats()["recorded_total"]
        node.health_report(verbose=True)
        after = node.incidents.recorder.stats()
        assert after["recorded_total"] == before + 1
        frame = node.incidents.recorder.last()
        assert frame["statuses"]  # per-indicator statuses
        assert "shed_recent" in frame and "evictions_recent" in frame
        assert "breaker" in frame and "hbm_total_bytes" in frame

    def test_manual_capture_capsule_anatomy(self, node):
        node.health_report(verbose=True)
        incident = node.incidents.capture(reason="unit grab")
        assert incident["status"] == "resolved"  # nothing to watch
        assert incident["trigger"] == {
            "kind": "manual",
            "reason": "unit grab",
        }
        capsule = incident["capsule"]
        assert capsule["enrichment"] == "complete"  # sync for manual
        assert capsule["frames"], "ring frames spliced in"
        assert all(
            f["at_ms"] <= incident["started_at_ms"]
            for f in capsule["frames"]
        )
        assert "hot_threads" in capsule and node.node_name in (
            capsule["hot_threads"]
        )
        # The window's slowest exemplar, spliced via the trace ring.
        traces = capsule["traces"]
        assert traces and traces[0]["trace_id"]
        assert "remediation" in capsule
        assert incident["time_to_green_ms"] is None  # manual: no arc

    def test_transition_opens_then_green_resolves(self, node):
        service = node.incidents
        service.on_report(
            [{"indicator": "transport", "from": "green", "to": "yellow"}],
            {
                "transport": {
                    "status": "yellow",
                    "symptom": "slow peer [node-9]",
                }
            },
            False,
        )
        summaries = service.incidents(verbose=False)
        mine = [
            s
            for s in summaries
            if s["trigger"].get("indicator") == "transport"
        ]
        assert mine and mine[0]["status"] == "open"
        incident_id = mine[0]["id"]
        _wait_enriched(service, incident_id)
        # A repeat transition while open must NOT double-capture; an
        # escalation (yellow -> red) is noted on the open capsule.
        service.on_report(
            [{"indicator": "transport", "from": "yellow", "to": "red"}],
            {"transport": {"status": "red", "symptom": "worse"}},
            False,
        )
        still = [
            s
            for s in service.incidents(verbose=False)
            if s["trigger"].get("indicator") == "transport"
        ]
        assert len(still) == 1 and still[0]["id"] == incident_id
        assert service.get(incident_id).get("escalations")
        # Remediation linkage: an executed action lands on the open
        # capsule live through the action hook.
        node.remediation.note_on_demand_repack("inc")
        actions = service.get(incident_id)["capsule"]["remediation"][
            "actions"
        ]
        assert any(a["kind"] == "on_demand_repack" for a in actions)
        # Green resolves with a time-to-green.
        service.on_report(
            [],
            {"transport": {"status": "green", "symptom": "ok"}},
            False,
        )
        resolved = service.get(incident_id)
        assert resolved["status"] == "resolved"
        assert resolved["time_to_green_ms"] is not None
        assert resolved["time_to_green_ms"] >= 0
        assert resolved["capsule"]["post_frames"] is not None

    def test_cat_rows_and_404(self, node):
        rows = node.cat_incidents()
        assert rows, "prior tests captured incidents"
        for row in rows:
            assert set(row) == {
                "id",
                "trigger",
                "kind",
                "status",
                "start",
                "time_to_green_ms",
                "actions",
            }
            assert all(isinstance(v, str) for v in row.values())
            assert row["status"] in ("open", "resolved")
        with pytest.raises(ApiError) as err:
            node.get_incident("inc-9999")
        assert err.value.status == 404

    def test_bundle_export_writes_json(self, node, monkeypatch):
        with tempfile.TemporaryDirectory(prefix="estpu-inc-") as d:
            monkeypatch.setattr(node.incidents, "export_dir", d)
            incident = node.incidents.capture(reason="export grab")
            path = os.path.join(d, f"incident-{incident['id']}.json")
            assert os.path.exists(path)
            with open(path) as f:
                bundle = json.load(f)
            assert bundle["id"] == incident["id"]
            assert bundle["capsule"]["frames"]


class TestRingBound:
    def test_resolved_incidents_age_out_open_survive(self, monkeypatch):
        monkeypatch.setenv("ESTPU_INCIDENTS_CAPACITY", "3")
        n = Node(node_name="ring-node")
        try:
            service = n.incidents
            assert service.capacity == 3
            # One OPEN incident, then a flood of manual (resolved) grabs:
            # the open one must survive the eviction sweep.
            service.on_report(
                [
                    {
                        "indicator": "transport",
                        "from": "green",
                        "to": "yellow",
                    }
                ],
                {"transport": {"status": "yellow", "symptom": "s"}},
                False,
            )
            open_id = service.incidents(verbose=False)[0]["id"]
            _wait_enriched(service, open_id)
            for i in range(5):
                service.capture(reason=f"flood-{i}")
            summaries = service.incidents(verbose=False)
            assert len(summaries) == 3
            assert any(s["id"] == open_id for s in summaries)
            assert service.stats()["open"] == 1
        finally:
            n.close()


class TestDisabledMode:
    def test_present_but_inert(self, monkeypatch):
        monkeypatch.setenv("ESTPU_INCIDENTS", "0")
        n = Node(node_name="off-node")
        try:
            assert n.incidents.enabled is False
            n.health_report(verbose=True)
            assert n.incidents.recorder.stats()["frames"] == 0
            grabbed = n.incidents.capture(reason="ignored")
            assert grabbed == {"enabled": False, "captured": False}
            out = n.get_incidents(verbose=True)
            assert out["enabled"] is False
            assert out["incidents"] == []
            # The stats section keeps its full shape (the AnnCache
            # disabled_stats law).
            stats = n.incidents.stats()
            assert stats["enabled"] is False
            assert stats["open"] == 0 and stats["captured_total"] == 0
            assert "recorder" in stats
            assert n._local_node_stats()["incidents"]["enabled"] is False
        finally:
            n.close()

    def test_hook_is_a_noop_when_disabled(self, monkeypatch):
        monkeypatch.setenv("ESTPU_INCIDENTS", "0")
        registry = MetricsRegistry()
        service = IncidentService.__new__(IncidentService)
        # Construct via __init__ against a bare sentinel node: disabled
        # mode must never touch it.
        IncidentService.__init__(service, node=None, metrics=registry)
        service.on_report(
            [{"indicator": "transport", "from": "green", "to": "red"}],
            {"transport": {"status": "red", "symptom": "s"}},
            True,
        )
        assert service.incidents() == []
        service.on_remediation_record({"kind": "retune"})


# ------------------------------------------------- LocalCluster auto-capture


class TestLocalClusterIncidentArc:
    @pytest.fixture(scope="class")
    def rest(self):
        mesh = os.environ.get("ESTPU_MESH_SERVING")
        os.environ["ESTPU_MESH_SERVING"] = "0"
        server = RestServer(replication_nodes=3)
        server.dispatch("PUT", "/iarc", {}, REPLICATED_INDEX)
        server.dispatch(
            "PUT", "/iarc/_doc/1", {}, json.dumps({"b": "alpha"})
        )
        yield server
        server.close()
        if mesh is None:
            os.environ.pop("ESTPU_MESH_SERVING", None)
        else:
            os.environ["ESTPU_MESH_SERVING"] = mesh

    def _wait_green(self, rest, timeout_s=30.0):
        def green():
            status, rep = rest.dispatch("GET", "/_health_report", {}, "")
            assert status == 200
            return rep if rep["status"] == "green" else None

        return _until(green, timeout_s, "green report")

    def test_kill_freezes_capsule_with_pre_trigger_frame(self, rest):
        self._wait_green(rest)
        node = rest.node
        frames_before = node.incidents.recorder.stats()["recorded_total"]
        assert frames_before >= 1  # the green polls fed the ring
        rest.cluster.kill("node-2")
        try:
            # ONE health poll both diagnoses and freezes: the capture
            # rides the report's own transition hook.
            status, rep = rest.dispatch("GET", "/_health_report", {}, "")
            assert status == 200 and rep["status"] != "green"
            status, out = rest.dispatch(
                "GET", "/_incidents", {"verbose": "false"}, ""
            )
            assert status == 200
            opened = [
                s for s in out["incidents"] if s["status"] == "open"
            ]
            assert opened, f"no incident frozen: {out}"
            sa = [
                s
                for s in opened
                if s["trigger"].get("indicator") == "shards_availability"
            ]
            assert sa, f"no shards_availability trigger: {opened}"
            incident = _wait_enriched(node.incidents, sa[0]["id"])
            capsule = incident["capsule"]
            # The named diagnosis, straight from the triggering report.
            detail = capsule["indicator"]
            assert detail is not None and detail["status"] != "green"
            assert any(
                "node-2" in d["cause"] for d in detail["diagnosis"]
            )
            # >= 1 recorder frame from BEFORE the trigger.
            assert any(
                f["at_ms"] < incident["started_at_ms"]
                and f["statuses"]
                for f in capsule["frames"]
            )
            assert "traces" in capsule and "hot_threads" in capsule
        finally:
            rest.cluster.restart("node-2")
        self._wait_green(rest)

        def resolved():
            status, out = rest.dispatch(
                "GET", "/_incidents", {"verbose": "false"}, ""
            )
            assert status == 200
            done = [
                s
                for s in out["incidents"]
                if s["trigger"].get("indicator") == "shards_availability"
                and s["status"] == "resolved"
            ]
            return done[0] if done else None

        record = _until(resolved, 30.0, "incident resolution")
        assert record["time_to_green_ms"] is not None
        assert record["time_to_green_ms"] > 0

    def test_verbose_false_skips_capsules_and_fan(self, rest):
        status, out = rest.dispatch(
            "GET", "/_incidents", {"verbose": "false"}, ""
        )
        assert status == 200
        assert "_nodes" not in out and "nodes" not in out
        for summary in out["incidents"]:
            assert "capsule" not in summary
        status, full = rest.dispatch("GET", "/_incidents", {}, "")
        assert status == 200
        assert full["_nodes"]["failed"] == 0
        assert set(full["nodes"]) >= {"node-0", "node-1", "node-2"}

    def test_incidents_polling_stays_untraced(self, rest):
        """Trace-identity law: a paced /_incidents poll must not churn
        the trace ring — the newest trace ids are unchanged by the
        scrapes (same law as /_health_report)."""

        def newest_ids():
            return [
                t["trace_id"]
                for t in rest.node.get_traces(limit=5)["traces"]
            ]

        before = newest_ids()
        for _ in range(5):
            status, _out = rest.dispatch(
                "GET", "/_incidents", {"verbose": "false"}, ""
            )
            assert status == 200
            rest.dispatch("GET", "/_incidents/inc-0001", {}, "")
        assert newest_ids() == before  # polls buffered NO traces
        # ... while an ordinary request DOES trace.
        rest.dispatch(
            "POST",
            "/iarc/_search",
            {},
            json.dumps({"query": {"match": {"b": "alpha"}}}),
        )
        after = newest_ids()
        assert after != before
        assert after[0] not in before

    def test_cat_incidents_format_json(self, rest):
        status, rows = rest.dispatch(
            "GET", "/_cat/incidents", {"format": "json"}, ""
        )
        assert status == 200
        assert isinstance(rows, list) and rows
        assert rows[0]["id"].startswith("inc-")


# -------------------------------------------------- ProcCluster capsule fan


@pytest.fixture(scope="module")
def procs():
    from elasticsearch_tpu.cluster.procs import ProcCluster

    cluster = ProcCluster(
        2, data_path=tempfile.mkdtemp(prefix="estpu-inc-procs-")
    )
    yield cluster
    cluster.close()


class TestProcClusterIncidentFan:
    def test_fan_collects_member_recorders(self, procs):
        from elasticsearch_tpu.cluster.gateway import ProcGateway

        procs.wait_for_status("green", 60)
        node = Node(
            node_name="front",
            replication=ProcGateway(procs, timeout_s=8.0),
        )
        try:
            node.health_report(verbose=True)
            # The front's recorder rode the procs HealthService hook;
            # every worker armed its member-side ring from the
            # health_inputs ship.
            assert node.incidents.recorder.stats()["frames"] >= 1
            out = node.get_incidents(verbose=True)
            assert out["_nodes"]["failed"] == 0
            assert set(out["nodes"]) >= {"front", "node-0", "node-1"}
            for worker in procs.workers:
                section = out["nodes"][worker]
                assert section["recorder"]["frames"] >= 1
                assert section["frames"], "newest member frames ride along"
            # Manual grab works over the proc topology too, including
            # the spliced-trace path through the `_ctl` scatter.
            incident = node.incidents.capture(reason="proc grab")
            assert incident["capsule"]["enrichment"] == "complete"
            assert incident["capsule"]["frames"]
        finally:
            node.close()
