"""Engine (write path, refresh, deletes) + SearchService (query-then-fetch)."""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.search.service import SearchRequest, SearchService

DOCS = [
    {"title": "the quick brown fox", "tag": "animal", "rank": 10},
    {"title": "quick quick fox jumps", "tag": "animal", "rank": 20},
    {"title": "lazy dog sleeps all day", "tag": "animal", "rank": 5},
    {"title": "brown bread recipe", "tag": "food", "rank": 30},
    {"title": "quick bread with brown butter", "tag": "food", "rank": 15},
    {"title": "fox hunting ban", "tag": "politics", "rank": 25},
]


def make_mappings():
    return Mappings(
        properties={
            "title": {"type": "text"},
            "tag": {"type": "keyword"},
            "rank": {"type": "long"},
        }
    )


def make_engine(docs=DOCS, refresh_every=None):
    """Build an engine; refresh_every=n splits docs into multiple segments."""
    engine = Engine(make_mappings())
    for i, doc in enumerate(docs):
        engine.index(doc, f"doc{i}")
        if refresh_every and (i + 1) % refresh_every == 0:
            engine.refresh()
    engine.refresh()
    return engine


def search(engine, body, index_name="test"):
    service = SearchService(engine, index_name)
    return service.search(SearchRequest.from_json(body))


def test_basic_match_search():
    engine = make_engine()
    resp = search(engine, {"query": {"match": {"title": "quick fox"}}})
    assert resp.total == 4
    assert resp.hits[0].doc_id in {"doc0", "doc1"}
    assert resp.max_score == pytest.approx(resp.hits[0].score)
    assert all(h.source is not None for h in resp.hits)


def test_multi_segment_scores_match_single_segment():
    """Segmentation must not change scores: shard-level stats like Lucene."""
    one = search(make_engine(), {"query": {"match": {"title": "quick brown fox"}}})
    many = search(
        make_engine(refresh_every=2),
        {"query": {"match": {"title": "quick brown fox"}}},
    )
    assert one.total == many.total
    assert [h.doc_id for h in one.hits] == [h.doc_id for h in many.hits]
    for a, b in zip(one.hits, many.hits):
        assert a.score == pytest.approx(b.score, rel=1e-6)


def test_delete_and_update():
    engine = make_engine()
    engine.delete("doc0")
    engine.index({"title": "completely different now", "tag": "other", "rank": 99}, "doc1")
    engine.refresh()
    resp = search(engine, {"query": {"match": {"title": "quick fox"}}})
    ids = {h.doc_id for h in resp.hits}
    assert "doc0" not in ids and "doc1" not in ids
    resp2 = search(engine, {"query": {"match": {"title": "completely different"}}})
    assert [h.doc_id for h in resp2.hits] == ["doc1"]


def test_delete_before_refresh_drops_buffered_doc():
    engine = Engine(make_mappings())
    engine.index({"title": "ephemeral doc"}, "tmp")
    engine.delete("tmp")
    engine.refresh()
    assert engine.num_docs == 0
    resp = search(engine, {"query": {"match_all": {}}})
    assert resp.total == 0


def test_realtime_get():
    engine = Engine(make_mappings())
    engine.index({"title": "unrefreshed"}, "a")
    assert engine.get("a") == {"title": "unrefreshed"}  # from buffer
    engine.refresh()
    assert engine.get("a") == {"title": "unrefreshed"}  # from segment
    assert engine.get("missing") is None


def test_pagination():
    engine = make_engine()
    all_hits = search(engine, {"query": {"match_all": {}}, "size": 6})
    page2 = search(engine, {"query": {"match_all": {}}, "size": 2, "from": 2})
    assert [h.doc_id for h in page2.hits] == [
        h.doc_id for h in all_hits.hits[2:4]
    ]
    assert page2.total == 6


def test_sort_by_field_asc_desc():
    engine = make_engine(refresh_every=2)
    asc = search(engine, {"query": {"match_all": {}}, "sort": [{"rank": "asc"}]})
    ranks = [h.sort[0] for h in asc.hits]
    assert ranks == sorted(ranks)
    assert asc.hits[0].doc_id == "doc2"  # rank 5
    desc = search(engine, {"query": {"match_all": {}}, "sort": [{"rank": {"order": "desc"}}]})
    assert desc.hits[0].doc_id == "doc3"  # rank 30
    assert [h.doc_id for h in desc.hits] == [h.doc_id for h in asc.hits][::-1]


def test_sort_missing_last():
    engine = Engine(make_mappings())
    engine.index({"title": "has rank", "rank": 1}, "a")
    engine.index({"title": "no rank"}, "b")
    engine.refresh()
    resp = search(engine, {"query": {"match_all": {}}, "sort": [{"rank": "asc"}]})
    assert [h.doc_id for h in resp.hits] == ["a", "b"]
    assert resp.hits[1].sort == [None]


def test_sort_filters_to_query_matches():
    engine = make_engine()
    resp = search(
        engine,
        {"query": {"term": {"tag": "food"}}, "sort": [{"rank": "asc"}]},
    )
    assert [h.doc_id for h in resp.hits] == ["doc4", "doc3"]


def test_sort_score_asc_returns_lowest_scoring():
    engine = make_engine()
    desc = search(engine, {"query": {"match": {"title": "quick"}}, "size": 100})
    asc = search(
        engine,
        {"query": {"match": {"title": "quick"}}, "sort": [{"_score": "asc"}], "size": 2},
    )
    # The two LOWEST-scoring hits, ascending.
    want = [h.doc_id for h in sorted(desc.hits, key=lambda h: (h.score, h.global_doc))][:2]
    assert [h.doc_id for h in asc.hits] == want


def test_multi_key_sort_supported_numeric_only():
    engine = make_engine()
    # Multi-key numeric sorts lexsort on the host path (ISSUE 8); hits
    # carry one sort value per key and order by (key1, key2, doc).
    resp = search(
        engine,
        {"query": {"match_all": {}}, "sort": [{"rank": "asc"}, "_doc"]},
    )
    values = [h.sort[0] for h in resp.hits if h.sort[0] is not None]
    assert values == sorted(values)
    # Non-numeric keys still reject, on any key position.
    with pytest.raises(ValueError, match="No mapping found for \\[tag\\]"):
        search(
            engine,
            {"query": {"match_all": {}}, "sort": [{"rank": "asc"}, {"tag": "desc"}]},
        )
    # search_after remains single-cursor: multi-key sorts refuse it.
    with pytest.raises(ValueError, match="search_after with a multi-key"):
        search(
            engine,
            {
                "query": {"match_all": {}},
                "sort": [{"rank": "asc"}, {"rank": "desc"}],
                "search_after": [5],
            },
        )


def test_source_string_form():
    engine = make_engine()
    resp = search(engine, {"query": {"match_all": {}}, "_source": "title", "size": 1})
    assert set(resp.hits[0].source.keys()) == {"title"}


def test_source_filtering():
    engine = make_engine()
    resp = search(
        engine, {"query": {"match_all": {}}, "_source": ["title"], "size": 1}
    )
    assert set(resp.hits[0].source.keys()) == {"title"}
    resp2 = search(engine, {"query": {"match_all": {}}, "_source": False, "size": 1})
    assert resp2.hits[0].source is None


def test_response_json_shape():
    engine = make_engine()
    body = search(engine, {"query": {"match": {"title": "fox"}}}).to_json("test")
    assert body["hits"]["total"] == {"value": 3, "relation": "eq"}
    assert body["_shards"]["successful"] == 1
    hit = body["hits"]["hits"][0]
    assert {"_index", "_id", "_score", "_source"} <= set(hit)


def test_empty_index_search():
    engine = Engine(make_mappings())
    engine.refresh()
    resp = search(engine, {"query": {"match": {"title": "anything"}}})
    assert resp.total == 0 and resp.hits == []


def test_seqno_monotonic():
    engine = Engine(make_mappings())
    r1 = engine.index({"title": "a"}, "x")
    r2 = engine.index({"title": "b"}, "y")
    r3 = engine.delete("x")
    assert r1["_seq_no"] < r2["_seq_no"] < r3["_seq_no"]
    assert r3["result"] == "deleted"
    r4 = engine.delete("never-existed")
    assert r4["result"] == "not_found"
