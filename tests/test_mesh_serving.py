"""SPMD mesh serving as THE production `_search` path (VERDICT r4 item 1).

A multi-shard index on a sufficient device mesh must serve eligible REST
searches through ONE shard_map program (`parallel/mesh_serving.MeshView` →
`sharded_execute`), asserted via the `served` hook, with results IDENTICAL
to the host-loop coordinator across the query-DSL matrix; refresh must be
incremental (only changed shards re-uploaded).
"""

import json

import numpy as np
import pytest

from elasticsearch_tpu.rest.server import RestServer

WORDS = ["ant", "bee", "cat", "dog", "elk", "fox", "gnu", "hen"]

MAPPINGS = {
    "properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "rank": {"type": "long"},
    }
}


@pytest.fixture(scope="module")
def rest():
    rest = RestServer()
    status, _ = rest.dispatch(
        "PUT",
        "/mesh",
        {},
        json.dumps(
            {
                "settings": {"index": {"number_of_shards": 8}},
                "mappings": MAPPINGS,
            }
        ),
    )
    assert status == 200
    rng = np.random.default_rng(17)
    lines = []
    for i in range(160):
        lines.append(json.dumps({"index": {"_id": f"d{i}"}}))
        lines.append(
            json.dumps(
                {
                    "body": " ".join(rng.choice(WORDS, rng.integers(2, 9))),
                    "tag": str(rng.choice(["x", "y", "z"])),
                    "rank": int(rng.integers(0, 500)),
                }
            )
        )
    status, resp = rest.dispatch(
        "POST", "/mesh/_bulk", {"refresh": "true"}, "\n".join(lines)
    )
    assert status == 200 and not resp["errors"]
    return rest


def mesh_view(rest):
    mv = rest.node.get_index("mesh").search.mesh_view
    assert mv is not None, "8-device CPU mesh should enable SPMD serving"
    return mv


def both_paths(rest, body: dict) -> tuple[dict, dict, bool]:
    """(mesh response, host-loop response, mesh_used) for one request."""
    svc = rest.node.get_index("mesh")
    mv = mesh_view(rest)
    before = mv.served
    status, via_mesh = rest.dispatch(
        "POST", "/mesh/_search", {}, json.dumps(body)
    )
    assert status == 200, via_mesh
    used = mv.served > before
    svc.search.mesh_view = None
    # The node's request cache would otherwise replay the mesh answer.
    rest.node.request_cache.clear()
    try:
        status, via_host = rest.dispatch(
            "POST", "/mesh/_search", {}, json.dumps(body)
        )
    finally:
        svc.search.mesh_view = mv
        rest.node.request_cache.clear()
    assert status == 200, via_host
    return via_mesh, via_host, used


DSL_MATRIX = [
    {"query": {"match": {"body": "bee cat"}}, "size": 12},
    {"query": {"match": {"body": "ant bee cat dog"}}, "size": 30},
    {"query": {"term": {"tag": "x"}}, "size": 10},
    {
        "query": {
            "bool": {
                "must": [{"match": {"body": "ant"}}],
                "filter": [{"term": {"tag": "x"}}],
            }
        }
    },
    {
        "query": {
            "bool": {
                "should": [
                    {"match": {"body": "fox"}},
                    {"match": {"body": "hen"}},
                ],
                "must_not": [{"term": {"tag": "z"}}],
            }
        }
    },
    {"query": {"range": {"rank": {"gte": 100, "lte": 400}}}, "size": 10},
    {"query": {"exists": {"field": "rank"}}, "size": 5},
    {"query": {"match_phrase": {"body": "bee cat"}}, "size": 5},
    {
        "query": {
            "dis_max": {
                "queries": [
                    {"match": {"body": "fox"}},
                    {"match": {"body": "hen"}},
                ],
                "tie_breaker": 0.3,
            }
        }
    },
    {
        "query": {
            "constant_score": {
                "filter": {"term": {"tag": "y"}},
                "boost": 2.5,
            }
        }
    },
    {"query": {"ids": {"values": ["d3", "d7", "d11"]}}},
    {"query": {"match_all": {}}, "from": 5, "size": 7},
    {"query": {"match": {"body": "bee"}}, "track_total_hits": 3},
    {"query": {"match": {"body": "bee"}}, "track_total_hits": False},
    {
        "query": {"match": {"body": "bee cat"}},
        "highlight": {"fields": {"body": {}}},
        "fields": ["tag"],
        "docvalue_fields": ["rank"],
    },
    {"query": {"match": {"body": "nosuchterm"}}},
]


@pytest.mark.parametrize("body", DSL_MATRIX)
def test_dsl_matrix_identical_and_mesh_used(rest, body):
    via_mesh, via_host, used = both_paths(rest, body)
    assert used, f"mesh path not used for {body}"
    for key in ("hits",):
        m, h = via_mesh[key], via_host[key]
        assert m.get("total") == h.get("total")
        assert m["max_score"] == h["max_score"]
        assert [x["_id"] for x in m["hits"]] == [x["_id"] for x in h["hits"]]
        assert [x["_score"] for x in m["hits"]] == [
            x["_score"] for x in h["hits"]
        ]
        for mh, hh in zip(m["hits"], h["hits"]):
            assert mh.get("_source") == hh.get("_source")
            assert mh.get("highlight") == hh.get("highlight")
            assert mh.get("fields") == hh.get("fields")
    assert via_mesh["_shards"]["total"] == 8


def test_newly_eligible_shapes_serve_on_mesh(rest):
    """Sorted, aggregating and size:0 requests — the production shapes
    ISSUE 8 moved into the one-launch SPMD program — now serve via the
    mesh (parity with the host loop is asserted by the fuzz suite in
    test_mesh_sorted_aggs.py)."""
    mv = mesh_view(rest)
    for body in [
        {"query": {"match_all": {}}, "sort": [{"rank": "desc"}]},
        {
            "query": {"match": {"body": "bee"}},
            "aggs": {"tags": {"terms": {"field": "tag"}}},
        },
        {"query": {"match_all": {}}, "size": 0},
    ]:
        before = mv.served
        status, resp = rest.dispatch(
            "POST", "/mesh/_search", {}, json.dumps(body)
        )
        assert status == 200, resp
        rest.node.request_cache.clear()
        assert mv.served == before + 1, f"mesh should serve {body}"


def test_ineligible_shapes_fall_back_counted(rest):
    mv = mesh_view(rest)
    for body, reason in [
        (
            {
                "query": {"match": {"body": "bee"}},
                "rescore": {
                    "window_size": 5,
                    "query": {"rescore_query": {"match": {"body": "cat"}}},
                },
            },
            "ineligible_shape",
        ),
        (
            {
                "query": {"match_all": {}},
                "sort": [{"rank": "asc"}, {"rank": "desc"}],
            },
            "sort_shape",
        ),
        (
            {
                "query": {"match_all": {}},
                "size": 0,
                "aggs": {
                    "t": {
                        "terms": {"field": "tag"},
                        "aggs": {"s": {"sum": {"field": "rank"}}},
                    }
                },
            },
            "agg_shape",
        ),
    ]:
        before = mv.served
        before_falls = mv.fallbacks.get(reason, 0)
        status, resp = rest.dispatch(
            "POST", "/mesh/_search", {}, json.dumps(body)
        )
        assert status == 200, resp
        rest.node.request_cache.clear()
        assert mv.served == before, f"mesh should not serve {body}"
        assert mv.fallbacks.get(reason, 0) == before_falls + 1, (
            f"fallback for {body} must be counted as [{reason}]: "
            f"{mv.fallbacks}"
        )
    # The counter is cataloged + surfaced: _nodes/stats carries the
    # per-view reasons and the node-wide served_by_shape breakdown.
    stats = rest.node.nodes_stats()
    node_stats = next(iter(stats["nodes"].values()))
    mesh_stats = node_stats["mesh_serving"]
    assert mesh_stats["views"]["mesh"]["fallbacks"].get("sort_shape")
    assert sum(mesh_stats["served_by_shape"].values()) >= 1


def test_incremental_refresh_single_shard(rest):
    mv = mesh_view(rest)
    rest.dispatch(
        "POST", "/mesh/_search", {}, json.dumps({"query": {"match_all": {}}})
    )
    packs0, rebuilds0 = mv.packs, mv.rebuilds
    # One doc update touches exactly one shard.
    status, _ = rest.dispatch(
        "PUT",
        "/mesh/_doc/d9",
        {"refresh": "true"},
        json.dumps({"body": "zebra ant", "tag": "x", "rank": 1}),
    )
    assert status in (200, 201)
    via_mesh, via_host, used = both_paths(
        rest, {"query": {"match": {"body": "zebra"}}}
    )
    assert used
    assert [h["_id"] for h in via_mesh["hits"]["hits"]] == ["d9"]
    assert via_mesh["hits"]["hits"] == via_host["hits"]["hits"]
    assert mv.rebuilds == rebuilds0
    assert mv.packs - packs0 == 1, "only the changed shard re-uploads"


def test_delete_visibility_and_totals(rest):
    mv = mesh_view(rest)
    status, resp = rest.dispatch(
        "DELETE", "/mesh/_doc/d9", {"refresh": "true"}, None
    )
    assert status == 200
    packs0 = mv.packs
    via_mesh, via_host, used = both_paths(
        rest, {"query": {"match": {"body": "zebra"}}}
    )
    assert used
    assert via_mesh["hits"]["total"]["value"] == 0
    assert via_host["hits"]["total"]["value"] == 0
    assert mv.packs - packs0 == 1


def test_growth_triggers_full_rebuild_then_parity(rest):
    mv = mesh_view(rest)
    rest.dispatch(
        "POST", "/mesh/_search", {}, json.dumps({"query": {"match_all": {}}})
    )
    docs_pad0 = mv._shapes["docs"]
    # Enough docs to overflow the per-shard doc padding on some shard.
    lines = []
    for i in range(1000, 1000 + docs_pad0 * 8 + 50):
        lines.append(json.dumps({"index": {"_id": f"g{i}"}}))
        lines.append(
            json.dumps({"body": "grow bee", "tag": "x", "rank": i})
        )
    status, resp = rest.dispatch(
        "POST", "/mesh/_bulk", {"refresh": "true"}, "\n".join(lines)
    )
    assert status == 200 and not resp["errors"]
    rebuilds0 = mv.rebuilds
    via_mesh, via_host, used = both_paths(
        rest, {"query": {"match": {"body": "grow"}}, "size": 25}
    )
    assert used
    assert mv.rebuilds == rebuilds0 + 1
    assert mv._shapes["docs"] > docs_pad0
    assert [h["_id"] for h in via_mesh["hits"]["hits"]] == [
        h["_id"] for h in via_host["hits"]["hits"]
    ]
    assert [h["_score"] for h in via_mesh["hits"]["hits"]] == [
        h["_score"] for h in via_host["hits"]["hits"]
    ]


def test_msearch_through_mesh(rest):
    mv = mesh_view(rest)
    before = mv.served
    payload = "\n".join(
        [
            json.dumps({}),
            json.dumps({"query": {"match": {"body": "bee"}}}),
            json.dumps({}),
            json.dumps({"query": {"term": {"tag": "y"}}}),
        ]
    )
    status, resp = rest.dispatch("POST", "/mesh/_msearch", {}, payload)
    assert status == 200
    assert len(resp["responses"]) == 2
    assert all(r["_shards"]["total"] == 8 for r in resp["responses"])
    assert mv.served >= before + 2
