"""Tasks registry, query timeout (partial results), cancellation.

Reference: tasks/TaskManager.java, cancellation polled in the scoring
loop (search/internal/ContextIndexSearcher.java:91), QueryPhase timeout.
"""

import threading
import time

import pytest

from elasticsearch_tpu.common.tasks import Task, TaskCancelledError, TaskManager
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.rest.server import RestServer
from elasticsearch_tpu.search.service import SearchRequest, SearchService

MAPPINGS = {"properties": {"t": {"type": "text"}, "n": {"type": "long"}}}


def seed(node, index="tk", n=40, segments=4):
    node.create_index(index, {"mappings": MAPPINGS})
    per = n // segments
    for i in range(n):
        node.index_doc(index, {"t": f"w{i % 3} common", "n": i}, f"d{i}")
        if (i + 1) % per == 0:
            node.refresh(index)
    node.refresh(index)


def test_task_manager_basics():
    tm = TaskManager("nodeX")
    t1 = tm.register("indices:data/read/search", "idx[a]")
    t2 = tm.register("indices:data/write/bulk", "bulk")
    assert t1.id == "nodeX:1" and t2.id == "nodeX:2"
    assert {t.id for t in tm.list()} == {t1.id, t2.id}
    assert [t.id for t in tm.list("indices:data/read/*")] == [t1.id]
    tm.cancel(t1.id)
    assert tm.get(t1.id).cancelled
    with pytest.raises(TaskCancelledError):
        t1.raise_if_cancelled()
    tm.unregister(t1)
    tm.unregister(t2)
    assert tm.list() == []


def test_expired_deadline_returns_partial_timed_out():
    engine = Engine(Mappings.from_json(MAPPINGS))
    for i in range(20):
        engine.index({"t": "x y z", "n": i}, f"d{i}")
    engine.refresh()
    task = Task(
        id="n:1", action="s", description="",
        deadline=time.monotonic() - 1.0,
    )
    resp = SearchService(engine).search(
        SearchRequest.from_json({"query": {"match_all": {}}}), task=task
    )
    assert resp.timed_out is True
    assert resp.hits == [] and resp.total == 0


def test_timeout_zero_over_node_and_not_cached():
    node = Node()
    seed(node)
    r = node.search("tk", {"query": {"match_all": {}}, "timeout": "0ms",
                          "size": 0})
    assert r["timed_out"] is True
    # a timed-out (partial) response must not poison the request cache
    r2 = node.search("tk", {"query": {"match_all": {}}, "size": 0})
    assert r2["timed_out"] is False
    assert r2["hits"]["total"]["value"] == 40


def test_timeout_minus_one_disables():
    node = Node()
    seed(node)
    r = node.search("tk", {"query": {"match_all": {}}, "timeout": -1,
                          "size": 0})
    assert r["timed_out"] is False
    assert r["hits"]["total"]["value"] == 40


def test_agg_only_request_honors_timeout():
    node = Node()
    seed(node)
    r = node.search(
        "tk",
        {
            "size": 0,
            "timeout": "0ms",
            "aggs": {"mx": {"max": {"field": "n"}}},
        },
    )
    assert r["timed_out"] is True
    assert r["aggregations"]["mx"]["value"] is None  # no segment ran


def test_generous_timeout_not_timed_out():
    node = Node()
    seed(node)
    r = node.search("tk", {"query": {"match": {"t": "common"}},
                          "timeout": "1m"})
    assert r["timed_out"] is False
    assert r["hits"]["total"]["value"] == 40


def test_cancel_mid_search(monkeypatch):
    node = Node()
    # Pin the solo (unbatched) DEVICE serving path: this test blocks
    # inside execute_auto, which neither the exec micro-batcher's launch
    # kernels nor an oracle-routed plan would call (queued cancellation
    # has its own tests in test_exec_batcher.py).
    node.exec_batcher = None
    node.exec_planner = None
    seed(node, segments=8)
    from elasticsearch_tpu.search import service as service_mod

    started = threading.Event()
    release = threading.Event()
    orig = service_mod.bm25_device.execute_auto

    def slow(*args, **kwargs):
        started.set()
        release.wait(timeout=5)
        return orig(*args, **kwargs)

    monkeypatch.setattr(service_mod.bm25_device, "execute_auto", slow)
    result: dict = {}

    def run():
        try:
            node.search("tk", {"query": {"match": {"t": "common"}}})
            result["outcome"] = "completed"
        except ApiError as e:
            result["outcome"] = e.err_type

    worker = threading.Thread(target=run)
    worker.start()
    assert started.wait(timeout=5)
    tasks = node.list_tasks("indices:data/read/search")
    running = tasks["nodes"][node.node_name]["tasks"]
    assert len(running) == 1
    task_id = next(iter(running))
    node.cancel_task(task_id)
    release.set()
    worker.join(timeout=10)
    assert result["outcome"] == "task_cancelled_exception"
    # the task is gone from the registry after the request unwinds
    assert node.list_tasks()["nodes"][node.node_name]["tasks"] == {}


def test_tasks_rest_routes():
    rest = RestServer()
    status, resp = rest.dispatch("GET", "/_tasks", {}, "")
    assert status == 200
    assert rest.node.node_name in resp["nodes"]
    status, resp = rest.dispatch("GET", "/_tasks/none:99", {}, "")
    assert status == 404
    status, resp = rest.dispatch("POST", "/_tasks/none:99/_cancel", {}, "")
    assert status == 404
    # a live task is visible and cancellable over REST
    task = rest.node.tasks.register("indices:data/read/search", "probe")
    status, resp = rest.dispatch("GET", f"/_tasks/{task.id}", {}, "")
    assert status == 200 and resp["task"]["action"] == "indices:data/read/search"
    status, resp = rest.dispatch("POST", f"/_tasks/{task.id}/_cancel", {}, "")
    assert status == 200
    assert rest.node.tasks.get(task.id).cancelled
    rest.node.tasks.unregister(task)
