"""Multi-process cluster serving (cluster/procs.py): 2 spawned OS worker
processes + a voting-only tiebreaker in the supervisor, all RPC over real
TCP sockets. kill -9 is a REAL SIGKILL of a data-owning process here —
half-open sockets, stale address files, no lock ever unwound — and the
headline claims (promotion within deadline, zero acked-write loss,
socket-layer partition + heal convergence) are asserted against it.

The tier-1 slice is ONE end-to-end scenario per cluster boot (workers
pay a full JAX import each, so boots are amortized); the restart/rejoin
matrix rides the `slow` lane."""

import tempfile

import pytest

from elasticsearch_tpu.cluster.procs import ProcCluster

MAPPINGS = {
    "properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
    }
}

QUERIES = [
    {"query": {"match": {"body": "payload"}}, "size": 50},
    {"query": {"term": {"tag": "red"}}, "size": 50},
    {"query": {"match_all": {}}, "size": 50},
]


def _routing(cluster, node_id, index="s", shard="0"):
    return cluster.state_of(node_id)["state"]["indices"][index]["shards"][
        shard
    ]


@pytest.fixture(scope="module")
def procs():
    cluster = ProcCluster(
        2, data_path=tempfile.mkdtemp(prefix="estpu-socket-smoke-")
    )
    yield cluster
    cluster.close()


class TestTwoProcessCluster:
    def test_kill9_promotion_partition_heal_zero_acked_loss(self, procs):
        """The acceptance scenario, one boot: index through real sockets,
        serve the search mix, SIGKILL the primary-owning process, verify
        promotion + every acked write, write on, partition at the socket
        layer, heal, verify convergence and the restarted process'
        rejoin."""
        cluster = procs
        cluster.create_index("s", n_shards=1, n_replicas=1, mappings=MAPPINGS)
        acked = []
        for i in range(24):
            resp = cluster.write(
                "s",
                f"d{i}",
                {
                    "body": f"payload term{i % 5}",
                    "tag": "red" if i % 2 else "blue",
                },
            )
            assert resp["result"] == "created", resp
            acked.append(f"d{i}")
        # The search mix serves through real sockets (scatter from the
        # supervisor's coordinating node to the worker-owned copies).
        for body in QUERIES:
            out = cluster.search("s", body)
            assert out["_shards"]["failed"] == 0, out["_shards"]
            assert out["hits"]["total"]["value"] > 0
        out = cluster.search("s", {"query": {"match_all": {}}, "size": 50})
        assert out["hits"]["total"]["value"] == len(acked)

        routing = _routing(cluster, cluster.workers[0])
        primary = routing["primary"]
        assert primary in cluster.workers
        assert "tiebreaker" not in (
            [routing["primary"]] + routing["replicas"]
        ), "voting-only tiebreaker must never hold a copy"
        survivor = [w for w in cluster.workers if w != primary][0]

        # ------------------------------------------------ kill -9 the owner
        cluster.kill_9(primary)
        cluster.wait_for(
            lambda: _routing(cluster, survivor)["primary"] == survivor,
            timeout_s=30.0,
            what="promotion after kill -9",
        )
        new_routing = _routing(cluster, survivor)
        assert new_routing["primary_term"] == routing["primary_term"] + 1
        # Zero acked-write loss through real process death.
        missing = [d for d in acked if cluster.read("s", d) is None]
        assert not missing, f"acked docs lost: {missing}"
        out = cluster.search("s", {"query": {"match_all": {}}, "size": 50})
        assert out["hits"]["total"]["value"] == len(acked)
        # Writes continue against the promoted primary.
        resp = cluster.write("s", "after-kill", {"body": "payload after"})
        assert resp["result"] == "created"
        acked.append("after-kill")

        # -------------------------------------------- restart: rejoin
        cluster.restart(primary)
        cluster.wait_for(
            lambda: primary in _routing(cluster, survivor)["in_sync"],
            timeout_s=60.0,
            what="restarted worker rejoining in-sync",
        )

        # ------------------------- socket-layer partition, then heal
        minority = primary  # freshly rejoined worker gets isolated
        majority = [survivor, "tiebreaker"]
        cluster.partition({minority}, set(majority))
        # The majority side keeps accepting acked writes (the isolated
        # copy is failed out of the in-sync set via quorum publication).
        resp = cluster.write("s", "during-split", {"body": "payload split"})
        assert resp["result"] == "created"
        acked.append("during-split")
        cluster.wait_for(
            lambda: minority
            not in _routing(cluster, survivor)["in_sync"],
            timeout_s=30.0,
            what="isolated copy failed out of in-sync",
        )
        cluster.heal_partition()
        cluster.wait_for(
            lambda: minority in _routing(cluster, survivor)["in_sync"],
            timeout_s=60.0,
            what="healed worker recovered back in-sync",
        )
        missing = [d for d in acked if cluster.read("s", d) is None]
        assert not missing, f"acked docs lost through split: {missing}"
        out = cluster.search("s", {"query": {"match_all": {}}, "size": 50})
        assert out["hits"]["total"]["value"] == len(acked)

        # Step errors are cataloged and visible, not silent.
        for worker in cluster.workers:
            assert "step_errors" in cluster.state_of(worker)


class TestStaticAddressBook:
    """Multi-host address-book mode (the `discovery.seed_hosts` analog):
    every member's transport address is explicit configuration — no
    shared-filesystem address directory, no inherited fds — the form a
    REAL multi-host deployment (one process per TPU host over DCN) would
    use. Workers bind their configured ports, discover each other from
    the static map alone, and the serving path works end to end."""

    def test_boot_discover_and_serve_with_explicit_seeds(self):
        import socket as socketlib

        # Pre-pick free ports by binding then releasing them; the gap to
        # the worker's own bind is the standard best-effort race.
        ports = []
        holders = []
        for _ in range(3):
            s = socketlib.socket()
            s.bind(("127.0.0.1", 0))
            holders.append(s)
            ports.append(s.getsockname()[1])
        for s in holders:
            s.close()
        seed_addrs = {
            "node-0": f"127.0.0.1:{ports[0]}",
            "node-1": f"127.0.0.1:{ports[1]}",
            "tiebreaker": f"127.0.0.1:{ports[2]}",
        }
        cluster = ProcCluster(
            2,
            data_path=tempfile.mkdtemp(prefix="estpu-static-book-"),
            seed_addrs=seed_addrs,
        )
        try:
            # Members really bound their CONFIGURED addresses.
            for node_id in cluster.workers:
                transport = cluster.state_of(node_id)
                assert transport["node"] == node_id
            for node_id, addr in seed_addrs.items():
                host, port = addr.split(":")
                looked_up = cluster._book.lookup(node_id)
                assert looked_up == (host, int(port))
            # Discovery: an elected master whose membership names every
            # seed — from the static map alone.
            cluster.wait_for_status("green", timeout_s=60.0)
            assert set(cluster._local_node.state.nodes) >= set(
                cluster.workers
            )
            # Serving path over the configured addresses.
            cluster.create_index(
                "s", n_shards=1, n_replicas=1, mappings=MAPPINGS
            )
            for i in range(5):
                cluster.write("s", f"d{i}", {"body": f"payload {i}"})
            out = cluster.search(
                "s", {"query": {"match": {"body": "payload"}}, "size": 10}
            )
            assert out["hits"]["total"]["value"] == 5
            assert cluster.read("s", "d0") is not None
        finally:
            cluster.close()


@pytest.mark.slow
class TestProcessChurn:
    def test_repeated_kill9_restart_cycles(self):
        """Two full kill -9 → promote → restart → rejoin cycles, killing a
        DIFFERENT owner each time; every acked write survives both."""
        cluster = ProcCluster(
            2, data_path=tempfile.mkdtemp(prefix="estpu-churn-")
        )
        try:
            cluster.create_index(
                "c", n_shards=1, n_replicas=1, mappings=MAPPINGS
            )
            acked = []
            for i in range(10):
                cluster.write("c", f"seed{i}", {"body": f"payload {i}"})
                acked.append(f"seed{i}")
            for round_i in range(2):
                routing = _routing(cluster, cluster.workers[0], index="c")
                primary = routing["primary"]
                survivor = [w for w in cluster.workers if w != primary][0]
                cluster.kill_9(primary)
                cluster.wait_for(
                    lambda s=survivor: _routing(cluster, s, index="c")[
                        "primary"
                    ]
                    == s,
                    timeout_s=30.0,
                    what=f"promotion round {round_i}",
                )
                for i in range(5):
                    doc = f"r{round_i}-{i}"
                    resp = cluster.write(
                        "c", doc, {"body": f"payload {doc}"}
                    )
                    assert resp["result"] == "created"
                    acked.append(doc)
                cluster.restart(primary)
                cluster.wait_for(
                    lambda s=survivor, p=primary: p
                    in _routing(cluster, s, index="c")["in_sync"],
                    timeout_s=60.0,
                    what=f"rejoin round {round_i}",
                )
                missing = [
                    d for d in acked if cluster.read("c", d) is None
                ]
                assert not missing, f"round {round_i} lost: {missing}"
        finally:
            cluster.close()
