"""Device kernel ↔ CPU oracle parity.

The acceptance gate from BASELINE.md: the jitted TPU query path must return
*identical* top-k (doc ids, tie order) and fp32-equal scores versus the
independent numpy oracle that replicates Lucene BM25 scoring.
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.oracle import OracleSearcher

VOCAB = [
    "alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
    "hotel", "india", "juliet", "kilo", "lima", "mike", "november",
    "oscar", "papa", "quebec", "romeo", "sierra", "tango",
]


def build_corpus(rng, n_docs=500, seed_fields=True):
    mappings = Mappings(
        properties={
            "title": {"type": "text"},
            "body": {"type": "text"},
            "tag": {"type": "keyword"},
            "rank": {"type": "long"},
        }
    )
    builder = SegmentBuilder(mappings)
    for i in range(n_docs):
        n_title = rng.integers(1, 8)
        n_body = rng.integers(5, 60)
        doc = {
            "title": " ".join(rng.choice(VOCAB, n_title)),
            "body": " ".join(rng.choice(VOCAB, n_body)),
            "tag": str(rng.choice(["red", "green", "blue", "cyan"])),
            "rank": int(rng.integers(0, 1000)),
        }
        if not seed_fields and rng.random() < 0.1:
            del doc["rank"]  # exercise missing doc values
        builder.add(doc)
    segment = builder.build()
    return mappings, segment


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(7)
    mappings, segment = build_corpus(rng, 500, seed_fields=False)
    dev = pack_segment(segment)
    seg_tree = bm25_device.segment_tree(dev)
    compiler = Compiler(dev.fields, dev.doc_values, mappings)
    oracle = OracleSearcher(segment, mappings)
    return mappings, segment, dev, seg_tree, compiler, oracle


def run_both(corpus, query_json, k=10):
    _, _, _, seg_tree, compiler, oracle = corpus
    query = parse_query(query_json)
    compiled = compiler.compile(query)
    d_scores, d_ids, d_total = bm25_device.execute(
        seg_tree, compiled.spec, compiled.arrays, k
    )
    d_scores = np.asarray(d_scores)
    d_ids = np.asarray(d_ids)
    d_total = int(d_total)
    # Trim device padding: slots beyond total hits carry -inf.
    n_valid = min(k, d_total)
    d_scores, d_ids = d_scores[:n_valid], d_ids[:n_valid]
    assert not np.isinf(d_scores).any()

    o_scores, o_ids, o_total = oracle.search(query, k)
    return (d_scores, d_ids, d_total), (o_scores, o_ids, o_total)


def assert_parity(corpus, query_json, k=10):
    (d_scores, d_ids, d_total), (o_scores, o_ids, o_total) = run_both(
        corpus, query_json, k
    )
    assert d_total == o_total, f"total hits: device {d_total} != oracle {o_total}"
    np.testing.assert_array_equal(d_ids, o_ids)
    np.testing.assert_allclose(d_scores, o_scores, rtol=1e-6, atol=1e-6)


def test_single_term_match(corpus):
    assert_parity(corpus, {"match": {"title": "alpha"}})


def test_multi_term_disjunction(corpus):
    assert_parity(corpus, {"match": {"body": "alpha bravo charlie delta"}})


def test_match_operator_and(corpus):
    assert_parity(
        corpus, {"match": {"body": {"query": "alpha bravo", "operator": "and"}}}
    )


def test_match_minimum_should_match(corpus):
    assert_parity(
        corpus,
        {"match": {"body": {"query": "alpha bravo charlie", "minimum_should_match": 2}}},
    )


def test_term_on_keyword_no_norms(corpus):
    assert_parity(corpus, {"term": {"tag": "red"}})


def test_terms_constant_score(corpus):
    assert_parity(corpus, {"terms": {"tag": ["red", "blue"], "boost": 2.5}})


def test_term_numeric_becomes_range(corpus):
    _, segment, *_ = corpus
    v = int([s for s in segment.sources if "rank" in s][0]["rank"])
    assert_parity(corpus, {"term": {"rank": v}})


def test_range_query(corpus):
    assert_parity(corpus, {"range": {"rank": {"gte": 100, "lt": 600}}})


def _mini_numeric_corpus():
    mappings = Mappings(
        properties={
            "price": {"type": "double"},
            "flag": {"type": "boolean"},
            "n": {"type": "long"},
        }
    )
    builder = SegmentBuilder(mappings)
    builder.add({"price": 0.1, "flag": True, "n": 16777217})
    builder.add({"price": 0.2, "flag": False, "n": 5})
    builder.add({"price": 0.3, "flag": True, "n": 7})
    segment = builder.build()
    dev = pack_segment(segment)
    seg_tree = bm25_device.segment_tree(dev)
    compiler = Compiler(dev.fields, dev.doc_values, mappings)
    oracle = OracleSearcher(segment, mappings)
    return seg_tree, compiler, oracle


def _run_mini(seg_tree, compiler, oracle, query_json, k=10):
    query = parse_query(query_json)
    c = compiler.compile(query)
    _, d_ids, d_total = bm25_device.execute(seg_tree, c.spec, c.arrays, k)
    _, o_ids, o_total = oracle.search(query, k)
    n = min(k, int(d_total))
    assert int(d_total) == o_total, (query_json, int(d_total), o_total)
    assert sorted(np.asarray(d_ids)[:n].tolist()) == sorted(o_ids.tolist())
    return int(d_total), sorted(o_ids.tolist())


def test_term_on_f32_unrepresentable_double():
    """term on 0.1 (not f32-exact) must match under stored-value semantics."""
    total, ids = _run_mini(*_mini_numeric_corpus(), {"term": {"price": 0.1}})
    assert total == 1 and ids == [0]


def test_range_lte_f32_unrepresentable_bound():
    total, ids = _run_mini(
        *_mini_numeric_corpus(), {"range": {"price": {"lte": 0.2}}}
    )
    assert total == 2 and ids == [0, 1]


def test_term_long_beyond_f32_mantissa():
    total, ids = _run_mini(*_mini_numeric_corpus(), {"term": {"n": 16777217}})
    assert total == 1 and ids == [0]


def test_terms_on_numeric_field():
    total, ids = _run_mini(*_mini_numeric_corpus(), {"terms": {"n": [5, 7]}})
    assert total == 2 and ids == [1, 2]


def test_term_boolean_string_value():
    total, ids = _run_mini(*_mini_numeric_corpus(), {"term": {"flag": "true"}})
    assert total == 2 and ids == [0, 2]


def test_exists_numeric(corpus):
    assert_parity(corpus, {"exists": {"field": "rank"}})


def test_exists_zero_token_value():
    """A value analyzing to zero tokens (all stopwords) still exists."""
    mappings = Mappings(
        properties={"t": {"type": "text", "analyzer": "english"}}
    )
    builder = SegmentBuilder(mappings)
    builder.add({"t": "the of and"})  # all stopwords -> 0 tokens
    builder.add({"t": "fox jumps"})
    builder.add({})  # no field at all
    segment = builder.build()
    dev = pack_segment(segment)
    seg_tree = bm25_device.segment_tree(dev)
    compiler = Compiler(dev.fields, dev.doc_values, mappings)
    oracle = OracleSearcher(segment, mappings)
    q = parse_query({"exists": {"field": "t"}})
    c = compiler.compile(q)
    _, d_ids, d_total = bm25_device.execute(seg_tree, c.spec, c.arrays, 10)
    _, o_ids, o_total = oracle.search(q, 10)
    assert int(d_total) == o_total == 2
    assert sorted(np.asarray(d_ids)[:2].tolist()) == sorted(o_ids.tolist()) == [0, 1]


def test_exists_text(corpus):
    assert_parity(corpus, {"exists": {"field": "title"}})


def test_match_all(corpus):
    assert_parity(corpus, {"match_all": {}})


def test_match_none_missing_term(corpus):
    (d_scores, d_ids, d_total), (o_scores, o_ids, o_total) = run_both(
        corpus, {"match": {"title": "zzzmissing"}}
    )
    assert d_total == o_total == 0
    assert len(d_ids) == len(o_ids) == 0


def test_bool_must_filter(corpus):
    assert_parity(
        corpus,
        {
            "bool": {
                "must": [{"match": {"body": "alpha bravo"}}],
                "filter": [{"term": {"tag": "red"}}],
            }
        },
    )


def test_bool_must_not(corpus):
    assert_parity(
        corpus,
        {
            "bool": {
                "must": [{"match": {"title": "echo"}}],
                "must_not": [{"range": {"rank": {"lt": 500}}}],
            }
        },
    )


def test_bool_should_scoring_on_top_of_must(corpus):
    assert_parity(
        corpus,
        {
            "bool": {
                "must": [{"match": {"body": "alpha"}}],
                "should": [{"match": {"title": "bravo"}}, {"term": {"tag": "green"}}],
            }
        },
    )


def test_bool_pure_should_requires_one(corpus):
    assert_parity(
        corpus,
        {"bool": {"should": [{"match": {"title": "kilo"}}, {"match": {"title": "lima"}}]}},
    )


def test_bool_minimum_should_match_2(corpus):
    assert_parity(
        corpus,
        {
            "bool": {
                "should": [
                    {"match": {"body": "alpha"}},
                    {"match": {"body": "bravo"}},
                    {"match": {"body": "charlie"}},
                ],
                "minimum_should_match": 2,
            }
        },
    )


def test_nested_bool(corpus):
    assert_parity(
        corpus,
        {
            "bool": {
                "must": [
                    {
                        "bool": {
                            "should": [
                                {"match": {"title": "alpha"}},
                                {"match": {"title": "bravo"}},
                            ]
                        }
                    },
                    {"match": {"body": "charlie"}},
                ],
                "filter": [{"range": {"rank": {"gte": 0}}}],
            }
        },
    )


def test_constant_score(corpus):
    assert_parity(
        corpus,
        {"constant_score": {"filter": {"match": {"body": "delta"}}, "boost": 3.0}},
    )


def test_boost_propagation(corpus):
    assert_parity(corpus, {"match": {"title": {"query": "alpha", "boost": 2.0}}})


def test_large_k_exceeds_hits(corpus):
    assert_parity(corpus, {"match": {"title": "alpha"}}, k=400)


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_randomized_queries(corpus, seed):
    """Fuzz: random bool queries must match the oracle exactly."""
    rng = np.random.default_rng(seed)

    def rand_leaf():
        r = rng.random()
        if r < 0.45:
            n = int(rng.integers(1, 5))
            return {"match": {str(rng.choice(["title", "body"])): " ".join(rng.choice(VOCAB, n))}}
        if r < 0.65:
            return {"term": {"tag": str(rng.choice(["red", "green", "blue", "black"]))}}
        if r < 0.85:
            lo = int(rng.integers(0, 900))
            return {"range": {"rank": {"gte": lo, "lte": lo + int(rng.integers(10, 400))}}}
        return {"exists": {"field": str(rng.choice(["rank", "title", "tag"]))}}

    for _ in range(8):
        q = {
            "bool": {
                "must": [rand_leaf() for _ in range(int(rng.integers(0, 3)))],
                "should": [rand_leaf() for _ in range(int(rng.integers(0, 3)))],
                "filter": [rand_leaf() for _ in range(int(rng.integers(0, 2)))],
                "must_not": [rand_leaf() for _ in range(int(rng.integers(0, 2)))],
            }
        }
        assert_parity(corpus, q, k=20)
