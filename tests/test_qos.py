"""Per-tenant QoS (ISSUE 17): weighted admission lanes.

Contracts under test:
- the QosController units: weight parsing, windowed cost accounting,
  the strictly-more-over-quota shed-victim rule, weighted DRR overtake,
  the hard inflight ceiling with work-conserving lane quotas, and the
  per-lane Retry-After estimate;
- the micro-batcher's weighted shedding: a full queue evicts the most
  over-quota lane's queued rider before 429ing an innocent arrival, and
  the arriving lane absorbs its own backpressure when it IS the worst;
- tenant threading: `X-Opaque-Id` (or the `ESTPU_QOS_HEADER` override)
  becomes the QoS lane from REST dispatch down to the insights
  exemplars and the `exec_saturation` health indicator, which NAMES the
  top shed tenants;
- the in-process fairness arc: one tenant flooding heavy aggregations
  cannot push 100 light tenants' windowed queue-wait p99 out of budget
  (gated on the per-lane `estpu_qos_queue_wait_recent_ms` window).
"""

import threading
import time

import pytest

from elasticsearch_tpu.exec.batcher import (
    IndexingPressureRejected,
    MicroBatcher,
)
from elasticsearch_tpu.exec.qos import (
    DEFAULT_LANE,
    OVERFLOW_LANE,
    QosController,
    parse_weights,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.obs.health import (
    HealthContext,
    indicator_exec_saturation,
)


class TestController:
    def test_parse_weights(self):
        assert parse_weights("a:4,b:0.5") == {"a": 4.0, "b": 0.5}
        assert parse_weights(" bigco : 2 ") == {"bigco": 2.0}
        # Malformed entries are dropped, not fatal; zero/negative weights
        # cannot silence a lane entirely.
        assert parse_weights("a,b:x,c:-1,:3,") == {}
        assert parse_weights(None) == {}
        # Tenant ids may themselves contain colons (trace-style ids).
        assert parse_weights("org:team:2") == {"org:team": 2.0}

    def test_windowed_cost_accounting(self):
        qos = QosController(window_s=60.0)
        qos.charge("a", 120.0)
        qos.charge("a", 30.0)
        assert qos.window_cost_ms("a") == pytest.approx(150.0)
        assert qos.window_cost_ms("never-seen") == 0.0

    def test_retry_after_uses_the_lanes_own_p50(self):
        qos = QosController()
        # Lane "slow" has been waiting ~8s; lane "fast" ~5ms. The 429
        # advertised to "fast" must not inherit "slow"'s misery.
        for _ in range(8):
            qos.note_queue_wait("slow", 8.0)
            qos.note_queue_wait("fast", 0.005)
        assert qos.retry_after_s("slow") >= 8
        assert qos.retry_after_s("fast") == 1
        # Cold lane: the fallback estimate, clamped to the 1s floor.
        assert qos.retry_after_s("cold") == 1

    def test_pick_shed_lane_is_strict(self):
        qos = QosController()
        qos.charge("hog", 5000.0)
        qos.charge("mid", 100.0)
        # The hog is strictly more over-quota than the arriving light
        # lane: it is the victim.
        assert qos.pick_shed_lane(["hog", "mid"], arriving="light") == "hog"
        # When the arrival IS the worst offender, nobody else pays:
        # pick_shed_lane declines and the arrival absorbs its own 429.
        assert qos.pick_shed_lane(["mid"], arriving="hog") is None

    def test_weights_scale_the_over_quota_ordering(self):
        qos = QosController()
        qos.set_weight("paid", 10.0)
        qos.charge("paid", 1000.0)
        qos.charge("free", 500.0)
        # Per unit weight the free lane (500/1) out-consumed the paid
        # lane (1000/10): weighted shedding targets the free lane.
        assert qos.pick_shed_lane(["paid", "free"], arriving="x") == "free"

    def test_drr_overtake(self):
        qos = QosController(quantum_ms=5.0)
        qos.charge("spender", 400.0)  # deep negative deficit
        # The spender's group is due EARLIER, but a fresh lane's group
        # overtakes: deficit-round-robin drains light lanes first.
        picked = qos.drr_pick(
            [("g-spender", 1.0, "spender"), ("g-fresh", 2.0, "fresh")]
        )
        assert picked == "g-fresh"
        # With only one candidate there is nothing to arbitrate.
        assert qos.drr_pick([("only", 1.0, "spender")]) == "only"

    def test_drr_never_starves(self):
        qos = QosController(quantum_ms=5.0)
        qos.charge("spender", 200.0)
        # Credit accrues every round: the spender eventually drains even
        # while alone in the candidate set with a deep deficit.
        picked = qos.drr_pick(
            [("g1", 1.0, "spender"), ("g2", 2.0, "spender")]
        )
        assert picked == "g1"

    def test_admission_hard_ceiling_and_shed(self):
        qos = QosController(inflight_budget=1, admit_wait_s=0.2)
        adm = qos.admit("a")
        adm.__enter__()
        try:
            t0 = time.monotonic()
            with pytest.raises(IndexingPressureRejected) as err:
                with qos.admit("b"):
                    pass
            assert time.monotonic() - t0 >= 0.2
            assert err.value.lane == "b"
            assert err.value.retry_after_s >= 1
        finally:
            adm.__exit__(None, None, None)
        # The slot freed: the same lane admits instantly now.
        with qos.admit("b"):
            pass
        stats = qos.stats()
        assert stats["lanes"]["b"]["shed"] == 1
        assert stats["lanes"]["b"]["admitted"] == 1
        assert stats["inflight"] == 0

    def test_admission_is_work_conserving(self):
        # One lane may hold the WHOLE budget while nobody else wants it:
        # weights bind under contention, never idle the device.
        qos = QosController(inflight_budget=4, admit_wait_s=0.2)
        admissions = [qos.admit("solo") for _ in range(4)]
        for a in admissions:
            a.__enter__()
        assert qos.stats()["inflight"] == 4
        for a in admissions:
            a.__exit__(None, None, None)

    def test_admission_quota_binds_under_contention(self):
        # Budget 2, two lanes: while lane b is WAITING, lane a (already
        # holding its half-share) cannot grab the freed slot first.
        qos = QosController(inflight_budget=2, admit_wait_s=5.0)
        first = qos.admit("a")
        second = qos.admit("a")
        first.__enter__()
        second.__enter__()  # work-conserving: both slots to lane a
        order = []

        def want(lane):
            with qos.admit(lane):
                order.append(lane)
                time.sleep(0.05)

        tb = threading.Thread(target=want, args=("b",))
        tb.start()
        time.sleep(0.1)  # b is now waiting on the full budget
        ta = threading.Thread(target=want, args=("a",))
        ta.start()
        time.sleep(0.05)
        first.__exit__(None, None, None)  # one slot frees
        tb.join(timeout=5)
        second.__exit__(None, None, None)
        ta.join(timeout=5)
        assert order[0] == "b", "the waiting light lane wins the freed slot"

    def test_lane_lru_bound(self):
        qos = QosController()
        for i in range(QosController.MAX_LANES + 40):
            qos.charge(f"lane-{i}", 1.0)
        assert len(qos.stats()["lanes"]) <= QosController.MAX_LANES

    def test_lane_exhaustion_folds_into_overflow(self, monkeypatch):
        # A tenant-id cardinality attack (random X-Opaque-Id per request)
        # must not mint unbounded lanes/instrument series: past the
        # ESTPU_QOS_MAX_LANES bound, NEW keys share one overflow lane.
        monkeypatch.setenv("ESTPU_QOS_MAX_LANES", "8")
        qos = QosController()
        for i in range(100):
            qos.charge(f"attacker-{i}", 1.0)
        lanes = qos.stats()["lanes"]
        assert len(lanes) <= 8
        assert OVERFLOW_LANE in lanes
        # Early tenants stay KNOWN: an idle dedicated lane may be
        # LRU-evicted, but the key re-mints its own lane on return.
        # A folded tenant STAYS folded (no instrument-series flapping).
        qos.charge("attacker-0", 1.0)
        assert "attacker-0" in qos.stats()["lanes"]
        qos.charge("attacker-99", 1.0)
        assert "attacker-99" not in qos.stats()["lanes"]
        # The default lane and explicitly weighted tenants always get
        # dedicated lanes, even after exhaustion.
        monkeypatch.setenv("ESTPU_QOS_WEIGHTS", "bigco:4")
        qos2 = QosController()
        for i in range(50):
            qos2.charge(f"noise-{i}", 1.0)
        qos2.charge("bigco", 1.0)
        qos2.note_queue_wait(DEFAULT_LANE, 0.001)
        lanes2 = qos2.stats()["lanes"]
        assert "bigco" in lanes2 and DEFAULT_LANE in lanes2

    def test_overflow_shed_names_the_overflow_lane(self, monkeypatch):
        # err.lane (and the 429 body built from it) must carry the
        # RESOLVED lane, so operators see [_overflow], not a random id.
        monkeypatch.setenv("ESTPU_QOS_MAX_LANES", "2")
        qos = QosController(inflight_budget=1, admit_wait_s=0.01)
        for i in range(4):
            qos.charge(f"t-{i}", 1.0)
        with qos.admit("t-0"):
            with pytest.raises(IndexingPressureRejected) as exc:
                with qos.admit("t-brand-new"):
                    pass
        assert f"[{OVERFLOW_LANE}]" in str(exc.value)

    def test_health_inputs_shape(self):
        qos = QosController()
        qos.note_queue_wait("bigco", 0.9)
        out = qos.health_inputs()
        assert out["lanes"] >= 1
        assert "bigco" in out["queue_wait_p99_ms_by_lane"]
        assert out["queue_wait_p99_ms_by_lane"]["bigco"] == pytest.approx(
            900.0, rel=0.01
        )


class _GatedSearcher:
    """search_many blocks until released — keeps riders queued so the
    shedding paths are reachable deterministically."""

    def __init__(self):
        self.gate = threading.Event()
        self.calls = []

    def search_many(self, requests, tasks=None):
        self.gate.wait(timeout=10)
        self.calls.append(list(requests))
        return [f"r:{r}" for r in requests]

    def search(self, request, task=None, **kwargs):
        return f"solo:{request}"


class TestBatcherWeightedShedding:
    def _run(self, batcher, searcher, request, lane, results, errors):
        try:
            results.append(
                (lane, batcher.execute(searcher, request, tenant_key=lane))
            )
        except IndexingPressureRejected as e:
            errors.append((lane, e))

    def test_full_queue_evicts_the_over_quota_lane_first(self):
        qos = QosController(inflight_budget=64)
        qos.charge("hog", 10_000.0)  # windowed history: the hog overspent
        batcher = MicroBatcher(
            max_wait_s=0.2, queue_limit=2, qos=qos
        )
        searcher = _GatedSearcher()
        results: list = []
        errors: list = []
        try:
            # First rider launches immediately and parks inside
            # search_many; the next two fill the queue to its limit.
            threads = [
                threading.Thread(
                    target=self._run,
                    args=(batcher, searcher, f"q{i}", "hog", results, errors),
                )
                for i in range(3)
            ]
            for t in threads:
                t.start()
                time.sleep(0.05)
            # An innocent light arrival finds the queue full: weighted
            # shedding evicts a queued hog rider instead of 429ing it.
            tl = threading.Thread(
                target=self._run,
                args=(batcher, searcher, "light-q", "light", results, errors),
            )
            tl.start()
            time.sleep(0.1)
            searcher.gate.set()
            for t in threads:
                t.join(timeout=10)
            tl.join(timeout=10)
        finally:
            searcher.gate.set()
            batcher.close()
        assert [lane for lane, _ in errors] == ["hog"]
        err = errors[0][1]
        assert err.lane == "hog"
        assert err.retry_after_s >= 1
        served = {lane for lane, _ in results}
        assert "light" in served
        assert qos.stats()["lanes"]["hog"]["shed"] == 1

    def test_worst_offender_arrival_absorbs_its_own_429(self):
        qos = QosController(inflight_budget=64)
        qos.charge("hog", 10_000.0)
        batcher = MicroBatcher(max_wait_s=0.2, queue_limit=2, qos=qos)
        searcher = _GatedSearcher()
        results: list = []
        errors: list = []
        try:
            threads = [
                threading.Thread(
                    target=self._run,
                    args=(
                        batcher, searcher, f"q{i}", "light", results, errors,
                    ),
                )
                for i in range(3)
            ]
            for t in threads:
                t.start()
                time.sleep(0.05)
            # The hog arrives at a queue full of LIGHT riders it already
            # out-spent: nobody else pays — the hog itself is shed.
            with pytest.raises(IndexingPressureRejected) as err:
                batcher.execute(searcher, "hog-q", tenant_key="hog")
            assert err.value.lane == "hog"
            searcher.gate.set()
            for t in threads:
                t.join(timeout=10)
        finally:
            searcher.gate.set()
            batcher.close()
        assert not errors, "no queued light rider was evicted"
        assert qos.stats()["lanes"]["hog"]["shed"] == 1


class TestHealthIndicatorNamesTenants:
    def _ctx(self, shed_recent):
        return HealthContext(
            node_inputs={
                "node-0": {
                    "batcher": {"enabled": True, "queued": 3},
                    "queue_wait_recent": {"p99": 40.0, "count": 10},
                    "shed_recent": shed_recent,
                    "qos": {
                        "lanes": 4,
                        "shed_recent_by_lane": {"bigco": shed_recent},
                        "queue_wait_p99_ms_by_lane": {"bigco": 900.0},
                    },
                }
            }
        )

    def test_red_names_the_top_shed_tenants(self):
        out = indicator_exec_saturation(self._ctx(120))
        assert out["status"] == "red"
        assert "[bigco]=120" in out["symptom"]
        assert any(
            "[bigco]=120" in d["cause"] for d in out["diagnosis"]
        )
        node = out["details"]["nodes"]["node-0"]
        assert node["shed_recent_by_lane"] == {"bigco": 120}
        assert node["queue_wait_p99_ms_by_lane"] == {"bigco": 900.0}

    def test_yellow_names_them_too(self):
        out = indicator_exec_saturation(self._ctx(3))
        assert out["status"] == "yellow"
        assert "[bigco]=3" in out["symptom"]


class TestTenantThreading:
    @pytest.fixture()
    def rest(self):
        import json

        from elasticsearch_tpu.rest.server import RestServer

        rest = RestServer()
        status, _ = rest.dispatch(
            "PUT",
            "/tidx",
            {},
            json.dumps(
                {"mappings": {"properties": {"v": {"type": "integer"}}}}
            ),
        )
        assert status == 200
        for i in range(8):
            rest.dispatch(
                "PUT", f"/tidx/_doc/{i}", {}, json.dumps({"v": i})
            )
        rest.dispatch("POST", "/tidx/_refresh", {}, "")
        yield rest
        rest.close()

    def test_opaque_id_becomes_the_lane_and_insight_tenant(self, rest):
        import json

        body = json.dumps({"query": {"match_all": {}}, "size": 2})
        status, _ = rest.dispatch(
            "POST",
            "/tidx/_search",
            {},
            body,
            headers={"X-Opaque-Id": "tenant-zed"},
        )
        assert status == 200
        assert "tenant-zed" in rest.node.qos.stats()["lanes"]
        status, insights = rest.dispatch(
            "GET", "/_insights/queries", {}, ""
        )
        assert status == 200
        tenants = {q.get("tenant") for q in insights["queries"]}
        assert "tenant-zed" in tenants

    def test_absent_header_rides_the_default_lane(self, rest):
        import json

        body = json.dumps({"query": {"match_all": {}}, "size": 1})
        status, _ = rest.dispatch("POST", "/tidx/_search", {}, body)
        assert status == 200
        assert DEFAULT_LANE in rest.node.qos.stats()["lanes"]

    def test_qos_header_override(self, monkeypatch):
        import json

        from elasticsearch_tpu.rest.server import RestServer

        monkeypatch.setenv("ESTPU_QOS_HEADER", "X-Team")
        rest = RestServer()
        try:
            rest.dispatch(
                "PUT",
                "/oidx",
                {},
                json.dumps(
                    {"mappings": {"properties": {"v": {"type": "integer"}}}}
                ),
            )
            rest.dispatch("PUT", "/oidx/_doc/0", {}, json.dumps({"v": 1}))
            rest.dispatch("POST", "/oidx/_refresh", {}, "")
            status, _ = rest.dispatch(
                "POST",
                "/oidx/_search",
                {},
                json.dumps({"query": {"match_all": {}}}),
                headers={"X-Team": "blue", "X-Opaque-Id": "ignored"},
            )
            assert status == 200
            lanes = rest.node.qos.stats()["lanes"]
            assert "blue" in lanes
            assert "ignored" not in lanes
        finally:
            rest.close()


class TestFairnessArcInProcess:
    """One tenant floods heavy aggregations; 100 light tenants' windowed
    queue-wait p99 stays in budget (the in-process half of the ISSUE 17
    fairness acceptance arc — the socketed half lives in
    test_chaos_arcs.py)."""

    LIGHT_BUDGET_MS = 1500.0

    def test_flood_does_not_starve_light_lanes(self):
        n = Node(data_path=None)
        try:
            n.create_index(
                "fair",
                {
                    "mappings": {
                        "properties": {
                            "f": {"type": "keyword"},
                            "v": {"type": "integer"},
                        }
                    }
                },
            )
            for i in range(64):
                n.index_doc("fair", {"f": f"k{i % 8}", "v": i}, str(i))
            n.refresh("fair")
            heavy_body = {
                "size": 0,
                "aggs": {
                    "byf": {
                        "terms": {"field": "f"},
                        "aggs": {"sv": {"sum": {"field": "v"}}},
                    }
                },
            }
            light_body = {
                "size": 0,
                "aggs": {"mv": {"max": {"field": "v"}}},
            }
            # Pin a small admission budget so the flood actually
            # contends (the default 16 would never saturate here).
            n.qos.inflight_budget = 4
            stop = threading.Event()
            flood_errors: list = []

            def flood():
                while not stop.is_set():
                    try:
                        n.search(
                            "fair",
                            dict(heavy_body),
                            request_cache=False,
                            tenant="hog",
                        )
                    # A flood MAY be shed — that is the mechanism working.
                    except Exception as e:  # noqa: BLE001
                        flood_errors.append(e)
                        if not isinstance(e, Exception):
                            raise

            floods = [
                threading.Thread(target=flood, daemon=True)
                for _ in range(8)
            ]
            for t in floods:
                t.start()
            time.sleep(0.2)  # flood is established
            try:
                for i in range(100):
                    n.search(
                        "fair",
                        dict(light_body),
                        request_cache=False,
                        tenant=f"light-{i}",
                    )
            finally:
                stop.set()
                for t in floods:
                    t.join(timeout=10)
            # Gate on the per-lane rolling windows: every light lane's
            # p99 admission wait stays in budget while the hog floods.
            worst = 0.0
            gated = 0
            for i in range(100):
                w = n.metrics.window(
                    "estpu_qos_queue_wait_recent_ms", lane=f"light-{i}"
                )
                if w is None:
                    continue
                gated += 1
                worst = max(worst, w.snapshot()["p99"])
            assert gated == 100, "every light lane must have a wait window"
            assert worst < self.LIGHT_BUDGET_MS, (
                f"light-lane p99 {worst:.1f}ms blew the "
                f"{self.LIGHT_BUDGET_MS}ms fairness budget"
            )
            # The hog really was contending (its lane did the spending).
            assert n.qos.window_cost_ms("hog") > 0.0
        finally:
            n.close()
