"""Satellite robustness fixes riding with ISSUE 1.

- parse_distance_meters: longest-suffix-first so nmi/cm/mm are reachable
- wildcard/_all search matching zero indices → empty success, not 404
- triple-mustache raw rendering of non-strings emits valid JSON
- rejected docs leave no ghost dynamic mappings behind
"""

import json

import pytest

from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.query.dsl import parse_distance_meters
from elasticsearch_tpu.rest.server import RestServer
from elasticsearch_tpu.script.mustache import render


class TestDistanceUnits:
    @pytest.mark.parametrize(
        "text,meters",
        [
            ("1m", 1.0),
            ("1km", 1000.0),
            ("1mi", 1609.344),
            ("1nmi", 1852.0),  # previously shadowed by "mi"
            ("1yd", 0.9144),
            ("1ft", 0.3048),
            ("1cm", 0.01),
            ("1mm", 0.001),
        ],
    )
    def test_every_suffix_reachable(self, text, meters):
        assert parse_distance_meters(text) == pytest.approx(meters)

    def test_bare_numbers(self):
        assert parse_distance_meters(250) == 250.0
        assert parse_distance_meters("250") == 250.0
        assert parse_distance_meters("2.5km") == 2500.0

    def test_nmi_is_not_miles(self):
        # The regression this guards: "10nmi" parsed as 10 miles.
        assert parse_distance_meters("10nmi") == pytest.approx(18520.0)
        assert parse_distance_meters("10nmi") != pytest.approx(16093.44)


class TestAllowNoIndices:
    def test_all_with_no_indices_is_empty_success(self):
        node = Node()
        out = node.search("_all", {"query": {"match_all": {}}})
        assert out["hits"]["total"]["value"] == 0
        assert out["hits"]["hits"] == []
        assert out["_shards"]["total"] == 0

    def test_wildcard_matching_nothing_is_empty_success(self):
        rest = RestServer()
        rest.dispatch(
            "PUT", "/existing", {},
            json.dumps({"mappings": {"properties": {"a": {"type": "text"}}}}),
        )
        status, resp = rest.dispatch(
            "POST", "/nomatch-*/_search", {},
            json.dumps({"query": {"match_all": {}}}),
        )
        assert status == 200, resp
        assert resp["hits"]["total"]["value"] == 0
        status, resp = rest.dispatch("GET", "/_search", {}, "")
        assert status == 200  # _all over one index still works
        # _count over a zero-match wildcard follows the same contract.
        status, resp = rest.dispatch("POST", "/nomatch-*/_count", {}, "")
        assert status == 200 and resp["count"] == 0

    def test_concrete_missing_name_still_404s(self):
        rest = RestServer()
        status, resp = rest.dispatch(
            "POST", "/missing/_search", {},
            json.dumps({"query": {"match_all": {}}}),
        )
        assert status == 404
        assert resp["error"]["type"] == "index_not_found_exception"

    def test_empty_node_all_search_via_rest(self):
        rest = RestServer()
        status, resp = rest.dispatch("GET", "/_search", {}, "")
        assert status == 200, resp
        assert resp["hits"]["total"]["value"] == 0


class TestMustacheRawRendering:
    def test_bool_renders_as_json(self):
        assert render("{{{v}}}", {"v": True}) == "true"
        assert render("{{{v}}}", {"v": False}) == "false"

    def test_none_renders_as_json_null(self):
        assert render("{{{v}}}", {"v": None}) == "null"

    def test_missing_variable_renders_empty(self):
        assert render("{{{gone}}}", {}) == ""

    def test_dict_and_list_render_as_json(self):
        out = render("{{{v}}}", {"v": {"match": {"f": "x"}}})
        assert json.loads(out) == {"match": {"f": "x"}}
        out = render("{{{v}}}", {"v": [1, "two", True, None]})
        assert json.loads(out) == [1, "two", True, None]

    def test_string_stays_raw_unescaped(self):
        assert render('{{{v}}}', {"v": 'say "hi" \\'}) == 'say "hi" \\'

    def test_rendered_template_parses_as_search_body(self):
        template = '{"query": {"bool": {"filter": {{{filters}}}}}}'
        out = render(
            template, {"filters": [{"term": {"tag": "x"}}]}
        )
        body = json.loads(out)
        assert body["query"]["bool"]["filter"] == [{"term": {"tag": "x"}}]


class TestNoGhostMappings:
    def test_rejected_doc_leaves_no_dynamic_mapping(self):
        rest = RestServer()
        rest.dispatch(
            "PUT", "/gm", {},
            json.dumps({"mappings": {"properties": {"n": {"type": "long"}}}}),
        )
        # "ghost" (a NEW dynamic field) stages before "n" rejects.
        status, resp = rest.dispatch(
            "PUT", "/gm/_doc/1", {},
            json.dumps({"ghost": "hello", "n": "not-a-number"}),
        )
        assert status == 400, resp
        status, resp = rest.dispatch("GET", "/gm/_mapping", {}, "")
        props = resp["gm"]["mappings"]["properties"]
        assert "ghost" not in props, "rejected doc left a ghost mapping"
        # A subsequent VALID doc maps the field normally.
        status, _ = rest.dispatch(
            "PUT", "/gm/_doc/2", {}, json.dumps({"ghost": "hello", "n": 4})
        )
        assert status == 200
        _, resp = rest.dispatch("GET", "/gm/_mapping", {}, "")
        assert "ghost" in resp["gm"]["mappings"]["properties"]

    def test_rejected_rank_features_leave_no_leaf_mappings(self):
        rest = RestServer()
        rest.dispatch(
            "PUT", "/rf", {},
            json.dumps(
                {"mappings": {"properties": {
                    "feats": {"type": "rank_features"},
                    "n": {"type": "long"},
                }}}
            ),
        )
        status, _ = rest.dispatch(
            "PUT", "/rf/_doc/1", {},
            json.dumps({"feats": {"a": 1.5, "b": 2.0}, "n": "bad"}),
        )
        assert status == 400
        _, resp = rest.dispatch("GET", "/rf/_mapping", {}, "")
        props = resp["rf"]["mappings"]["properties"]
        assert "feats.a" not in props and "feats.b" not in props

    def test_dynamic_mapping_still_works_for_accepted_docs(self):
        node = Node()
        node.create_index("dyn")
        node.index_doc("dyn", {"fresh": "text value", "num": 3}, "1")
        svc = node.get_index("dyn")
        assert svc.mappings.get("fresh") is not None
        assert svc.mappings.get("fresh.keyword") is not None
        assert svc.mappings.get("num").type in ("long", "double")
        node.refresh("dyn")
        out = node.search("dyn", {"query": {"match": {"fresh": "text"}}})
        assert out["hits"]["total"]["value"] == 1
