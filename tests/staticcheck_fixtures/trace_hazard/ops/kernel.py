"""Fixture: trace-hazard positives + suppressed twins (not collected by
pytest; analyzed as a mini-project by tests/test_staticcheck.py)."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("spec", "k"))
def execute(spec, arrays, k):
    total = jnp.sum(arrays)
    bad = float(total)  # host-sync: traced value forced to host
    ok = float(k)  # static arg: fine
    if total > 0:  # traced-branch: data-dependent Python if
        bad += 1.0
    # staticcheck: ignore[host-sync] fixture: suppressed twin
    bad2 = np.asarray(total)
    # staticcheck: ignore[traced-branch] fixture: suppressed twin
    if total > 1:
        ok += 1.0
    return helper(arrays), bad, bad2, ok


def helper(xs):
    # Reachable from the jit root above: flagged transitively.
    return xs.item()  # host-sync via reachability


def ephemeral(xs):
    return jax.jit(helper)(xs)  # jit-ephemeral: fresh cache per call


def caller(arrays):
    # list literal in the static [spec] position: unhashable.
    return execute([1, 2], arrays, 10)
