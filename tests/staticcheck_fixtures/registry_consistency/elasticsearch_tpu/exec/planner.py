"""Fixture planner: [ghost] has no cost seed and no surfacing site;
[packed] is surfaced (user.py) but UNSEEDED — the multi-tenant backend
registered without a cost seed must fail the gate."""


class ExecPlanner:
    BACKENDS = ("device", "ghost", "packed")
