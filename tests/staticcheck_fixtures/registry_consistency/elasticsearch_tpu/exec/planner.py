"""Fixture planner: [ghost] has no cost seed and no surfacing site;
[packed], [mesh_spmd] and [cached_mask] are surfaced (user.py) but
UNSEEDED — the multi-tenant backend, the SPMD mesh plan class, and the
filter-cache masked-execution backend registered without an
exec/cost.py seed must each fail the gate."""


class ExecPlanner:
    BACKENDS = ("device", "ghost", "packed", "mesh_spmd", "cached_mask")
