"""Fixture planner: [ghost] has no cost seed and no surfacing site."""


class ExecPlanner:
    BACKENDS = ("device", "ghost")
