"""Fixture planner: [ghost] has no cost seed and no surfacing site;
[packed], [mesh_spmd], [cached_mask] and [ann_ivf] are surfaced
(user.py) but UNSEEDED — the multi-tenant backend, the SPMD mesh plan
class, the filter-cache masked-execution backend, and the IVF ANN
backend registered without an exec/cost.py seed must each fail the
gate."""


class ExecPlanner:
    BACKENDS = (
        "device", "ghost", "packed", "mesh_spmd", "cached_mask", "ann_ivf",
    )
