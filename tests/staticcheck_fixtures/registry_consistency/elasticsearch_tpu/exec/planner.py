"""Fixture planner: [ghost] has no cost seed and no surfacing site;
[packed] and [mesh_spmd] are surfaced (user.py) but UNSEEDED — the
multi-tenant backend and the SPMD mesh plan class registered without an
exec/cost.py seed must each fail the gate."""


class ExecPlanner:
    BACKENDS = ("device", "ghost", "packed", "mesh_spmd")
