"""Fixture cost model: only [device] is seeded."""

SEEDED = ("device",)
