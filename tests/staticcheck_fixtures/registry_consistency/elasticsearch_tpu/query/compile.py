"""Fixture bool-spec module: raw construction + out-of-range index."""

BOOL_SPEC_FIELDS = (
    "kind",
    "must",
    "should",
    "filter",
    "must_not",
    "msm",
    "lead",
)


def make_bool_spec(must, should, filter_, must_not, msm, lead):
    return (
        "bool",
        tuple(must),
        tuple(should),
        tuple(filter_),
        tuple(must_not),
        int(msm),
        int(lead),
    )


def rogue_build(groups, msm):
    return ("bool", tuple(groups), int(msm))  # raw construction


def rogue_read(spec):
    if spec[0] == "bool":
        return spec[7]  # index beyond the declared arity
    return None


def suppressed_build(groups, msm):
    # staticcheck: ignore[bool-spec] fixture: suppressed twin
    return ("bool", tuple(groups), int(msm))
