"""Fixture remediation-planner registry (registry-action).

[steady] is registered AND implemented (clean); [phantom] is registered
with no planner; plan_rogue is implemented but never registered — both
directions must fail the gate.
"""

ACTIONS = (
    "steady",
    "phantom",
)


def plan_steady(ctx):
    return []


def plan_rogue(ctx):
    return []
