"""Fixture health-indicator registry (registry-indicator).

[good] is registered AND implemented (clean); [missing] is registered
with no implementation; indicator_ghost is implemented but never
registered — both directions must fail the gate.
"""

INDICATORS = (
    "good",
    "missing",
)


def indicator_good(ctx):
    return {"status": "green", "symptom": "fixture"}


def indicator_ghost(ctx):
    return {"status": "green", "symptom": "never renders"}
