"""Fixture HBM-ledger label registry (registry-breaker-label)."""

LEDGER_LABELS = (
    "segment",
    "filter_cache",
)
