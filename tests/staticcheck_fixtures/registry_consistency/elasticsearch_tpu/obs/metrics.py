"""Fixture catalog: [estpu_dead_total] is never referenced by code."""

CATALOG = {
    "estpu_good_total": ("counter", "fixture"),
    "estpu_kind_total": ("counter", "fixture"),
    "estpu_dead_total": ("counter", "fixture"),
    "estpu_good_recent_ms": ("windowed_histogram", "fixture"),
}
