"""Fixture fault registry: [dead.site] has no call site."""

SITES = ("search.kernel", "dead.site")


def fault_point(site: str, **ctx) -> None:
    pass
