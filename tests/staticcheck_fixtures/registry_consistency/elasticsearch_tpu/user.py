"""Fixture consumers: the call sites the registry contracts check."""

from .faults.registry import fault_point


def arm_faults():
    fault_point("search.kernel")  # registered: fine
    fault_point("unregistered.site")  # not in SITES
    # A socket-transport site that never made it into SITES must fail
    # exactly like any other unregistered chaos hook.
    fault_point("transport.tcp.frame")
    # staticcheck: ignore[registry-fault-site] fixture: suppressed twin
    fault_point("other.bad")


def arm_async_qos_faults():
    # New-subsystem chaos hooks must register like any other: an
    # async-search reduce fold or a QoS lane shed that never made it
    # into SITES fails the gate.
    fault_point("async.reduce")
    fault_point("qos.shed")


def make_instruments(m):
    m.counter("estpu_good_total", "cataloged: fine")
    m.counter("estpu_rogue_total", "not in CATALOG")
    m.gauge("estpu_kind_total", "cataloged as counter: kind mismatch")
    m.histogram("estpu_packed_rogue_total", "packed instrument not in CATALOG")
    m.counter("estpu_mesh_rogue_total", "mesh instrument not in CATALOG")


def route(backend="device"):
    return backend


def route_packed():
    # Surfacing site for the packed backend (so only its MISSING cost
    # seed fires, isolating that half of the contract).
    return "packed"


def route_mesh():
    # Surfacing site for the SPMD mesh backend: an unseeded mesh plan
    # class must fail exactly like an unseeded packed one.
    return "mesh_spmd"


def route_cached_mask():
    # Surfacing site for the filter-cache masked-execution backend: an
    # unseeded cached_mask registration must fail exactly like packed.
    return "cached_mask"


def route_ann():
    # Surfacing site for the IVF ANN backend: an unseeded ann_ivf
    # registration must fail exactly like packed.
    return "ann_ivf"


def make_ann_instruments(m):
    m.counter("estpu_ann_rogue_total", "ANN instrument not in CATALOG")


def make_filter_cache_instruments(m):
    m.counter(
        "estpu_filter_cache_rogue_total",
        "filter-cache instrument not in CATALOG",
    )


def make_transport_instruments(m):
    m.counter(
        "estpu_transport_rogue_total",
        "socket-transport instrument not in CATALOG",
    )


def make_nodes_fan_instruments(m):
    # A cluster-observability fan-in instrument (`_nodes/stats` scatter,
    # trace-fragment shipping, hot-threads sampling) that never made it
    # into the CATALOG must fail like any other rogue registration.
    m.counter(
        "estpu_nodes_rogue_total",
        "nodes fan-in instrument not in CATALOG",
    )


def make_merge_instruments(m):
    # A refresh/merge instrument that never made it into the CATALOG must
    # fail exactly like any other rogue estpu_* registration.
    m.counter(
        "estpu_merge_rogue_total",
        "merge instrument not in CATALOG",
    )


def make_hbm_instruments(m):
    # An HBM-ledger instrument that never made it into the CATALOG must
    # fail like any other rogue estpu_* registration.
    m.counter(
        "estpu_hbm_rogue_total",
        "HBM ledger instrument not in CATALOG",
    )


def make_health_instruments(m):
    # A health-report instrument that never made it into the CATALOG
    # must fail like any other rogue estpu_* registration.
    m.counter(
        "estpu_health_rogue_total",
        "health instrument not in CATALOG",
    )
    # Rolling-window instruments are instruments too: an uncataloged
    # estpu_*_recent windowed counter/histogram fails the same gate
    # (and a cataloged one stays clean).
    m.windowed_counter("estpu_rogue_recent", "window not in CATALOG")
    m.windowed_histogram("estpu_good_recent_ms", "cataloged: fine")


def make_async_qos_instruments(m):
    # Async-search store and per-tenant QoS instruments are instruments
    # too: uncataloged estpu_async_* / estpu_qos_* registrations fail the
    # gate exactly like any other rogue estpu_* name.
    m.counter(
        "estpu_async_rogue_total",
        "async-search instrument not in CATALOG",
    )
    m.counter(
        "estpu_qos_rogue_total",
        "QoS lane instrument not in CATALOG",
    )


def make_incident_instruments(m):
    # Flight-recorder and incident-autopsy instruments are instruments
    # too: uncataloged estpu_recorder_* / estpu_incident_* registrations
    # fail the gate exactly like any other rogue estpu_* name.
    m.counter(
        "estpu_recorder_rogue_total",
        "flight-recorder instrument not in CATALOG",
    )
    m.counter(
        "estpu_incident_rogue_total",
        "incident instrument not in CATALOG",
    )


def charge_breaker(breaker, n):
    breaker.add(n, label="segment")  # registered ledger label: fine
    # f-string labels match by static prefix, like fault-site patterns.
    breaker.add(n, label=f"segment[{n} docs]")
    # A breaker label allocated outside the ledger's registry splits the
    # breaker and ledger accountings — the drift the law forbids.
    breaker.add(n, label="rogue_label")
    # staticcheck: ignore[registry-breaker-label] fixture: suppressed twin
    breaker.release(n, label="other_rogue")
