"""Fixture: exception/clock hygiene positives, twins, and exemptions."""

import time


def swallows():
    try:
        step()
    except Exception:  # broad-except: can swallow cancellation
        pass


def wall_duration():
    t0 = time.time()  # wallclock-duration
    step()
    return time.monotonic() - t0  # monotonic: fine


def suppressed():
    try:
        step()
    # staticcheck: ignore[broad-except] fixture: suppressed twin
    except Exception:
        pass
    # staticcheck: ignore[wallclock-duration] fixture: suppressed twin
    return time.time()


def guarded():
    try:
        step()
    except TaskCancelledError:
        raise
    except Exception:  # exempt: cancellation re-raised above
        pass


def cleanup_reraise(res):
    try:
        step()
    except Exception:  # exempt: bare re-raise cannot swallow
        res.close()
        raise


class TaskCancelledError(Exception):
    pass


def step():
    pass
