"""Fixture: lock-discipline positives + suppressed twins."""

import threading
import time


class Pair:
    def __init__(self):
        self.alpha = threading.Lock()
        self.beta = threading.Lock()

    def ab(self):
        with self.alpha:
            with self.beta:  # alpha -> beta
                pass

    def ba(self):
        with self.beta:
            with self.alpha:  # beta -> alpha: lock-order inversion
                pass

    def sleepy(self):
        with self.alpha:
            time.sleep(0.1)  # lock-blocking-call

    def sleepy_ok(self):
        with self.alpha:
            # staticcheck: ignore[lock-blocking-call] fixture: suppressed twin
            time.sleep(0.1)

    def nested_same(self):
        with self.alpha:
            with self.alpha:  # plain Lock re-entered: self-deadlock
                pass
