"""Search templates (mustache-lite) and stored scripts.

Reference: modules/lang-mustache (MustacheScriptEngine,
TransportSearchTemplateAction, RestRenderSearchTemplateAction) and
script/ScriptService.java (cluster-state stored scripts).
"""

import json

import pytest

from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.script.mustache import TemplateError, render


def test_mustache_variables_and_escaping():
    assert render("hello {{name}}", {"name": "world"}) == "hello world"
    assert render('{"q": "{{text}}"}', {"text": 'say "hi"'}) == (
        '{"q": "say \\"hi\\""}'
    )
    assert render("{{a.b}}", {"a": {"b": 7}}) == "7"
    assert render("{{missing}}", {}) == ""
    assert render("{{{raw}}}", {"raw": 'x"y'}) == 'x"y'
    assert render("{{flag}}", {"flag": True}) == "true"


def test_mustache_tojson_join_sections():
    assert render("{{#toJson}}v{{/toJson}}", {"v": [1, 2, {"a": "b"}]}) == (
        json.dumps([1, 2, {"a": "b"}])
    )
    assert render("{{#join}}v{{/join}}", {"v": ["a", "b", "c"]}) == "a,b,c"
    out = render(
        "{{#items}}[{{.}}]{{/items}}", {"items": ["x", "y"]}
    )
    assert out == "[x][y]"
    assert render("{{#on}}yes{{/on}}{{^on}}no{{/on}}", {"on": False}) == "no"
    assert render("{{#on}}yes{{/on}}{{^on}}no{{/on}}", {"on": 1}) == "yes"
    assert render("a{{! comment }}b", {}) == "ab"


def test_mustache_errors():
    with pytest.raises(TemplateError):
        render("{{#a}}unclosed", {})
    with pytest.raises(TemplateError):
        render("{{/a}}", {})


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path))
    n.create_index("products", {"mappings": {"properties": {
        "name": {"type": "text"}, "price": {"type": "double"}}}})
    for i, (name, price) in enumerate(
        [("red shirt", 10.0), ("blue shirt", 25.0), ("red hat", 40.0)]
    ):
        n.index_doc("products", {"name": name, "price": price}, str(i))
    n.refresh("products")
    return n


def test_search_template_inline(node):
    out = node.search_template(
        "products",
        {
            "source": {
                "query": {"match": {"name": "{{q}}"}},
                "size": "{{size}}",
            },
            "params": {"q": "red", "size": 10},
        },
    )
    ids = [h["_id"] for h in out["hits"]["hits"]]
    assert sorted(ids) == ["0", "2"]


def test_stored_search_template_and_render(node):
    node.put_script(
        "find-by-name",
        {
            "script": {
                "lang": "mustache",
                "source": '{"query": {"match": {"name": "{{q}}"}}}',
            }
        },
    )
    got = node.get_script("find-by-name")
    assert got["found"] and got["script"]["lang"] == "mustache"
    rendered = node.render_template(
        {"id": "find-by-name", "params": {"q": "hat"}}
    )
    assert rendered["template_output"] == {
        "query": {"match": {"name": "hat"}}
    }
    out = node.search_template(
        "products", {"id": "find-by-name", "params": {"q": "hat"}}
    )
    assert [h["_id"] for h in out["hits"]["hits"]] == ["2"]
    node.delete_script("find-by-name")
    with pytest.raises(ApiError):
        node.get_script("find-by-name")


def test_stored_painless_script_in_query(node):
    node.put_script(
        "price-boost",
        {"script": {"lang": "painless", "source": "_score * doc['price'].value"}},
    )
    out = node.search(
        "products",
        {
            "query": {
                "script_score": {
                    "query": {"match": {"name": "shirt"}},
                    "script": {"id": "price-boost"},
                }
            }
        },
    )
    hits = out["hits"]["hits"]
    assert [h["_id"] for h in hits] == ["1", "0"]  # price re-ranks blue first


def test_stored_scripts_persist_across_restart(node, tmp_path):
    node.put_script(
        "t1", {"script": {"lang": "mustache", "source": '{"size": {{n}}}'}}
    )
    n2 = Node(data_path=str(tmp_path))
    assert n2.get_script("t1")["found"]
    out = n2.render_template({"id": "t1", "params": {"n": 3}})
    assert out["template_output"] == {"size": 3}


def test_put_script_validation(node):
    with pytest.raises(ApiError):
        node.put_script("bad", {"script": {"lang": "mustache", "source": "{{#x}}"}})
    with pytest.raises(ApiError):
        node.put_script("bad", {"script": {"lang": "painless", "source": "import os"}})
    with pytest.raises(ApiError):
        node.put_script("bad", {"script": {"lang": "groovy", "source": "x"}})
    with pytest.raises(ApiError):
        node.put_script("bad", {"nope": 1})
    with pytest.raises(ApiError):
        node.search_template("products", {"params": {}})
    with pytest.raises(ApiError):
        node.search_template(
            "products", {"source": "{{q}}", "params": {"q": "notjson"}}
        )
