"""Positional queries (match_phrase / match_phrase_prefix) and multi-term
expansion queries (prefix / wildcard / fuzzy / ids / dis_max / multi_match):
device execution vs the independent CPU oracle, plus semantic spot checks.

Mirrors the reference's query-level test strategy (randomized corpora,
dueling implementations — e.g. server/src/test/.../search/query/).
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler, aggregate_field_stats
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.oracle import OracleSearcher
from elasticsearch_tpu.search.service import SearchRequest, SearchService

MAPPINGS = Mappings.from_json(
    {
        "properties": {
            "body": {"type": "text"},
            "tag": {"type": "keyword"},
        }
    }
)

VOCAB = [
    "quick", "brown", "fox", "jumps", "over", "lazy", "dog", "the",
    "quiet", "quality", "quarter", "brief", "broken",
]


def build_segment(rng, n=150):
    builder = SegmentBuilder(MAPPINGS)
    for i in range(n):
        words = rng.choice(VOCAB, size=rng.integers(2, 12))
        builder.add(
            {"body": " ".join(words), "tag": str(rng.choice(["a", "b"]))},
            f"d{i}",
        )
    return builder.build()


def run_both(segment, query_json, k=20):
    """(device results, oracle results) for one query on one segment."""
    query = parse_query(query_json)
    oracle = OracleSearcher(segment, MAPPINGS)
    o_scores, o_ids, o_total = oracle.search(query, k)

    device = pack_segment(segment)
    stats = aggregate_field_stats([segment])
    compiler = Compiler(
        fields=device.fields,
        doc_values=device.doc_values,
        mappings=MAPPINGS,
        stats=stats,
        id_index={d: i for i, d in enumerate(segment.ids)},
    )
    compiled = compiler.compile(query)
    seg = bm25_device.segment_tree(device)
    d_scores, d_ids, d_total = jax_get(
        bm25_device.execute(seg, compiled.spec, compiled.arrays, k)
    )
    n = min(int(o_total), k)
    return (
        (np.asarray(d_scores)[:n], np.asarray(d_ids)[:n], int(d_total)),
        (o_scores[:n], o_ids[:n], int(o_total)),
    )


def jax_get(x):
    import jax

    return jax.device_get(x)


def assert_parity(device_res, oracle_res, exact_scores=True):
    d_scores, d_ids, d_total = device_res
    o_scores, o_ids, o_total = oracle_res
    assert d_total == o_total
    np.testing.assert_array_equal(d_ids, o_ids)
    if exact_scores:
        np.testing.assert_array_equal(d_scores, o_scores)
    else:
        # Fused mul+add expressions (dis_max tie-breaker) may round once
        # on device (XLA FMA contraction) vs twice on the oracle: scores
        # agree to 1-2 ulp, ranking is exact.
        np.testing.assert_allclose(d_scores, o_scores, rtol=3e-7)


@pytest.fixture(scope="module")
def segment():
    return build_segment(np.random.default_rng(11))


PARITY_QUERIES = [
    {"match_phrase": {"body": "quick brown"}},
    {"match_phrase": {"body": "quick brown fox"}},
    {"match_phrase": {"body": "lazy dog"}},
    {"match_phrase": {"body": {"query": "fox jumps", "boost": 2.0}}},
    {"match_phrase": {"body": "quick quick"}},
    {"match_phrase_prefix": {"body": "quick bro"}},
    {"match_phrase_prefix": {"body": "lazy do"}},
    {"match_phrase_prefix": {"body": "qu"}},
    {"prefix": {"body": "qu"}},
    {"prefix": {"body": {"value": "bro", "boost": 3.0}}},
    {"wildcard": {"body": "qu*k"}},
    {"wildcard": {"body": "?uick"}},
    {"fuzzy": {"body": {"value": "quick", "fuzziness": 1}}},
    {"fuzzy": {"body": {"value": "borwn", "fuzziness": "AUTO"}}},
    {"ids": {"values": ["d3", "d7", "d100", "nope"]}},
    {
        "multi_match": {
            "query": "quick dog",
            "fields": ["body"],
        }
    },
    {
        "bool": {
            "must": [{"match_phrase": {"body": "quick brown"}}],
            "filter": [{"term": {"tag": "a"}}],
        }
    },
]

# Queries whose device lowering contains a fused mul+add (FMA contraction):
# ranking exact, scores within ulps.
FMA_PARITY_QUERIES = [
    {
        "dis_max": {
            "queries": [
                {"match": {"body": "quick"}},
                {"match": {"body": "dog"}},
            ],
            "tie_breaker": 0.3,
        }
    },
    {
        "multi_match": {
            "query": "quick dog fox",
            "fields": ["body", "tag"],
            "tie_breaker": 0.5,
        }
    },
]


@pytest.mark.parametrize("query_json", PARITY_QUERIES)
def test_device_oracle_parity(segment, query_json):
    device_res, oracle_res = run_both(segment, query_json)
    assert_parity(device_res, oracle_res)


@pytest.mark.parametrize("query_json", FMA_PARITY_QUERIES)
def test_device_oracle_parity_fused(segment, query_json):
    device_res, oracle_res = run_both(segment, query_json)
    assert_parity(device_res, oracle_res, exact_scores=False)


def _mk_engine(docs):
    engine = Engine(MAPPINGS)
    for i, d in enumerate(docs):
        engine.index(d, f"x{i}")
    engine.refresh()
    return engine


def _search(engine, body):
    return SearchService(engine).search(SearchRequest.from_json(body))


def test_phrase_semantics_order_matters():
    engine = _mk_engine(
        [
            {"body": "quick brown fox"},
            {"body": "brown quick fox"},
            {"body": "quick fox brown"},
        ]
    )
    resp = _search(engine, {"query": {"match_phrase": {"body": "quick brown"}}})
    assert [h.doc_id for h in resp.hits] == ["x0"]
    assert resp.total == 1


def test_phrase_counts_multiple_occurrences():
    engine = _mk_engine(
        [
            {"body": "ab cd ab cd ab cd"},  # phrase "ab cd" x3
            {"body": "ab cd xx xx xx xx"},  # x1, same length
        ]
    )
    resp = _search(engine, {"query": {"match_phrase": {"body": "ab cd"}}})
    assert [h.doc_id for h in resp.hits] == ["x0", "x1"]
    assert resp.hits[0].score > resp.hits[1].score


def test_phrase_does_not_cross_multi_value_boundary():
    engine = _mk_engine(
        [
            {"body": ["hello world", "goodbye moon"]},
            {"body": ["hello", "world"]},  # split across values: gap 100
        ]
    )
    resp = _search(engine, {"query": {"match_phrase": {"body": "hello world"}}})
    assert [h.doc_id for h in resp.hits] == ["x0"]


def test_phrase_respects_stopword_gaps():
    """With an analyzer that removes stopwords, the query 'jump the fence'
    analyzes to jump@0 fence@2 — matching docs with one token between."""
    mappings = Mappings.from_json(
        {
            "properties": {
                "t": {"type": "text", "analyzer": "english"},
            }
        }
    )
    engine = Engine(mappings)
    engine.index({"t": "jump the fence"}, "gap")  # jump@0 fence@2
    engine.index({"t": "jump fence"}, "nogap")  # jump@0 fence@1
    engine.refresh()
    resp = SearchService(engine).search(
        SearchRequest.from_json(
            {"query": {"match_phrase": {"t": "jump the fence"}}}
        )
    )
    assert [h.doc_id for h in resp.hits] == ["gap"]


def test_phrase_on_keyword_field_acts_as_term():
    """The keyword analyzer emits one token, so match_phrase on a keyword
    field degrades to an exact term match — same as the reference."""
    engine = _mk_engine([{"tag": "a", "body": "x"}, {"tag": "a b", "body": "y"}])
    resp = _search(engine, {"query": {"match_phrase": {"tag": "a"}}})
    assert [h.doc_id for h in resp.hits] == ["x0"]
    resp = _search(engine, {"query": {"match_phrase": {"tag": "a b"}}})
    assert [h.doc_id for h in resp.hits] == ["x1"]


def test_phrase_slop_rejected_for_now():
    engine = _mk_engine([{"body": "a b"}])
    with pytest.raises(ValueError, match="slop"):
        _search(
            engine,
            {"query": {"match_phrase": {"body": {"query": "a b", "slop": 2}}}},
        )


def test_multi_match_best_vs_most_fields():
    mappings = Mappings.from_json(
        {
            "properties": {
                "title": {"type": "text"},
                "body": {"type": "text"},
            }
        }
    )
    engine = Engine(mappings)
    engine.index({"title": "quick fox", "body": "quick fox"}, "both")
    engine.index({"title": "quick fox", "body": "slow snail"}, "title_only")
    engine.refresh()
    svc = SearchService(engine)
    best = svc.search(
        SearchRequest.from_json(
            {
                "query": {
                    "multi_match": {
                        "query": "quick",
                        "fields": ["title", "body"],
                        "type": "best_fields",
                    }
                }
            }
        )
    )
    most = svc.search(
        SearchRequest.from_json(
            {
                "query": {
                    "multi_match": {
                        "query": "quick",
                        "fields": ["title", "body"],
                        "type": "most_fields",
                    }
                }
            }
        )
    )
    assert best.total == most.total == 2
    # most_fields sums both fields: "both" beats "title_only" decisively
    assert most.hits[0].doc_id == "both"
    assert most.hits[0].score > most.hits[1].score


def test_ids_query_through_rest_shape():
    engine = _mk_engine([{"body": "a"}, {"body": "b"}, {"body": "c"}])
    resp = _search(engine, {"query": {"ids": {"values": ["x0", "x2"]}}})
    assert sorted(h.doc_id for h in resp.hits) == ["x0", "x2"]
    assert all(h.score == 1.0 for h in resp.hits)


def test_prefix_and_wildcard_constant_score():
    engine = _mk_engine(
        [{"body": "quick"}, {"body": "quality"}, {"body": "dog"}]
    )
    resp = _search(engine, {"query": {"prefix": {"body": "qu"}}})
    assert sorted(h.doc_id for h in resp.hits) == ["x0", "x1"]
    assert {h.score for h in resp.hits} == {1.0}
    resp = _search(engine, {"query": {"wildcard": {"body": "q*y"}}})
    assert [h.doc_id for h in resp.hits] == ["x1"]


def test_fuzzy_prefix_length_and_expansion():
    engine = _mk_engine(
        [{"body": "quick"}, {"body": "quack"}, {"body": "brick"}]
    )
    resp = _search(
        engine,
        {"query": {"fuzzy": {"body": {"value": "quick", "fuzziness": 1}}}},
    )
    assert sorted(h.doc_id for h in resp.hits) == ["x0", "x1"]
    resp = _search(
        engine,
        {
            "query": {
                "fuzzy": {
                    "body": {
                        "value": "quick",
                        "fuzziness": 2,
                        "prefix_length": 1,
                    }
                }
            }
        },
    )
    # prefix_length=1 keeps only q-terms
    assert sorted(h.doc_id for h in resp.hits) == ["x0", "x1"]


def test_phrase_works_when_one_segment_has_zero_tokens():
    """A segment whose text values analyzed to nothing must not flip the
    field to positionless for the whole index."""
    engine = Engine(MAPPINGS)
    engine.index({"body": ""}, "empty")
    engine.refresh()  # segment 1: zero tokens for body
    engine.index({"body": "hello world"}, "hit")
    engine.refresh()  # segment 2: real positions
    resp = _search(engine, {"query": {"match_phrase": {"body": "hello world"}}})
    assert [h.doc_id for h in resp.hits] == ["hit"]


def test_sharded_phrase_and_ids(rng):
    import jax
    from jax.sharding import Mesh

    from elasticsearch_tpu.parallel.sharded import ShardedIndex

    docs = []
    for i in range(60):
        words = rng.choice(VOCAB, size=rng.integers(2, 8))
        docs.append((f"s{i}", {"body": " ".join(words)}))
    docs.append(("phrase_doc", {"body": "quick brown fox jumps"}))
    mesh = Mesh(np.array(jax.devices()[:4]), ("shard",))
    idx = ShardedIndex.from_docs(docs, MAPPINGS, mesh)
    scores, ids, total = idx.search(
        parse_query({"match_phrase": {"body": "quick brown fox"}}), k=10
    )
    found = {idx.segments[s].ids[l] for s, l in (idx.locate(g) for g in ids)}
    assert "phrase_doc" in found
    # Oracle cross-check: every shard-local phrase hit is found
    expected = set()
    for doc_id, src in docs:
        words = src["body"].split()
        if any(
            words[i : i + 3] == ["quick", "brown", "fox"]
            for i in range(len(words))
        ):
            expected.add(doc_id)
    assert found == set(list(expected)[: len(found)]) or found <= expected
    assert total == len(expected)

    _, ids2, total2 = idx.search(
        parse_query({"ids": {"values": ["s3", "s17", "phrase_doc", "zz"]}}),
        k=10,
    )
    got = {idx.segments[s].ids[l] for s, l in (idx.locate(g) for g in ids2)}
    assert got == {"s3", "s17", "phrase_doc"}
    assert total2 == 3


def test_positions_survive_persist_and_load(tmp_path):
    engine = Engine(MAPPINGS, data_path=str(tmp_path / "idx"))
    engine.index({"body": "quick brown fox"}, "p0")
    engine.index({"body": "brown quick fox"}, "p1")
    engine.flush()
    engine.close()
    # fresh engine recovers from disk; phrase still works
    engine2 = Engine(MAPPINGS, data_path=str(tmp_path / "idx"))
    resp = SearchService(engine2).search(
        SearchRequest.from_json(
            {"query": {"match_phrase": {"body": "quick brown"}}}
        )
    )
    assert [h.doc_id for h in resp.hits] == ["p0"]
    engine2.close()
