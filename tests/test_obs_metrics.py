"""Unified metrics registry (obs/metrics.py).

Contracts: the registry migration keeps `_nodes/stats` backward
compatible (same key sets/value semantics as the pre-migration counter
dicts); `GET /_metrics` parses as valid Prometheus text exposition
(cumulative histogram buckets, declared families); histogram bucket
invariants hold; device-level instruments (compile count/ms, H2D bytes,
padding waste) record at the launch sites.
"""

import json
import re
import threading

import pytest

from elasticsearch_tpu.faults import REGISTRY
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.obs.metrics import (
    DeviceInstruments,
    Histogram,
    MetricsRegistry,
)
from elasticsearch_tpu.obs.tracing import TRACER
from elasticsearch_tpu.rest.server import PlainText, RestServer


@pytest.fixture(autouse=True)
def _clean_obs():
    REGISTRY.clear()
    TRACER.clear()
    yield
    REGISTRY.clear()
    TRACER.clear()


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>[-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))$"
)
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')


def parse_prometheus(text: str) -> dict:
    """Strict-enough parser for the exposition format: returns
    {family: {"type": kind, "samples": [(name, labels, value)]}} and
    raises AssertionError on any malformed line."""
    families: dict = {}
    current = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, kind = rest.rsplit(" ", 1)
            assert kind in ("counter", "gauge", "histogram"), line
            assert name not in families, f"family declared twice: {name}"
            families[name] = {"type": kind, "samples": []}
            current = name
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name = m.group("name")
        labels = {}
        if m.group("labels"):
            for pair in re.split(r",(?=[a-zA-Z_])", m.group("labels")):
                assert _LABEL_RE.match(pair), f"bad label pair {pair!r}"
                k, _, v = pair.partition("=")
                labels[k] = v.strip('"')
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in families:
                base = name[: -len(suffix)]
        assert base in families, f"sample before TYPE: {line!r}"
        assert current is not None
        value = float(m.group("value").replace("Inf", "inf"))
        families[base]["samples"].append((name, labels, value))
    return families


def assert_histogram_series_valid(families: dict, family: str) -> None:
    """Cumulative non-decreasing buckets; +Inf bucket == count."""
    entry = families[family]
    assert entry["type"] == "histogram"
    by_labels: dict = {}
    for name, labels, value in entry["samples"]:
        key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        slot = by_labels.setdefault(
            key, {"buckets": [], "sum": None, "count": None}
        )
        if name.endswith("_bucket"):
            slot["buckets"].append((labels["le"], value))
        elif name.endswith("_sum"):
            slot["sum"] = value
        elif name.endswith("_count"):
            slot["count"] = value
    assert by_labels
    for slot in by_labels.values():
        assert slot["buckets"], slot
        values = [v for _, v in slot["buckets"]]
        assert values == sorted(values), "buckets must be cumulative"
        assert slot["buckets"][-1][0] == "+Inf"
        assert slot["buckets"][-1][1] == slot["count"]


class TestRegistryPrimitives:
    def test_counter_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("estpu_test_total", "t", kind="a")
        c.inc()
        c.inc(2.5)
        assert reg.value("estpu_test_total", kind="a") == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        # Same (name, labels) returns the same instrument.
        assert reg.counter("estpu_test_total", kind="a") is c

    def test_kind_conflict_rejected(self):
        reg = MetricsRegistry()
        reg.counter("estpu_x_total")
        with pytest.raises(ValueError):
            reg.gauge("estpu_x_total")

    def test_invalid_names_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("bad-name")
        with pytest.raises(ValueError):
            reg.counter("ok_name", **{"bad-label": 1})

    def test_histogram_bucket_invariants(self):
        h = Histogram((1.0, 2.0, 4.0))
        for v in (0.5, 1.0, 1.5, 3.0, 100.0):
            h.observe(v)
        snap = h.snapshot()
        # Per-bucket counts + inf == count; sum is the observation sum.
        assert sum(snap["buckets"].values()) + snap["inf"] == snap["count"]
        assert snap["count"] == 5
        assert snap["buckets"] == {"1": 2, "2": 1, "4": 1}
        assert snap["inf"] == 1
        assert snap["sum"] == pytest.approx(106.0)
        with pytest.raises(ValueError):
            Histogram(())
        with pytest.raises(ValueError):
            Histogram((1.0, 1.0))

    def test_histogram_exposition_cumulative(self):
        reg = MetricsRegistry()
        h = reg.histogram("estpu_test_hist", (1.0, 2.0), "t")
        for v in (0.5, 1.5, 9.0):
            h.observe(v)
        families = parse_prometheus(reg.exposition())
        assert_histogram_series_valid(families, "estpu_test_hist")

    def test_gauge_callback(self):
        reg = MetricsRegistry()
        state = {"v": 1}
        reg.gauge("estpu_test_gauge", fn=lambda: state["v"])
        assert reg.value("estpu_test_gauge") == 1
        state["v"] = 7
        assert reg.value("estpu_test_gauge") == 7

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("estpu_esc_total", label='a"b\\c\nd').inc()
        text = reg.exposition()
        assert '\\"' in text and "\\\\" in text and "\\n" in text
        parse_prometheus(text)

    def test_merged_exposition_sums_collisions(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("estpu_m_total", kind="x").inc(2)
        b.counter("estpu_m_total", kind="x").inc(3)
        b.counter("estpu_m_total", kind="y").inc(1)
        families = parse_prometheus(a.exposition(b))
        samples = {
            tuple(sorted(lbl.items())): v
            for _n, lbl, v in families["estpu_m_total"]["samples"]
        }
        assert samples[(("kind", "x"),)] == 5
        assert samples[(("kind", "y"),)] == 1

    def test_thread_safety_under_contention(self):
        reg = MetricsRegistry()
        c = reg.counter("estpu_tsafe_total")
        h = reg.histogram("estpu_tsafe_hist", (1.0, 10.0))

        def spin():
            for _ in range(500):
                c.inc()
                h.observe(0.5)

        threads = [threading.Thread(target=spin) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 4000
        assert h.snapshot()["count"] == 4000


class TestDeviceInstruments:
    def test_first_launch_counts_as_compile(self):
        reg = MetricsRegistry()
        dev = DeviceInstruments(reg)
        dev.launch("terms", ("spec-a", 10), 0.25)
        dev.launch("terms", ("spec-a", 10), 0.001)  # warm: no new compile
        dev.launch("terms", ("spec-b", 10), 0.10)
        assert dev.compile_count() == 2
        assert dev.compile_ms_total() == pytest.approx(350.0)
        snap = dev.snapshot()
        assert snap["launches_by_plan_class"] == {"terms": 3}
        assert snap["compiles_by_plan_class"] == {"terms": 2}

    def test_padding_waste_pct(self):
        reg = MetricsRegistry()
        dev = DeviceInstruments(reg)
        dev.padding(actual_tiles=6, padded_tiles=8)
        dev.padding(actual_tiles=8, padded_tiles=8)
        assert dev.padding_waste_pct() == pytest.approx(12.5)
        families = parse_prometheus(reg.exposition())
        assert_histogram_series_valid(
            families, "estpu_device_padding_waste_ratio"
        )

    def test_h2d_bytes(self):
        import numpy as np

        reg = MetricsRegistry()
        dev = DeviceInstruments(reg)
        dev.h2d({"a": np.zeros(8, np.float32), "b": np.zeros(4, np.int32)})
        assert reg.value("estpu_device_h2d_bytes_total") == 48

    def test_blockmax_pruned_tile_fraction(self):
        """The two-phase prune-effectiveness instrument: histogram series
        are Prometheus-valid and the stats view reports count + mean."""
        reg = MetricsRegistry()
        dev = DeviceInstruments(reg)
        snap = dev.snapshot()["blockmax_pruned_tile_fraction"]
        assert snap == {"count": 0, "mean": 0.0}  # present before any obs
        dev.blockmax_pruned(0.75)
        dev.blockmax_pruned(0.25)
        dev.blockmax_pruned(1.5)  # clamped into [0, 1]
        snap = dev.snapshot()["blockmax_pruned_tile_fraction"]
        assert snap["count"] == 3
        assert snap["mean"] == pytest.approx(2.0 / 3.0, abs=1e-3)
        families = parse_prometheus(reg.exposition())
        assert_histogram_series_valid(
            families, "estpu_device_blockmax_pruned_tile_fraction"
        )


class TestNodeStatsMigration:
    """`_nodes/stats` stays backward compatible after the counter dicts
    moved onto the registry: same key sets as the seed shapes, counters
    behave identically."""

    @pytest.fixture
    def node(self, monkeypatch):
        monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
        node = Node()
        node.create_index(
            "m", {"mappings": {"properties": {"b": {"type": "text"}}}}
        )
        for i in range(8):
            node.index_doc("m", {"b": f"alpha w{i % 2}"}, f"d{i}")
        node.refresh("m")
        return node

    def test_request_cache_stats_shape_and_behavior(self, node):
        body = {"query": {"match": {"b": "alpha"}}, "size": 0}
        node.search("m", dict(body))
        node.search("m", dict(body))
        stats = node.nodes_stats()["nodes"][node.node_name]
        rc = stats["indices"]["request_cache"]
        assert set(rc) == {"entries", "hit_count", "miss_count", "evictions"}
        assert rc["hit_count"] == 1
        assert rc["miss_count"] == 1
        assert rc["entries"] == 1

    def test_exec_sections_keep_seed_shape(self, node):
        node.search("m", {"query": {"match": {"b": "alpha"}}})
        stats = node.nodes_stats()["nodes"][node.node_name]
        batcher = stats["exec"]["batcher"]
        assert {
            "max_wait_ms", "batches", "requests", "coalesced_requests",
            "occupancy_histogram", "queue_cancellations", "rejected",
            "queued", "retried_individually", "groups_quarantined",
            "quarantine_hits", "quarantined_now", "queue_wait_p50_ms",
            "queue_wait_p99_ms",
        } <= set(batcher)
        assert batcher["requests"] >= 1
        assert batcher["batches"] >= 1
        # Occupancy view: pow-2 string buckets, counts sum to batches.
        occ = batcher["occupancy_histogram"]
        assert all(k.isdigit() for k in occ)
        assert sum(occ.values()) == batcher["batches"]
        planner = stats["exec"]["planner"]
        assert set(planner) == {"decisions", "ewma"}
        from elasticsearch_tpu.exec import ExecPlanner

        assert set(planner["decisions"]) >= set(ExecPlanner.BACKENDS)

    def test_search_resilience_keys_and_faults(self, node):
        stats = node.nodes_stats()["nodes"][node.node_name]
        assert set(stats["search_resilience"]) == {
            "partial_responses",
            "shard_failures",
            "search_phase_failures",
            "batcher",
        }
        assert stats["faults"] == REGISTRY.stats()
        # New sections are additive, never replacing seed keys.
        assert "device" in stats and "obs" in stats

    def test_resilience_counters_still_count(self, node, monkeypatch):
        from elasticsearch_tpu.faults import FaultSpec

        REGISTRY.put(FaultSpec(site="search.kernel", error_rate=1.0))
        with pytest.raises(Exception):
            node.search(
                "m",
                {"query": {"match": {"b": "alpha"}}, "profile": True},
            )
        REGISTRY.clear()
        assert node.search_resilience["search_phase_failures"] >= 1


class TestMetricsEndpoint:
    def test_metrics_endpoint_parses_as_prometheus(self, monkeypatch):
        monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
        rest = RestServer()
        rest.dispatch(
            "PUT",
            "/p",
            {},
            json.dumps(
                {"mappings": {"properties": {"b": {"type": "text"}}}}
            ),
        )
        rest.dispatch(
            "PUT", "/p/_doc/1", {}, json.dumps({"b": "alpha beta"})
        )
        rest.dispatch("POST", "/p/_refresh", {}, "")
        rest.dispatch(
            "POST",
            "/p/_search",
            {},
            json.dumps({"query": {"match": {"b": "alpha"}}}),
        )
        status, payload = rest.dispatch("GET", "/_metrics", {}, "")
        assert status == 200
        assert isinstance(payload, PlainText)
        assert payload.content_type.startswith("text/plain")
        families = parse_prometheus(payload.text)
        assert "estpu_exec_batcher_requests_total" in families
        assert "estpu_request_cache_misses_total" in families
        assert "estpu_exec_planner_decisions_total" in families
        assert "estpu_search_resilience_total" in families
        assert "estpu_faults_armed" in families
        assert_histogram_series_valid(
            families, "estpu_exec_batcher_occupancy"
        )

    def test_replicated_metrics_merge_gateway_and_cluster(self, monkeypatch):
        monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
        rest = RestServer(replication_nodes=3)
        try:
            rest.dispatch(
                "PUT",
                "/r",
                {},
                json.dumps(
                    {
                        "settings": {
                            "index": {
                                "number_of_shards": 2,
                                "number_of_replicas": 1,
                            }
                        },
                        "mappings": {
                            "properties": {"b": {"type": "text"}}
                        },
                    }
                ),
            )
            rest.dispatch(
                "PUT", "/r/_doc/1", {}, json.dumps({"b": "alpha"})
            )
            rest.dispatch("POST", "/r/_refresh", {}, "")
            status, _ = rest.dispatch(
                "POST",
                "/r/_search",
                {},
                json.dumps({"query": {"match": {"b": "alpha"}}}),
            )
            assert status == 200
            status, payload = rest.dispatch("GET", "/_metrics", {}, "")
            assert status == 200
            families = parse_prometheus(payload.text)
            gw = {
                lbl["op"]: v
                for _n, lbl, v in families[
                    "estpu_replication_gateway_total"
                ]["samples"]
            }
            assert gw["searches"] >= 1
            cluster = families["estpu_cluster_search_resilience_total"]
            nodes = {lbl["node"] for _n, lbl, v in cluster["samples"]}
            assert len(nodes) == 3
            # The exposition view and the _nodes/stats view read the SAME
            # counters.
            status, stats = rest.dispatch("GET", "/_nodes/stats", {}, "")
            node_stats = next(iter(stats["nodes"].values()))
            assert node_stats["replication"]["searches"] == int(
                gw["searches"]
            )
        finally:
            rest.close()

    def test_device_metrics_flow_to_bench_fields(self, monkeypatch):
        """The same registry fields bench.py emits: compile_count,
        compile_ms_total, padding_waste_pct."""
        monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
        monkeypatch.setenv("ESTPU_EXEC_PLANNER", "0")
        node = Node()
        node.create_index(
            "d", {"mappings": {"properties": {"b": {"type": "text"}}}}
        )
        for i in range(6):
            node.index_doc("d", {"b": f"alpha w{i % 2}"}, f"d{i}")
        node.refresh("d")
        node.search(
            "d", {"query": {"match": {"b": "alpha"}}, "profile": True}
        )
        node.search(
            "d", {"query": {"match": {"b": "alpha"}}, "profile": True}
        )
        dev = node.nodes_stats()["nodes"][node.node_name]["device"]
        assert dev["compile_count"] >= 1
        assert dev["compile_ms_total"] > 0
        assert (
            sum(dev["launches_by_plan_class"].values())
            > dev["compile_count"] - 1
        )
        assert dev["h2d_bytes_total"] > 0
        assert 0.0 <= dev["padding_waste_pct"] <= 100.0
