"""Segment merging: tiered compaction bounds segment count, force-merge,
results unchanged, deletes purged, persistence across merge.

Reference: index/EsTieredMergePolicy.java (policy), ForceMergeRequest.
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.search.service import SearchRequest, SearchService

MAPPINGS = Mappings.from_json(
    {
        "properties": {
            "body": {"type": "text"},
            "tag": {"type": "keyword"},
            "n": {"type": "long"},
        }
    }
)

WORDS = ["one", "two", "three", "four", "five"]


def fill(engine, n, refresh_every, rng, prefix="d"):
    for i in range(n):
        engine.index(
            {
                "body": " ".join(rng.choice(WORDS, rng.integers(1, 5))),
                "tag": str(rng.choice(["a", "b"])),
                "n": i,
            },
            f"{prefix}{i}",
        )
        if (i + 1) % refresh_every == 0:
            engine.refresh()
    engine.refresh()


def search_ids(engine, body):
    resp = SearchService(engine).search(SearchRequest.from_json(body))
    return [(h.doc_id, h.score) for h in resp.hits], resp.total


def test_refresh_keeps_segment_count_bounded():
    engine = Engine(MAPPINGS, max_segments=5, merge_factor=4)
    rng = np.random.default_rng(1)
    fill(engine, 200, 10, rng)  # 20 refreshes
    assert len(engine.segments) <= 5
    assert engine.num_docs == 200
    hits, total = search_ids(engine, {"query": {"match": {"body": "one"}}})
    assert total > 0


def test_merge_preserves_results_exactly():
    rng = np.random.default_rng(2)
    merged = Engine(MAPPINGS, max_segments=3, merge_factor=3)
    flat = Engine(MAPPINGS)
    for i in range(120):
        doc = {
            "body": " ".join(rng.choice(WORDS, rng.integers(1, 5))),
            "tag": str(rng.choice(["a", "b"])),
            "n": i,
        }
        merged.index(doc, f"d{i}")
        flat.index(doc, f"d{i}")
        if (i + 1) % 8 == 0:
            merged.refresh()
    merged.refresh()
    flat.refresh()  # single segment, never merged
    assert len(merged.segments) <= 3
    # Merging renumbers doc ids (Lucene merges do too), so equal-score tie
    # ORDER may differ; scores and per-score membership must not. size
    # covers the whole corpus so no group truncates.
    for body in [
        {"query": {"match": {"body": "two three"}}, "size": 200},
        {"query": {"bool": {"must": [{"match": {"body": "one"}}],
                            "filter": [{"term": {"tag": "a"}}]}}, "size": 200},
        {"query": {"match_all": {}}, "sort": [{"n": "desc"}], "size": 10},
    ]:
        got, got_total = search_ids(merged, body)
        want, want_total = search_ids(flat, body)
        assert got_total == want_total
        assert [s for _, s in got] == [s for _, s in want]
        by_score_got: dict = {}
        by_score_want: dict = {}
        for h, s in got:
            by_score_got.setdefault(s, set()).add(h)
        for h, s in want:
            by_score_want.setdefault(s, set()).add(h)
        assert by_score_got == by_score_want


def test_force_merge_purges_deletes_and_updates_stats():
    engine = Engine(MAPPINGS, max_segments=100)
    rng = np.random.default_rng(3)
    fill(engine, 60, 15, rng)
    for i in range(0, 60, 2):
        engine.delete(f"d{i}")
    engine.refresh()
    stats_before = engine.field_stats()["body"]
    out = engine.force_merge(1)
    assert out["num_segments"] == 1
    assert engine.num_docs == 30
    # Purged deletes leave the statistics (Lucene merge semantics)
    stats_after = engine.field_stats()["body"]
    assert stats_after.doc_count == 30
    assert stats_before.doc_count > stats_after.doc_count
    # realtime get still routes correctly after renumbering
    assert engine.get("d1") is not None
    assert engine.get("d0") is None
    hits, total = search_ids(engine, {"query": {"match_all": {}}, "size": 40})
    assert total == 30
    assert {h for h, _ in hits} == {f"d{i}" for i in range(1, 60, 2)}


def test_merge_then_write_then_merge():
    engine = Engine(MAPPINGS, max_segments=2, merge_factor=2)
    rng = np.random.default_rng(4)
    fill(engine, 30, 5, rng)
    assert len(engine.segments) <= 2
    fill(engine, 30, 5, rng, prefix="e")
    assert len(engine.segments) <= 2
    assert engine.num_docs == 60
    engine.index({"body": "one", "n": 999}, "d3")  # overwrite post-merge
    engine.refresh()
    assert engine.get("d3")["n"] == 999
    assert engine.num_docs == 60


def test_merge_persistence(tmp_path):
    engine = Engine(MAPPINGS, data_path=str(tmp_path / "x"), max_segments=100)
    rng = np.random.default_rng(5)
    fill(engine, 40, 10, rng)
    engine.delete("d0")
    engine.force_merge(1)
    engine.flush()
    engine.close()
    engine2 = Engine(MAPPINGS, data_path=str(tmp_path / "x"))
    assert len(engine2.segments) == 1
    assert engine2.num_docs == 39
    assert engine2.get("d0") is None
    assert engine2.get("d5") is not None
    # versions/seqnos survived the merge + restart
    meta = engine2.get_with_meta("d5")
    assert meta["_seq_no"] >= 0 and meta["_version"] >= 1
    engine2.close()


def test_forcemerge_rest_route():
    node = Node()
    node.create_index("m", {"settings": {"index": {"number_of_shards": 2}}})
    for i in range(20):
        node.index_doc("m", {"body": f"w{i}"}, f"d{i}")
        if i % 4 == 0:
            node.refresh("m")
    node.refresh("m")
    from elasticsearch_tpu.rest.server import RestServer

    rest = RestServer(node=node)
    status, resp = rest.dispatch(
        "POST", "/m/_forcemerge", {"max_num_segments": "1"}, ""
    )
    assert status == 200
    assert resp["num_segments"] == 2  # one per shard
    r = node.search("m", {"query": {"match_all": {}}, "size": 0})
    assert r["hits"]["total"]["value"] == 20


def test_scroll_survives_merge():
    node = Node()
    node.create_index("s", {"mappings": {"properties": {"n": {"type": "long"}}}})
    for i in range(30):
        node.index_doc("s", {"n": i}, f"d{i}")
        if i % 6 == 0:
            node.refresh("s")
    node.refresh("s")
    r = node.search(
        "s", {"query": {"match_all": {}}, "size": 7, "sort": [{"n": "asc"}]},
        scroll="1m",
    )
    sid = r["_scroll_id"]
    got = [h["_source"]["n"] for h in r["hits"]["hits"]]
    node.force_merge("s", 1)  # compact while the scroll is open
    while True:
        r = node.scroll({"scroll_id": sid})
        if not r["hits"]["hits"]:
            break
        got += [h["_source"]["n"] for h in r["hits"]["hits"]]
    assert got == list(range(30))
