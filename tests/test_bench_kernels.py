"""Parity tests for the benchmark-grade execution kernels.

Covers the kernels bench.py drives on real hardware (BASELINE configs
3/4/5): generic sequential execution, single-device multi-shard
scatter/gather (`execute_shards*`), and the fused two-phase rescore
(`execute_rescore*`). Reference semantics: SearchPhaseController.java:398
(merge order), search/rescore/QueryRescorer.java (combine), x-pack vectors
ScoreScriptUtils (cosine).
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.tiles import TILE, pack_segment
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.oracle import OracleSearcher
from elasticsearch_tpu.utils.corpus import build_zipf_segment

N = 3000
NT_FLOOR = 64


def _corpus(seed=5, n=N):
    mappings, segment = build_zipf_segment(n, vocab_size=2000, seed=seed)
    dev = pack_segment(segment)
    return mappings, segment, dev


def _bool_query(t1, t2, tf):
    return parse_query(
        {
            "bool": {
                "must": [{"match": {"body": f"{t1} {t2}"}}],
                "filter": [{"term": {"body": tf}}],
            }
        }
    )


def _queries(segment, rng, nq=6):
    fld = segment.fields["body"]
    by_df = sorted(fld.terms, key=lambda t: -fld.df[fld.terms[t]])
    mid = by_df[10:200]
    out = []
    for _ in range(nq):
        t1, t2, tf = rng.choice(mid, 3, replace=False)
        out.append(_bool_query(t1, t2, str(tf)))
    return out


def test_execute_sequential_matches_per_query():
    from elasticsearch_tpu.query.compile import equalize_compiled

    mappings, segment, dev = _corpus()
    seg = bm25_device.segment_tree(dev)
    compiler = Compiler(dev.fields, dev.doc_values, mappings, nt_floor=NT_FLOOR)
    rng = np.random.default_rng(7)
    compiled = [compiler.compile(q) for q in _queries(segment, rng)]
    # Per-query lead-clause choices may split the batch into spec groups;
    # equalization (which also resolves mixed leads to the must-driven
    # fold) restores the single shared spec this batched scan needs.
    compiled = equalize_compiled(compiled)
    assert len({c.spec for c in compiled}) == 1, "equalize must unify specs"
    spec = compiled[0].spec
    import jax

    stacked = jax.tree.map(lambda *xs: np.stack(xs), *[c.arrays for c in compiled])
    s_b, i_b, t_b = jax.device_get(
        bm25_device.execute_sequential(seg, spec, stacked, 10)
    )
    for row, c in enumerate(compiled):
        s, i, t = jax.device_get(bm25_device.execute(seg, spec, c.arrays, 10))
        assert int(t_b[row]) == int(t)
        # Slots past the hit count carry -inf scores and DON'T-CARE ids
        # (the documented padding contract; the sparse and dense kernels
        # pad differently) — compare the valid region only.
        n = min(10, int(t))
        np.testing.assert_array_equal(s_b[row][:n], s[:n])
        np.testing.assert_array_equal(i_b[row][:n], i[:n])
        assert np.all(s_b[row][n:] == np.float32(-np.inf))
        assert np.all(s[n:] == np.float32(-np.inf))


@pytest.fixture(scope="module")
def sharded_corpus():
    shards = [_corpus(seed=11 + s, n=N - 37 * s) for s in range(4)]
    n_pad = max(seg.num_docs for _, seg, _ in shards)
    min_tiles = {
        "body": max(
            len(seg.fields["body"].doc_ids) // TILE + 2 for _, seg, _ in shards
        )
    }
    mappings = shards[0][0]
    devs = [
        pack_segment(seg, pad_docs_to=n_pad, field_min_tiles=min_tiles)
        for _, seg, _ in shards
    ]
    import jax

    trees = [bm25_device.segment_tree(d) for d in devs]
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *trees)
    segments = [seg for _, seg, _ in shards]
    return mappings, segments, devs, stacked, n_pad


def _oracle_merge(segments, mappings, query, k, docs_per_shard):
    rows = []
    for s, seg in enumerate(segments):
        scores, ids, total = OracleSearcher(seg, mappings).search(query, k)
        for rank in range(len(ids)):
            rows.append(
                (
                    -np.float32(scores[rank]),
                    s,
                    int(ids[rank]),
                    np.float32(scores[rank]),
                )
            )
    rows.sort(key=lambda r: (r[0], r[1], r[2]))
    top = rows[:k]
    gids = [s * docs_per_shard + d for _, s, d, _ in top]
    return np.array([sc for *_, sc in top], np.float32), gids


def test_execute_shards_matches_oracle_merge(sharded_corpus):
    mappings, segments, devs, stacked, n_pad = sharded_corpus
    rng = np.random.default_rng(3)
    queries = _queries(segments[0], rng, nq=4)
    import jax

    from elasticsearch_tpu.query.compile import equalize_compiled

    for query in queries:
        per_shard = equalize_compiled([
            Compiler(d.fields, d.doc_values, mappings, nt_floor=NT_FLOOR).compile(
                query
            )
            for d in devs
        ])
        assert len({c.spec for c in per_shard}) == 1
        spec = per_shard[0].spec
        arrays = jax.tree.map(
            lambda *xs: np.stack(xs), *[c.arrays for c in per_shard]
        )
        s, g, t = jax.device_get(
            bm25_device.execute_shards(stacked, spec, arrays, 10, n_pad)
        )
        o_scores, o_gids = _oracle_merge(segments, mappings, query, 10, n_pad)
        o_total = sum(
            OracleSearcher(seg, mappings).search(query, 1)[2] for seg in segments
        )
        n = len(o_gids)
        assert list(g[:n]) == o_gids
        np.testing.assert_allclose(s[:n], o_scores, rtol=2e-6)
        assert int(t) == o_total


def test_execute_shards_batch_and_sequential(sharded_corpus):
    mappings, segments, devs, stacked, n_pad = sharded_corpus
    rng = np.random.default_rng(4)
    queries = _queries(segments[0], rng, nq=4)
    import jax

    from elasticsearch_tpu.query.compile import equalize_compiled

    # Equalize every (query, shard) plan to ONE shared spec (per-position
    # bucket maxima; mixed lead choices resolve to the must-driven fold).
    flat = equalize_compiled([
        Compiler(d.fields, d.doc_values, mappings, nt_floor=NT_FLOOR).compile(
            query
        )
        for query in queries
        for d in devs
    ])
    spec = flat[0].spec
    all_compiled = []
    for qi in range(len(queries)):
        per_shard = flat[qi * len(devs) : (qi + 1) * len(devs)]
        all_compiled.append(
            jax.tree.map(lambda *xs: np.stack(xs), *[c.arrays for c in per_shard])
        )
    batched = jax.tree.map(lambda *xs: np.stack(xs), *all_compiled)
    s_b, g_b, t_b = jax.device_get(
        bm25_device.execute_shards_batch(stacked, spec, batched, 10, n_pad)
    )
    s_q, g_q, t_q = jax.device_get(
        bm25_device.execute_shards_sequential(stacked, spec, batched, 10, n_pad)
    )
    for row in range(len(queries)):
        s1, g1, t1 = jax.device_get(
            bm25_device.execute_shards(stacked, spec, all_compiled[row], 10, n_pad)
        )
        np.testing.assert_array_equal(s_b[row], s1)
        np.testing.assert_array_equal(g_b[row], g1)
        np.testing.assert_array_equal(s_q[row], s1)
        np.testing.assert_array_equal(g_q[row], g1)
        assert int(t_b[row]) == int(t_q[row]) == int(t1)


def test_execute_rescore_matches_oracle():
    mappings, segment, _ = _corpus(seed=21)
    rng = np.random.default_rng(9)
    segment.doc_values["f1"] = rng.random(N).astype(np.float32)
    segment.doc_values["f2"] = rng.random(N).astype(np.float32)
    dev = pack_segment(segment)
    seg = bm25_device.segment_tree(dev)
    compiler = Compiler(dev.fields, dev.doc_values, mappings)
    fld = segment.fields["body"]
    by_df = sorted(fld.terms, key=lambda t: -fld.df[fld.terms[t]])
    query = parse_query({"match": {"body": f"{by_df[5]} {by_df[30]}"}})
    source = (
        "params.w0 * _score + params.w1 * doc['f1'].value"
        " + params.w2 * doc['f2'].value"
    )
    params = {"w0": 0.2, "w1": 3.0, "w2": 1.5}
    rquery = parse_query(
        {
            "script_score": {
                "query": {"match_all": {}},
                "script": {"source": source, "params": params},
            }
        }
    )
    c = compiler.compile(query)
    rc = compiler.compile(rquery)
    window, k = 50, 10
    import jax

    s, ids, total = jax.device_get(
        bm25_device.execute_rescore(
            seg, c.spec, c.arrays, rc.spec, rc.arrays, k, window,
            np.float32(1.0), np.float32(1.0),
        )
    )
    # Oracle: top-window by BM25, combine in the same fp32 op order.
    oracle = OracleSearcher(segment, mappings)
    o_scores, o_ids, o_total = oracle.search(query, window)
    f1 = segment.doc_values["f1"][o_ids]
    f2 = segment.doc_values["f2"][o_ids]
    rs = (
        np.float32(params["w0"]) * np.float32(1.0)
        + np.float32(params["w1"]) * f1
        + np.float32(params["w2"]) * f2
    ).astype(np.float32)
    comb = (np.float32(1.0) * o_scores + np.float32(1.0) * rs).astype(np.float32)
    order = np.argsort(-comb, kind="stable")[:k]
    assert list(ids[: len(order)]) == [int(o_ids[j]) for j in order]
    np.testing.assert_allclose(s[: len(order)], comb[order], rtol=2e-6)
    assert int(total) == o_total

    # Sequential variant: bit-identical to the one-shot kernel.
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *[c.arrays, c.arrays])
    rstacked = jax.tree.map(lambda *xs: np.stack(xs), *[rc.arrays, rc.arrays])
    s_q, i_q, t_q = jax.device_get(
        bm25_device.execute_rescore_sequential(
            seg, c.spec, stacked, rc.spec, rstacked, k, window,
            np.float32(1.0), np.float32(1.0),
        )
    )
    for row in range(2):
        np.testing.assert_array_equal(s_q[row], s)
        np.testing.assert_array_equal(i_q[row], ids)
        assert int(t_q[row]) == int(total)
