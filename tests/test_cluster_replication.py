"""Host replication layer (VERDICT r4 item 4): primary/replica write
fan-out, promotion on primary death, no acknowledged write lost, replica
rejoin via ops-based catch-up, stale-primary rejection, quorum safety.

The reference's acceptance shape: InternalTestCluster + MockTransportService
(test/framework) driving ReplicationOperation.java:111 semantics with
ReplicationTracker.java:68 in-sync sets; here LocalCluster + TransportHub.

The whole suite is parameterized over BOTH transports: the in-memory hub
(tier-1, every run) and real TCP loopback sockets (`slow` lane — the full
matrix re-proven over the wire; the trimmed tier-1 socket slice lives in
test_tcp_transport.py / test_socket_procs.py).
"""

import pytest

from elasticsearch_tpu.cluster import (
    LocalCluster,
    NoShardAvailableError,
    ReplicationFailedError,
)
from elasticsearch_tpu.index.seqno import LocalCheckpointTracker
from elasticsearch_tpu.parallel.routing import shard_for_id

MAPPINGS = {"properties": {"body": {"type": "text"}}}


@pytest.fixture(
    params=["hub", pytest.param("tcp", marks=pytest.mark.slow)]
)
def transport(request):
    return request.param


@pytest.fixture
def make_cluster(transport):
    """LocalCluster factory bound to the parameterized transport; closes
    everything it made on teardown (tests may also close explicitly —
    close is idempotent)."""
    made = []

    def make(n_nodes: int = 3, **kwargs) -> LocalCluster:
        c = LocalCluster(n_nodes, transport=transport, **kwargs)
        made.append(c)
        return c

    yield make
    for c in made:
        c.close()


@pytest.fixture
def cluster(make_cluster):
    return make_cluster(3)


def doc_ids(n, prefix="d"):
    return [f"{prefix}{i}" for i in range(n)]


def load(cluster, index, ids):
    acked = []
    for doc_id in ids:
        resp = cluster.any_node().execute_write(
            index, doc_id, {"body": f"payload {doc_id}"}
        )
        assert resp["result"] in ("created", "updated")
        acked.append(doc_id)
    return acked


class TestCheckpointTracker:
    def test_contiguous(self):
        t = LocalCheckpointTracker()
        for s in range(5):
            t.mark(s)
        assert t.checkpoint == 4

    def test_out_of_order(self):
        t = LocalCheckpointTracker()
        t.mark(2)
        assert t.checkpoint == -1
        t.mark(0)
        assert t.checkpoint == 0
        t.mark(1)
        assert t.checkpoint == 2

    def test_advance_to(self):
        t = LocalCheckpointTracker()
        t.mark(7)
        t.advance_to(5)
        assert t.checkpoint == 5
        t.mark(6)
        assert t.checkpoint == 7


class TestBootstrapAndWrites:
    def test_election_and_state(self, cluster):
        master = cluster.master()
        assert master is not None and master.node_id == "node-0"
        assert all(
            n.state.master == "node-0" for n in cluster.nodes.values()
        )

    def test_replicated_write_reaches_all_in_sync(self, cluster):
        cluster.create_index("idx", n_shards=2, n_replicas=1, mappings=MAPPINGS)
        acked = load(cluster, "idx", doc_ids(20))
        # Every copy of every shard holds its routed docs.
        meta = cluster.any_node().state.indices["idx"]
        for doc_id in acked:
            shard = shard_for_id(doc_id, meta.n_shards)
            routing = meta.shards[shard]
            for node_id in routing.assigned():
                engine = cluster.nodes[node_id].engines[("idx", shard)]
                assert engine.get(doc_id) is not None, (doc_id, node_id)

    def test_global_checkpoint_advances(self, cluster):
        cluster.create_index("gcp", n_shards=1, n_replicas=2, mappings=MAPPINGS)
        resp = None
        for doc_id in doc_ids(10, "g"):
            resp = cluster.any_node().execute_write(
                "gcp", doc_id, {"body": "x"}
            )
        assert resp["_global_checkpoint"] == resp["_seq_no"]

    def test_search_scatter(self, cluster):
        cluster.create_index("s", n_shards=2, n_replicas=1, mappings=MAPPINGS)
        load(cluster, "s", doc_ids(15, "s"))
        out = cluster.any_node().search("s", {"query": {"match_all": {}}, "size": 20})
        assert out["hits"]["total"]["value"] == 15
        assert len(out["hits"]["hits"]) == 15


class TestKillPrimary:
    def test_promotion_no_acked_loss_and_writes_continue(self, cluster):
        cluster.create_index("kp", n_shards=1, n_replicas=2, mappings=MAPPINGS)
        acked = load(cluster, "kp", doc_ids(50, "k"))
        routing = cluster.any_node().state.indices["kp"].shards[0]
        old_primary = routing.primary
        old_term = routing.primary_term
        cluster.kill(old_primary)
        cluster.step()  # failure detection (+ election if master died)
        survivor = cluster.any_node()
        new_routing = survivor.state.indices["kp"].shards[0]
        assert new_routing.primary is not None
        assert new_routing.primary != old_primary
        assert new_routing.primary_term == old_term + 1
        # No acknowledged doc lost through promotion.
        for doc_id in acked:
            assert survivor.get_doc("kp", doc_id) is not None, doc_id
        out = survivor.search("kp", {"query": {"match_all": {}}, "size": 100})
        assert out["hits"]["total"]["value"] == len(acked)
        # Writes continue under the new primary.
        more = load(cluster, "kp", doc_ids(10, "after"))
        for doc_id in more:
            assert survivor.get_doc("kp", doc_id) is not None

    def test_master_and_primary_same_node_killed(self, cluster):
        cluster.create_index("mp", n_shards=1, n_replicas=2, mappings=MAPPINGS)
        acked = load(cluster, "mp", doc_ids(30, "m"))
        # node-0 is both master and (first-assigned) primary.
        assert cluster.any_node().state.indices["mp"].shards[0].primary == "node-0"
        cluster.kill("node-0")
        cluster.step()  # re-election + promotion
        survivor = cluster.any_node()
        assert survivor.state.master in ("node-1", "node-2")
        assert survivor.state.indices["mp"].shards[0].primary != "node-0"
        for doc_id in acked:
            assert survivor.get_doc("mp", doc_id) is not None
        load(cluster, "mp", doc_ids(5, "post"))


class TestReplicaRejoin:
    def test_ops_based_catchup(self, make_cluster):
        # 5 nodes all holding a copy (no spares): a killed replica cannot
        # be replaced, so its restart must rejoin THAT copy via ops-based
        # catch-up; killing the primary afterwards still keeps a quorum.
        cluster = make_cluster(5)
        try:
            cluster.create_index(
                "rj", n_shards=1, n_replicas=4, mappings=MAPPINGS
            )
            acked = load(cluster, "rj", doc_ids(40, "r"))
            routing = cluster.any_node().state.indices["rj"].shards[0]
            victim = routing.replicas[0]
            primary_engine = cluster.nodes[routing.primary].engines[("rj", 0)]
            cluster.kill(victim)
            cluster.step()
            # Writes while the replica is down (ops-based catch-up later).
            acked += load(cluster, "rj", doc_ids(25, "while-down"))
            history_before = len(primary_engine._ops_history)
            node = cluster.restart(victim)
            cluster.step()  # join + allocate as recovering
            cluster.step()  # run recovery + finalize
            routing = cluster.any_node().state.indices["rj"].shards[0]
            assert victim in routing.replicas and victim in routing.in_sync
            # Ops-based (not resync): history was never trimmed.
            assert history_before <= primary_engine.history_retention
            # The rejoined COPY holds every acked doc.
            engine = node.engines[("rj", 0)]
            for doc_id in acked:
                assert engine.get(doc_id) is not None, doc_id
            # And survives promotion: kill the primary; service continues.
            cluster.kill(routing.primary)
            cluster.step()
            after = node.state.indices["rj"].shards[0]
            assert after.primary is not None and after.primary != routing.primary
            for doc_id in acked:
                assert node.get_doc("rj", doc_id) is not None, doc_id
            load(cluster, "rj", doc_ids(5, "resumed"))
        finally:
            cluster.close()

    def test_full_resync_when_history_trimmed(self, cluster):
        cluster.create_index("fr", n_shards=1, n_replicas=2, mappings=MAPPINGS)
        routing = cluster.any_node().state.indices["fr"].shards[0]
        primary = cluster.nodes[routing.primary]
        primary.engines[("fr", 0)].history_retention = 5
        acked = load(cluster, "fr", doc_ids(10, "a"))
        victim = routing.replicas[0]
        cluster.kill(victim)
        cluster.step()
        acked += load(cluster, "fr", doc_ids(30, "b"))  # >> retention
        node = cluster.restart(victim)
        cluster.step()
        cluster.step()
        routing = cluster.any_node().state.indices["fr"].shards[0]
        assert victim in routing.in_sync
        engine = node.engines[("fr", 0)]
        for doc_id in acked:
            assert engine.get(doc_id) is not None, doc_id


class TestFailureModes:
    def test_unreachable_replica_failed_out_then_heals(self, cluster):
        cluster.create_index("fo", n_shards=1, n_replicas=1, mappings=MAPPINGS)
        routing = cluster.any_node().state.indices["fo"].shards[0]
        replica = routing.replicas[0]
        primary = routing.primary
        cluster.hub.drop_action(primary, replica, "replica_op")
        resp = cluster.any_node().execute_write("fo", "x1", {"body": "x"})
        assert resp["result"] == "created"  # acked after failing the copy
        routing = cluster.any_node().state.indices["fo"].shards[0]
        assert replica not in routing.in_sync
        cluster.hub.clear_drops()
        cluster.step()  # heal: re-allocate + recover
        cluster.step()
        routing = cluster.any_node().state.indices["fo"].shards[0]
        assert replica in routing.in_sync
        assert cluster.nodes[replica].engines[("fo", 0)].get("x1") is not None

    def test_stale_primary_cannot_ack(self, cluster):
        cluster.create_index("sp", n_shards=1, n_replicas=2, mappings=MAPPINGS)
        load(cluster, "sp", doc_ids(5, "s"))
        routing = cluster.any_node().state.indices["sp"].shards[0]
        old_primary = routing.primary
        others = [n for n in cluster.seeds if n != old_primary]
        # Partition the primary away; majority side elects + promotes.
        cluster.hub.partition({old_primary}, set(others))
        for n in others:
            cluster.nodes[n].try_elect()
        majority = cluster.nodes[others[0]]
        majority_master = cluster.master()
        assert majority_master is not None
        majority_master.health_round()
        new_routing = majority.state.indices["sp"].shards[0]
        assert new_routing.primary != old_primary
        # The deposed primary cannot acknowledge writes: every in-sync copy
        # is unreachable and the master cannot be asked to fail them.
        stale = cluster.nodes[old_primary]
        with pytest.raises((ReplicationFailedError, NoShardAvailableError)):
            stale.execute_write("sp", "sx", {"body": "stale"})
        # The majority side keeps serving.
        ok = majority.execute_write("sp", "sy", {"body": "fresh"})
        assert ok["result"] == "created"
        cluster.hub.heal_partition()

    def test_red_shard_refuses_writes(self, cluster):
        cluster.create_index("red", n_shards=1, n_replicas=0, mappings=MAPPINGS)
        routing = cluster.any_node().state.indices["red"].shards[0]
        holder = routing.primary
        survivors = [n for n in cluster.seeds if n != holder]
        cluster.kill(holder)
        cluster.step()
        node = cluster.nodes[survivors[0]]
        assert node.state.indices["red"].shards[0].primary is None
        with pytest.raises(NoShardAvailableError):
            node.execute_write("red", "r1", {"body": "x"})

    def test_minority_master_steps_down(self, cluster):
        master = cluster.master()
        others = {n for n in cluster.seeds if n != master.node_id}
        cluster.hub.partition({master.node_id}, others)
        master.health_round()  # publication loses quorum -> steps down
        assert master.state.master is None
        for n in others:
            cluster.nodes[n].try_elect()
        new_master = cluster.master()
        assert new_master is not None and new_master.node_id in others
        cluster.hub.heal_partition()


class TestDeleteReplication:
    def test_delete_fans_out(self, cluster):
        cluster.create_index("del", n_shards=1, n_replicas=2, mappings=MAPPINGS)
        load(cluster, "del", doc_ids(8, "d"))
        resp = cluster.any_node().execute_write(
            "del", "d3", None, op="delete"
        )
        assert resp["result"] == "deleted"
        routing = cluster.any_node().state.indices["del"].shards[0]
        for node_id in routing.assigned():
            assert cluster.nodes[node_id].engines[("del", 0)].get("d3") is None


class TestConcurrentChaos:
    def test_writes_race_promotion_no_acked_loss(self, make_cluster):
        """Writer threads race a primary kill with the background stepper
        running; every write that was ACKED must survive promotion."""
        import threading

        cluster = make_cluster(3)
        try:
            cluster.create_index(
                "chaos", n_shards=1, n_replicas=2, mappings=MAPPINGS
            )
            cluster.start_stepper(0.02)
            acked: list[str] = []
            acked_lock = threading.Lock()
            stop = threading.Event()

            def writer(tid: int):
                i = 0
                while not stop.is_set() and i < 200:
                    doc_id = f"w{tid}-{i}"
                    i += 1
                    try:
                        node = cluster.any_node()
                        resp = node.execute_write(
                            "chaos", doc_id, {"body": f"x {doc_id}"}
                        )
                        if resp["result"] in ("created", "updated"):
                            with acked_lock:
                                acked.append(doc_id)
                    except Exception:
                        continue  # unacked: allowed to be lost

            threads = [
                threading.Thread(target=writer, args=(t,)) for t in range(3)
            ]
            for t in threads:
                t.start()
            import time as _time

            _time.sleep(0.15)
            victim = cluster.any_node().state.indices["chaos"].shards[0].primary
            cluster.kill(victim)
            for t in threads:
                t.join(timeout=30)
            stop.set()
            # Let the stepper finish promotion/healing.
            deadline = _time.time() + 10
            while _time.time() < deadline:
                routing = None
                for n in cluster.nodes.values():
                    if not n.closed:
                        routing = n.state.indices["chaos"].shards[0]
                        break
                if routing is not None and routing.primary not in (None, victim):
                    break
                _time.sleep(0.05)
            cluster.stop_stepper()
            survivor = cluster.any_node()
            routing = survivor.state.indices["chaos"].shards[0]
            assert routing.primary is not None and routing.primary != victim
            missing = [
                d for d in acked if survivor.get_doc("chaos", d) is None
            ]
            assert not missing, f"{len(missing)} acked docs lost: {missing[:5]}"
            assert len(acked) > 50  # the run actually exercised writes
        finally:
            cluster.close()


class TestRestartSafety:
    def test_restarted_empty_copy_not_promoted(self, make_cluster):
        """kill+restart a replica with NO control round between, then kill
        the primary: the restarted (empty) copy must never be promoted —
        the session map strips its stale in-sync membership first."""
        cluster = make_cluster(5)
        try:
            cluster.create_index(
                "rs", n_shards=1, n_replicas=1, mappings=MAPPINGS
            )
            acked = load(cluster, "rs", doc_ids(20, "r"))
            routing = cluster.any_node().state.indices["rs"].shards[0]
            replica = routing.replicas[0]
            primary = routing.primary
            # Restart the replica silently (no step: master never saw it die).
            cluster.kill(replica)
            node = cluster.restart(replica)
            cluster.kill(primary)
            cluster.step()
            cluster.step()  # heal/recover rounds
            view = cluster.any_node().state.indices["rs"].shards[0]
            if view.primary is not None:
                # Whoever got promoted/recovered must hold every acked doc.
                holder = cluster.nodes[view.primary]
                for doc_id in acked:
                    assert holder.get_doc("rs", doc_id) is not None, doc_id
            else:
                # Red is the honest outcome when both real copies died.
                assert view.primary is None

            # The empty restarted copy must not silently satisfy reads.
            engine = node.engines.get(("rs", 0))
            if engine is not None and view.primary == replica:
                for doc_id in acked:
                    assert engine.get(doc_id) is not None, doc_id
        finally:
            cluster.close()

    def test_global_checkpoint_unpinned_after_fail_out(self, make_cluster):
        """Failing a copy out of the in-sync set must release its grip on
        the primary's global checkpoint."""
        cluster = make_cluster(3)
        try:
            cluster.create_index(
                "gc2", n_shards=1, n_replicas=1, mappings=MAPPINGS
            )
            routing = cluster.any_node().state.indices["gc2"].shards[0]
            primary, replica = routing.primary, routing.replicas[0]
            cluster.hub.drop_action(primary, replica, "replica_op")
            resp = cluster.any_node().execute_write(
                "gc2", "a", {"body": "x"}
            )
            assert resp["result"] == "created"
            resp = cluster.any_node().execute_write(
                "gc2", "b", {"body": "y"}
            )
            # With the dead copy reconciled away, the checkpoint is the
            # primary's own (the only in-sync copy).
            assert resp["_global_checkpoint"] == resp["_seq_no"]
        finally:
            cluster.hub.clear_drops()
            cluster.close()


class TestDivergenceSafety:
    def test_term_resync_purges_phantom_on_surviving_replica(self, make_cluster):
        """A replica holding the dead primary's never-acked op (phantom)
        must be reset to the new primary's ops line after promotion."""
        cluster = make_cluster(3)
        try:
            cluster.create_index(
                "dv", n_shards=1, n_replicas=2, mappings=MAPPINGS
            )
            acked = load(cluster, "dv", doc_ids(10, "a"))
            routing = cluster.any_node().state.indices["dv"].shards[0]
            primary = routing.primary
            replicas = sorted(routing.replicas)
            promoted, phantom_holder = replicas[0], replicas[1]
            # Simulate the dead primary's unacked fan-out reaching only one
            # replica: inject the op directly into that copy.
            victim_engine = cluster.nodes[phantom_holder].engines[("dv", 0)]
            phantom_seqno = victim_engine.max_seqno + 1
            victim_engine.apply_replica(
                {
                    "op": "index",
                    "id": "phantom",
                    "source": {"body": "never acked"},
                    "version": 1,
                    "seqno": phantom_seqno,
                    "term": routing.primary_term,
                }
            )
            assert victim_engine.get("phantom") is not None
            cluster.kill(primary)
            cluster.step()  # promotion (+ election if the master died)
            cluster.step()  # term resync + healing
            view = cluster.any_node().state.indices["dv"].shards[0]
            assert view.primary == promoted
            # The phantom is gone from the surviving replica's fresh line.
            engine = cluster.nodes[phantom_holder].engines[("dv", 0)]
            assert engine.get("phantom") is None
            for doc_id in acked:
                assert engine.get(doc_id) is not None, doc_id
        finally:
            cluster.close()

    def test_deposed_primary_with_phantom_resyncs_on_rejoin(self, make_cluster):
        """An isolated primary that accepted (but could not replicate or
        ack) an op rejoins after healing via full resync — the phantom op
        never resurrects."""
        cluster = make_cluster(3)
        try:
            cluster.create_index(
                "dp", n_shards=1, n_replicas=2, mappings=MAPPINGS
            )
            acked = load(cluster, "dp", doc_ids(10, "a"))
            routing = cluster.any_node().state.indices["dp"].shards[0]
            old_primary = routing.primary
            others = [n for n in cluster.seeds if n != old_primary]
            cluster.hub.partition({old_primary}, set(others))
            # The isolated primary applies locally but cannot ack.
            stale = cluster.nodes[old_primary]
            with pytest.raises(
                (ReplicationFailedError, NoShardAvailableError)
            ):
                stale.execute_write("dp", "phantom", {"body": "lost"})
            assert stale.engines[("dp", 0)].get("phantom") is not None
            # Majority side elects, promotes, and takes new acked writes.
            for n in others:
                cluster.nodes[n].try_elect()
            cluster.master().health_round()
            majority = cluster.nodes[others[0]]
            acked.append("real")
            majority.execute_write("dp", "real", {"body": "acked"})
            # Heal: the old primary rejoins; term mismatch forces resync.
            cluster.hub.heal_partition()
            for _ in range(3):
                cluster.step()
            view = majority.state.indices["dp"].shards[0]
            assert old_primary in view.in_sync
            engine = cluster.nodes[old_primary].engines[("dp", 0)]
            assert engine.get("phantom") is None, "phantom op resurrected"
            for doc_id in acked:
                assert engine.get(doc_id) is not None, doc_id
        finally:
            cluster.close()
