"""SPMD mesh serving circuit breaker (ISSUE 1 tentpole c).

The old latch ("3 exec failures → disabled for the life of the process")
is replaced by an error-classifying breaker: transient failures (device
OOM, executor hiccups) open the circuit, half-open after a cooldown, and
re-enable on the first success; sticky failures (compile/parity bugs)
latch off permanently. Disable/re-enable events surface in /_nodes/stats.
"""

import json
import time

import numpy as np
import pytest

from elasticsearch_tpu.parallel import mesh_serving
from elasticsearch_tpu.parallel.mesh_serving import (
    MeshServingBreaker,
    classify_mesh_error,
)
from elasticsearch_tpu.rest.server import RestServer


class TestErrorClassifier:
    def test_oom_and_runtime_errors_are_transient(self):
        assert classify_mesh_error(RuntimeError("RESOURCE_EXHAUSTED")) == (
            "transient"
        )
        assert classify_mesh_error(MemoryError()) == "transient"
        assert classify_mesh_error(RuntimeError("device out of memory")) == (
            "transient"
        )
        # Unknown runtime failures default to transient: a cooldown'd
        # retry is recoverable, a permanent disable is not.
        assert classify_mesh_error(RuntimeError("weird")) == "transient"

    def test_compile_and_parity_errors_are_sticky(self):
        assert classify_mesh_error(TypeError("bad lowering")) == "sticky"
        assert classify_mesh_error(ValueError("shape off")) == "sticky"
        assert classify_mesh_error(
            RuntimeError("INVALID_ARGUMENT: mismatched operand")
        ) == "sticky"


class TestBreakerStateMachine:
    def test_transient_trips_then_half_opens_then_closes(self):
        b = MeshServingBreaker(failure_threshold=2, cooldown_s=0.05)
        assert b.allow()
        b.record_failure(RuntimeError("RESOURCE_EXHAUSTED"))
        assert b.allow()  # below threshold
        b.record_failure(RuntimeError("RESOURCE_EXHAUSTED"))
        assert not b.allow()  # open
        assert b.disable_events == 1
        time.sleep(0.06)
        assert b.allow()  # half-open trial
        b.record_success()
        assert b.state == "closed"
        assert b.reenable_events == 1
        assert b.allow()

    def test_half_open_failure_reopens(self):
        b = MeshServingBreaker(failure_threshold=1, cooldown_s=0.05)
        b.record_failure(RuntimeError("oom OOM"))
        assert not b.allow()
        time.sleep(0.06)
        assert b.allow()  # half-open
        b.record_failure(RuntimeError("OOM again"))
        assert not b.allow()  # straight back open
        assert b.disable_events == 2

    def test_sticky_never_reenables(self):
        b = MeshServingBreaker(failure_threshold=3, cooldown_s=0.0)
        b.record_failure(TypeError("compile bug"))
        assert b.sticky
        assert not b.allow()
        time.sleep(0.01)
        assert not b.allow()  # cooldown elapsed; still latched
        assert b.stats()["state"] == "disabled"

    def test_success_resets_transient_count(self):
        b = MeshServingBreaker(failure_threshold=2, cooldown_s=10.0)
        b.record_failure(RuntimeError("OOM"))
        b.record_success()
        b.record_failure(RuntimeError("OOM"))
        assert b.allow()  # counter was reset; one more failure needed


MAPPINGS = {
    "properties": {"body": {"type": "text"}, "tag": {"type": "keyword"}}
}


@pytest.fixture
def rest():
    rest = RestServer()
    status, _ = rest.dispatch(
        "PUT",
        "/mb",
        {},
        json.dumps(
            {
                "settings": {"index": {"number_of_shards": 2}},
                "mappings": MAPPINGS,
            }
        ),
    )
    assert status == 200
    rng = np.random.default_rng(7)
    lines = []
    for i in range(40):
        lines.append(json.dumps({"index": {"_id": f"d{i}"}}))
        lines.append(
            json.dumps(
                {
                    "body": " ".join(
                        rng.choice(["ant", "bee", "cat"], rng.integers(2, 6))
                    ),
                    "tag": "x",
                }
            )
        )
    status, resp = rest.dispatch(
        "POST", "/mb/_bulk", {"refresh": "true"}, "\n".join(lines)
    )
    assert status == 200 and not resp["errors"]
    yield rest
    rest.close()


def search(rest):
    status, resp = rest.dispatch(
        "POST",
        "/mb/_search",
        {"request_cache": "false"},
        json.dumps({"query": {"match": {"body": "bee"}}}),
    )
    assert status == 200, resp
    rest.node.request_cache.clear()
    return resp


def test_transient_exec_failure_reenables_after_cooldown(rest, monkeypatch):
    """Acceptance: an injected transient mesh exec failure no longer
    disables the SPMD path for the life of the process — it re-enables
    after the cooldown and the path serves again."""
    mv = rest.node.get_index("mb").search.mesh_view
    assert mv is not None
    # Generous cooldown: the "still within cooldown" search below must
    # land before it elapses even on a loaded full-suite run.
    mv.breaker = MeshServingBreaker(failure_threshold=2, cooldown_s=1.0)
    search(rest)
    assert mv.served >= 1  # the mesh path actually works here
    served_before = mv.served

    def boom(*args, **kwargs):
        raise RuntimeError("RESOURCE_EXHAUSTED: injected device OOM")

    real = mesh_serving.sharded_execute
    monkeypatch.setattr(mesh_serving, "sharded_execute", boom)
    # Requests during the failure window still answer 200 via the host
    # loop; the breaker opens at the threshold.
    for _ in range(2):
        out = search(rest)
        assert out["hits"]["total"]["value"] > 0
    assert mv.served == served_before
    assert mv.breaker.state == "open"
    assert mv.breaker.disable_events == 1
    assert mv.exec_failures == 2

    # The fault clears, but the circuit is still open: within the
    # cooldown the mesh is not retried.
    monkeypatch.setattr(mesh_serving, "sharded_execute", real)
    search(rest)
    assert mv.served == served_before

    # After the cooldown the half-open trial succeeds and the SPMD path
    # serves again — no process restart required.
    time.sleep(1.05)
    search(rest)
    assert mv.served == served_before + 1
    assert mv.breaker.state == "closed"
    assert mv.breaker.reenable_events == 1
    # And it keeps serving.
    search(rest)
    assert mv.served == served_before + 2


def test_disable_reenable_events_visible_in_nodes_stats(rest, monkeypatch):
    mv = rest.node.get_index("mb").search.mesh_view
    mv.breaker = MeshServingBreaker(failure_threshold=1, cooldown_s=0.05)

    def boom(*args, **kwargs):
        raise RuntimeError("RESOURCE_EXHAUSTED: injected")

    real = mesh_serving.sharded_execute
    monkeypatch.setattr(mesh_serving, "sharded_execute", boom)
    search(rest)
    monkeypatch.setattr(mesh_serving, "sharded_execute", real)
    time.sleep(0.06)
    search(rest)  # half-open success
    status, resp = rest.dispatch("GET", "/_nodes/stats", {}, "")
    assert status == 200
    mesh_stats = resp["nodes"][rest.node.node_name]["mesh_serving"]
    assert mesh_stats["disable_events"] == 1
    assert mesh_stats["reenable_events"] == 1
    view = mesh_stats["views"]["mb"]
    assert view["state"] == "closed"
    assert view["served"] >= 1


def test_sticky_failure_stays_disabled(rest, monkeypatch):
    mv = rest.node.get_index("mb").search.mesh_view
    mv.breaker = MeshServingBreaker(failure_threshold=3, cooldown_s=0.0)
    served_before = mv.served

    def boom(*args, **kwargs):
        raise RuntimeError("INVALID_ARGUMENT: mismatched shard shapes")

    real = mesh_serving.sharded_execute
    monkeypatch.setattr(mesh_serving, "sharded_execute", boom)
    search(rest)  # one sticky failure latches immediately
    monkeypatch.setattr(mesh_serving, "sharded_execute", real)
    time.sleep(0.01)
    search(rest)
    assert mv.served == served_before  # never retried
    assert mv.breaker.stats()["state"] == "disabled"
