"""Aliases, dynamic settings, admin surface, and by-query operations.

Reference: aliases (metadata/AliasMetadata + TransportIndicesAliases),
update-settings action, cat APIs, and the reindex module
(delete_by_query/update_by_query/reindex).
"""

import json

import pytest

from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.rest.server import RestServer

MAPPINGS = {"properties": {"t": {"type": "text"}, "n": {"type": "long"}}}


def seed(node, index="a", n=30, **extra):
    node.create_index(index, {"mappings": MAPPINGS, **extra})
    for i in range(n):
        node.index_doc(index, {"t": f"w{i % 3} body", "n": i}, f"d{i}")
    node.refresh(index)


def test_alias_crud_and_resolution():
    node = Node()
    seed(node, "logs-1")
    node.update_aliases(
        {"actions": [{"add": {"index": "logs-1", "alias": "logs"}}]}
    )
    # search/doc APIs resolve the alias
    r = node.search("logs", {"query": {"match_all": {}}, "size": 0})
    assert r["hits"]["total"]["value"] == 30
    assert node.get_doc("logs", "d3")["found"]
    node.index_doc("logs", {"t": "via alias", "n": 99}, "extra", refresh=True)
    assert node.get_doc("logs-1", "extra")["found"]
    # listing
    out = node.get_aliases()
    assert out["logs-1"]["aliases"] == {"logs": {}}
    # ambiguous alias rejects
    seed(node, "logs-2", n=3)
    node.update_aliases(
        {"actions": [{"add": {"index": "logs-2", "alias": "logs"}}]}
    )
    with pytest.raises(ApiError):
        node.search("logs", {})
    node.delete_alias("logs-2", "logs")
    assert node.search("logs", {"size": 0})["hits"]["total"]["value"] == 31
    # deleting the index drops its aliases
    node.delete_index("logs-1")
    assert "logs" not in node.aliases


def test_alias_name_collisions():
    node = Node()
    seed(node, "x", n=1)
    seed(node, "y", n=1)
    with pytest.raises(ApiError):
        node.update_aliases(
            {"actions": [{"add": {"index": "x", "alias": "y"}}]}
        )
    node.update_aliases({"actions": [{"add": {"index": "x", "alias": "al"}}]})
    with pytest.raises(ApiError):
        node.create_index("al", {})


def test_create_index_with_aliases_and_persistence(tmp_path):
    node = Node(data_path=str(tmp_path))
    node.create_index("base", {"aliases": {"current": {}}})
    node.index_doc("current", {"t": "hello"}, "1", refresh=True)
    node.close()
    node2 = Node(data_path=str(tmp_path))
    assert node2.get_doc("current", "1")["found"]
    node2.close()


def test_dynamic_settings():
    node = Node()
    seed(node)
    node.put_pipeline(
        "tagger", {"processors": [{"set": {"field": "tagged", "value": 1}}]}
    )
    node.put_settings("a", {"index": {"default_pipeline": "tagger"}})
    node.index_doc("a", {"t": "x", "n": 1}, "new", refresh=True)
    assert node.get_doc("a", "new")["_source"]["tagged"] == 1
    out = node.get_settings("a")
    assert out["a"]["settings"]["index"]["default_pipeline"] == "tagger"
    # dotted form + merge settings reach the engines
    node.put_settings("a", {"index.merge.max_segment_count": 3})
    assert node.get_index("a").engines[0].max_segments == 3
    with pytest.raises(ApiError):  # static setting
        node.put_settings("a", {"index": {"number_of_shards": 4}})


def test_index_info_and_cat_apis():
    node = Node()
    seed(node, "info", n=5, settings={"index": {"number_of_shards": 2}})
    rest = RestServer(node=node)
    status, r = rest.dispatch("GET", "/info", {}, "")
    assert status == 200
    assert r["info"]["settings"]["index"]["number_of_shards"] == "2"  # settings serialize as strings, like the reference
    assert "t" in r["info"]["mappings"]["properties"]
    status, _ = rest.dispatch("HEAD", "/info", {}, "")
    assert status == 200
    status, _ = rest.dispatch("HEAD", "/missing", {}, "")
    assert status == 404
    status, r = rest.dispatch("GET", "/_cat/health", {}, "")
    assert r[0]["status"] == "green"
    status, r = rest.dispatch("GET", "/_cat/count/info", {}, "")
    assert r[0]["count"] == "5"
    status, r = rest.dispatch("GET", "/_cat/shards", {}, "")
    assert len([x for x in r if x["index"] == "info"]) == 2
    status, r = rest.dispatch("GET", "/_cat/segments", {}, "")
    assert any(x["index"] == "info" for x in r)
    status, r = rest.dispatch("GET", "/_cluster/stats", {}, "")
    assert r["indices"]["count"] >= 1
    status, r = rest.dispatch("GET", "/_nodes", {}, "")
    assert "node-0" in r["nodes"]


@pytest.mark.parametrize("n_shards", [1, 3])
def test_delete_by_query(n_shards):
    node = Node()
    seed(node, n=30, settings={"index": {"number_of_shards": n_shards}})
    out = node.delete_by_query(
        "a", {"query": {"match": {"t": "w1"}}}, refresh=True
    )
    expected = len([i for i in range(30) if i % 3 == 1])
    assert out["deleted"] == out["total"] == expected
    r = node.search("a", {"query": {"match_all": {}}, "size": 0})
    assert r["hits"]["total"]["value"] == 30 - expected
    # no matches second time
    out = node.delete_by_query("a", {"query": {"match": {"t": "w1"}}})
    assert out["deleted"] == 0


def test_update_by_query_with_pipeline():
    node = Node()
    seed(node, n=12)
    node.put_pipeline(
        "mark", {"processors": [{"set": {"field": "marked", "value": True}}]}
    )
    out = node.update_by_query(
        "a", {"query": {"range": {"n": {"lt": 5}}}},
        refresh=True, pipeline="mark",
    )
    assert out["updated"] == out["total"] == 5
    r = node.search(
        "a", {"query": {"term": {"marked": True}}, "size": 0}
    )
    # marked is dynamically mapped boolean
    assert r["hits"]["total"]["value"] == 5
    with pytest.raises(ApiError):
        node.update_by_query("a", {"script": {"source": "x"}})


def test_reindex_with_query_and_pipeline():
    node = Node()
    seed(node, "src9", n=20)
    node.put_pipeline(
        "stamp", {"processors": [{"set": {"field": "copied", "value": 1}}]}
    )
    out = node.reindex(
        {
            "source": {"index": "src9", "query": {"range": {"n": {"gte": 10}}}},
            "dest": {"index": "dst9", "pipeline": "stamp"},
        },
        refresh=True,
    )
    assert out["created"] == out["total"] == 10
    r = node.search("dst9", {"query": {"match_all": {}}, "size": 0})
    assert r["hits"]["total"]["value"] == 10
    assert node.get_doc("dst9", "d15")["_source"]["copied"] == 1
    # reindex again: existing ids update, not duplicate
    out = node.reindex(
        {"source": {"index": "src9"}, "dest": {"index": "dst9"}},
        refresh=True,
    )
    assert out["updated"] == 10 and out["created"] == 10
    with pytest.raises(ApiError):
        node.reindex({"source": {"index": "missing"}, "dest": {"index": "x"}})


def test_aliases_atomic_and_delete_protection():
    node = Node()
    seed(node, "at1", n=2)
    with pytest.raises(ApiError):  # second action invalid -> nothing applies
        node.update_aliases(
            {
                "actions": [
                    {"add": {"index": "at1", "alias": "ok"}},
                    {"add": {"index": "missing", "alias": "bad"}},
                ]
            }
        )
    assert "ok" not in node.aliases
    with pytest.raises(ApiError):  # remove of absent alias -> 404
        node.update_aliases(
            {"actions": [{"remove": {"index": "at1", "alias": "nope"}}]}
        )
    node.update_aliases({"actions": [{"add": {"index": "at1", "alias": "al"}}]})
    with pytest.raises(ApiError):  # deleting via alias is rejected
        node.delete_index("al")
    assert "at1" in node.indices
    with pytest.raises(ApiError):  # GET missing index aliases -> 404
        node.get_aliases("zzz")


def test_reindex_edge_cases():
    node = Node()
    seed(node, "re1", n=4)
    out = node.reindex(
        {
            "source": {"index": "re1", "query": {"term": {"t": "absent"}}},
            "dest": {"index": "fresh"},
        }
    )
    assert out["total"] == 0 and "fresh" in node.indices  # 200, dest created
    with pytest.raises(ApiError):
        node.reindex({"source": {"index": "re1"}, "dest": {"index": "re1"}})
    node.update_aliases({"actions": [{"add": {"index": "re1", "alias": "rale"}}]})
    with pytest.raises(ApiError):  # alias resolving to the source
        node.reindex({"source": {"index": "re1"}, "dest": {"index": "rale"}})


def test_max_result_window_enforced():
    node = Node()
    seed(node, n=5)
    with pytest.raises(ApiError):
        node.search("a", {"from": 9995, "size": 10})
    node.put_settings("a", {"index": {"max_result_window": 50}})
    with pytest.raises(ApiError):
        node.search("a", {"size": 60})
    assert node.search("a", {"size": 50})["hits"]["total"]["value"] == 5


def test_update_by_query_collects_per_doc_failures():
    node = Node()
    node.create_index("f", {"mappings": {"properties": {"n": {"type": "long"}}}})
    node.index_doc("f", {"n": 1}, "1", refresh=True)
    node.put_pipeline(
        "breaker",
        {"processors": [{"set": {"field": "n", "value": "not-a-number"}}]},
    )
    out = node.update_by_query("f", {}, refresh=True, pipeline="breaker")
    assert out["updated"] == 0
    assert len(out["failures"]) == 1 and out["failures"][0]["id"] == "1"


def test_byquery_rest_routes():
    rest = RestServer()
    seed(rest.node, "r", n=9)
    status, r = rest.dispatch(
        "POST",
        "/r/_delete_by_query",
        {"refresh": "true"},
        json.dumps({"query": {"range": {"n": {"lt": 3}}}}),
    )
    assert status == 200 and r["deleted"] == 3
    status, r = rest.dispatch(
        "POST", "/r/_update_by_query", {"refresh": "true"}, ""
    )
    assert status == 200 and r["updated"] == 6
    status, r = rest.dispatch(
        "POST",
        "/_reindex",
        {"refresh": "true"},
        json.dumps({"source": {"index": "r"}, "dest": {"index": "r2"}}),
    )
    assert status == 200 and r["created"] == 6
