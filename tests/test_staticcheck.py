"""The analyzer analyzed: fixture snippets trigger each rule exactly as
designed (positive + suppressed twin per rule), seeded defects fail the
gate, and the live repo itself runs clean — the tier-1 contract of
ISSUE 6 (`python -m staticcheck` as a merge gate).
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from staticcheck.core import Project, load_baseline, run_project

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(__file__), "staticcheck_fixtures")


def run_fixture(name: str):
    return run_project(Project(os.path.join(FIXTURES, name)))


def rules_of(findings):
    out = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out


# ------------------------------------------------------------ trace-hazard


class TestTraceHazard:
    @pytest.fixture(scope="class")
    def report(self):
        return run_fixture("trace_hazard")

    def test_host_sync_fires(self, report):
        hits = [f for f in report.findings if f.rule == "host-sync"]
        # float(total) in the root + .item() in the reachable helper;
        # float(k) on the static arg stays clean.
        assert len(hits) == 2
        assert {f.context for f in hits} == {"execute", "helper"}

    def test_traced_branch_fires_once(self, report):
        hits = [f for f in report.findings if f.rule == "traced-branch"]
        assert len(hits) == 1

    def test_jit_ephemeral_fires(self, report):
        assert rules_of(report.findings).get("jit-ephemeral") == 1

    def test_unhashable_static_fires(self, report):
        hits = [
            f for f in report.findings if f.rule == "jit-unhashable-static"
        ]
        assert len(hits) == 1
        assert "[spec]" in hits[0].message

    def test_suppressed_twins(self, report):
        sup = rules_of(report.suppressed)
        assert sup.get("host-sync") == 1
        assert sup.get("traced-branch") == 1

    def test_gate_fails(self, report):
        assert report.failed


# --------------------------------------------------------- lock-discipline


class TestLockDiscipline:
    @pytest.fixture(scope="class")
    def report(self):
        return run_fixture("lock_discipline")

    def test_lock_order_inversion(self, report):
        hits = [f for f in report.findings if f.rule == "lock-order"]
        # One cycle, reported once.
        assert len(hits) == 1
        assert "Pair.alpha" in hits[0].message
        assert "Pair.beta" in hits[0].message

    def test_blocking_call(self, report):
        hits = [
            f for f in report.findings if f.rule == "lock-blocking-call"
        ]
        assert len(hits) == 1
        assert "time.sleep" in hits[0].message

    def test_self_deadlock(self, report):
        assert rules_of(report.findings).get("lock-self-deadlock") == 1

    def test_suppressed_twin(self, report):
        assert rules_of(report.suppressed).get("lock-blocking-call") == 1

    def test_gate_fails(self, report):
        assert report.failed


# ----------------------------------------------------- registry-consistency


class TestRegistryConsistency:
    @pytest.fixture(scope="class")
    def report(self):
        return run_fixture("registry_consistency")

    def test_unseeded_unsurfaced_backend(self, report):
        msgs = [
            f.message
            for f in report.findings
            if f.rule == "registry-backend"
        ]
        # [ghost] lacks both a cost seed and any surfacing site;
        # [packed], [mesh_spmd], [cached_mask] and [ann_ivf] are surfaced
        # but unseeded (exactly one finding each) — registering the
        # multi-tenant backend, the SPMD mesh plan class, the filter-
        # cache masked-execution backend, or the IVF ANN backend without
        # an exec/cost.py seed must fail the gate; [device] is covered
        # and stays clean.
        assert len(msgs) == 6
        assert sum("[ghost]" in m for m in msgs) == 2
        packed = [m for m in msgs if "[packed]" in m]
        assert len(packed) == 1 and "cost seed" in packed[0]
        mesh = [m for m in msgs if "[mesh_spmd]" in m]
        assert len(mesh) == 1 and "cost seed" in mesh[0]
        cached = [m for m in msgs if "[cached_mask]" in m]
        assert len(cached) == 1 and "cost seed" in cached[0]
        ann = [m for m in msgs if "[ann_ivf]" in m]
        assert len(ann) == 1 and "cost seed" in ann[0]

    def test_fault_sites(self, report):
        msgs = [
            f.message
            for f in report.findings
            if f.rule == "registry-fault-site"
        ]
        assert any("[unregistered.site]" in m for m in msgs)
        assert any("[dead.site]" in m for m in msgs)
        # an unregistered socket-transport site fails like any other
        assert any("[transport.tcp.frame]" in m for m in msgs)
        # ... and so do the async-search reduce fold and QoS shed sites
        assert any("[async.reduce]" in m for m in msgs)
        assert any("[qos.shed]" in m for m in msgs)
        assert len(msgs) == 5

    def test_fault_site_suppressed_twin(self, report):
        assert rules_of(report.suppressed).get("registry-fault-site") == 1

    def test_metrics_catalog(self, report):
        msgs = [
            f.message for f in report.findings if f.rule == "registry-metric"
        ]
        assert any("[estpu_rogue_total]" in m for m in msgs)  # uncataloged
        assert any("[estpu_kind_total]" in m for m in msgs)  # kind clash
        assert any("[estpu_dead_total]" in m for m in msgs)  # dead entry
        # an uncataloged packed-occupancy instrument fails like any other
        assert any("[estpu_packed_rogue_total]" in m for m in msgs)
        # ... and so does an uncataloged mesh serving instrument
        assert any("[estpu_mesh_rogue_total]" in m for m in msgs)
        # ... and an uncataloged filter-cache instrument
        assert any("[estpu_filter_cache_rogue_total]" in m for m in msgs)
        # ... and an uncataloged ANN instrument
        assert any("[estpu_ann_rogue_total]" in m for m in msgs)
        # ... and an uncataloged socket-transport instrument
        assert any("[estpu_transport_rogue_total]" in m for m in msgs)
        # ... and an uncataloged refresh/merge instrument
        assert any("[estpu_merge_rogue_total]" in m for m in msgs)
        # ... and an uncataloged cluster-observability fan-in instrument
        assert any("[estpu_nodes_rogue_total]" in m for m in msgs)
        # ... and an uncataloged HBM-ledger instrument
        assert any("[estpu_hbm_rogue_total]" in m for m in msgs)
        # ... and an uncataloged health instrument
        assert any("[estpu_health_rogue_total]" in m for m in msgs)
        # ... and an uncataloged rolling-window instrument; the
        # cataloged windowed twin (estpu_good_recent_ms) stays clean.
        assert any("[estpu_rogue_recent]" in m for m in msgs)
        assert not any("[estpu_good_recent_ms]" in m for m in msgs)
        # ... and uncataloged async-search / QoS-lane instruments
        assert any("[estpu_async_rogue_total]" in m for m in msgs)
        assert any("[estpu_qos_rogue_total]" in m for m in msgs)
        # ... and uncataloged flight-recorder / incident instruments
        assert any("[estpu_recorder_rogue_total]" in m for m in msgs)
        assert any("[estpu_incident_rogue_total]" in m for m in msgs)
        assert len(msgs) == 17

    def test_indicator_registry(self, report):
        msgs = [
            f.message
            for f in report.findings
            if f.rule == "registry-indicator"
        ]
        # [missing] is registered with no implementation; [ghost] is
        # implemented but unregistered; [good] is clean.
        assert len(msgs) == 2
        assert any("[missing]" in m for m in msgs)
        assert any("[ghost]" in m for m in msgs)
        assert not any("[good]" in m for m in msgs)

    def test_action_registry(self, report):
        msgs = [
            f.message
            for f in report.findings
            if f.rule == "registry-action"
        ]
        # [phantom] is registered with no planner; [rogue] is planned
        # but unregistered; [steady] is clean.
        assert len(msgs) == 2
        assert any("[phantom]" in m for m in msgs)
        assert any("[rogue]" in m for m in msgs)
        assert not any("[steady]" in m for m in msgs)

    def test_breaker_labels(self, report):
        msgs = [
            f.message
            for f in report.findings
            if f.rule == "registry-breaker-label"
        ]
        # A breaker label allocated outside obs/device.py LEDGER_LABELS
        # fails the gate; registered labels (exact or f-string prefix)
        # stay clean, and the suppressed twin suppresses.
        assert len(msgs) == 1
        assert "[rogue_label]" in msgs[0]
        assert (
            rules_of(report.suppressed).get("registry-breaker-label") == 1
        )

    def test_bool_spec(self, report):
        msgs = [f.message for f in report.findings if f.rule == "bool-spec"]
        assert any("raw ('bool'" in m for m in msgs)
        assert any("index [7]" in m for m in msgs)
        assert len(msgs) == 2
        assert rules_of(report.suppressed).get("bool-spec") == 1

    def test_gate_fails(self, report):
        assert report.failed


# ------------------------------------------------------------------ hygiene


class TestHygiene:
    @pytest.fixture(scope="class")
    def report(self):
        return run_fixture("hygiene")

    def test_broad_except_fires_once(self, report):
        hits = [f for f in report.findings if f.rule == "broad-except"]
        # `guarded` (cancellation re-raised first) and `cleanup_reraise`
        # (bare re-raise) are exempt by construction.
        assert len(hits) == 1
        assert hits[0].context == "swallows"

    def test_wallclock_fires_once(self, report):
        hits = [
            f for f in report.findings if f.rule == "wallclock-duration"
        ]
        assert len(hits) == 1
        assert hits[0].context == "wall_duration"

    def test_suppressed_twins(self, report):
        sup = rules_of(report.suppressed)
        assert sup.get("broad-except") == 1
        assert sup.get("wallclock-duration") == 1

    def test_gate_fails(self, report):
        assert report.failed


# ------------------------------------------------------- framework contract


class TestFramework:
    def test_reasonless_suppression_does_not_suppress(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            "import time\n"
            "def f():\n"
            "    # staticcheck: ignore[wallclock-duration]\n"
            "    return time.time()\n"
        )
        report = run_project(Project(str(tmp_path)))
        assert rules_of(report.findings).get("wallclock-duration") == 1

    def test_unused_suppression_is_advisory(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            "x = 1  # staticcheck: ignore[broad-except] nothing here\n"
        )
        report = run_project(Project(str(tmp_path)))
        assert rules_of(report.findings) == {"unused-suppression": 1}
        assert not report.failed  # advisory: never gates

    def test_docstring_mention_is_not_a_suppression(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            '"""Docs: # staticcheck: ignore[broad-except] example."""\n'
        )
        report = run_project(Project(str(tmp_path)))
        assert report.findings == []

    def test_inline_suppression_covers_only_its_own_line(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            "import time\n"
            "def f():\n"
            "    a = time.time()\n"
            "    b = time.time()  "
            "# staticcheck: ignore[wallclock-duration] only this line\n"
            "    return a, b\n"
        )
        report = run_project(Project(str(tmp_path)))
        hits = [
            f for f in report.findings if f.rule == "wallclock-duration"
        ]
        # The unannotated call one line ABOVE the comment still gates.
        assert [f.line for f in hits] == [3]
        assert [f.line for f in report.suppressed] == [4]

    def test_only_typo_exits_nonzero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "staticcheck", "--only", "hygeine"],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 2
        assert "unknown pass famil" in proc.stderr

    def test_write_baseline_excludes_advisory_findings(self, tmp_path):
        import json

        (tmp_path / "mod.py").write_text(
            "import time\n"
            "x = 1  # staticcheck: ignore[broad-except] stale\n"
            "def f():\n    return time.time()\n"
        )
        baseline_path = tmp_path / "baseline.json"
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "staticcheck",
                "--root",
                str(tmp_path),
                "--baseline",
                str(baseline_path),
                "--write-baseline",
            ],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        entries = json.loads(baseline_path.read_text())
        rules = {e["rule"] for e in entries}
        # The real finding is grandfathered; the stale suppression is not.
        assert rules == {"wallclock-duration"}

    def test_baseline_grandfathers_findings(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            "import time\n\ndef f():\n    return time.time()\n"
        )
        project = Project(str(tmp_path))
        first = run_project(project)
        assert first.failed
        baseline = {f.fingerprint for f in first.findings}
        second = run_project(Project(str(tmp_path)), baseline=baseline)
        assert not second.failed
        assert len(second.baselined) == len(first.findings)


# ------------------------------------------------------------ the live repo


class TestLiveRepo:
    def test_repo_has_zero_non_baselined_findings(self):
        baseline = load_baseline(
            os.path.join(REPO_ROOT, "staticcheck", "baseline.json")
        )
        report = run_project(Project(REPO_ROOT), baseline=baseline)
        rendered = "\n".join(f.render() for f in report.findings)
        assert not report.failed, f"new staticcheck findings:\n{rendered}"

    def test_check_static_script_passes_and_summarizes(self):
        proc = subprocess.run(
            [sys.executable, os.path.join("scripts", "check_static.py")],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "staticcheck summary" in proc.stdout
