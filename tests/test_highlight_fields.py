"""Highlighting + docvalue_fields/fields fetch subphases.

Reference: search/fetch/subphase/highlight/ (plain highlighter),
FetchDocValuesPhase, FetchFieldsPhase.
"""

import pytest

from elasticsearch_tpu.node import Node

MAPPINGS = {
    "properties": {
        "title": {"type": "text"},
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "price": {"type": "double"},
        "qty": {"type": "long"},
    }
}


@pytest.fixture()
def node():
    node = Node()
    node.create_index("h", {"mappings": MAPPINGS})
    node.index_doc(
        "h",
        {
            "title": "The quick brown fox",
            "body": "A quick brown fox jumps over the lazy dog. "
                    "The dog was not amused by the quick fox at all.",
            "tag": "animal",
            "price": 9.5,
            "qty": 3,
        },
        "1",
    )
    node.index_doc(
        "h",
        {
            "title": "Slow green turtle",
            "body": "Turtles are slow and green and calm.",
            "tag": "animal",
            "price": 5.0,
            "qty": 7,
        },
        "2",
    )
    node.refresh("h")
    return node


def test_basic_highlight(node):
    r = node.search(
        "h",
        {
            "query": {"match": {"body": "quick fox"}},
            "highlight": {"fields": {"body": {}}},
        },
    )
    hit = r["hits"]["hits"][0]
    assert hit["_id"] == "1"
    frags = hit["highlight"]["body"]
    assert frags and all("<em>" in f for f in frags)
    joined = " ".join(frags)
    assert "<em>quick</em>" in joined and "<em>fox</em>" in joined
    # non-matching hit has no highlight key
    for h in r["hits"]["hits"]:
        if h["_id"] == "2":
            assert "highlight" not in h


def test_highlight_custom_tags_and_whole_field(node):
    r = node.search(
        "h",
        {
            "query": {"match": {"title": "fox"}},
            "highlight": {
                "pre_tags": ["<b>"],
                "post_tags": ["</b>"],
                "fields": {"title": {"number_of_fragments": 0}},
            },
        },
    )
    frags = r["hits"]["hits"][0]["highlight"]["title"]
    assert frags == ["The quick brown <b>fox</b>"]


def test_highlight_fragmentation(node):
    long_body = " ".join(
        ["filler word soup"] * 12 + ["needle"] + ["more padding here"] * 12
    )
    node.index_doc("h", {"body": long_body}, "3", refresh=True)
    r = node.search(
        "h",
        {
            "query": {"match": {"body": "needle"}},
            "highlight": {
                "fields": {"body": {"fragment_size": 60,
                                    "number_of_fragments": 2}}
            },
        },
    )
    frags = r["hits"]["hits"][0]["highlight"]["body"]
    assert len(frags) >= 1
    assert all(len(f) < 200 for f in frags)
    assert any("<em>needle</em>" in f for f in frags)
    assert len(long_body) > 300  # fragmentation actually trimmed


def test_highlight_field_match_requirements(node):
    # query matches title; asking to highlight body yields nothing by
    # default, but require_field_match: false highlights cross-field
    r = node.search(
        "h",
        {
            "query": {"match": {"title": "quick"}},
            "highlight": {"fields": {"body": {}}},
        },
    )
    hit = r["hits"]["hits"][0]
    assert "highlight" not in hit or "body" not in hit.get("highlight", {})
    r = node.search(
        "h",
        {
            "query": {"match": {"title": "quick"}},
            "highlight": {
                "fields": {"body": {"require_field_match": False}}
            },
        },
    )
    assert "<em>quick</em>" in " ".join(
        r["hits"]["hits"][0]["highlight"]["body"]
    )


def test_highlight_phrase_and_prefix_queries(node):
    r = node.search(
        "h",
        {
            "query": {"match_phrase": {"body": "lazy dog"}},
            "highlight": {"fields": {"body": {}}},
        },
    )
    joined = " ".join(r["hits"]["hits"][0]["highlight"]["body"])
    assert "<em>lazy</em>" in joined and "<em>dog</em>" in joined
    r = node.search(
        "h",
        {
            "query": {"prefix": {"body": "turt"}},
            "highlight": {"fields": {"body": {}}},
        },
    )
    assert "<em>Turtles</em>" in " ".join(
        r["hits"]["hits"][0]["highlight"]["body"]
    )


def test_docvalue_fields_and_fields(node):
    r = node.search(
        "h",
        {
            "query": {"ids": {"values": ["1"]}},
            "docvalue_fields": ["price", "qty"],
            "fields": ["tag", "title"],
            "_source": False,
        },
    )
    hit = r["hits"]["hits"][0]
    assert "_source" not in hit
    assert hit["fields"]["price"] == [9.5]
    assert hit["fields"]["qty"] == [3]
    assert hit["fields"]["tag"] == ["animal"]
    assert hit["fields"]["title"] == ["The quick brown fox"]


def test_docvalue_fields_keyword_boolean_date():
    n = Node()
    n.create_index(
        "types",
        {
            "mappings": {
                "properties": {
                    "k": {"type": "keyword"},
                    "b": {"type": "boolean"},
                    "d": {"type": "date"},
                }
            }
        },
    )
    n.index_doc(
        "types",
        {"k": "red", "b": True, "d": 1700000000000},
        "1",
        refresh=True,
    )
    r = n.search(
        "types",
        {
            "query": {"match_all": {}},
            "docvalue_fields": ["k", "b", "d"],
        },
    )
    fields = r["hits"]["hits"][0]["fields"]
    assert fields["k"] == ["red"]
    assert fields["b"] == [True]
    assert fields["d"] == ["2023-11-14T22:13:20.000Z"]


def test_highlight_honors_query_analyzer_override():
    n = Node()
    n.create_index(
        "ov",
        {
            "mappings": {
                "properties": {
                    "t": {"type": "text", "analyzer": "standard"}
                }
            }
        },
    )
    n.index_doc("ov", {"t": "quick brown fox"}, "1", refresh=True)
    r = n.search(
        "ov",
        {
            "query": {"match": {"t": {"query": "QUICK",
                                      "analyzer": "standard"}}},
            "highlight": {"fields": {"t": {}}},
        },
    )
    assert "<em>quick</em>" in " ".join(
        r["hits"]["hits"][0]["highlight"]["t"]
    )


def test_highlight_on_sharded_index():
    n = Node()
    n.create_index(
        "sh",
        {
            "settings": {"index": {"number_of_shards": 4}},
            "mappings": MAPPINGS,
        },
    )
    for i in range(20):
        n.index_doc("sh", {"body": f"document {i} mentions zebra today"},
                    f"d{i}")
    n.refresh("sh")
    r = n.search(
        "sh",
        {
            "query": {"match": {"body": "zebra"}},
            "size": 3,
            "highlight": {"fields": {"body": {}}},
            "docvalue_fields": [],
        },
    )
    assert len(r["hits"]["hits"]) == 3
    for h in r["hits"]["hits"]:
        assert "<em>zebra</em>" in " ".join(h["highlight"]["body"])


def test_docvalue_fields_object_form_and_missing(node):
    r = node.search(
        "h",
        {
            "query": {"ids": {"values": ["2"]}},
            "docvalue_fields": [{"field": "price"}, {"field": "nope"}],
        },
    )
    hit = r["hits"]["hits"][0]
    assert hit["fields"] == {"price": [5.0]}
