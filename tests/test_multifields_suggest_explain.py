"""Multi-fields (.keyword), the term suggester, and _explain.

Reference: FieldMapper multiFields + dynamic templates default,
search/suggest/term (DirectSpellChecker), TransportExplainAction.
"""

import json

import pytest

from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.rest.server import RestServer


def test_explicit_multifields():
    node = Node()
    node.create_index(
        "m",
        {
            "mappings": {
                "properties": {
                    "title": {
                        "type": "text",
                        "fields": {"keyword": {"type": "keyword"}},
                    }
                }
            }
        },
    )
    node.index_doc("m", {"title": "Quick Brown Fox"}, "1", refresh=True)
    node.index_doc("m", {"title": "quick brown fox"}, "2", refresh=True)
    # text parent: analyzed match
    r = node.search("m", {"query": {"match": {"title": "quick"}}})
    assert r["hits"]["total"]["value"] == 2
    # .keyword: exact, case-sensitive term
    r = node.search(
        "m", {"query": {"term": {"title.keyword": "Quick Brown Fox"}}}
    )
    assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]
    # terms agg over .keyword
    r = node.search(
        "m",
        {"size": 0, "aggs": {"t": {"terms": {"field": "title.keyword"}}}},
    )
    keys = {b["key"] for b in r["aggregations"]["t"]["buckets"]}
    assert keys == {"Quick Brown Fox", "quick brown fox"}
    # mappings round-trip the sub-fields
    out = node.get_mapping("m")["m"]["mappings"]["properties"]["title"]
    assert out["fields"]["keyword"]["type"] == "keyword"


def test_dynamic_strings_get_keyword_subfield():
    node = Node()
    node.create_index("dyn", {})
    node.index_doc("dyn", {"city": "San Francisco"}, "1", refresh=True)
    node.index_doc("dyn", {"city": "Berlin"}, "2", refresh=True)
    r = node.search(
        "dyn", {"query": {"term": {"city.keyword": "San Francisco"}}}
    )
    assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]
    r = node.search(
        "dyn",
        {"size": 0, "aggs": {"c": {"terms": {"field": "city.keyword"}}}},
    )
    assert {b["key"] for b in r["aggregations"]["c"]["buckets"]} == {
        "San Francisco",
        "Berlin",
    }
    # sort by keyword? keyword sort unsupported (numeric only) — but the
    # sub-field must round-trip persistence via mappings JSON
    props = node.get_mapping("dyn")["dyn"]["mappings"]["properties"]
    assert props["city"]["fields"]["keyword"]["ignore_above"] == 256


def test_ignore_above():
    node = Node()
    node.create_index(
        "ia",
        {
            "mappings": {
                "properties": {
                    "tag": {"type": "keyword", "ignore_above": 5}
                }
            }
        },
    )
    node.index_doc("ia", {"tag": "short"}, "1")
    node.index_doc("ia", {"tag": "waytoolongvalue"}, "2")
    node.refresh("ia")
    r = node.search("ia", {"query": {"term": {"tag": "short"}}})
    assert r["hits"]["total"]["value"] == 1
    r = node.search("ia", {"query": {"term": {"tag": "waytoolongvalue"}}})
    assert r["hits"]["total"]["value"] == 0  # not indexed
    # still stored in _source
    assert node.get_doc("ia", "2")["_source"]["tag"] == "waytoolongvalue"


def test_term_suggester():
    node = Node()
    node.create_index("s", {"mappings": {"properties": {"t": {"type": "text"}}}})
    for i, words in enumerate(
        ["amsterdam rotterdam", "amsterdam utrecht", "rotterdam harbor"]
    ):
        node.index_doc("s", {"t": words}, f"d{i}")
    node.refresh("s")
    r = node.search(
        "s",
        {
            "size": 0,
            "suggest": {
                "fix": {"text": "amsterdom", "term": {"field": "t"}}
            },
        },
    )
    entry = r["suggest"]["fix"][0]
    assert entry["text"] == "amsterdom"
    assert entry["offset"] == 0 and entry["length"] == 9
    assert entry["options"][0]["text"] == "amsterdam"
    assert entry["options"][0]["freq"] == 2
    # an existing term suggests nothing under suggest_mode=missing
    r = node.search(
        "s",
        {
            "size": 0,
            "suggest": {
                "fix": {"text": "utrecht", "term": {"field": "t"}}
            },
        },
    )
    assert r["suggest"]["fix"][0]["options"] == []
    # multi-token text yields one entry per token
    r = node.search(
        "s",
        {
            "size": 0,
            "suggest": {
                "fix": {
                    "text": "amsterdem harbar",
                    "term": {"field": "t", "suggest_mode": "always"},
                }
            },
        },
    )
    entries = r["suggest"]["fix"]
    assert len(entries) == 2
    assert entries[0]["options"][0]["text"] == "amsterdam"
    assert entries[1]["options"][0]["text"] == "harbor"


def test_put_mapping_merges_subfields():
    node = Node()
    node.create_index("pm", {})
    node.index_doc("pm", {"title": "San Francisco"}, "1", refresh=True)
    # update the root field: the dynamic .keyword sub-field must survive
    node.put_mapping(
        "pm", {"properties": {"title": {"type": "text"}}}
    )
    r = node.search(
        "pm", {"query": {"term": {"title.keyword": "San Francisco"}}}
    )
    assert r["hits"]["total"]["value"] == 1
    with pytest.raises(ApiError):  # sub-field type change rejected
        node.put_mapping(
            "pm",
            {
                "properties": {
                    "title": {
                        "type": "text",
                        "fields": {"keyword": {"type": "long"}},
                    }
                }
            },
        )


def test_dotted_source_key_does_not_shadow_subfield():
    node = Node()
    node.create_index("dot", {})
    node.index_doc("dot", {"title": "Foo Bar"}, "1", refresh=True)
    # a literal dotted key reuses the existing sub-field mapping (keyword)
    node.index_doc("dot", {"title.keyword": "Baz"}, "2", refresh=True)
    r = node.search("dot", {"query": {"term": {"title.keyword": "Foo Bar"}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]
    r = node.search("dot", {"query": {"term": {"title.keyword": "Baz"}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["2"]


def test_explain_does_not_refresh():
    node = Node()
    node.create_index("nr", {"mappings": {"properties": {"t": {"type": "text"}}}})
    node.index_doc("nr", {"t": "visible"}, "1", refresh=True)
    node.index_doc("nr", {"t": "buffered"}, "2")  # no refresh
    with pytest.raises(ApiError):  # unrefreshed doc is not searchable
        node.explain("nr", "2", {"query": {"match_all": {}}})
    # ...and the explain must NOT have published it
    r = node.search("nr", {"query": {"match_all": {}}, "size": 0})
    assert r["hits"]["total"]["value"] == 1


def test_explain_rest():
    rest = RestServer()
    node = rest.node
    node.create_index("e", {"mappings": {"properties": {"t": {"type": "text"}}}})
    node.index_doc("e", {"t": "alpha beta"}, "1", refresh=True)
    node.index_doc("e", {"t": "gamma delta"}, "2", refresh=True)
    status, r = rest.dispatch(
        "POST", "/e/_explain/1", {},
        json.dumps({"query": {"match": {"t": "alpha"}}}),
    )
    assert status == 200 and r["matched"] is True
    assert r["explanation"]["value"] > 0
    # matches the _search score for the same doc
    sr = node.search("e", {"query": {"match": {"t": "alpha"}}})
    assert r["explanation"]["value"] == sr["hits"]["hits"][0]["_score"]
    status, r = rest.dispatch(
        "POST", "/e/_explain/2", {},
        json.dumps({"query": {"match": {"t": "alpha"}}}),
    )
    assert status == 200 and r["matched"] is False
    status, r = rest.dispatch(
        "POST", "/e/_explain/nope", {},
        json.dumps({"query": {"match_all": {}}}),
    )
    assert status == 404
