"""Parity tests for the candidate-centric (sparse) kernel and block-max.

The sparse path must be bit-exact with the oracle: stable sort + left-fold
run sums reproduce the oracle's per-term fp32 accumulation order, and
top-k tie-breaks (equal score -> lower doc id) must match.
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.ops.bm25 import search_field
from elasticsearch_tpu.query.compile import Compiler
from elasticsearch_tpu.query.dsl import MatchQuery
from elasticsearch_tpu.utils.corpus import build_zipf_segment, pick_query_terms


@pytest.fixture(scope="module")
def corpus():
    mappings, seg = build_zipf_segment(4000, vocab_size=900, seed=5)
    dev = pack_segment(seg)
    compiler = Compiler(dev.fields, dev.doc_values, mappings)
    seg_tree = bm25_device.segment_tree(dev)
    return mappings, seg, dev, compiler, seg_tree


def _oracle(seg, terms, k):
    fld = seg.fields["body"]
    return search_field(fld, terms, seg.num_docs, k)


class TestSparseParity:
    def test_spec_is_sparse_capable(self, corpus):
        _, _, _, compiler, _ = corpus
        c = compiler.compile(MatchQuery("body", "t1 t2 t3"))
        assert bm25_device.supports_sparse(c.spec)
        assert len(c.spec) == 4  # (kind, field, NT, T_pad)

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_randomized_exact_parity(self, corpus, seed):
        mappings, seg, dev, compiler, seg_tree = corpus
        rng = np.random.default_rng(seed)
        for terms in pick_query_terms(seg, rng, 8, terms_per_query=4):
            c = compiler.compile(MatchQuery("body", " ".join(terms)))
            assert bm25_device.supports_sparse(c.spec)
            d_s, d_i, d_tot = map(
                np.asarray,
                bm25_device.execute_sparse(seg_tree, c.spec, c.arrays, 10),
            )
            o_s, o_i = _oracle(seg, terms, 10)
            n = len(o_i)
            assert int(d_tot) == int(
                np.count_nonzero(
                    _matched_mask(seg, terms)
                )
            )
            assert list(d_i[:n]) == list(o_i)
            # Bit-exact scores (same fp32 accumulation order as the oracle)
            assert np.array_equal(d_s[:n], o_s), (d_s[:n], o_s)

    def test_duplicate_terms_run_fold(self, corpus):
        # Duplicate query terms double a doc's contributions -> exercises
        # run lengths up to the full term-occurrence count.
        mappings, seg, dev, compiler, seg_tree = corpus
        terms = ["t1", "t1", "t2", "t1"]
        c = compiler.compile(MatchQuery("body", " ".join(terms)))
        d_s, d_i, d_tot = map(
            np.asarray, bm25_device.execute_sparse(seg_tree, c.spec, c.arrays, 10)
        )
        o_s, o_i = _oracle(seg, terms, 10)
        n = len(o_i)
        assert list(d_i[:n]) == list(o_i)
        assert np.array_equal(d_s[:n], o_s)

    def test_matches_dense_path(self, corpus):
        mappings, seg, dev, compiler, seg_tree = corpus
        c = compiler.compile(MatchQuery("body", "t0 t5 t11"))
        s1, i1, t1 = map(
            np.asarray, bm25_device.execute_sparse(seg_tree, c.spec, c.arrays, 17)
        )
        s2, i2, t2 = map(
            np.asarray, bm25_device.execute(seg_tree, c.spec, c.arrays, 17)
        )
        assert int(t1) == int(t2)
        n = min(17, int(t1))
        assert list(i1[:n]) == list(i2[:n])
        assert np.array_equal(s1[:n], s2[:n])

    def test_deleted_docs_excluded(self, corpus):
        import jax

        mappings, seg, dev, compiler, seg_tree = corpus
        c = compiler.compile(MatchQuery("body", "t1 t2"))
        s0, i0, _ = map(
            np.asarray, bm25_device.execute_sparse(seg_tree, c.spec, c.arrays, 5)
        )
        victim = int(i0[0])
        live = np.ones(seg.num_docs, dtype=bool)
        live[victim] = False
        seg_tree2 = dict(seg_tree)
        seg_tree2["live"] = jax.device_put(live)
        s1, i1, _ = map(
            np.asarray,
            bm25_device.execute_sparse(seg_tree2, c.spec, c.arrays, 5),
        )
        assert victim not in list(i1)

    def test_k_larger_than_candidates(self, corpus):
        mappings, seg, dev, compiler, seg_tree = corpus
        # Rare term: few candidates; ask for far more.
        rare = min(
            seg.fields["body"].terms,
            key=lambda t: seg.fields["body"].df[seg.fields["body"].terms[t]],
        )
        c = compiler.compile(MatchQuery("body", rare))
        d_s, d_i, d_tot = map(
            np.asarray,
            bm25_device.execute_sparse(seg_tree, c.spec, c.arrays, 3000),
        )
        o_s, o_i = _oracle(seg, [rare], 3000)
        n = len(o_i)
        assert list(d_i[:n]) == list(o_i)


def _matched_mask(seg, terms):
    fld = seg.fields["body"]
    m = np.zeros(seg.num_docs, dtype=bool)
    for t in terms:
        docs, _ = fld.postings(t)
        m[docs] = True
    return m


class TestBlockmax:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_blockmax_exact_topk(self, corpus, seed):
        mappings, seg, dev, compiler, seg_tree = corpus
        rng = np.random.default_rng(seed)
        queries = pick_query_terms(seg, rng, 8, terms_per_query=4)
        compiled = [
            compiler.compile(MatchQuery("body", " ".join(t))) for t in queries
        ]
        # Group by spec (blockmax needs one spec per batch)
        from collections import defaultdict

        groups = defaultdict(list)
        for q, c in zip(queries, compiled):
            groups[c.spec].append((q, c))
        for spec, items in groups.items():
            arrays_list = [c.arrays for _, c in items]
            s, i, t, rel = bm25_device.execute_batch_blockmax(
                seg_tree, spec, arrays_list, 10
            )
            assert rel in ("eq", "gte")
            for row, (terms, _c) in enumerate(items):
                o_s, o_i = _oracle(seg, terms, 10)
                true_total = int(np.count_nonzero(_matched_mask(seg, terms)))
                n = len(o_i)
                assert list(i[row][:n]) == list(o_i)
                assert np.array_equal(s[row][:n], o_s)
                # totals: exact when eq, lower bound (>= k) when gte
                assert int(t[row]) <= true_total
                if rel == "eq":
                    assert int(t[row]) == true_total
                else:
                    assert int(t[row]) >= min(10, true_total)

    def test_blockmax_prunes_on_skewed_corpus(self, corpus):
        """On a Zipf corpus with one dominant term the tail tiles of the
        head term should actually get pruned (the mechanism is live)."""
        mappings, seg, dev, compiler, seg_tree = corpus
        fld = seg.fields["body"]
        by_df = sorted(fld.terms, key=lambda t: -fld.df[fld.terms[t]])
        terms = [by_df[0], by_df[len(by_df) // 2], by_df[len(by_df) // 2 + 1]]
        c = compiler.compile(MatchQuery("body", " ".join(terms)))
        if c.spec[2] < 32:
            pytest.skip("worklist too small to exercise pruning")
        s, i, t, rel = bm25_device.execute_batch_blockmax(
            seg_tree, c.spec, [c.arrays], 10
        )
        o_s, o_i = _oracle(seg, terms, 10)
        assert list(i[0][: len(o_i)]) == list(o_i)
        assert np.array_equal(s[0][: len(o_i)], o_s)


class TestSequentialKernel:
    """execute_sequential_sparse must be bit-identical to the per-query
    kernel — the latency bench's parity contract (bench.py)."""

    def test_sequential_matches_per_query(self, corpus):
        mappings, seg, dev, compiler, seg_tree = corpus
        import jax

        rng = np.random.default_rng(7)
        queries = [
            compiler.compile(MatchQuery("body", " ".join(t)))
            for t in pick_query_terms(seg, rng, 6, terms_per_query=3)
        ]
        spec = queries[0].spec
        same_spec = [c for c in queries if c.spec == spec]
        assert len(same_spec) >= 2
        stacked = jax.tree.map(
            lambda *xs: np.stack(xs), *[c.arrays for c in same_spec]
        )
        s_b, i_b, t_b = map(
            np.asarray,
            bm25_device.execute_sequential_sparse(seg_tree, spec, stacked, 10),
        )
        for row, c in enumerate(same_spec):
            s1, i1, t1 = map(
                np.asarray,
                bm25_device.execute_sparse(seg_tree, c.spec, c.arrays, 10),
            )
            assert np.array_equal(s_b[row], s1)
            assert np.array_equal(i_b[row], i1)
            assert int(t_b[row]) == int(t1)
