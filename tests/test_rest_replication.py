"""The cluster replication layer AS the REST serving path (ISSUE 1).

Acceptance shape: REST `_doc`/`_bulk`/`_search` requests route through
`ClusterNode` primaries via the `ReplicationGateway` — an acknowledged
write is seqno-replicated to every in-sync copy before the 200 returns —
and the REST router keeps serving 2xx across a primary kill: writes retry
against the promoted primary, reads fail over to in-sync replicas, and a
shard with no reachable copy degrades to honest `_shards.failed` partial
results. A full-cluster restart recovers membership/in-sync sets/primary
terms from persisted state and refuses to promote a stale copy.
"""

import json
import threading
import time

import pytest

from elasticsearch_tpu.cluster import LocalCluster, NoShardAvailableError
from elasticsearch_tpu.rest.server import RestServer

MAPPINGS = {"properties": {"body": {"type": "text"}}}

INDEX_BODY = json.dumps(
    {
        "settings": {
            "index": {"number_of_shards": 1, "number_of_replicas": 2}
        },
        "mappings": MAPPINGS,
    }
)


@pytest.fixture
def rest():
    rest = RestServer(replication_nodes=3)
    yield rest
    rest.close()


def put_doc(rest, index, doc_id, body):
    return rest.dispatch(
        "PUT", f"/{index}/_doc/{doc_id}", {}, json.dumps(body)
    )


class TestReplicatedWrites:
    def test_ack_means_every_in_sync_copy_applied(self, rest):
        status, _ = rest.dispatch("PUT", "/rep", {}, INDEX_BODY)
        assert status == 200
        for i in range(10):
            status, resp = put_doc(rest, "rep", f"d{i}", {"body": f"x {i}"})
            assert status == 200, resp
            # 1 primary + 2 replicas, all in sync before the ack.
            assert resp["_shards"] == {
                "total": 3,
                "successful": 3,
                "failed": 0,
            }
        # Every copy holds every acked doc (the invariant the _shards
        # numbers claim).
        routing = rest.cluster.any_node().state.indices["rep"].shards[0]
        assert len(routing.in_sync) == 3
        for node_id in routing.assigned():
            engine = rest.cluster.nodes[node_id].engines[("rep", 0)]
            for i in range(10):
                assert engine.get(f"d{i}") is not None, (node_id, i)

    def test_search_and_get_route_through_cluster(self, rest):
        rest.dispatch("PUT", "/sr", {}, INDEX_BODY)
        for i in range(12):
            put_doc(rest, "sr", f"s{i}", {"body": "needle haystack"})
        status, resp = rest.dispatch(
            "POST",
            "/sr/_search",
            {},
            json.dumps({"query": {"match": {"body": "needle"}}, "size": 20}),
        )
        assert status == 200
        assert resp["hits"]["total"]["value"] == 12
        assert resp["_shards"]["failed"] == 0
        status, resp = rest.dispatch("GET", "/sr/_doc/s3", {}, "")
        assert status == 200 and resp["found"]
        # Local engines hold nothing: the data plane IS the cluster.
        assert rest.node.indices["sr"].num_docs == 0

    def test_bulk_and_by_query_replicate(self, rest):
        rest.dispatch("PUT", "/bk", {}, INDEX_BODY)
        lines = []
        for i in range(8):
            lines.append(json.dumps({"index": {"_id": f"b{i}"}}))
            lines.append(json.dumps({"body": "bulk payload"}))
        status, resp = rest.dispatch(
            "POST", "/bk/_bulk", {"refresh": "true"}, "\n".join(lines)
        )
        assert status == 200 and not resp["errors"]
        routing = rest.cluster.any_node().state.indices["bk"].shards[0]
        for node_id in routing.assigned():
            engine = rest.cluster.nodes[node_id].engines[("bk", 0)]
            assert engine.get("b4") is not None
        status, resp = rest.dispatch(
            "POST",
            "/bk/_delete_by_query",
            {},
            json.dumps({"query": {"match": {"body": "bulk"}}}),
        )
        assert status == 200 and resp["deleted"] == 8
        for node_id in routing.assigned():
            engine = rest.cluster.nodes[node_id].engines[("bk", 0)]
            assert engine.get("b4") is None

    def test_put_mapping_reaches_serving_engines(self, rest):
        """An explicit mapping added AFTER index creation must govern how
        the replicated engines index later documents — not just the REST
        node's local view."""
        rest.dispatch("PUT", "/pm", {}, INDEX_BODY)
        status, _ = rest.dispatch(
            "PUT",
            "/pm/_mapping",
            {},
            json.dumps({"properties": {"tag": {"type": "keyword"}}}),
        )
        assert status == 200
        put_doc(rest, "pm", "1", {"body": "x", "tag": "Hello World"})
        # keyword => exact, unanalyzed match on the full value.
        status, resp = rest.dispatch(
            "POST",
            "/pm/_search",
            {},
            json.dumps({"query": {"term": {"tag": "Hello World"}}}),
        )
        assert status == 200
        assert resp["hits"]["total"]["value"] == 1, resp

    def test_large_delete_by_query_drains_past_one_page(self, rest):
        rest.dispatch(
            "PUT",
            "/big",
            {},
            json.dumps(
                {
                    "settings": {
                        "index": {
                            "number_of_shards": 1,
                            "number_of_replicas": 1,
                            "max_result_window": 10,  # tiny page for the test
                        }
                    },
                    "mappings": MAPPINGS,
                }
            ),
        )
        for i in range(35):
            put_doc(rest, "big", f"g{i}", {"body": "purge me"})
        status, resp = rest.dispatch(
            "POST",
            "/big/_delete_by_query",
            {},
            json.dumps({"query": {"match": {"body": "purge"}}}),
        )
        assert status == 200
        assert resp["deleted"] == 35  # several pages, nothing truncated
        # update_by_query refuses a >1-page match set instead of silently
        # processing a prefix.
        for i in range(15):
            put_doc(rest, "big", f"u{i}", {"body": "update me"})
        status, resp = rest.dispatch(
            "POST",
            "/big/_update_by_query",
            {},
            json.dumps({"query": {"match": {"body": "update"}}}),
        )
        assert status == 400, resp

    def test_concurrent_updates_do_not_lose_writes(self, rest):
        """Two racing _update requests: the built-in CAS turns the loser
        into a 409 instead of silently dropping the winner's merge."""
        rest.dispatch("PUT", "/upd", {}, INDEX_BODY)
        put_doc(rest, "upd", "1", {"body": "base"})
        results = []
        lock = threading.Lock()

        def updater(field):
            status, resp = rest.dispatch(
                "POST",
                "/upd/_update/1",
                {},
                json.dumps({"doc": {field: "set"}}),
            )
            with lock:
                results.append((field, status))

        threads = [
            threading.Thread(target=updater, args=(f,))
            for f in ("alpha", "beta")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _, resp = rest.dispatch("GET", "/upd/_doc/1", {}, "")
        doc = resp["_source"]
        applied = [f for f, s in results if s == 200]
        # Every 200'd update's field is present in the final doc.
        for field in applied:
            assert doc.get(field) == "set", (results, doc)

    def test_version_conflict_maps_to_409(self, rest):
        rest.dispatch("PUT", "/vc", {}, INDEX_BODY)
        put_doc(rest, "vc", "a", {"body": "one"})
        status, resp = rest.dispatch(
            "PUT", "/vc/_create/a", {}, json.dumps({"body": "two"})
        )
        assert status == 409, resp


class TestKillPrimaryUnderRestTraffic:
    def test_writes_and_reads_keep_succeeding_after_promotion(self, rest):
        status, _ = rest.dispatch("PUT", "/kp", {}, INDEX_BODY)
        assert status == 200
        acked = []
        for i in range(30):
            status, resp = put_doc(rest, "kp", f"k{i}", {"body": f"kp {i}"})
            assert status == 200
            acked.append(f"k{i}")
        routing = rest.cluster.any_node().state.indices["kp"].shards[0]
        old_primary, old_term = routing.primary, routing.primary_term
        rest.cluster.kill(old_primary)
        # NO manual control round here: the REST router + gateway retries
        # must absorb the failure window themselves.
        for i in range(30, 50):
            status, resp = put_doc(rest, "kp", f"k{i}", {"body": f"kp {i}"})
            assert status == 200, resp
            acked.append(f"k{i}")
        view = rest.cluster.any_node().state.indices["kp"].shards[0]
        assert view.primary is not None and view.primary != old_primary
        assert view.primary_term == old_term + 1
        # Zero acknowledged-write loss, via the public REST API.
        for doc_id in acked:
            status, resp = rest.dispatch("GET", f"/kp/_doc/{doc_id}", {}, "")
            assert status == 200 and resp["found"], doc_id
        status, resp = rest.dispatch(
            "POST",
            "/kp/_search",
            {},
            json.dumps({"query": {"match_all": {}}, "size": 100}),
        )
        assert status == 200
        assert resp["hits"]["total"]["value"] == len(acked)

    def test_concurrent_rest_writers_race_primary_kill(self, rest):
        status, _ = rest.dispatch("PUT", "/chaos", {}, INDEX_BODY)
        assert status == 200
        acked: list[str] = []
        lock = threading.Lock()

        def writer(tid: int):
            for i in range(150):
                doc_id = f"w{tid}-{i}"
                try:
                    status, _ = put_doc(
                        rest, "chaos", doc_id, {"body": f"c {doc_id}"}
                    )
                except Exception:
                    continue  # failed request: never acked, may be lost
                if status == 200:
                    with lock:
                        acked.append(doc_id)

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(0.02)
        victim = (
            rest.cluster.any_node().state.indices["chaos"].shards[0].primary
        )
        rest.cluster.kill(victim)
        for t in threads:
            t.join(timeout=60)
        # Wait for the control plane to finish promotion (the stepper runs
        # in the background; requests above already retried through it).
        deadline = time.monotonic() + 10
        view = None
        while time.monotonic() < deadline:
            view = rest.cluster.any_node().state.indices["chaos"].shards[0]
            if view.primary not in (None, victim):
                break
            rest.cluster.step()
        assert view.primary is not None and view.primary != victim
        missing = []
        for doc_id in acked:
            status, resp = rest.dispatch(
                "GET", f"/chaos/_doc/{doc_id}", {}, ""
            )
            if status != 200 or not resp.get("found"):
                missing.append(doc_id)
        assert not missing, f"{len(missing)} acked docs lost: {missing[:5]}"
        assert len(acked) > 50  # the run actually exercised writes

    def test_reads_fail_over_to_replica_when_primary_unassigned(self, rest):
        # No replicas to promote: the shard goes red for writes, but the
        # doc API answers 503 (retryable) rather than hanging or 500.
        status, _ = rest.dispatch(
            "PUT",
            "/red",
            {},
            json.dumps(
                {
                    "settings": {
                        "index": {
                            "number_of_shards": 1,
                            "number_of_replicas": 0,
                        }
                    },
                    "mappings": MAPPINGS,
                }
            ),
        )
        assert status == 200
        put_doc(rest, "red", "r1", {"body": "only copy"})
        holder = rest.cluster.any_node().state.indices["red"].shards[0].primary
        rest.cluster.kill(holder)
        status, resp = put_doc(rest, "red", "r2", {"body": "no home"})
        assert status == 503, resp
        assert resp["error"]["type"] in (
            "unavailable_shards_exception",
            "search_phase_execution_exception",
        )


class TestPartialSearchResults:
    def test_shards_failed_reported_honestly(self, rest):
        status, _ = rest.dispatch(
            "PUT",
            "/part",
            {},
            json.dumps(
                {
                    "settings": {
                        "index": {
                            "number_of_shards": 2,
                            "number_of_replicas": 0,
                        }
                    },
                    "mappings": MAPPINGS,
                }
            ),
        )
        assert status == 200
        for i in range(40):
            status, _ = put_doc(rest, "part", f"p{i}", {"body": "findme"})
            assert status == 200
        meta = rest.cluster.any_node().state.indices["part"]
        # Kill the node holding shard 0's (replica-less) primary.
        victim = meta.shards[0].primary
        survivor_primary = meta.shards[1].primary
        assert victim != survivor_primary  # round-robin allocation
        rest.cluster.kill(victim)
        status, resp = rest.dispatch(
            "POST",
            "/part/_search",
            {},
            json.dumps({"query": {"match": {"body": "findme"}}, "size": 50}),
        )
        assert status == 200
        assert resp["_shards"]["total"] == 2
        assert resp["_shards"]["failed"] == 1
        assert resp["_shards"]["successful"] == 1
        # Partial: only the surviving shard's docs, but SOME result.
        assert 0 < resp["hits"]["total"]["value"] < 40


class TestClusterHealthAndStats:
    def test_health_reflects_cluster_state(self, rest):
        rest.dispatch("PUT", "/h", {}, INDEX_BODY)
        status, resp = rest.dispatch("GET", "/_cluster/health", {}, "")
        assert status == 200
        assert resp["status"] == "green"
        assert resp["number_of_nodes"] == 3
        victim = rest.cluster.any_node().state.indices["h"].shards[0].primary
        rest.cluster.kill(victim)
        rest.cluster.step()
        status, resp = rest.dispatch("GET", "/_cluster/health", {}, "")
        assert status == 200
        assert resp["number_of_nodes"] == 2
        # 2 live nodes can hold primary + 1 replica; the configured 2nd
        # replica is unallocatable -> yellow (never silently green).
        assert resp["status"] in ("yellow", "green")

    def test_nodes_stats_exposes_replication_counters(self, rest):
        rest.dispatch("PUT", "/ns", {}, INDEX_BODY)
        put_doc(rest, "ns", "a", {"body": "x"})
        status, resp = rest.dispatch("GET", "/_nodes/stats", {}, "")
        assert status == 200
        node_stats = resp["nodes"]["node-0"]
        assert node_stats["replication"]["writes"] >= 1
        assert node_stats["replication"]["master"] is not None
        assert "mesh_serving" in node_stats


class TestFullClusterRestartRecovery:
    def test_metadata_recovered_and_stale_copy_not_promoted(self, tmp_path):
        data = str(tmp_path / "cluster-state")
        cluster = LocalCluster(3, data_path=data)
        try:
            cluster.create_index(
                "dur", n_shards=1, n_replicas=1, mappings=MAPPINGS
            )
            for i in range(10):
                cluster.any_node().execute_write(
                    "dur", f"d{i}", {"body": f"x {i}"}
                )
            before = cluster.any_node().state.indices["dur"].shards[0]
            old_term = before.primary_term
            assert before.primary is not None
        finally:
            cluster.close()

        # Full-cluster restart: every in-memory copy is gone; only the
        # persisted ClusterState survives.
        revived = LocalCluster(3, data_path=data)
        try:
            node = revived.any_node()
            # Metadata recovered: the index, its mappings, its term.
            assert "dur" in node.state.indices
            meta = node.state.indices["dur"]
            assert meta.mappings == MAPPINGS
            routing = meta.shards[0]
            # The old in-sync membership belongs to dead incarnations —
            # promoting any restarted (empty) copy would fabricate an
            # empty index that claims to be authoritative. Red is the
            # only safe answer.
            assert routing.primary is None
            assert routing.primary_term >= old_term  # never reset
            with pytest.raises(NoShardAvailableError):
                node.execute_write("dur", "late", {"body": "nope"})
        finally:
            revived.close()

    def test_partial_restart_keeps_acked_writes(self, tmp_path):
        """One node restarting (not the whole cluster) must not disturb
        the live majority: state recovery + session stripping keep the
        survivors authoritative and the acked docs durable."""
        data = str(tmp_path / "partial-state")
        cluster = LocalCluster(3, data_path=data)
        try:
            cluster.create_index(
                "pr", n_shards=1, n_replicas=2, mappings=MAPPINGS
            )
            acked = []
            for i in range(15):
                cluster.any_node().execute_write(
                    "pr", f"p{i}", {"body": f"x {i}"}
                )
                acked.append(f"p{i}")
            routing = cluster.any_node().state.indices["pr"].shards[0]
            victim = routing.replicas[0]
            cluster.kill(victim)
            cluster.restart(victim)
            cluster.step()  # detect + strip stale membership
            cluster.step()  # re-recover the copy
            view = cluster.any_node().state.indices["pr"].shards[0]
            assert view.primary is not None
            for doc_id in acked:
                assert (
                    cluster.any_node().get_doc("pr", doc_id) is not None
                ), doc_id
        finally:
            cluster.close()


class TestReplicatedRestartViaRest:
    def test_rest_cluster_restart_refuses_stale_promotion(self, tmp_path):
        data = str(tmp_path / "rest-cluster-state")
        rest = RestServer(replication_nodes=3, cluster_data_path=data)
        try:
            rest.dispatch("PUT", "/rr", {}, INDEX_BODY)
            for i in range(5):
                status, _ = put_doc(rest, "rr", f"r{i}", {"body": "x"})
                assert status == 200
        finally:
            rest.close()
        revived = LocalCluster(3, data_path=data)
        try:
            routing = revived.any_node().state.indices["rr"].shards[0]
            assert routing.primary is None  # refuses stale promotion
            assert routing.primary_term >= 1
        finally:
            revived.close()
