"""Aggregations: device execution vs numpy oracle.

Coverage model mirrors the reference's aggregation test strategy
(server/src/test/.../search/aggregations/metrics + bucket): randomized
corpora, every agg type checked against an independently computed expected
result, including under deletes, multiple segments, and query filtering.
"""

import math

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.search.service import SearchRequest, SearchService

MAPPINGS = {
    "properties": {
        "title": {"type": "text"},
        "tag": {"type": "keyword"},
        "price": {"type": "double"},
        "qty": {"type": "long"},
        "ts": {"type": "date"},
    }
}

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]
TAGS = ["red", "green", "blue", "yellow"]


def build_engine(rng, n=240, segments=3, with_deletes=True):
    engine = Engine(Mappings.from_json(MAPPINGS))
    docs = []
    per_seg = n // segments
    for i in range(n):
        doc = {
            "title": " ".join(rng.choice(WORDS, size=rng.integers(1, 6))),
            "tag": str(rng.choice(TAGS)),
            "price": round(float(rng.uniform(0, 100)), 2),
            "qty": int(rng.integers(0, 50)),
            "ts": int(rng.integers(1_600_000_000_000, 1_700_000_000_000)),
        }
        # some docs miss some fields
        if rng.random() < 0.15:
            del doc["price"]
        if rng.random() < 0.1:
            del doc["tag"]
        docs.append(doc)
        engine.index(doc, f"d{i}")
        if (i + 1) % per_seg == 0:
            engine.refresh()
    engine.refresh()
    deleted = set()
    if with_deletes:
        for i in rng.choice(n, size=n // 10, replace=False):
            engine.delete(f"d{int(i)}")
            deleted.add(int(i))
        engine.refresh()
    live_docs = [d for i, d in enumerate(docs) if i not in deleted]
    return engine, live_docs


def run_aggs(engine, body):
    svc = SearchService(engine)
    resp = svc.search(SearchRequest.from_json(body))
    return resp


def matches(doc, word):
    return word in doc.get("title", "").split()


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(7)
    return build_engine(rng)


def test_metric_aggs_match_all(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "p_min": {"min": {"field": "price"}},
                "p_max": {"max": {"field": "price"}},
                "p_sum": {"sum": {"field": "price"}},
                "p_avg": {"avg": {"field": "price"}},
                "p_cnt": {"value_count": {"field": "price"}},
                "p_stats": {"stats": {"field": "price"}},
            },
        },
    )
    prices = [d["price"] for d in docs if "price" in d]
    a = resp.aggregations
    assert resp.total == len(docs)
    assert a["p_cnt"]["value"] == len(prices)
    assert a["p_min"]["value"] == pytest.approx(min(prices), rel=1e-6)
    assert a["p_max"]["value"] == pytest.approx(max(prices), rel=1e-6)
    assert a["p_sum"]["value"] == pytest.approx(sum(prices), rel=1e-4)
    assert a["p_avg"]["value"] == pytest.approx(
        sum(prices) / len(prices), rel=1e-4
    )
    st = a["p_stats"]
    assert st["count"] == len(prices)
    assert st["avg"] == pytest.approx(sum(prices) / len(prices), rel=1e-4)


def test_metric_aggs_filtered_by_query(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "query": {"match": {"title": "alpha"}},
            "aggs": {"q_sum": {"sum": {"field": "qty"}}},
        },
    )
    expected_docs = [d for d in docs if matches(d, "alpha")]
    assert resp.total == len(expected_docs)
    assert resp.aggregations["q_sum"]["value"] == pytest.approx(
        sum(d["qty"] for d in expected_docs), rel=1e-6
    )


def test_terms_keyword(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {"size": 0, "aggs": {"tags": {"terms": {"field": "tag"}}}},
    )
    expected = {}
    for d in docs:
        if "tag" in d:
            expected[d["tag"]] = expected.get(d["tag"], 0) + 1
    buckets = resp.aggregations["tags"]["buckets"]
    got = {b["key"]: b["doc_count"] for b in buckets}
    assert got == expected
    # count-desc, key-asc tiebreak ordering
    counts = [b["doc_count"] for b in buckets]
    assert counts == sorted(counts, reverse=True)
    assert resp.aggregations["tags"]["sum_other_doc_count"] == 0


def test_terms_keyword_with_sub_metrics(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "tags": {
                    "terms": {"field": "tag"},
                    "aggs": {
                        "avg_p": {"avg": {"field": "price"}},
                        "max_q": {"max": {"field": "qty"}},
                    },
                }
            },
        },
    )
    for b in resp.aggregations["tags"]["buckets"]:
        sel = [d for d in docs if d.get("tag") == b["key"]]
        prices = [d["price"] for d in sel if "price" in d]
        assert b["doc_count"] == len(sel)
        if prices:
            assert b["avg_p"]["value"] == pytest.approx(
                sum(prices) / len(prices), rel=1e-4
            )
        assert b["max_q"]["value"] == pytest.approx(
            max(d["qty"] for d in sel), rel=1e-6
        )


def test_terms_size_and_other_count(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {"size": 0, "aggs": {"tags": {"terms": {"field": "tag", "size": 2}}}},
    )
    expected = {}
    for d in docs:
        if "tag" in d:
            expected[d["tag"]] = expected.get(d["tag"], 0) + 1
    ranked = sorted(expected.items(), key=lambda kv: (-kv[1], kv[0]))
    buckets = resp.aggregations["tags"]["buckets"]
    assert [(b["key"], b["doc_count"]) for b in buckets] == ranked[:2]
    assert resp.aggregations["tags"]["sum_other_doc_count"] == sum(
        c for _, c in ranked[2:]
    )


def test_terms_numeric_host_fallback(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {"size": 0, "aggs": {"qtys": {"terms": {"field": "qty", "size": 100}}}},
    )
    expected = {}
    for d in docs:
        expected[d["qty"]] = expected.get(d["qty"], 0) + 1
    got = {b["key"]: b["doc_count"] for b in resp.aggregations["qtys"]["buckets"]}
    assert got == expected
    assert all(isinstance(b["key"], int) for b in resp.aggregations["qtys"]["buckets"])


def test_cardinality_keyword_and_numeric(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "t_card": {"cardinality": {"field": "tag"}},
                "q_card": {"cardinality": {"field": "qty"}},
            },
        },
    )
    assert resp.aggregations["t_card"]["value"] == len(
        {d["tag"] for d in docs if "tag" in d}
    )
    assert resp.aggregations["q_card"]["value"] == len(
        {d["qty"] for d in docs}
    )


def test_histogram(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "h": {
                    "histogram": {"field": "price", "interval": 10},
                    "aggs": {"s": {"sum": {"field": "qty"}}},
                }
            },
        },
    )
    expected = {}
    for d in docs:
        if "price" in d:
            key = math.floor(d["price"] / 10) * 10
            cur = expected.setdefault(key, [0, 0])
            cur[0] += 1
            cur[1] += d["qty"]
    buckets = resp.aggregations["h"]["buckets"]
    got = {b["key"]: (b["doc_count"], b["s"]["value"]) for b in buckets}
    for key, (cnt, qsum) in expected.items():
        assert got[key][0] == cnt
        assert got[key][1] == pytest.approx(qsum, rel=1e-5)
    # interior empty buckets kept (min_doc_count default 0)
    keys = sorted(got)
    assert keys == [keys[0] + 10 * i for i in range(len(keys))]


def test_date_histogram_fixed_interval(corpus):
    engine, docs = corpus
    day = 86_400_000
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "d": {
                    "date_histogram": {
                        "field": "ts",
                        "fixed_interval": "30d",
                        "min_doc_count": 1,
                    }
                }
            },
        },
    )
    expected = {}
    for d in docs:
        key = math.floor(d["ts"] / (30 * day)) * 30 * day
        expected[key] = expected.get(key, 0) + 1
    got = {b["key"]: b["doc_count"] for b in resp.aggregations["d"]["buckets"]}
    assert got == expected
    for b in resp.aggregations["d"]["buckets"]:
        assert b["key_as_string"].endswith("Z")


def test_range_agg(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "r": {
                    "range": {
                        "field": "price",
                        "ranges": [
                            {"to": 25},
                            {"from": 25, "to": 75},
                            {"from": 75},
                        ],
                    },
                    "aggs": {"aq": {"avg": {"field": "qty"}}},
                }
            },
        },
    )
    buckets = resp.aggregations["r"]["buckets"]
    prices = [(d.get("price"), d["qty"]) for d in docs if "price" in d]
    exp = [
        [pq for pq in prices if pq[0] < 25],
        [pq for pq in prices if 25 <= pq[0] < 75],
        [pq for pq in prices if pq[0] >= 75],
    ]
    for b, sel in zip(buckets, exp):
        assert b["doc_count"] == len(sel)
        if sel:
            assert b["aq"]["value"] == pytest.approx(
                sum(q for _, q in sel) / len(sel), rel=1e-4
            )


def test_filter_and_global_and_missing(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "query": {"match": {"title": "beta"}},
            "aggs": {
                "cheap": {
                    "filter": {"range": {"price": {"lt": 50}}},
                    "aggs": {"n": {"value_count": {"field": "price"}}},
                },
                "everything": {
                    "global": {},
                    "aggs": {"all_sum": {"sum": {"field": "qty"}}},
                },
                "no_tag": {"missing": {"field": "tag"}},
            },
        },
    )
    matched = [d for d in docs if matches(d, "beta")]
    cheap = [d for d in matched if d.get("price", 1e9) < 50]
    a = resp.aggregations
    assert a["cheap"]["doc_count"] == len(cheap)
    assert a["cheap"]["n"]["value"] == len(cheap)
    # global ignores the query
    assert a["everything"]["doc_count"] == len(docs)
    assert a["everything"]["all_sum"]["value"] == pytest.approx(
        sum(d["qty"] for d in docs), rel=1e-5
    )
    assert a["no_tag"]["doc_count"] == len(
        [d for d in matched if "tag" not in d]
    )


def test_filters_agg_keyed(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "f": {
                    "filters": {
                        "filters": {
                            "has_alpha": {"match": {"title": "alpha"}},
                            "cheap": {"range": {"price": {"lt": 30}}},
                        }
                    }
                }
            },
        },
    )
    b = resp.aggregations["f"]["buckets"]
    assert b["has_alpha"]["doc_count"] == len(
        [d for d in docs if matches(d, "alpha")]
    )
    assert b["cheap"]["doc_count"] == len(
        [d for d in docs if d.get("price", 1e9) < 30]
    )


def test_aggs_with_hits(corpus):
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 5,
            "query": {"match": {"title": "gamma"}},
            "aggs": {"s": {"sum": {"field": "qty"}}},
        },
    )
    matched = [d for d in docs if matches(d, "gamma")]
    assert resp.total == len(matched)
    assert len(resp.hits) == min(5, len(matched))
    assert resp.aggregations["s"]["value"] == pytest.approx(
        sum(d["qty"] for d in matched), rel=1e-5
    )


def test_aggs_empty_index():
    engine = Engine(Mappings.from_json(MAPPINGS))
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "m": {"max": {"field": "price"}},
                "t": {"terms": {"field": "tag"}},
                "h": {"histogram": {"field": "price", "interval": 5}},
                "r": {"range": {"field": "price", "ranges": [{"to": 10}]}},
                "c": {"cardinality": {"field": "tag"}},
            },
        },
    )
    a = resp.aggregations
    assert resp.total == 0
    assert a["m"]["value"] is None
    assert a["t"]["buckets"] == []
    assert a["h"]["buckets"] == []
    assert a["r"]["buckets"][0]["doc_count"] == 0
    assert a["c"]["value"] == 0


def test_duplicate_agg_name_across_nesting_levels(corpus):
    """A filter-nested histogram sharing its name with a top-level one must
    not clobber the top-level plan (plan state is per-node, not per-name)."""
    engine, docs = corpus
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "h": {"histogram": {"field": "price", "interval": 10}},
                "f": {
                    "filter": {"range": {"price": {"lt": 50}}},
                    "aggs": {
                        "h": {"histogram": {"field": "price", "interval": 5}}
                    },
                },
            },
        },
    )
    outer = {
        b["key"]: b["doc_count"] for b in resp.aggregations["h"]["buckets"]
    }
    inner = {
        b["key"]: b["doc_count"]
        for b in resp.aggregations["f"]["h"]["buckets"]
    }
    exp_outer, exp_inner = {}, {}
    for d in docs:
        if "price" not in d:
            continue
        k10 = math.floor(d["price"] / 10) * 10
        exp_outer[k10] = exp_outer.get(k10, 0) + 1
        if d["price"] < 50:
            k5 = math.floor(d["price"] / 5) * 5
            exp_inner[k5] = exp_inner.get(k5, 0) + 1
    assert {k: v for k, v in outer.items() if v} == exp_outer
    assert {k: v for k, v in inner.items() if v} == exp_inner


def test_filters_empty_index_keeps_bucket_shape():
    engine = Engine(Mappings.from_json(MAPPINGS))
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "f": {
                    "filters": {
                        "filters": {
                            "a": {"match": {"title": "alpha"}},
                            "b": {"match": {"title": "beta"}},
                        }
                    }
                },
                "fl": {
                    "filters": {
                        "filters": [{"match": {"title": "alpha"}}]
                    }
                },
            },
        },
    )
    assert resp.aggregations["f"]["buckets"] == {
        "a": {"doc_count": 0},
        "b": {"doc_count": 0},
    }
    assert resp.aggregations["fl"]["buckets"] == [{"doc_count": 0}]


def test_field_absent_from_one_segment():
    """Every agg type must work when a mapped field has no values in one
    refreshed segment (reference: ValuesSource skips docs missing the
    field; unmapped-in-segment never errors)."""
    engine = Engine(Mappings.from_json(MAPPINGS))
    for i in range(8):  # segment 1: no price/tag/ts at all
        engine.index({"title": "alpha words here", "qty": i}, f"a{i}")
    engine.refresh()
    for i in range(8):  # segment 2: full docs
        engine.index(
            {
                "title": "alpha more words",
                "tag": "red" if i % 2 else "blue",
                "price": 10.0 * i,
                "qty": 100 + i,
                "ts": 1_650_000_000_000 + i * 86_400_000,
            },
            f"b{i}",
        )
    engine.refresh()
    resp = run_aggs(
        engine,
        {
            "size": 0,
            "aggs": {
                "avg_p": {"avg": {"field": "price"}},
                "tags": {"terms": {"field": "tag"}},
                "qtys": {"terms": {"field": "qty", "size": 50}},
                "card_t": {"cardinality": {"field": "tag"}},
                "card_p": {"cardinality": {"field": "price"}},
                "hist": {"histogram": {"field": "price", "interval": 25}},
                "rng": {
                    "range": {"field": "price", "ranges": [{"to": 35}, {"from": 35}]}
                },
                "no_tag": {"missing": {"field": "tag"}},
                "no_such": {"missing": {"field": "unmapped_field"}},
                "m_unmapped": {"max": {"field": "unmapped_field"}},
            },
        },
    )
    a = resp.aggregations
    prices = [10.0 * i for i in range(8)]
    assert a["avg_p"]["value"] == pytest.approx(sum(prices) / 8, rel=1e-6)
    assert {b["key"]: b["doc_count"] for b in a["tags"]["buckets"]} == {
        "red": 4,
        "blue": 4,
    }
    got_q = {b["key"]: b["doc_count"] for b in a["qtys"]["buckets"]}
    assert got_q == {**{i: 1 for i in range(8)}, **{100 + i: 1 for i in range(8)}}
    assert a["card_t"]["value"] == 2
    assert a["card_p"]["value"] == 8
    assert sum(b["doc_count"] for b in a["hist"]["buckets"]) == 8
    assert a["rng"]["buckets"][0]["doc_count"] == 4  # 0,10,20,30
    assert a["rng"]["buckets"][1]["doc_count"] == 4
    assert a["no_tag"]["doc_count"] == 8
    assert a["no_such"]["doc_count"] == 16
    assert a["m_unmapped"]["value"] is None


def test_agg_parse_errors(corpus):
    engine, _ = corpus
    svc = SearchService(engine)
    with pytest.raises(ValueError):
        svc.search(
            SearchRequest.from_json(
                {"aggs": {"bad": {"nope_type": {"field": "price"}}}}
            )
        )
    with pytest.raises(ValueError):
        svc.search(
            SearchRequest.from_json(
                {"aggs": {"t": {"terms": {"field": "title"}}}}
            )
        )  # text field has no keyword ordinals
    with pytest.raises(ValueError):
        svc.search(
            SearchRequest.from_json(
                {"aggs": {"h": {"histogram": {"field": "price"}}}}
            )
        )  # missing interval


def test_keyword_field_rejected_in_numeric_agg_positions(corpus):
    engine, _ = corpus
    svc = SearchService(engine)
    for body in [
        {"aggs": {"s": {"sum": {"field": "tag"}}}},
        {"aggs": {"h": {"histogram": {"field": "tag", "interval": 1}}}},
        {"aggs": {"r": {"range": {"field": "tag", "ranges": [{"to": 1}]}}}},
        {
            "aggs": {
                "t": {
                    "terms": {"field": "tag"},
                    "aggs": {"s": {"sum": {"field": "title"}}},
                }
            }
        },
    ]:
        with pytest.raises(ValueError):
            svc.search(SearchRequest.from_json(body))


def test_bad_sort_rejected_even_when_agg_only(corpus):
    engine, _ = corpus
    svc = SearchService(engine)
    with pytest.raises(ValueError):
        svc.search(
            SearchRequest.from_json(
                {
                    "size": 0,
                    "sort": [{"no_such_field": "asc"}],
                    "aggs": {"s": {"sum": {"field": "qty"}}},
                }
            )
        )


def test_rest_aggregations_route(corpus, tmp_path):
    from elasticsearch_tpu.rest.server import RestServer

    rest = RestServer()
    rest.node.create_index("idx", {"mappings": MAPPINGS})
    engine, docs = corpus
    # reuse corpus docs through the REST bulk path
    lines = []
    for i, d in enumerate(docs[:50]):
        lines.append('{"index": {"_id": "r%d"}}' % i)
        import json as _json

        lines.append(_json.dumps(d))
    status, _ = rest.dispatch("POST", "/idx/_bulk", {"refresh": "true"}, "\n".join(lines))
    assert status == 200
    status, resp = rest.dispatch(
        "POST",
        "/idx/_search",
        {},
        '{"size": 0, "aggs": {"tags": {"terms": {"field": "tag"}}}}',
    )
    assert status == 200
    expected = {}
    for d in docs[:50]:
        if "tag" in d:
            expected[d["tag"]] = expected.get(d["tag"], 0) + 1
    got = {
        b["key"]: b["doc_count"]
        for b in resp["aggregations"]["tags"]["buckets"]
    }
    assert got == expected
