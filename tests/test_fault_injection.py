"""Fault-injection subsystem + degraded-mode search contracts.

Covers: registry determinism and the `POST /_fault` admin API; coordinator
partial results with honest `_shards.failed`/`failures[]` (including
`_msearch`); `allow_partial_search_results=false` → 503; batcher failure
isolation (individual retry, quarantine); the shed-429 Retry-After hint;
adaptive replica selection (EWMA reroute away from failing copies); and
the nested dotted-key dynamic-mapping fix.
"""

import json
import threading
import time

import pytest

from elasticsearch_tpu.cluster.response_collector import (
    ResponseCollectorService,
)
from elasticsearch_tpu.common.indexing_pressure import (
    IndexingPressureRejected,
)
from elasticsearch_tpu.exec.batcher import MicroBatcher
from elasticsearch_tpu.faults import (
    REGISTRY,
    FaultRegistry,
    FaultSpec,
    InjectedFaultError,
)
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.rest.server import RestServer


@pytest.fixture(autouse=True)
def _clean_registry():
    REGISTRY.clear()
    yield
    REGISTRY.clear()


# ---------------------------------------------------------------- registry


class TestRegistry:
    def test_seeded_schedule_is_deterministic(self):
        def schedule(seed):
            reg = FaultRegistry()
            reg.put(FaultSpec(site="x", error_rate=0.5, seed=seed))
            out = []
            for _ in range(50):
                try:
                    reg.check("x")
                    out.append(0)
                except InjectedFaultError:
                    out.append(1)
            return out

        assert schedule(7) == schedule(7)
        assert schedule(7) != schedule(8)
        assert 0 < sum(schedule(7)) < 50

    def test_count_budget_exhausts(self):
        reg = FaultRegistry()
        reg.put(FaultSpec(site="x", error_rate=1.0, count=2))
        fired = 0
        for _ in range(5):
            try:
                reg.check("x")
            except InjectedFaultError:
                fired += 1
        assert fired == 2
        assert reg.stats()["specs"][0]["exhausted"] is True

    def test_pattern_matching_and_error_classes(self):
        from elasticsearch_tpu.cluster.transport import ConnectTransportError
        from elasticsearch_tpu.common.breaker import BreakerError

        reg = FaultRegistry()
        reg.put(FaultSpec(site="transport.send.*", error="transport"))
        with pytest.raises(ConnectTransportError):
            reg.check("transport.send.shard_search")
        reg.check("other.site")  # no match, no fault
        reg.clear()
        reg.put(FaultSpec(site="breaker.*", error="breaker"))
        with pytest.raises(BreakerError):
            reg.check("breaker.reserve")

    def test_delay_only_spec_sleeps(self):
        reg = FaultRegistry()
        reg.put(FaultSpec(site="slow", error=None, delay_ms=30))
        t0 = time.monotonic()
        reg.check("slow")  # no error raised
        assert time.monotonic() - t0 >= 0.025

    def test_env_parsing(self):
        specs = FaultRegistry.parse_env(
            "coordinator.shard:rate=0.3:error=transport:seed=7,"
            "batcher.launch:delay_ms=5:count=10:error=none"
        )
        assert specs[0].site == "coordinator.shard"
        assert specs[0].error_rate == 0.3
        assert specs[0].error == "transport"
        assert specs[0].seed == 7
        assert specs[1].error is None
        assert specs[1].delay_ms == 5.0
        assert specs[1].count == 10
        with pytest.raises(ValueError):
            FaultRegistry.parse_env("x:bogus=1")
        with pytest.raises(ValueError):
            FaultRegistry.parse_env("x:rate=1.5")


# ------------------------------------------------------------- REST admin


INDEX_3SHARD = json.dumps(
    {
        "settings": {"index": {"number_of_shards": 3}},
        "mappings": {"properties": {"body": {"type": "text"}}},
    }
)


def _seed_docs(rest, index, n=30):
    lines = []
    for i in range(n):
        lines.append(json.dumps({"index": {"_index": index, "_id": f"d{i}"}}))
        lines.append(json.dumps({"body": f"findme token{i % 5}"}))
    status, resp = rest.dispatch("POST", "/_bulk", {}, "\n".join(lines))
    assert status == 200 and not resp["errors"]
    rest.dispatch("POST", f"/{index}/_refresh", {}, "")


@pytest.fixture
def rest(monkeypatch):
    # The host-loop coordinator path is what this suite faults; keep the
    # SPMD mesh out of the way.
    monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
    server = RestServer()
    yield server
    server.close()


class TestFaultAdminApi:
    def test_arm_inspect_disarm(self, rest):
        status, resp = rest.dispatch(
            "POST",
            "/_fault",
            {},
            json.dumps(
                {"site": "coordinator.shard", "error_rate": 0.5, "seed": 3}
            ),
        )
        assert status == 200 and resp["acknowledged"]
        status, resp = rest.dispatch("GET", "/_fault", {}, "")
        assert status == 200
        assert resp["active"] is True
        (spec,) = resp["specs"]
        assert spec["site"] == "coordinator.shard"
        assert spec["error_rate"] == 0.5
        status, resp = rest.dispatch("DELETE", "/_fault", {}, "")
        assert status == 200 and resp["cleared"] == 1
        status, resp = rest.dispatch("GET", "/_fault", {}, "")
        assert resp["active"] is False

    def test_bad_spec_is_400(self, rest):
        status, resp = rest.dispatch(
            "POST", "/_fault", {}, json.dumps({"error_rate": 1.0})
        )
        assert status == 400
        status, _ = rest.dispatch(
            "POST", "/_fault", {},
            json.dumps({"site": "x", "error": "nonsense"}),
        )
        assert status == 400


class TestDegradedCoordinator:
    def _arm_one_shard_fault(self, rest, **kw):
        body = {"site": "coordinator.shard", "error_rate": 1.0, "count": 1}
        body.update(kw)
        status, _ = rest.dispatch("POST", "/_fault", {}, json.dumps(body))
        assert status == 200

    def test_partial_results_with_honest_failures(self, rest):
        status, _ = rest.dispatch("PUT", "/fi", {}, INDEX_3SHARD)
        assert status == 200
        _seed_docs(rest, "fi")
        # Baseline: full result.
        q = json.dumps({"query": {"match": {"body": "findme"}}, "size": 30})
        status, full = rest.dispatch("POST", "/fi/_search", {}, q)
        assert status == 200
        assert full["_shards"] == {
            "total": 3, "successful": 3, "skipped": 0, "failed": 0,
        }
        baseline = {
            h["_id"]: h["_score"] for h in full["hits"]["hits"]
        }
        # One shard fails exactly once: partial 200 with failures[].
        self._arm_one_shard_fault(rest)
        status, part = rest.dispatch("POST", "/fi/_search", {}, q)
        assert status == 200
        sh = part["_shards"]
        assert sh["failed"] == 1
        assert sh["successful"] + sh["failed"] + sh["skipped"] == sh["total"]
        (failure,) = sh["failures"]
        assert failure["index"] == "fi"
        assert failure["reason"]["type"] == "InjectedFaultError"
        # Correct subset: identical scores, fewer docs, order preserved.
        hits = part["hits"]["hits"]
        assert 0 < len(hits) < len(baseline)
        for hit in hits:
            assert baseline[hit["_id"]] == hit["_score"]

    def test_allow_partial_false_body_and_url_503(self, rest):
        status, _ = rest.dispatch("PUT", "/fi", {}, INDEX_3SHARD)
        assert status == 200
        _seed_docs(rest, "fi")
        # sort:_score keeps the request off the micro-batcher (whose
        # individual-retry machinery would absorb a one-shot fault —
        # tested separately): one shard fails, partials are disallowed,
        # the whole request must 503.
        q = {
            "query": {"match": {"body": "findme"}},
            "sort": [{"_score": "desc"}],
        }
        self._arm_one_shard_fault(rest)
        status, resp = rest.dispatch(
            "POST", "/fi/_search", {},
            json.dumps({**q, "allow_partial_search_results": False}),
        )
        assert status == 503
        assert resp["error"]["type"] == "search_phase_execution_exception"
        self._arm_one_shard_fault(rest)
        status, resp = rest.dispatch(
            "POST", "/fi/_search",
            {"allow_partial_search_results": "false"}, json.dumps(q),
        )
        assert status == 503
        # Faults cleared (count budget spent): the same request succeeds.
        status, resp = rest.dispatch(
            "POST", "/fi/_search",
            {"allow_partial_search_results": "false"}, json.dumps(q),
        )
        assert status == 200 and resp["_shards"]["failed"] == 0

    def test_bogus_allow_partial_values_are_400(self, rest):
        """A misspelled boolean must never silently invert the caller's
        no-partials demand — URL and body forms both reject it."""
        status, _ = rest.dispatch("PUT", "/fi", {}, INDEX_3SHARD)
        assert status == 200
        q = {"query": {"match_all": {}}}
        status, resp = rest.dispatch(
            "POST", "/fi/_search",
            {"allow_partial_search_results": "maybe"}, json.dumps(q),
        )
        assert status == 400, resp
        status, resp = rest.dispatch(
            "POST", "/fi/_search", {},
            json.dumps({**q, "allow_partial_search_results": "nope"}),
        )
        assert status == 400, resp
        # Case-insensitive accepted spellings still work.
        status, resp = rest.dispatch(
            "POST", "/fi/_search",
            {"allow_partial_search_results": "False"}, json.dumps(q),
        )
        assert status == 200, resp

    def test_one_shot_fault_on_batched_path_degrades_honestly(self, rest):
        """A count-budgeted shard fault on the coalesced (batched) path
        serves an honest partial 200 — the failure never poisons the
        batch or escalates to an error."""
        status, _ = rest.dispatch("PUT", "/fi", {}, INDEX_3SHARD)
        assert status == 200
        _seed_docs(rest, "fi")
        self._arm_one_shard_fault(rest)  # count=1
        status, resp = rest.dispatch(
            "POST", "/fi/_search", {},
            json.dumps({"query": {"match": {"body": "findme"}}}),
        )
        assert status == 200
        sh = resp["_shards"]
        assert sh["failed"] == 1
        assert sh["successful"] + sh["failed"] + sh["skipped"] == sh["total"]
        assert sh["failures"][0]["reason"]["type"] == "InjectedFaultError"

    def test_single_shard_index_fault_is_503_not_500(self, rest):
        status, _ = rest.dispatch(
            "PUT", "/one", {},
            json.dumps({"mappings": {"properties": {"body": {"type": "text"}}}}),
        )
        assert status == 200
        _seed_docs(rest, "one", n=5)
        # Persistent fault: the retry fails too, and a 1-shard index has
        # no partial to degrade to.
        status, _ = rest.dispatch(
            "POST", "/_fault", {}, json.dumps({"site": "search.kernel"})
        )
        assert status == 200
        status, resp = rest.dispatch(
            "POST", "/one/_search", {},
            json.dumps({"query": {"match": {"body": "findme"}}}),
        )
        # All (one) shards failed: 503, never a raw 500 or partial 200.
        assert status == 503
        assert resp["error"]["type"] == "search_phase_execution_exception"

    def test_msearch_items_carry_failures(self, rest):
        status, _ = rest.dispatch("PUT", "/fi", {}, INDEX_3SHARD)
        assert status == 200
        _seed_docs(rest, "fi")
        self._arm_one_shard_fault(rest)
        payload = "\n".join(
            [
                json.dumps({"index": "fi"}),
                json.dumps({"query": {"match": {"body": "findme"}}}),
                json.dumps({"index": "fi"}),
                json.dumps({"query": {"match": {"body": "findme"}}}),
            ]
        )
        status, resp = rest.dispatch("POST", "/_msearch", {}, payload)
        assert status == 200
        shard_sum = [
            r["_shards"]["successful"] + r["_shards"]["failed"]
            + r["_shards"]["skipped"]
            for r in resp["responses"]
        ]
        assert shard_sum == [3, 3]
        assert sum(
            r["_shards"]["failed"] for r in resp["responses"]
        ) == 1

    def test_counters_surface_in_nodes_stats(self, rest):
        status, _ = rest.dispatch("PUT", "/fi", {}, INDEX_3SHARD)
        assert status == 200
        _seed_docs(rest, "fi")
        self._arm_one_shard_fault(rest)
        q = json.dumps({"query": {"match": {"body": "findme"}}})
        status, _ = rest.dispatch("POST", "/fi/_search", {}, q)
        assert status == 200
        status, stats = rest.dispatch("GET", "/_nodes/stats", {}, "")
        node = next(iter(stats["nodes"].values()))
        res = node["search_resilience"]
        assert res["partial_responses"] >= 1
        assert res["shard_failures"] >= 1
        assert node["faults"]["specs"][0]["injected_errors"] == 1


# ---------------------------------------------------- batcher isolation


class FlakySearcher:
    """search_many fails marked requests; the solo path always works."""

    def __init__(self, poison=()):
        self.poison = set(poison)
        self.batch_calls = []
        self.solo_calls = []
        self.lock = threading.Lock()

    def search_many(self, requests, tasks=None):
        with self.lock:
            self.batch_calls.append(list(requests))
        return [
            InjectedFaultError(f"boom:{r}") if r in self.poison
            else f"batched:{r}"
            for r in requests
        ]

    def search(self, request, task=None, record_filter_usage=True):
        with self.lock:
            self.solo_calls.append(request)
        return f"solo:{request}"


class TestBatcherIsolation:
    def test_failed_subrequest_retried_individually(self):
        batcher = MicroBatcher(max_wait_s=0.2)
        stub = FlakySearcher(poison={"bad"})
        results = {}

        def go(name, delay):
            time.sleep(delay)
            results[name] = batcher.execute(stub, name)

        threads = [threading.Thread(target=go, args=("a", 0.0))]
        threads += [
            threading.Thread(target=go, args=(n, 0.05))
            for n in ("bad", "c", "d")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # Batchmates unharmed, the poisoned one served via the solo path.
        assert results["c"] == "batched:c"
        assert results["d"] == "batched:d"
        assert results["bad"] == "solo:bad"
        assert batcher.stats()["retried_individually"] == 1
        batcher.close()

    def test_injected_batcher_fault_spares_batchmates(self):
        REGISTRY.put(
            FaultSpec(site="batcher.launch", error_rate=1.0, count=1)
        )
        batcher = MicroBatcher(max_wait_s=0.2)
        stub = FlakySearcher()
        results = {}

        def go(name, delay):
            time.sleep(delay)
            results[name] = batcher.execute(stub, name)

        threads = [
            threading.Thread(target=go, args=(n, d))
            for n, d in (("a", 0.0), ("b", 0.05), ("c", 0.05))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # Exactly one request took the fault and was served solo; no
        # request failed.
        solo = [v for v in results.values() if v.startswith("solo:")]
        assert len(solo) == 1
        assert len(results) == 3
        batcher.close()

    def test_repeat_offender_group_quarantined(self):
        batcher = MicroBatcher(max_wait_s=0.0)
        stub = FlakySearcher(poison={"bad"})
        for _ in range(batcher.QUARANTINE_FAILURES):
            assert batcher.execute(stub, "bad") == "solo:bad"
        stats = batcher.stats()
        assert stats["groups_quarantined"] == 1
        n_batches = len(stub.batch_calls)
        # Quarantined: served on the per-request path, no coalesced launch.
        assert batcher.execute(stub, "bad") == "solo:bad"
        assert len(stub.batch_calls) == n_batches
        assert batcher.stats()["quarantine_hits"] == 1
        batcher.close()

    def test_shed_carries_retry_after_hint(self):
        batcher = MicroBatcher(max_wait_s=0.05, queue_limit=1)
        slow = FlakySearcher()
        orig = slow.search_many

        def slow_many(requests, tasks=None):
            time.sleep(0.4)
            return orig(requests, tasks)

        slow.search_many = slow_many
        t = threading.Thread(target=lambda: batcher.execute(slow, "a"))
        t.start()
        time.sleep(0.1)  # a executing; fill the queue
        t2 = threading.Thread(target=lambda: batcher.execute(slow, "b"))
        t2.start()
        time.sleep(0.05)
        with pytest.raises(IndexingPressureRejected) as ei:
            batcher.execute(slow, "c")
        assert 1 <= ei.value.retry_after_s <= 30
        t.join(timeout=5)
        t2.join(timeout=5)
        batcher.close()

    def test_rest_429_sets_retry_after_header(self, rest):
        status, _ = rest.dispatch("PUT", "/fi", {}, INDEX_3SHARD)
        assert status == 200
        _seed_docs(rest, "fi", n=5)

        def shed(*a, **kw):
            err = IndexingPressureRejected("queue full")
            err.retry_after_s = 7
            raise err

        rest.node.exec_batcher.execute = shed
        status, resp = rest.dispatch(
            "POST", "/fi/_search", {},
            json.dumps({"query": {"match": {"body": "findme"}}}),
        )
        assert status == 429
        assert resp["error"]["type"] == "es_rejected_execution_exception"
        assert rest._tl.response_headers["Retry-After"] == "7"


# ------------------------------------------- adaptive replica selection


class TestResponseCollector:
    def test_failing_copy_drops_behind_healthy_ones(self):
        rc = ResponseCollectorService()
        copies = ["n0", "n1", "n2"]
        for node in copies:
            rc.record_response(node, 0.01)
        assert rc.ordered(copies) == copies  # ties keep caller order
        rc.record_failure("n0")
        assert rc.ordered(copies)[0] != "n0"
        assert rc.ordered(copies)[-1] == "n0"
        # Successes rehabilitate the copy (penalty decays toward zero).
        for _ in range(20):
            rc.record_response("n0", 0.001)
        assert rc.ordered(copies)[0] == "n0"

    def test_slow_copy_ranks_behind_fast_ones(self):
        rc = ResponseCollectorService()
        rc.record_response("slow", 0.5)
        rc.record_response("fast", 0.001)
        assert rc.ordered(["slow", "fast"]) == ["fast", "slow"]
        snap = rc.snapshot()
        assert snap["slow"]["rank"] > snap["fast"]["rank"]
        assert snap["fast"]["responses"] == 1

    def test_queue_pressure_raises_rank(self):
        rc = ResponseCollectorService()
        for _ in range(5):
            rc.record_response("busy", 0.01, queue_size=20)
            rc.record_response("idle", 0.01, queue_size=0)
        assert rc.ordered(["busy", "idle"]) == ["idle", "busy"]


# --------------------------------------------------- nested dotted keys


class TestNestedDottedKeys:
    MAPPINGS = {
        "properties": {
            "title": {"type": "text"},
            "comments": {
                "type": "nested",
                "properties": {
                    "author": {"type": "keyword"},
                    "body": {"type": "text"},
                },
            },
        }
    }

    def test_literal_dotted_key_routes_into_nested_scope(self):
        engine = Engine(Mappings.from_json(self.MAPPINGS))
        engine.index({"title": "t", "comments.author": "alice"}, "d1")
        engine.refresh()
        # No flat field collides with the nested path name.
        assert "comments.author" not in engine.mappings.fields
        (handle,) = engine.segments
        block = handle.segment.nested["comments"]
        assert block.seg.num_docs == 1
        fld = block.seg.fields["comments.author"]
        assert "alice" in fld.terms
        # And the nested query finds it like a properly-shaped doc.
        from elasticsearch_tpu.search.service import (
            SearchRequest,
            SearchService,
        )

        resp = SearchService(engine).search(
            SearchRequest.from_json(
                {
                    "query": {
                        "nested": {
                            "path": "comments",
                            "query": {
                                "term": {"comments.author": "alice"}
                            },
                        }
                    }
                }
            )
        )
        assert [h.doc_id for h in resp.hits] == ["d1"]

    def test_deep_dotted_key_expands_through_nested_parent(self):
        engine = Engine(Mappings.from_json(self.MAPPINGS))
        engine.index({"comments.author": ["a", "b"]}, "d1")
        engine.refresh()
        (handle,) = engine.segments
        block = handle.segment.nested["comments"]
        # One nested sub-doc with a multi-valued author, not two.
        assert block.seg.num_docs == 1

    def test_dynamic_flat_mapping_refused_under_nested_prefix(self):
        m = Mappings.from_json(self.MAPPINGS)
        assert m.resolve_dynamic("comments.newfield", "x") is None
        # Ordinary dynamic mapping still works.
        assert m.resolve_dynamic("brand.new", "x") is not None
