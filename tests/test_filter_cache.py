"""Filter/bitset cache (ISSUE 9): cached-vs-uncached parity is the law.

A cached mask plane IS the filter subtree's own evaluation, so
substituting it can never move ids, order, fp32 scores, or totals — on
the plain device path, the sparse conjunction kernels, the two-phase
block-max path, the coalesced micro-batch path, or the SPMD mesh path.
These tests fuzz that contract, plus the cache policies themselves:
usage-tracking admission (one-off filters never admitted), HBM-budgeted
LRU eviction (least-recently-used planes evict first, breaker bytes
released), hard invalidation across refresh/update/delete, coalesced
batchmates sharing one plane, and the REST/observability surfaces
(`_cache/clear`, `_nodes/stats` indices.filter_cache, `/_metrics`).
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from elasticsearch_tpu.common.breaker import CircuitBreaker
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.filter_cache import FilterCache
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.query.compile import (
    cacheable_filter_key,
    collect_cacheable_filters,
)
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.service import SearchRequest, SearchService

WORDS = [f"w{i}" for i in range(40)]
TAGS = ["red", "green", "blue", "teal"]

MAPPINGS = {
    "properties": {
        "title": {"type": "text"},
        "tag": {"type": "keyword"},
        "price": {"type": "long"},
    }
}


def _doc(rng):
    return {
        "title": " ".join(rng.choices(WORDS, k=6)),
        "tag": rng.choice(TAGS),
        "price": rng.randint(0, 100),
    }


def _build_engine(n_docs=600, seed=7, segments=2) -> Engine:
    rng = random.Random(seed)
    eng = Engine(Mappings.from_json(MAPPINGS))
    per_seg = max(1, n_docs // segments)
    for i in range(n_docs):
        eng.index(_doc(rng), str(i))
        if (i + 1) % per_seg == 0:
            eng.refresh()
    eng.refresh()
    return eng


def _rand_filtered_query(rng):
    """A random filtered bool body: scored musts + cacheable filters."""
    must = [
        {"match": {"title": " ".join(rng.sample(WORDS, rng.randint(1, 3)))}}
    ]
    filters = []
    for _ in range(rng.randint(1, 2)):
        kind = rng.randint(0, 3)
        if kind == 0:
            filters.append({"term": {"tag": rng.choice(TAGS)}})
        elif kind == 1:
            filters.append(
                {"terms": {"tag": rng.sample(TAGS, rng.randint(1, 2))}}
            )
        elif kind == 2:
            lo = rng.randint(0, 80)
            filters.append({"range": {"price": {"gte": lo, "lt": lo + 40}}})
        else:
            filters.append({"exists": {"field": "price"}})
    body: dict = {"bool": {"must": must, "filter": filters}}
    if rng.random() < 0.3:
        body["bool"]["must_not"] = [{"term": {"tag": rng.choice(TAGS)}}]
    return body


def _hits_sig(resp):
    return (
        [(h.doc_id, h.score) for h in resp.hits],
        resp.total,
        resp.total_relation,
    )


class TestParityFuzz:
    def test_cached_vs_uncached_64_queries_device(self):
        """The headline gate: ≥64 random filtered bool queries, executed
        cold (admission pass), warm (cache hits), and on a cache-free
        twin service — all three bit-identical (ids + order + fp32
        scores + totals), across a multi-segment shard."""
        eng = _build_engine()
        cache = FilterCache(min_freq=1)  # admit on first sight: hits fuzz
        cached_svc = SearchService(eng, filter_cache=cache)
        plain_svc = SearchService(eng)
        rng = random.Random(11)
        for i in range(64):
            body = {"query": _rand_filtered_query(rng), "size": 10}
            request = SearchRequest.from_json(body)
            cold = cached_svc.search(SearchRequest.from_json(body))
            warm = cached_svc.search(SearchRequest.from_json(body))
            plain = plain_svc.search(request)
            assert _hits_sig(cold) == _hits_sig(plain), body
            assert _hits_sig(warm) == _hits_sig(plain), body
        stats = cache.stats()
        assert stats["admissions"] > 0
        assert stats["hit_count"] > 0

    def test_parity_on_blockmax_conj_path(self):
        """Untracked totals open the two-phase block-max conjunction
        backend; cached masks must survive it bit-exactly (the pruned
        second launch verifies filters via the plane gather)."""
        from elasticsearch_tpu.ops import bm25_device

        eng = _build_engine(segments=1)
        cache = FilterCache(min_freq=1)
        svc = SearchService(eng, filter_cache=cache)
        plain = SearchService(eng)
        handle = eng.segments[0]
        rng = random.Random(3)
        checked_masked_sparse = False
        for _ in range(16):
            body = {
                "query": _rand_filtered_query(rng),
                "size": 10,
                "track_total_hits": False,
            }
            warm_req = SearchRequest.from_json(body)
            svc.search(SearchRequest.from_json(body))  # admit
            # The masked plan must stay sparse-eligible (the conjunction
            # kernels accept cached_mask clauses).
            compiled = eng.compiler_for(handle).compile(warm_req.query)
            seg_tree = bm25_device.segment_tree(handle.device)
            masked, masks = svc._apply_filter_cache(
                handle, warm_req.query, compiled, seg_tree
            )
            if masks and bm25_device.supports_sparse(compiled.spec):
                assert bm25_device.supports_sparse(masked.spec)
                checked_masked_sparse = True
            warm = svc.search(warm_req)
            ref = plain.search(SearchRequest.from_json(body))
            assert _hits_sig(warm)[0] == _hits_sig(ref)[0]
        assert checked_masked_sparse

    def test_masked_blockmax_conj_kernel_bit_exact(self):
        """A masked plan the planner routes to blockmax_conj must return
        the same hits as masked execute_auto — the two-phase pruned
        kernel's phase-A filter verification and exact second launch both
        read the cached plane via seg["masks"]."""
        from elasticsearch_tpu.ops import bm25_device

        eng = _build_engine(n_docs=600, segments=1)
        cache = FilterCache(min_freq=1)
        svc = SearchService(eng, filter_cache=cache)
        handle = eng.segments[0]
        seg_tree = bm25_device.segment_tree(handle.device)
        rng = random.Random(13)
        checked = 0
        for _ in range(16):
            body = {
                "query": {
                    "bool": {
                        "must": [
                            {
                                "match": {
                                    "title": " ".join(rng.sample(WORDS, 2))
                                }
                            }
                        ],
                        "filter": [
                            {"term": {"tag": rng.choice(TAGS)}},
                            {"range": {"price": {"gte": rng.randint(0, 50)}}},
                        ],
                    }
                },
                "size": 10,
                "track_total_hits": False,
            }
            req = SearchRequest.from_json(body)
            svc.search(SearchRequest.from_json(body))  # admit planes
            compiled = eng.compiler_for(handle).compile(req.query)
            masked, masks = svc._apply_filter_cache(
                handle, req.query, compiled, seg_tree
            )
            if not masks or not bm25_device.supports_blockmax_conj(
                masked.spec
            ):
                continue
            seg_m = {**seg_tree, "masks": masks}
            s_a, i_a, t_a = bm25_device.execute_auto(
                seg_m, masked.spec, masked.arrays, 10
            )
            s_b, i_b, t_b, _rel = bm25_device.execute_batch_blockmax_conj(
                seg_m, masked.spec, [masked.arrays], 10
            )
            assert np.array_equal(np.asarray(i_a), np.asarray(i_b[0])), body
            assert np.array_equal(np.asarray(s_a), np.asarray(s_b[0])), body
            assert int(t_b[0]) <= int(t_a), body  # "gte" totals undercount
            checked += 1
        assert checked > 0

    def test_parity_immediately_after_refresh_update_delete(self):
        """Invalidation gate: writes + refresh mint new generations and
        segment handles, so the very next search recomputes (or
        re-admits) and stays bit-identical to a cache-free twin."""
        eng = _build_engine(n_docs=300, segments=1)
        cache = FilterCache(min_freq=1)
        svc = SearchService(eng, filter_cache=cache)
        plain = SearchService(eng)
        body = {
            "query": {
                "bool": {
                    "must": [{"match": {"title": "w1 w2 w3"}}],
                    "filter": [{"term": {"tag": "red"}}],
                }
            },
            "size": 10,
        }
        for _ in range(2):  # admit + hit
            svc.search(SearchRequest.from_json(body))
        # Update: make a doc enter the filter's matched set.
        eng.index({"title": "w1 w2 w3", "tag": "red", "price": 1}, "0")
        eng.refresh()
        got = svc.search(SearchRequest.from_json(body))
        ref = plain.search(SearchRequest.from_json(body))
        assert _hits_sig(got) == _hits_sig(ref)
        assert any(h.doc_id == "0" for h in got.hits)
        # Delete (soft): planes exclude live, which ANDs at query time.
        victim = got.hits[0].doc_id
        eng.delete(victim)
        eng.refresh()
        got = svc.search(SearchRequest.from_json(body))
        ref = plain.search(SearchRequest.from_json(body))
        assert _hits_sig(got) == _hits_sig(ref)
        assert all(h.doc_id != victim for h in got.hits)


class TestMeshParity:
    def test_sharded_mesh_masks_bit_identical(self):
        """parallel/sharded.py consults the cache: stacked [S, N] planes
        ride the seg pytree; results equal the cache-free mesh run."""
        import jax
        from jax.sharding import Mesh

        from elasticsearch_tpu.parallel.sharded import ShardedIndex

        devices = jax.devices()
        n_shards = min(4, len(devices))
        mesh = Mesh(np.array(devices[:n_shards]), ("shard",))
        rng = random.Random(5)
        docs = [(str(i), _doc(rng)) for i in range(600)]
        mappings = Mappings.from_json(MAPPINGS)
        plain = ShardedIndex.from_docs(docs, mappings, mesh)
        cached = ShardedIndex.from_docs(docs, mappings, mesh)
        cached.filter_cache = FilterCache(min_freq=1)
        qrng = random.Random(6)
        for _ in range(16):
            q = parse_query(_rand_filtered_query(qrng))
            ref = plain.search(q, k=10)
            for _rep in range(2):  # cold (admission) then warm (hit)
                got = cached.search(q, k=10)
                assert np.array_equal(ref[0], got[0])
                assert np.array_equal(ref[1], got[1])
                assert ref[2] == got[2]
        stats = cached.filter_cache.stats()
        assert stats["admissions"] > 0 and stats["hit_count"] > 0


class TestMeshServingPath:
    def test_mesh_serve_consults_cache_and_stays_exact(self):
        """The PRODUCTION mesh path (MeshView.serve's plain-score branch)
        consults the node filter cache: planes key under the engines'
        uid-tuple scope with the generation sum, results stay identical
        to the host loop, refresh invalidates, and per-index
        `_cache/clear` reaches the mesh-scope planes."""
        import json

        from elasticsearch_tpu.rest.server import RestServer

        rest = RestServer()
        status, _ = rest.dispatch(
            "PUT",
            "/m",
            {},
            json.dumps(
                {
                    "settings": {"index": {"number_of_shards": 4}},
                    "mappings": MAPPINGS,
                }
            ),
        )
        assert status == 200
        node = rest.node
        rng = random.Random(3)
        lines = []
        for i in range(240):
            lines.append(json.dumps({"index": {"_id": str(i)}}))
            lines.append(json.dumps(_doc(rng)))
        status, resp = rest.dispatch(
            "POST", "/m/_bulk", {"refresh": "true"}, "\n".join(lines)
        )
        assert status == 200 and not resp["errors"]
        svc = node.get_index("m")
        mv = svc.search.mesh_view
        if mv is None:
            pytest.skip("no device mesh available")
        assert mv.filter_cache is node.filter_cache
        body = {
            "query": {
                "bool": {
                    "must": [{"match": {"title": "w1 w2 w3"}}],
                    "filter": [
                        {"term": {"tag": "red"}},
                        {"range": {"price": {"gte": 10}}},
                    ],
                }
            },
            "size": 10,
        }

        def sig(out):
            return (
                [
                    (h["_id"], h["_score"])
                    for h in out["hits"]["hits"]
                ],
                out["hits"]["total"],
            )

        svc.search.mesh_view = None  # host-loop reference run
        try:
            ref = node.search("m", json.loads(json.dumps(body)))
        finally:
            svc.search.mesh_view = mv
        before = mv.served
        out1 = node.search("m", dict(body))  # sighting 1: no plane yet
        out2 = node.search("m", dict(body))  # sighting 2: built + admitted
        assert mv.served == before + 2
        scope = ("sharded", tuple(e.uid for e in svc.engines))
        assert any(k[0] == scope for k in node.filter_cache.keys())
        assert sig(out1) == sig(ref)
        assert sig(out2) == sig(ref)
        # Refresh invalidation: a new matching doc must appear at once
        # (the generation component stales every plane of this view).
        node.index_doc(
            "m", {"title": "w1 w2 w3", "tag": "red", "price": 50}, "new"
        )
        node.refresh("m")
        out3 = node.search("m", dict(body))
        assert any(h["_id"] == "new" for h in out3["hits"]["hits"])
        # Per-index clear reaches the mesh-scope planes.
        node.search("m", dict(body))  # re-admit at the new generation
        assert any(k[0] == scope for k in node.filter_cache.keys())
        node.clear_cache("m")
        assert not any(
            k[0] == scope for k in node.filter_cache.keys()
        )


class TestAdmission:
    def test_one_off_filters_never_admitted(self):
        eng = _build_engine(n_docs=200, segments=1)
        cache = FilterCache(min_freq=2)
        svc = SearchService(eng, filter_cache=cache)
        body = {
            "query": {
                "bool": {
                    "must": [{"match": {"title": "w1"}}],
                    "filter": [{"term": {"tag": "red"}}],
                }
            }
        }
        svc.search(SearchRequest.from_json(body))
        assert cache.stats()["entries"] == 0  # one sighting: not admitted
        svc.search(SearchRequest.from_json(body))
        assert cache.stats()["admissions"] == 1  # second sighting: stored
        hits_before = cache.stats()["hit_count"]
        svc.search(SearchRequest.from_json(body))
        assert cache.stats()["hit_count"] == hits_before + 1

    def test_history_ring_bounds_frequency(self):
        cache = FilterCache(min_freq=2, history=4)
        cache.record([("term", "tag", "red")])
        # Four other sightings roll the ring past the first.
        for i in range(4):
            cache.record([("term", "tag", f"other{i}")])
        cache.record([("term", "tag", "red")])
        # Only ONE "red" sighting survives in the window: not admitted.
        assert not cache.should_admit(("term", "tag", "red"))

    def test_min_freq_one_admits_immediately(self):
        cache = FilterCache(min_freq=1)
        cache.record([("exists", "price")])
        assert cache.should_admit(("exists", "price"))

    def test_duplicate_clauses_in_one_request_count_one_sighting(self):
        """bool.filter = [F, F] is still ONE sighting of F: a one-off
        query with a duplicated clause must not self-admit past
        min_freq=2 on its very first request."""
        eng = _build_engine(n_docs=200, segments=1)
        cache = FilterCache(min_freq=2)
        svc = SearchService(eng, filter_cache=cache)
        body = {
            "query": {
                "bool": {
                    "must": [{"match": {"title": "w1"}}],
                    "filter": [
                        {"term": {"tag": "red"}},
                        {"term": {"tag": "red"}},
                    ],
                }
            }
        }
        svc.search(SearchRequest.from_json(body))
        assert not cache.should_admit(("term", "tag", "red"))
        assert cache.stats()["entries"] == 0

    def test_sharded_scatter_counts_one_sighting_per_request(self):
        """An n-shard scatter is ONE user request: the coordinator
        records once and suppresses per-shard recording, so a one-off
        filter on a 3-shard index never self-admits past min_freq=2."""
        from elasticsearch_tpu.search.coordinator import (
            ShardedSearchCoordinator,
        )

        engines = [_build_engine(n_docs=60, seed=s, segments=1)
                   for s in (1, 2, 3)]
        cache = FilterCache(min_freq=2)
        coord = ShardedSearchCoordinator(engines, filter_cache=cache)
        body = {
            "query": {
                "bool": {
                    "must": [{"match": {"title": "w1"}}],
                    "filter": [{"term": {"tag": "red"}}],
                }
            }
        }
        coord.search(SearchRequest.from_json(body))
        # One request = one sighting: below the threshold, nothing admitted.
        assert cache.stats()["entries"] == 0
        assert not cache.should_admit(("term", "tag", "red"))
        coord.search(SearchRequest.from_json(body))
        # Second request reaches min_freq; per-shard passes admit planes.
        assert cache.stats()["admissions"] >= 1


class TestEviction:
    def _plane(self, n=64):
        return np.zeros(n, dtype=bool)

    def test_lru_eviction_order(self):
        cache = FilterCache(max_bytes=200)
        a, b, c = ("k", "a"), ("k", "b"), ("k", "c")
        cache.put((1, 0, 0, a), self._plane(), 80)
        cache.put((1, 0, 0, b), self._plane(), 80)
        assert cache.get((1, 0, 0, a)) is not None  # touch a: b becomes LRU
        cache.put((1, 0, 0, c), self._plane(), 80)
        assert cache.get((1, 0, 0, b)) is None  # b evicted, not a
        assert cache.get((1, 0, 0, a)) is not None
        assert cache.get((1, 0, 0, c)) is not None
        assert cache.stats()["evictions"] == 1

    def test_breaker_budget_enforced_and_released(self):
        breaker = CircuitBreaker(150)
        cache = FilterCache(max_bytes=1 << 20, breaker=breaker)
        cache.put((1, 0, 0, ("k", "a")), self._plane(), 100)
        assert breaker.used == 100
        # Second plane cannot fit alongside the first: the LRU evicts.
        cache.put((1, 0, 0, ("k", "b")), self._plane(), 100)
        assert breaker.used == 100
        assert cache.get((1, 0, 0, ("k", "a"))) is None
        # A plane larger than the whole budget is declined, not stored.
        assert not cache.put((1, 0, 0, ("k", "c")), self._plane(), 500)
        cache.clear()
        assert breaker.used == 0
        assert cache.stats()["entries"] == 0

    def test_external_breaker_pressure_does_not_wipe_cache(self):
        """When the HBM breaker rejects because OTHER labels hold the
        memory, eviction stops once the declined plane's own size has
        been freed — the rest of the warm cache survives instead of
        being wiped for a reservation that can never succeed."""
        breaker = CircuitBreaker(400)
        cache = FilterCache(max_bytes=1 << 20, breaker=breaker)
        cache.put((1, 0, 0, ("k", "a")), self._plane(), 60)
        cache.put((1, 0, 0, ("k", "b")), self._plane(), 60)
        # Recovery/settle-up pressure from another subsystem lands
        # unchecked and pushes usage over the limit: freeing our planes
        # cannot open headroom. Decline after freeing at most the
        # plane's own size (one eviction), keeping the other plane warm.
        breaker.add_unchecked(320)
        assert not cache.put((1, 0, 0, ("k", "c")), self._plane(), 60)
        assert cache.stats()["entries"] == 1
        assert breaker.used == 320 + 60

    def test_stale_generation_purged_on_store(self):
        cache = FilterCache(max_bytes=1 << 20)
        cache.put((1, 3, 10, ("k", "a")), self._plane(), 64)
        cache.put((1, 4, 11, ("k", "a")), self._plane(), 64)  # newer gen
        assert cache.get((1, 3, 10, ("k", "a"))) is None  # purged eagerly
        assert cache.get((1, 4, 11, ("k", "a"))) is not None


class TestCrossRefreshReuse:
    BODY = {
        "query": {
            "bool": {
                "must": [{"match": {"title": "w1 w2 w3"}}],
                "filter": [{"range": {"price": {"gte": 10, "lt": 90}}}],
            }
        }
    }

    def test_planes_survive_refresh_of_other_segments(self):
        """Solo keys scope on the segment-handle uid, NOT the engine
        generation: a refresh that only ADDS a segment leaves existing
        segments' planes resident and serving — the whole point of a
        filter cache under live write traffic."""
        eng = _build_engine(n_docs=200, seed=11, segments=1)
        cache = FilterCache(min_freq=1)
        svc = SearchService(eng, filter_cache=cache)
        svc.search(SearchRequest.from_json(self.BODY))
        assert cache.stats()["admissions"] >= 1
        keys_before = set(cache.keys())
        rng = random.Random(99)
        for i in range(20):
            eng.index(_doc(rng), f"new{i}")
        eng.refresh()  # new segment appended; old handles unchanged
        hits0 = cache.stats()["hit_count"]
        svc.search(SearchRequest.from_json(self.BODY))
        assert keys_before <= set(cache.keys())  # old planes still resident
        assert cache.stats()["hit_count"] > hits0  # and actually served

    def test_merged_away_segment_planes_pruned_on_store(self):
        """A merge mints a fresh handle uid; the dead handles' planes are
        pruned eagerly on the next store instead of lingering on the HBM
        breaker until LRU happens to reach them."""
        eng = _build_engine(n_docs=200, seed=12, segments=2)
        cache = FilterCache(min_freq=1)
        svc = SearchService(eng, filter_cache=cache)
        svc.search(SearchRequest.from_json(self.BODY))
        assert cache.stats()["entries"] == 2  # one plane per segment
        dead_keys = set(cache.keys())
        eng.force_merge(max_num_segments=1)
        svc.search(SearchRequest.from_json(self.BODY))
        live_keys = set(cache.keys())
        assert not (dead_keys & live_keys)  # old handles' planes pruned
        assert cache.stats()["entries"] == 1  # merged segment's plane only
        assert cache.stats()["bytes_resident"] > 0


class TestBatcherPlaneSharing:
    def test_coalesced_batchmates_share_one_plane(self):
        """Four same-filter batchmates in one search_many sweep use ONE
        cached plane (one cache entry; per-lane reuse counted), and each
        response equals its solo run bit-for-bit."""
        eng = _build_engine(n_docs=300, segments=1)
        cache = FilterCache(min_freq=1)
        svc = SearchService(eng, filter_cache=cache)
        plain = SearchService(eng)
        bodies = [
            {
                "query": {
                    "bool": {
                        "must": [{"match": {"title": f"w{j} w9"}}],
                        # term may win the lead fold (never substituted);
                        # the range filter is the shared cacheable plane.
                        "filter": [
                            {"term": {"tag": "red"}},
                            {"range": {"price": {"gte": 5}}},
                        ],
                    }
                },
                "size": 5,
            }
            for j in range(4)
        ]
        # Warm: admission happens on the first coalesced sweep already
        # (each batchmate records one sighting of the shared filters).
        svc.search_many([SearchRequest.from_json(b) for b in bodies])
        # TWO planes (term + range), each shared by all four batchmates —
        # never one entry per batchmate.
        assert cache.stats()["entries"] == 2
        reuse_before = cache.stats()["mask_reuse"]
        many = svc.search_many([SearchRequest.from_json(b) for b in bodies])
        # Every batchmate reuses both shared planes: 4 lanes × 2 planes.
        assert cache.stats()["mask_reuse"] >= reuse_before + 8
        assert cache.stats()["entries"] == 2
        solo = [plain.search(SearchRequest.from_json(b)) for b in bodies]
        for m, s in zip(many, solo):
            assert _hits_sig(m) == _hits_sig(s)


    def test_failed_launch_retry_records_no_second_sighting(self):
        """The micro-batcher's solo retry after a failed coalesced launch
        passes record_filter_usage=False — search_many already counted
        this request, and a retry that counted again would self-admit a
        one-off filter past min_freq=2 within a single user request."""
        eng = _build_engine(n_docs=200, segments=1)
        cache = FilterCache(min_freq=2)
        svc = SearchService(eng, filter_cache=cache)
        req = SearchRequest.from_json({
            "query": {
                "bool": {
                    "must": [{"match": {"title": "w1"}}],
                    "filter": [{"range": {"price": {"gte": 10, "lt": 90}}}],
                }
            }
        })
        key = collect_cacheable_filters(req.query)[0][2]
        svc.search_many([req])  # the coalesced attempt: ONE sighting
        svc.search(req, record_filter_usage=False)  # the batcher's retry
        assert not cache.should_admit(key)
        assert cache.stats()["entries"] == 0


class TestNormalization:
    def test_boost_and_order_insensitive(self):
        q1 = parse_query({"terms": {"tag": ["red", "blue"]}})
        q2 = parse_query({"terms": {"tag": ["blue", "red"], "boost": 3.0}})
        assert cacheable_filter_key(q1) == cacheable_filter_key(q2)

    def test_statistics_dependent_shapes_refused(self):
        assert cacheable_filter_key(parse_query({"match": {"title": "x"}})) is None
        assert (
            cacheable_filter_key(
                parse_query({"match_phrase": {"title": "a b"}})
            )
            is None
        )

    def test_pure_filter_bool_composite_cacheable(self):
        q = parse_query(
            {
                "bool": {
                    "filter": [{"term": {"tag": "red"}}],
                    "must_not": [{"range": {"price": {"lt": 10}}}],
                }
            }
        )
        assert cacheable_filter_key(q) is not None

    def test_collect_targets_top_level_filter_context_only(self):
        q = parse_query(
            {
                "bool": {
                    "must": [{"term": {"tag": "red"}}],
                    "filter": [
                        {"term": {"tag": "blue"}},
                        {"match": {"title": "x"}},
                    ],
                    "must_not": [{"exists": {"field": "price"}}],
                }
            }
        )
        got = collect_cacheable_filters(q)
        groups = {(g, i) for g, i, _k in got}
        # must clauses score -> never collected; the match filter is not
        # cacheable; the term filter and the exists exclusion are.
        assert groups == {("filter", 0), ("must_not", 0)}


class TestCostAndPlanner:
    def test_cached_mask_backend_registered_and_seeded(self):
        from elasticsearch_tpu.exec.cost import PlanFeatures, seed_ms
        from elasticsearch_tpu.exec.planner import ExecPlanner

        assert "cached_mask" in ExecPlanner.BACKENDS
        # Mask reuse removes the cached clauses' tiles from work_tiles,
        # so the masked seed undercuts the full-recompute device seed.
        full = seed_ms("device", PlanFeatures(n_docs=1_000_000, work_tiles=4096))
        masked = seed_ms(
            "cached_mask", PlanFeatures(n_docs=1_000_000, work_tiles=256)
        )
        assert masked < full
        assert np.isfinite(masked)

    def test_planner_counts_cached_mask_decisions(self):
        eng = _build_engine(n_docs=200, segments=1)
        from elasticsearch_tpu.exec.planner import ExecPlanner

        planner = ExecPlanner()
        cache = FilterCache(min_freq=1)
        svc = SearchService(eng, planner=planner, filter_cache=cache)
        body = {
            "query": {
                "bool": {
                    "must": [{"match": {"title": "w1 w2"}}],
                    # Two filters so one clause survives past the lead
                    # fold and masked execution actually engages.
                    "filter": [
                        {"term": {"tag": "red"}},
                        {"range": {"price": {"gte": 5}}},
                    ],
                }
            }
        }
        for _ in range(4):
            svc.search(SearchRequest.from_json(body))
        assert planner.decisions.get("cached_mask", 0) > 0


class TestRestAndObs:
    @pytest.fixture()
    def node(self):
        from elasticsearch_tpu.node import Node

        n = Node()
        n.create_index("idx", {"mappings": MAPPINGS})
        rng = random.Random(9)
        for i in range(200):
            n.index_doc("idx", _doc(rng), str(i))
        n.refresh("idx")
        yield n
        n.close()

    BODY = {
        "query": {
            "bool": {
                "must": [{"match": {"title": "w1 w2"}}],
                # Two filters: one may win the lead fold (which stays
                # inline by design); the other exercises the cache.
                "filter": [
                    {"term": {"tag": "red"}},
                    {"range": {"price": {"gte": 5}}},
                ],
            }
        }
    }

    def test_cache_clear_api_reports_counts(self, node):
        from elasticsearch_tpu.rest.server import RestServer

        rest = RestServer(node=node)
        import json

        for _ in range(3):
            status, _ = rest.dispatch(
                "POST", "/idx/_search", {}, json.dumps(self.BODY)
            )
            assert status == 200
        assert node.filter_cache.stats()["entries"] > 0
        status, out = rest.dispatch("POST", "/idx/_cache/clear", {}, "")
        assert status == 200
        assert out["cleared"]["filter_cache"] >= 1
        assert node.filter_cache.stats()["entries"] == 0
        # Bare /_cache/clear clears node-wide (idempotent here).
        status, out = rest.dispatch("POST", "/_cache/clear", {}, "")
        assert status == 200
        assert out["cleared"]["filter_cache"] == 0
        # Unknown concrete index 404s like the reference — alone AND as
        # an element of a comma list (a missing concrete name must not
        # silently succeed just because a real one rode along).
        status, _ = rest.dispatch("POST", "/nope/_cache/clear", {}, "")
        assert status == 404
        status, _ = rest.dispatch("POST", "/idx,nope/_cache/clear", {}, "")
        assert status == 404

    def test_nodes_stats_and_metrics_expose_filter_cache(self, node):
        for _ in range(3):
            node.search("idx", dict(self.BODY))
        section = node.nodes_stats()["nodes"][node.node_name]["indices"][
            "filter_cache"
        ]
        assert section["enabled"] is True
        assert section["admissions"] >= 1
        assert section["hit_count"] >= 1
        assert section["bytes_resident"] > 0
        text = node.metrics_text()
        assert "estpu_filter_cache_hits_total" in text
        assert "estpu_filter_cache_bytes_resident" in text

    def test_delete_index_drops_planes_and_breaker_bytes(self, node):
        for _ in range(3):
            node.search("idx", dict(self.BODY))
        assert node.filter_cache.stats()["entries"] > 0
        used_before = node.breaker.used
        node.delete_index("idx")
        # Orphaned planes would stay charged to the shared HBM breaker
        # forever (their engine uids can never be looked up again).
        assert node.filter_cache.stats()["entries"] == 0
        assert node.breaker.used < used_before

    def test_opt_out_env(self, monkeypatch):
        from elasticsearch_tpu.node import Node

        monkeypatch.setenv("ESTPU_FILTER_CACHE", "0")
        n = Node()
        try:
            n.create_index("idx", {"mappings": MAPPINGS})
            rng = random.Random(9)
            for i in range(100):
                n.index_doc("idx", _doc(rng), str(i))
            n.refresh("idx")
            out1 = n.search("idx", dict(self.BODY))
            out2 = n.search("idx", dict(self.BODY))
            assert out1["hits"]["total"] == out2["hits"]["total"]
            section = n.nodes_stats()["nodes"][n.node_name]["indices"][
                "filter_cache"
            ]
            assert section == {
                "enabled": False,
                "entries": 0,
                "bytes_resident": 0,
                "hit_count": 0,
                "miss_count": 0,
                "admissions": 0,
                "evictions": 0,
                "mask_reuse": 0,
                "budget_bytes": 0,
                "retunes": [],
            }
            # Clear-cache API still answers (zero filter planes).
            out = n.clear_cache("idx")
            assert out["cleared"]["filter_cache"] == 0
        finally:
            n.close()


class TestReplicatedClusterCache:
    """ISSUE 10 satellite: replicated ClusterNode per-shard searches
    consult the node filter cache, with the one-sighting-per-user-request
    admission contract held across the scatter."""

    BODY = {
        "query": {
            "bool": {
                "must": [{"match": {"title": "w1 w2"}}],
                "filter": [{"term": {"tag": "red"}}],
            }
        },
        "size": 20,
    }

    def _cluster_rest(self):
        import json

        from elasticsearch_tpu.rest.server import RestServer

        rest = RestServer(replication_nodes=3)
        rest.dispatch(
            "PUT",
            "/rc",
            {},
            json.dumps(
                {
                    "mappings": MAPPINGS,
                    "settings": {
                        # 4 shards over 3 nodes: pigeonhole guarantees
                        # some node serves >= 2 shard requests of ONE
                        # scatter — the shape where per-shard recording
                        # used to double-count sightings.
                        "index": {
                            "number_of_shards": 4,
                            "number_of_replicas": 2,
                        }
                    },
                }
            ),
        )
        rng = random.Random(5)
        for i in range(80):
            rest.dispatch(
                "PUT", f"/rc/_doc/{i}", {}, json.dumps(_doc(rng))
            )
        rest.dispatch("POST", "/rc/_refresh", {}, "")
        return rest

    def _freq_by_node(self, rest, key):
        return {
            nid: node.filter_cache._freq.get(key, 0)
            for nid, node in rest.cluster.nodes.items()
            if node.filter_cache is not None
        }

    def _entries_total(self, rest):
        return sum(
            len(node.filter_cache.keys())
            for node in rest.cluster.nodes.values()
            if node.filter_cache is not None
        )

    def test_scatter_counts_one_sighting_and_consults_cache(self):
        import json

        rest = self._cluster_rest()
        try:
            key = cacheable_filter_key(
                parse_query({"term": {"tag": "red"}})
            )
            status, first = rest.dispatch(
                "POST", "/rc/_search", {}, json.dumps(self.BODY)
            )
            assert status == 200
            # ONE user request = at most ONE sighting per node cache,
            # even for the node that served several shards of the
            # scatter (pre-fix, every shard request counted one and a
            # one-off filter self-admitted past min_freq=2 immediately).
            freqs = self._freq_by_node(rest, key)
            assert max(freqs.values()) == 1, freqs
            assert self._entries_total(rest) == 0
            status, second = rest.dispatch(
                "POST", "/rc/_search", {}, json.dumps(self.BODY)
            )
            assert status == 200
            # Second request reaches min_freq on the nodes serving the
            # scatter: planes admitted, results bit-identical.
            assert self._entries_total(rest) >= 1
            status, third = rest.dispatch(
                "POST", "/rc/_search", {}, json.dumps(self.BODY)
            )
            assert status == 200
            for a, b in ((first, second), (second, third)):
                assert [
                    (h["_id"], h["_score"]) for h in a["hits"]["hits"]
                ] == [(h["_id"], h["_score"]) for h in b["hits"]["hits"]]
                assert a["hits"]["total"] == b["hits"]["total"]
            # ... and the warm pass actually SERVED from the planes.
            hits = sum(
                node.filter_cache.stats()["hit_count"]
                for node in rest.cluster.nodes.values()
                if node.filter_cache is not None
            )
            assert hits >= 1
        finally:
            rest.close()

    def test_opt_out_env_disables_cluster_caches(self, monkeypatch):
        monkeypatch.setenv("ESTPU_FILTER_CACHE", "0")
        import json

        rest = self._cluster_rest()
        try:
            assert all(
                node.filter_cache is None
                for node in rest.cluster.nodes.values()
            )
            status, out = rest.dispatch(
                "POST", "/rc/_search", {}, json.dumps(self.BODY)
            )
            assert status == 200
        finally:
            rest.close()
