"""Nested field type, nested query (block join), and object flattening.

Reference semantics: index/mapper/NestedObjectMapper.java (hidden
sub-documents), index/query/NestedQueryBuilder.java:54 (score_mode join via
ToParentBlockJoinQuery), ObjectMapper/DocumentParser (object flattening,
arrays of objects flattening without a nested mapping).
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.oracle import OracleSearcher
from elasticsearch_tpu.search.service import SearchRequest, SearchService

MAPPINGS = {
    "properties": {
        "title": {"type": "text"},
        "user": {
            "type": "object",
            "properties": {"name": {"type": "keyword"}},
        },
        "comments": {
            "type": "nested",
            "properties": {
                "author": {"type": "keyword"},
                "body": {"type": "text"},
                "stars": {"type": "long"},
            },
        },
    }
}

DOCS = [
    {
        "title": "alpha post",
        "user": {"name": "ann"},
        "comments": [
            {"author": "bob", "body": "great post indeed", "stars": 5},
            {"author": "cat", "body": "terrible take", "stars": 1},
        ],
    },
    {
        "title": "beta post",
        "user": {"name": "bob"},
        "comments": [
            {"author": "bob", "body": "meh post", "stars": 3},
        ],
    },
    {
        "title": "gamma post",
        "user": {"name": "cat"},
        "comments": [
            {"author": "dan", "body": "great great great", "stars": 4},
            {"author": "bob", "body": "nope", "stars": 2},
        ],
    },
    {"title": "delta no comments", "user": {"name": "dan"}},
]


def test_mappings_nested_and_object_registration():
    m = Mappings.from_json(MAPPINGS)
    assert m.get("user.name").type == "keyword"
    assert m.get("comments").type == "nested"
    assert "comments" in m.nested
    scope = m.nested["comments"]
    assert scope.get("comments.author").type == "keyword"
    assert scope.get("comments.body").type == "text"
    # Round trip keeps the structure.
    again = Mappings.from_json(m.to_json())
    assert again.get("user.name").type == "keyword"
    assert "comments" in again.nested
    assert again.nested["comments"].get("comments.stars").type == "long"


def test_builder_produces_nested_blocks_and_flattens_objects():
    m = Mappings.from_json(MAPPINGS)
    b = SegmentBuilder(m)
    for i, d in enumerate(DOCS):
        b.add(d, f"d{i}")
    seg = b.build()
    assert seg.num_docs == 4
    # Object flattened: user.name searchable as keyword postings.
    assert "user.name" in seg.fields
    blk = seg.nested["comments"]
    assert blk.seg.num_docs == 5
    assert list(blk.parent_of) == [0, 0, 1, 2, 2]
    assert "comments.body" in blk.seg.fields
    assert "comments.stars" in blk.seg.doc_values


@pytest.fixture(scope="module")
def corpus():
    m = Mappings.from_json(MAPPINGS)
    b = SegmentBuilder(m)
    for i, d in enumerate(DOCS):
        b.add(d, f"d{i}")
    seg = b.build()
    dev = pack_segment(seg)
    return m, seg, dev


@pytest.mark.parametrize("mode", ["avg", "sum", "max", "min", "none"])
def test_nested_device_oracle_parity(corpus, mode):
    import jax

    m, seg, dev = corpus
    tree = bm25_device.segment_tree(dev)
    compiler = Compiler(dev.fields, dev.doc_values, m, nested=dev.nested)
    oracle = OracleSearcher(seg, m)
    query = parse_query(
        {
            "nested": {
                "path": "comments",
                "query": {"match": {"comments.body": "great post"}},
                "score_mode": mode,
            }
        }
    )
    c = compiler.compile(query)
    d_s, d_i, d_t = jax.device_get(
        bm25_device.execute(tree, c.spec, c.arrays, 4)
    )
    o_s, o_i, o_t = oracle.search(query, 4)
    n = len(o_i)
    assert list(d_i[:n]) == list(o_i), mode
    np.testing.assert_allclose(d_s[:n], o_s, rtol=2e-6)
    assert int(d_t) == o_t


def test_nested_with_filter_and_bool(corpus):
    import jax

    m, seg, dev = corpus
    tree = bm25_device.segment_tree(dev)
    compiler = Compiler(dev.fields, dev.doc_values, m, nested=dev.nested)
    oracle = OracleSearcher(seg, m)
    # Both conditions must hold on the SAME nested object: doc0 has a
    # 5-star comment by bob; doc2 has bob (2 stars) and 4 stars (dan) —
    # flattened semantics would wrongly match doc2.
    query = parse_query(
        {
            "nested": {
                "path": "comments",
                "query": {
                    "bool": {
                        "must": [{"term": {"comments.author": "bob"}}],
                        "filter": [{"range": {"comments.stars": {"gte": 4}}}],
                    }
                },
            }
        }
    )
    c = compiler.compile(query)
    d_s, d_i, d_t = jax.device_get(
        bm25_device.execute(tree, c.spec, c.arrays, 4)
    )
    assert int(d_t) == 1 and int(d_i[0]) == 0
    o_s, o_i, o_t = oracle.search(query, 4)
    assert o_t == 1 and list(o_i) == [0]


def test_nested_unmapped_path(corpus):
    m, seg, dev = corpus
    compiler = Compiler(dev.fields, dev.doc_values, m, nested=dev.nested)
    bad = parse_query(
        {"nested": {"path": "nope", "query": {"match_all": {}}}}
    )
    with pytest.raises(ValueError, match="nested"):
        compiler.compile(bad)
    ok = parse_query(
        {
            "nested": {
                "path": "nope",
                "query": {"match_all": {}},
                "ignore_unmapped": True,
            }
        }
    )
    assert compiler.compile(ok).spec == ("match_none",)


def test_nested_through_engine_and_rest_service(tmp_path):
    eng = Engine(Mappings.from_json(MAPPINGS), data_path=str(tmp_path))
    for i, d in enumerate(DOCS):
        eng.index(d, doc_id=f"d{i}")
    eng.refresh()
    svc = SearchService(eng)
    resp = svc.search(
        SearchRequest.from_json(
            {
                "query": {
                    "nested": {
                        "path": "comments",
                        "query": {"match": {"comments.body": "great"}},
                        "score_mode": "max",
                    }
                }
            }
        )
    )
    body = resp.to_json()
    ids = [h["_id"] for h in body["hits"]["hits"]]
    assert set(ids) == {"d0", "d2"}
    # Sources come back whole, nested objects intact.
    src = body["hits"]["hits"][0]["_source"]
    assert isinstance(src["comments"], list)
    # Object-flattened field is searchable.
    resp2 = svc.search(
        SearchRequest.from_json(
            {"query": {"term": {"user.name": "ann"}}}
        )
    )
    assert [h["_id"] for h in resp2.to_json()["hits"]["hits"]] == ["d0"]


def test_nested_durability_roundtrip(tmp_path):
    from elasticsearch_tpu.index.store import load_segment, persist_segment

    m = Mappings.from_json(MAPPINGS)
    b = SegmentBuilder(m)
    for i, d in enumerate(DOCS):
        b.add(d, f"d{i}")
    seg = b.build()
    persist_segment(str(tmp_path), 0, seg)
    loaded, live = load_segment(str(tmp_path), 0)
    assert live.all()
    blk = loaded.nested["comments"]
    assert blk.seg.num_docs == 5
    assert list(blk.parent_of) == [0, 0, 1, 2, 2]
    assert "comments.body" in blk.seg.fields
    # Loaded segment answers nested queries identically.
    o1 = OracleSearcher(seg, m)
    o2 = OracleSearcher(loaded, m)
    q = parse_query(
        {
            "nested": {
                "path": "comments",
                "query": {"match": {"comments.body": "great post"}},
            }
        }
    )
    s1, i1, t1 = o1.search(q, 4)
    s2, i2, t2 = o2.search(q, 4)
    assert list(i1) == list(i2) and t1 == t2
    np.testing.assert_array_equal(s1, s2)


def test_empty_array_is_a_noop():
    m = Mappings(properties={"title": {"type": "text"}})
    b = SegmentBuilder(m)
    b.add({"title": [], "tags": []}, "a")
    b.add({"title": "real doc"}, "b")
    seg = b.build()
    assert seg.num_docs == 2
    fld = seg.fields["title"]
    assert fld.doc_count == 1  # the empty-array doc indexed nothing


def test_rejected_write_leaves_no_ghost_nested_block():
    m = Mappings.from_json(MAPPINGS)
    b = SegmentBuilder(m)
    with pytest.raises(ValueError):
        b.add({"comments": [{"stars": "not-a-number"}]}, "bad")
    seg = b.build()
    assert seg.nested == {}  # no ghost empty block
    # And the engine stays mesh-eligible / nested-free.
    b2 = SegmentBuilder(m)
    with pytest.raises(ValueError):
        b2.add(
            {"comments": [{"stars": 4}, {"stars": "nope"}]}, "bad2"
        )
    assert b2.build().nested == {}


def test_concrete_value_for_object_field_rejected():
    m = Mappings.from_json(MAPPINGS)
    b = SegmentBuilder(m)
    with pytest.raises(ValueError, match="object"):
        b.add({"user": "bob"}, "x")
    with pytest.raises(ValueError, match="found an object"):
        b.add({"title": {"oops": 1}}, "y")


def test_to_json_lossless_for_deep_dynamic_and_nested_leaves():
    m = Mappings.from_json(MAPPINGS)
    b = SegmentBuilder(m)
    # Deep dynamic object + dynamic leaf under a nested path.
    b.add(
        {
            "a": {"b": {"c": 1}},
            "comments": [{"author": "x", "newfield": "hello"}],
        },
        "d0",
    )
    again = Mappings.from_json(m.to_json())
    assert again.get("a.b.c") is not None and again.get("a.b.c").type == "long"
    assert again.nested["comments"].get("comments.newfield") is not None


def test_nested_stats_aggregate_across_segments():
    """Same nested content in two segments scores identically (reader-level
    statistics — InternalSum-style drift guard for nested BM25)."""
    eng = Engine(Mappings.from_json(MAPPINGS))
    eng.index(
        {"title": "one", "comments": [{"body": "excellent analysis"}]},
        doc_id="a",
    )
    eng.refresh()  # segment 1
    eng.index(
        {"title": "two", "comments": [{"body": "excellent analysis"}]},
        doc_id="b",
    )
    eng.refresh()  # segment 2
    svc = SearchService(eng)
    resp = svc.search(
        SearchRequest.from_json(
            {
                "query": {
                    "nested": {
                        "path": "comments",
                        "query": {"match": {"comments.body": "excellent"}},
                    }
                }
            }
        )
    ).to_json()
    hits = resp["hits"]["hits"]
    assert len(hits) == 2
    assert hits[0]["_score"] == hits[1]["_score"], hits


def test_dynamic_object_flattening():
    m = Mappings()  # fully dynamic
    b = SegmentBuilder(m)
    b.add({"a": {"b": "hello world", "c": 7}}, "x")
    b.add({"a": {"b": "goodbye"}}, "y")
    # Array of objects without nested mapping FLATTENS (multi-values).
    b.add({"tags": [{"k": "red"}, {"k": "blue"}]}, "z")
    seg = b.build()
    assert "a.b" in seg.fields
    assert "a.c" in seg.doc_values
    oracle = OracleSearcher(seg, m)
    _, ids, total = oracle.search(
        parse_query({"match": {"a.b": "hello"}}), 3
    )
    assert total == 1 and list(ids) == [0]
    _, ids, total = oracle.search(
        parse_query({"match": {"tags.k": "blue"}}), 3
    )
    assert total == 1 and list(ids) == [2]
