"""Device observability (ISSUE 14): the HBM ledger consistency law, the
profiler capture API, per-launch timing, and the retrace census.

The consistency law under test: `device.hbm` ledger totals equal the sum
of each component's OWN byte stats — engine segments, filter-cache
planes, ANN tiles, packed planes, mesh snapshots — through refresh /
evict / `_cache/clear` / delete_index cycles, with zero drift between
the ledger and the breaker it writes through. A seeded shape-polymorphic
plan key must trip `estpu_device_retraces_total`.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu.common.breaker import CircuitBreaker
from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.obs import device as device_obs
from elasticsearch_tpu.obs.device import HbmLedger, LEDGER_LABELS
from elasticsearch_tpu.obs.metrics import DeviceInstruments, MetricsRegistry


def _make_node(monkeypatch, **env):
    for key, value in env.items():
        monkeypatch.setenv(key, str(value))
    return Node()


def _index_docs(node, index, n, seed=0, vectors=False, dims=8):
    rng = np.random.default_rng(seed)
    ops = []
    for i in range(n):
        doc = {
            "body": f"alpha beta {'gamma' if i % 3 else 'delta'} tok{i % 11}",
            "rank": float(rng.random()),
        }
        if vectors:
            doc["vec"] = [float(x) for x in rng.standard_normal(dims)]
        ops.append((str(i), doc))
    for doc_id, doc in ops:
        node.index_doc(index, doc, doc_id)
    node.refresh(index)


def _mappings(vectors=False, dims=8):
    props = {"body": {"type": "text"}, "rank": {"type": "float"}}
    if vectors:
        props["vec"] = {
            "type": "dense_vector",
            "dims": dims,
            "similarity": "l2_norm",
        }
    return {"mappings": {"properties": props}}


def _assert_ledger_law(node):
    """The consistency law: per-label ledger totals == component stats,
    breaker drift zero."""
    ledger = node.hbm_ledger
    seg_bytes = sum(
        e.device_bytes for svc in node.indices.values() for e in svc.engines
    )
    assert ledger.bytes_for("segment") == seg_bytes
    if node.filter_cache is not None:
        assert (
            ledger.bytes_for("filter_cache")
            == node.filter_cache.stats()["bytes_resident"]
        )
    if node.ann_cache is not None:
        assert (
            ledger.bytes_for("ann_cache")
            == node.ann_cache.stats()["bytes_resident"]
        )
    if node.packed_exec is not None:
        assert (
            ledger.bytes_for("packed_plane")
            == node.packed_exec.stats()["plane_bytes"]
        )
    mesh_bytes = 0
    for svc in node.indices.values():
        mv = getattr(svc.search, "mesh_view", None)
        if mv is not None:
            mesh_bytes += mv.plane_bytes
    assert ledger.bytes_for("mesh_plane") == mesh_bytes
    snap = ledger.snapshot()
    assert snap["breaker_drift_bytes"] == 0
    assert snap["total_bytes"] == sum(snap["by_label"].values())
    assert snap["high_watermark_bytes"] >= snap["total_bytes"]


# ---------------------------------------------------------------- ledger law


class TestLedgerConsistency:
    def test_segment_bytes_track_engines_through_refresh_and_merge(
        self, tmp_path, monkeypatch
    ):
        node = _make_node(monkeypatch)
        node.create_index("law", _mappings())
        for round_i in range(4):
            for i in range(20):
                node.index_doc(
                    "law",
                    {"body": f"w{i} alpha", "rank": 0.5},
                    f"r{round_i}-d{i}",
                )
            node.refresh("law")
            _assert_ledger_law(node)
        node.force_merge("law", 1)
        _assert_ledger_law(node)
        assert node.hbm_ledger.bytes_for("segment") > 0

    def test_fuzzed_refresh_evict_clear_delete_cycles(self, monkeypatch):
        """The acceptance-criteria fuzz: a random op sequence over
        refresh / filter-admission+eviction / `_cache/clear` /
        delete_index keeps the ledger bit-equal to component stats at
        every step."""
        node = _make_node(
            monkeypatch,
            ESTPU_FILTER_CACHE_MIN_FREQ=1,
            ESTPU_FILTER_CACHE_BYTES=4096,  # tiny: constant evictions
            ESTPU_ANN_MIN_DOCS=128,
        )
        rng = np.random.default_rng(5)
        node.create_index("fuzz", _mappings(vectors=True))
        _index_docs(node, "fuzz", 200, vectors=True)
        _assert_ledger_law(node)
        for step in range(60):
            op = rng.integers(0, 10)
            if op < 4:
                # Distinct range filters: admit (min_freq=1) and evict
                # under the 4KB budget.
                lo = round(float(rng.random()) * 0.8, 3)
                node.search(
                    "fuzz",
                    {
                        "query": {
                            "bool": {
                                "must": [{"match": {"body": "alpha"}}],
                                "filter": [
                                    {"range": {"rank": {"gte": lo}}}
                                ],
                            }
                        }
                    },
                )
            elif op < 6:
                node.search(
                    "fuzz",
                    {
                        "knn": {
                            "field": "vec",
                            "query_vector": [
                                float(x)
                                for x in rng.standard_normal(8)
                            ],
                            "k": 3,
                            "num_candidates": 32,
                        }
                    },
                )
            elif op < 8:
                node.index_doc(
                    "fuzz",
                    {"body": f"fresh alpha s{step}", "rank": 0.1},
                    f"new-{step}",
                )
                node.refresh("fuzz")
            elif op == 8:
                node.clear_cache("fuzz")
            else:
                node.delete_index("fuzz")
                assert node.hbm_ledger.total_bytes == 0
                node.create_index("fuzz", _mappings(vectors=True))
                _index_docs(node, "fuzz", 150, vectors=True, seed=step)
            _assert_ledger_law(node)

    def test_eviction_burst_race_stays_consistent(self, monkeypatch):
        """Threads hammering filter admissions under a tiny budget while
        another clears: the ledger must end bit-equal to the cache's own
        stats (the _drop_locked release path and the put path race)."""
        node = _make_node(
            monkeypatch,
            ESTPU_FILTER_CACHE_MIN_FREQ=1,
            ESTPU_FILTER_CACHE_BYTES=2048,
        )
        node.create_index("burst", _mappings())
        _index_docs(node, "burst", 150)
        errors: list[Exception] = []

        def hammer(seed):
            rng = np.random.default_rng(seed)
            try:
                for _ in range(30):
                    lo = round(float(rng.random()) * 0.9, 4)
                    node.search(
                        "burst",
                        {
                            "query": {
                                "bool": {
                                    "must": [{"match": {"body": "alpha"}}],
                                    "filter": [
                                        {"range": {"rank": {"gte": lo}}}
                                    ],
                                }
                            }
                        },
                    )
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        def clearer():
            try:
                for _ in range(10):
                    node.clear_cache("burst")
                    time.sleep(0.002)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=hammer, args=(s,)) for s in range(3)
        ] + [threading.Thread(target=clearer)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors
        _assert_ledger_law(node)
        assert node.hbm_ledger.bytes_for("filter_cache") >= 0

    def test_mesh_plane_bytes_register_and_release(self, monkeypatch):
        node = _make_node(monkeypatch)
        node.create_index(
            "meshed", {**_mappings(), "settings": {"number_of_shards": 2}}
        )
        _index_docs(node, "meshed", 60)
        mv = node.indices["meshed"].search.mesh_view
        assert mv is not None and mv.ledger is node.hbm_ledger
        # A plain search engages the SPMD path and builds the snapshot.
        node.search("meshed", {"query": {"match": {"body": "alpha"}}})
        assert mv.plane_bytes > 0
        _assert_ledger_law(node)
        # Refresh: the registration swaps to the new snapshot, no leak.
        node.index_doc("meshed", {"body": "alpha new", "rank": 0.2}, "x1")
        node.refresh("meshed")
        node.search("meshed", {"query": {"match": {"body": "alpha"}}})
        _assert_ledger_law(node)
        node.delete_index("meshed")
        assert node.hbm_ledger.bytes_for("mesh_plane") == 0
        assert node.hbm_ledger.total_bytes == 0

    def test_packed_plane_bytes_register(self, monkeypatch):
        node = _make_node(monkeypatch)
        if node.packed_exec is None:
            pytest.skip("packed executor disabled")
        for t in range(3):
            node.create_index(f"tenant{t}", _mappings())
            for i in range(8):
                node.index_doc(
                    f"tenant{t}", {"body": f"alpha t{t}", "rank": 0.1},
                    f"d{i}",
                )
            node.refresh(f"tenant{t}")
        out = node.packed_exec._ensure_plane(
            [node.indices[f"tenant{t}"] for t in range(3)]
        )
        assert out is not None
        assert node.hbm_ledger.bytes_for("packed_plane") > 0
        _assert_ledger_law(node)

    def test_hbm_gauges_exposed(self, monkeypatch):
        node = _make_node(monkeypatch)
        node.create_index("gauges", _mappings())
        _index_docs(node, "gauges", 30)
        text = node.metrics_text()
        assert 'estpu_hbm_bytes{index="gauges",label="segment"}' in text
        assert "estpu_hbm_high_watermark_bytes" in text

    def test_breaker_writes_through_any_ledger(self):
        ledger = HbmLedger()
        breaker = CircuitBreaker(10_000, ledger=ledger)
        breaker.add(1000, label="segment", scope=1)
        breaker.add_unchecked(500, label="segment", scope=1)
        breaker.release(300, label="segment", scope=1)
        assert ledger.bytes_for("segment", scope=1) == 1200
        assert breaker.used == 1200
        assert ledger.snapshot()["breaker_drift_bytes"] == 0
        # Decorated labels collapse onto their registered base label.
        breaker.add(100, label="segment[42 docs]", scope=2)
        assert ledger.bytes_for("segment") == 1300
        assert all(
            label in LEDGER_LABELS
            for label in ledger.snapshot()["by_label"]
        )


# ------------------------------------------------------------ disabled mode


class TestDisabledMode:
    def test_estpu_device_obs_zero_is_inert_but_serving(self, monkeypatch):
        node = _make_node(monkeypatch, ESTPU_DEVICE_OBS=0)
        assert node.device is None
        node.create_index("off", _mappings())
        _index_docs(node, "off", 40)
        resp = node.search("off", {"query": {"match": {"body": "alpha"}}})
        assert resp["hits"]["total"]["value"] > 0
        section = node.nodes_stats()["nodes"][node.node_name]["device"]
        assert section["enabled"] is False
        assert section["hbm"]["enabled"] is False
        assert section["hbm"]["total_bytes"] == 0


# ---------------------------------------------------------- retrace census


class TestRetraceCensus:
    def test_seeded_shape_polymorphic_key_trips_retraces(self):
        import jax
        import jax.numpy as jnp

        registry = MetricsRegistry()
        instruments = DeviceInstruments(registry)
        f = jax.jit(lambda x: x * 2 + 1)
        with instruments.timed("poly", ("poly", 1), "device") as t:
            t.dispatched(f(jnp.ones(3)))
        assert t.first and instruments.retraces_total() == 0
        # Same key, same shape: cache hit, still no retrace.
        with instruments.timed("poly", ("poly", 1), "device") as t:
            t.dispatched(f(jnp.ones(3)))
        assert t.compiles == 0
        assert instruments.retraces_total() == 0
        # The seeded defect: the SAME plan key launches a NEW shape — the
        # key failed to capture the varying dimension, XLA recompiles,
        # and the census flags it.
        before = device_obs.process_census()["retraces"]
        with instruments.timed("poly", ("poly", 1), "device") as t:
            t.dispatched(f(jnp.ones(7)))
        assert t.compiles >= 1
        assert instruments.retraces_total() >= 1
        assert (
            registry.value(
                "estpu_device_retraces_total", plan_class="poly"
            )
            >= 1
        )
        census = instruments.compile_census()
        assert "poly" in census["retraced_plan_classes"]
        assert device_obs.process_census()["retraces"] > before

    def test_census_surfaces_in_nodes_stats(self, monkeypatch):
        node = _make_node(monkeypatch)
        node.create_index("census", _mappings())
        _index_docs(node, "census", 30)
        node.search("census", {"query": {"match": {"body": "alpha"}}})
        section = node.nodes_stats()["nodes"][node.node_name]["device"]
        compile_section = section["compile"]
        assert "retraces_total" in compile_section
        assert "attributed_xla_compiles" in compile_section
        assert "retraced_plan_classes" in compile_section

    def test_launch_histograms_have_phases(self, monkeypatch):
        node = _make_node(monkeypatch)
        node.create_index("hist", _mappings())
        _index_docs(node, "hist", 50)
        # Same-shape concurrent searches coalesce through the batcher
        # into _device_batch's timed launch (queue/execute split).
        body = {"query": {"match": {"body": "alpha beta"}}}
        threads = [
            threading.Thread(
                target=lambda: node.search("hist", dict(body))
            )
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        node.search("hist", dict(body))
        family = node.metrics.family("estpu_launch_ms")
        assert family is not None and family[0] == "histogram"
        phases = {dict(key).get("phase") for key in family[2]}
        assert phases & {"queue", "execute", "total"}
        assert sum(snap["count"] for snap in family[2].values()) > 0


# -------------------------------------------------------------- profiler API


class TestProfilerCapture:
    def test_round_trip_produces_perfetto_dir_and_ring_stamp(
        self, monkeypatch, tmp_path
    ):
        node = _make_node(monkeypatch)
        node.create_index("prof", _mappings())
        _index_docs(node, "prof", 40)
        start = node.profiler_start(
            {"duration_s": 60, "trace_dir": str(tmp_path / "cap")}
        )
        assert start["acknowledged"] and start["trace_dir"]
        assert node.profiler_status()["running"] is True
        node.search("prof", {"query": {"match": {"body": "alpha"}}})
        stop = node.profiler_stop()
        assert stop["trace_dir"] == start["trace_dir"]
        files = [
            os.path.join(root, f)
            for root, _d, fs in os.walk(stop["trace_dir"])
            for f in fs
        ]
        assert any(f.endswith(".trace.json.gz") for f in files)
        assert node.profiler_status()["running"] is False
        # Capture window stamped into the obs trace ring.
        trace = node.get_trace(stop["trace_id"])
        names = {span["name"] for span in trace["spans"]}
        assert "profiler.capture" in names
        root_span = next(
            s for s in trace["spans"] if s["name"] == "profiler.capture"
        )
        assert root_span["tags"]["trace_dir"] == stop["trace_dir"]
        assert root_span["duration_ms"] >= stop["duration_ms"] * 0.5

    def test_double_start_409_and_stop_without_start_400(self, monkeypatch):
        node = _make_node(monkeypatch)
        node.profiler_start({"duration_s": 60})
        try:
            with pytest.raises(ApiError) as exc:
                node.profiler_start({"duration_s": 60})
            assert exc.value.status == 409
        finally:
            node.profiler_stop()
        with pytest.raises(ApiError) as exc:
            node.profiler_stop()
        assert exc.value.status == 400

    def test_bounded_duration_auto_stops(self, monkeypatch):
        node = _make_node(monkeypatch)
        node.profiler_start({"duration_s": 0.2})
        deadline = time.monotonic() + 10
        while (
            node.profiler_status()["running"]
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert node.profiler_status()["running"] is False
        # The watchdog's stop frees the single-flight slot.
        started = node.profiler_start({"duration_s": 60})
        assert started["acknowledged"]
        node.profiler_stop()

    def test_duration_clamped_to_bound_and_validated(self, monkeypatch):
        monkeypatch.setenv("ESTPU_PROFILER_MAX_S", "5")
        node = Node()
        out = node.profiler_start({"duration_s": 9999})
        assert out["max_duration_s"] == 5.0
        node.profiler_stop()
        with pytest.raises(ApiError) as exc:
            node.profiler_start({"duration_s": "soon"})
        assert exc.value.status == 400

    def test_rest_routes(self, monkeypatch, tmp_path):
        from elasticsearch_tpu.rest.server import RestServer

        rest = RestServer()
        status, out = rest.dispatch(
            "POST", "/_profiler/start", {}, "{}"
        )
        assert status == 200 and out["acknowledged"]
        status, out = rest.dispatch("POST", "/_profiler/start", {}, "{}")
        assert status == 409
        status, out = rest.dispatch("GET", "/_profiler", {}, "")
        assert status == 200 and out["running"] is True
        status, out = rest.dispatch("POST", "/_profiler/stop", {}, "")
        assert status == 200 and out["trace_dir"]
        status, out = rest.dispatch("POST", "/_profiler/stop", {}, "")
        assert status == 400
        rest.close()


# -------------------------------------------------------------- cat surfaces


class TestCatSurfaces:
    def test_cat_hbm_rows_match_ledger(self, monkeypatch):
        node = _make_node(monkeypatch)
        node.create_index("cat", _mappings())
        _index_docs(node, "cat", 40)
        rows = node.cat_hbm()
        seg_rows = [r for r in rows if r["label"] == "segment"]
        assert seg_rows and seg_rows[0]["index"] == "cat"
        assert int(seg_rows[0]["bytes"]) == node.hbm_ledger.bytes_for(
            "segment"
        )
        total_row = next(r for r in rows if r["label"] == "_total")
        assert int(total_row["bytes"]) == node.hbm_ledger.total_bytes
        assert int(total_row["high_watermark"]) >= int(total_row["bytes"])

    def test_cat_segments_device_bytes_column(self, monkeypatch):
        node = _make_node(monkeypatch)
        node.create_index("catseg", _mappings())
        _index_docs(node, "catseg", 40)
        rows = node.cat_segments()
        assert rows and all("device.bytes" in r for r in rows)
        total = sum(
            int(r["device.bytes"]) for r in rows if r["index"] == "catseg"
        )
        assert total == node.hbm_ledger.bytes_for("segment")

    def test_cat_hbm_rest_route(self, monkeypatch):
        from elasticsearch_tpu.rest.server import RestServer

        rest = RestServer()
        rest.node.create_index("viacat", _mappings())
        rest.node.index_doc("viacat", {"body": "alpha", "rank": 0.5}, "1")
        rest.node.refresh("viacat")
        status, rows = rest.dispatch(
            "GET", "/_cat/hbm", {"format": "json"}, ""
        )
        assert status == 200
        assert any(r["label"] == "segment" for r in rows)
        rest.close()


# ---------------------------------------------------------- profile response


class TestProfileDeviceBlock:
    def test_profile_true_carries_per_segment_device_block(
        self, monkeypatch
    ):
        node = _make_node(monkeypatch)
        node.create_index("pblock", _mappings())
        _index_docs(node, "pblock", 40)
        resp = node.search(
            "pblock",
            {"query": {"match": {"body": "alpha"}}, "profile": True},
        )
        segments = resp["profile"]["shards"][0]["searches"][0]["query"][0][
            "breakdown"
        ]["segments"]
        assert segments
        block = segments[0]["device"]
        assert {"launch_ms", "compile", "h2d_bytes"} <= set(block)
        assert block["launch_ms"] >= 0
        assert isinstance(block["compile"], bool)

    def test_knn_profile_device_block_has_split(self, monkeypatch):
        node = _make_node(monkeypatch, ESTPU_ANN_MIN_DOCS=64)
        node.create_index("pknn", _mappings(vectors=True))
        _index_docs(node, "pknn", 120, vectors=True)
        rng = np.random.default_rng(3)
        resp = node.search(
            "pknn",
            {
                "knn": {
                    "field": "vec",
                    "query_vector": [
                        float(x) for x in rng.standard_normal(8)
                    ],
                    "k": 3,
                    "num_candidates": 16,
                },
                "profile": True,
            },
        )
        segments = resp["profile"]["shards"][0]["searches"][0]["query"][0][
            "breakdown"
        ]["segments"]
        block = segments[0]["device"]
        assert {"launch_ms", "queue_ms", "execute_ms", "compile"} <= set(
            block
        )


# ----------------------------------------------------------- clustered stats


class TestClusterFan:
    def test_cluster_node_sections_carry_device_hbm(self):
        from elasticsearch_tpu.cluster import LocalCluster

        cluster = LocalCluster(n_nodes=2)
        try:
            cluster.create_index("fanned", n_shards=1, n_replicas=1)
            node = Node(replication=cluster)
            stats = node.nodes_stats()
            assert stats["_nodes"]["failed"] == 0
            member_sections = [
                section
                for name, section in stats["nodes"].items()
                if name != node.node_name
            ]
            assert member_sections
            for section in member_sections:
                hbm = section["device"]["hbm"]
                assert hbm["enabled"] is True
                assert hbm["total_bytes"] == sum(
                    hbm["by_label"].values()
                )
            # The coordinating front's cat view renders every member row.
            nodes_in_cat = {row["node"] for row in node.cat_hbm()}
            assert len(nodes_in_cat) >= 2
        finally:
            cluster.close()
