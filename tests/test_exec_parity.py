"""Exec-subsystem routing parity: backend choice never changes results.

The planner's hard invariant (exec/planner.py): every backend it may pick
— the device kernels, the block-max path, the CPU oracle — returns the
SAME top-k ids in the SAME order with fp32-equal scores and identical
totals. This fuzzes that invariant across randomized bool queries on a
multi-segment engine (so the oracle's pushed-down statistics scope is
actually exercised: segment-local stats differ from the engine aggregate),
plus batched-vs-solo parity through the micro-batcher's group executor.
"""

import numpy as np
import pytest

from elasticsearch_tpu.exec import CostModel, ExecPlanner
from elasticsearch_tpu.exec.cost import PlanFeatures
from elasticsearch_tpu.exec.planner import ast_signature, oracle_eligible
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.search.service import SearchRequest, SearchService

VOCAB = [
    "alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
    "hotel", "india", "juliet", "kilo", "lima", "mike", "november",
]
TAGS = ["red", "green", "blue", "cyan"]

MAPPINGS = Mappings(
    properties={
        "body": {"type": "text"},
        "title": {"type": "text"},
        "tag": {"type": "keyword"},
        "rank": {"type": "long"},
    }
)


class ForcedPlanner(ExecPlanner):
    """A planner that always routes to one backend (when eligible)."""

    def __init__(self, backend: str):
        super().__init__()
        self.forced = backend

    def decide(self, plan_class, candidates, feats=None):
        return self.forced if self.forced in candidates else candidates[0]


@pytest.fixture(scope="module")
def engine():
    rng = np.random.default_rng(11)
    eng = Engine(MAPPINGS)
    for i in range(400):
        eng.index(
            {
                "body": " ".join(rng.choice(VOCAB, rng.integers(3, 20))),
                "title": " ".join(rng.choice(VOCAB, rng.integers(1, 4))),
                "tag": str(rng.choice(TAGS)),
                "rank": int(rng.integers(0, 1000)),
            },
            f"d{i}",
        )
        if i % 120 == 119:
            eng.refresh()  # several segments: stats scope != segment scope
    eng.refresh()
    assert len(eng.segments) >= 3
    return eng


def random_bool_query(rng) -> dict:
    clauses: dict = {
        "must": [
            {
                "match": {
                    "body": " ".join(rng.choice(VOCAB, rng.integers(1, 4)))
                }
            }
        ]
    }
    if rng.random() < 0.5:
        clauses["filter"] = [{"term": {"tag": str(rng.choice(TAGS))}}]
    if rng.random() < 0.3:
        clauses.setdefault("filter", []).append(
            {"range": {"rank": {"gte": int(rng.integers(0, 800))}}}
        )
    if rng.random() < 0.3:
        clauses["must_not"] = [{"term": {"tag": str(rng.choice(TAGS))}}]
    if rng.random() < 0.3:
        clauses["should"] = [
            {"match": {"title": str(rng.choice(VOCAB))}}
        ]
    return {"bool": clauses}


def _hits(resp):
    return (
        [h.doc_id for h in resp.hits],
        np.array(
            [h.score if h.score is not None else 0.0 for h in resp.hits],
            dtype=np.float32,
        ),
        resp.total,
    )


def test_fuzz_oracle_routing_never_changes_top10(engine):
    """>= 50 randomized bool queries: device path vs forced-oracle path
    must agree on ids, order, fp32 scores, and totals."""
    rng = np.random.default_rng(23)
    svc_device = SearchService(engine, planner=None)
    svc_oracle = SearchService(engine, planner=ForcedPlanner("oracle"))
    checked = 0
    for _ in range(60):
        body = {"query": random_bool_query(rng), "size": 10}
        request = SearchRequest.from_json(body)
        assert oracle_eligible(request.query)
        dev = svc_device.search(SearchRequest.from_json(body))
        orc = svc_oracle.search(request)
        d_ids, d_scores, d_total = _hits(dev)
        o_ids, o_scores, o_total = _hits(orc)
        assert o_ids == d_ids, f"routing changed hit ids for {body}"
        np.testing.assert_allclose(
            o_scores, d_scores, rtol=1e-6, atol=1e-6,
            err_msg=f"routing changed scores for {body}",
        )
        assert o_total == d_total
        checked += 1
    assert checked >= 50


def test_fuzz_blockmax_routing_exact_topk(engine):
    """Pure term-disjunction shapes with untracked totals may route to
    block-max: top-k ids/order/scores must be exact (totals are gte)."""
    rng = np.random.default_rng(29)
    svc_device = SearchService(engine, planner=None)
    svc_block = SearchService(engine, planner=ForcedPlanner("blockmax"))
    for _ in range(12):
        body = {
            "query": {
                "match": {
                    "body": " ".join(rng.choice(VOCAB, rng.integers(2, 5)))
                }
            },
            "size": 10,
            "track_total_hits": False,
        }
        dev = svc_device.search(SearchRequest.from_json(body))
        blk = svc_block.search(SearchRequest.from_json(body))
        d_ids, d_scores, _ = _hits(dev)
        b_ids, b_scores, _ = _hits(blk)
        assert b_ids == d_ids
        np.testing.assert_allclose(b_scores, d_scores, rtol=1e-6, atol=1e-6)


def test_oracle_respects_deletes():
    """The oracle backend must honor the live mask exactly like the
    device kernels: deleted docs leave hits AND totals."""
    eng = Engine(MAPPINGS)
    for i in range(20):
        eng.index({"body": "alpha common", "rank": i}, f"d{i}")
    eng.refresh()
    eng.delete("d3")
    eng.delete("d7")
    eng.refresh()
    body = {"query": {"match": {"body": "common"}}, "size": 20}
    dev = SearchService(eng, planner=None).search(
        SearchRequest.from_json(body)
    )
    orc = SearchService(eng, planner=ForcedPlanner("oracle")).search(
        SearchRequest.from_json(body)
    )
    d_ids, d_scores, d_total = _hits(dev)
    o_ids, o_scores, o_total = _hits(orc)
    assert o_ids == d_ids and o_total == d_total == 18
    np.testing.assert_allclose(o_scores, d_scores, rtol=1e-6, atol=1e-6)
    assert "d3" not in o_ids and "d7" not in o_ids


def test_batched_vs_solo_single_shard(engine):
    """The micro-batcher's coalesced group executor (search_many) must be
    result-identical to per-request search()."""
    rng = np.random.default_rng(31)
    svc = SearchService(engine, planner=None)
    bodies = [
        {"query": random_bool_query(rng), "size": 10} for _ in range(10)
    ] + [
        {
            "query": {
                "match": {
                    "body": " ".join(rng.choice(VOCAB, rng.integers(1, 4)))
                }
            },
            "size": 7,
        }
        for _ in range(10)
    ]
    requests = [SearchRequest.from_json(b) for b in bodies]
    batched = svc.search_many(requests)
    for body, got in zip(bodies, batched):
        assert not isinstance(got, Exception)
        solo = svc.search(SearchRequest.from_json(body))
        g_ids, g_scores, g_total = _hits(got)
        s_ids, s_scores, s_total = _hits(solo)
        assert g_ids == s_ids, f"batched changed ids for {body}"
        np.testing.assert_allclose(g_scores, s_scores, rtol=1e-6, atol=1e-6)
        assert g_total == s_total
        assert got.max_score == pytest.approx(
            solo.max_score, rel=1e-6
        ) or got.max_score == solo.max_score


def test_batched_vs_solo_sharded_node():
    """Coordinator search_many (per-shard coalesced launches + merge)
    equals the solo scatter/merge path, including can_match skips."""
    rng = np.random.default_rng(37)
    node = Node()
    node.exec_batcher = None  # drive search_many explicitly below
    node.create_index(
        "fz",
        {
            "settings": {"index": {"number_of_shards": 3}},
            "mappings": MAPPINGS.to_json(),
        },
    )
    for i in range(150):
        node.index_doc(
            "fz",
            {
                "body": " ".join(rng.choice(VOCAB, rng.integers(3, 15))),
                "tag": str(rng.choice(TAGS)),
                "rank": int(rng.integers(0, 100)),
            },
            f"d{i}",
        )
    node.refresh("fz")
    coord = node.indices["fz"].search
    # Compare against the host-loop coordinator (the batched path's
    # twin); the SPMD mesh path accounts can_match skips differently.
    coord.mesh_view = None
    bodies = [
        {"query": random_bool_query(rng), "size": 10} for _ in range(6)
    ]
    requests = [SearchRequest.from_json(b) for b in bodies]
    batched = coord.search_many(requests)
    for body, got in zip(bodies, batched):
        assert not isinstance(got, Exception)
        solo = coord.search(SearchRequest.from_json(body))
        assert _hits(got)[0] == _hits(solo)[0]
        np.testing.assert_allclose(
            _hits(got)[1], _hits(solo)[1], rtol=1e-6, atol=1e-6
        )
        assert _hits(got)[2] == _hits(solo)[2]
        assert got.skipped == solo.skipped
    node.close()


def test_planner_learns_from_ewma():
    """After MIN_OBS explorations per backend the planner exploits the
    minimum-EWMA backend; new observations keep adapting it."""
    planner = ExecPlanner(CostModel())
    cls = (("terms", "body", 8, 4), 10)
    feats = PlanFeatures(n_docs=100_000, work_tiles=8)
    cands = ["device", "oracle"]
    for _ in range(planner.MIN_OBS):
        planner.cost.observe(cls, "device", 0.200)
        planner.cost.observe(cls, "oracle", 0.002)
    assert planner.decide(cls, cands, feats) == "oracle"
    # Drift: oracle degrades, device improves — the decision follows.
    for _ in range(40):
        planner.cost.observe(cls, "oracle", 0.500)
        planner.cost.observe(cls, "device", 0.001)
    assert planner.decide(cls, cands, feats) == "device"


def test_seeded_costs_route_small_corpus_to_oracle():
    """Before any calibration, the seeds alone must route tiny corpora
    (BENCH cfg1 shape) off the launch-dominated device path."""
    from elasticsearch_tpu.exec.cost import seed_ms

    tiny = PlanFeatures(n_docs=5_000, work_tiles=4)
    big = PlanFeatures(n_docs=1_000_000, work_tiles=512)
    assert seed_ms("oracle", tiny) < seed_ms("device", tiny)
    assert seed_ms("device", big) < seed_ms("oracle", big)


def test_ast_signature_groups_shapes():
    from elasticsearch_tpu.query.dsl import parse_query

    a = parse_query({"match": {"body": "alpha bravo"}})
    b = parse_query({"match": {"body": "kilo lima"}})
    c = parse_query({"match": {"title": "alpha bravo"}})
    d = parse_query(
        {"bool": {"must": [{"match": {"body": "alpha bravo"}}]}}
    )
    assert ast_signature(a) == ast_signature(b)
    assert ast_signature(a) != ast_signature(c)
    assert ast_signature(a) != ast_signature(d)


def test_profile_and_nodes_stats_surface_decisions():
    node = Node()
    node.create_index(
        "pf", {"mappings": {"properties": {"body": {"type": "text"}}}}
    )
    for i in range(25):
        node.index_doc("pf", {"body": f"alpha common w{i % 4}"}, f"d{i}")
    node.refresh("pf")
    for _ in range(3):
        node.search("pf", {"query": {"match": {"body": "alpha"}}})
    out = node.search(
        "pf", {"query": {"match": {"body": "alpha"}}, "profile": True}
    )
    shard = out["profile"]["shards"][0]
    assert shard["backends"], "profile must show the chosen backend"
    assert set(shard["backends"]) <= {"device", "blockmax", "oracle"}
    bd = out["took_breakdown"]
    assert set(bd) == {"plan_ms", "queue_ms", "execute_ms", "reduce_ms"}
    stats = node.nodes_stats()["nodes"][node.node_name]
    decisions = stats["exec"]["planner"]["decisions"]
    assert sum(decisions.values()) > 0
    assert "ewma" in stats["exec"]["planner"]
    assert "occupancy_histogram" in stats["exec"]["batcher"]
    assert "queue_wait_p50_ms" in stats["exec"]["batcher"]
    assert "evictions" in stats["indices"]["request_cache"]
    node.close()
