"""Shard request cache + HBM circuit breaker.

Reference: indices/IndicesRequestCache.java:57 (cache size=0 requests,
invalidate on refresh), indices/breaker/HierarchyCircuitBreakerService.
java:51 (reject allocations over the budget with 429).
"""

import json

import pytest

from elasticsearch_tpu.common.breaker import BreakerError, CircuitBreaker
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.server import RestServer

MAPPINGS = {"properties": {"t": {"type": "text"}, "n": {"type": "long"}}}


def seed(node, index="c", n=30, **kw):
    node.create_index(index, {"mappings": MAPPINGS, **kw})
    for i in range(n):
        node.index_doc(index, {"t": f"w{i % 3} common", "n": i}, f"d{i}")
    node.refresh(index)


def test_request_cache_hits_and_invalidation():
    node = Node()
    seed(node)
    body = {"size": 0, "aggs": {"mx": {"max": {"field": "n"}}}}
    r1 = node.search("c", body)
    misses0 = node.request_cache.misses
    r2 = node.search("c", body)
    # A hit serves the cached RESULT but reports an honest took for this
    # request (the cache lookup), never the cached execution's timing.
    assert r2.pop("took") >= 1
    r1.pop("took")
    assert r2 == r1
    assert node.request_cache.hits == 1
    assert node.request_cache.misses == misses0
    # a write + refresh bumps the generation: new key, fresh execution
    node.index_doc("c", {"t": "w0", "n": 999}, "new", refresh=True)
    r3 = node.search("c", body)
    assert r3["aggregations"]["mx"]["value"] == 999.0
    assert r3["hits"]["total"]["value"] == 31


def test_request_cache_only_size_zero_and_opt_out():
    node = Node()
    seed(node)
    with_hits = {"query": {"match_all": {}}, "size": 5}
    node.search("c", with_hits)
    node.search("c", with_hits)
    assert node.request_cache.hits == 0  # size>0 never caches
    body = {"size": 0}
    node.search("c", body, request_cache=False)
    node.search("c", body, request_cache=False)
    assert node.request_cache.hits == 0


def test_request_cache_returns_fresh_objects():
    node = Node()
    seed(node)
    body = {"size": 0, "aggs": {"mx": {"max": {"field": "n"}}}}
    r1 = node.search("c", body)
    r1["aggregations"]["mx"]["value"] = -1  # caller mutates its copy
    r2 = node.search("c", body)
    assert r2["aggregations"]["mx"]["value"] == 29.0


def test_breaker_rejects_oversized_refresh():
    breaker = CircuitBreaker(limit_bytes=8_000)
    engine = Engine(Mappings.from_json(MAPPINGS), breaker=breaker)
    for i in range(40):
        engine.index({"t": f"word{i} filler text here", "n": i}, f"d{i}")
    with pytest.raises(BreakerError):
        engine.refresh()
    # buffer intact: raising the limit lets the same docs land
    breaker.limit = 50 << 20
    engine.refresh()
    assert engine.num_docs == 40
    assert breaker.used == engine.device_bytes > 0


def test_breaker_accounting_through_merge_and_close():
    breaker = CircuitBreaker(limit_bytes=100 << 20)
    engine = Engine(
        Mappings.from_json(MAPPINGS), breaker=breaker, max_segments=100
    )
    for i in range(60):
        engine.index({"t": f"w{i % 5}", "n": i}, f"d{i}")
        if i % 10 == 9:
            engine.refresh()
    before = breaker.used
    assert before == engine.device_bytes
    engine.force_merge(1)
    assert breaker.used == engine.device_bytes
    assert len(engine.segments) == 1
    engine.close()
    assert breaker.used == 0


def test_breaker_429_over_rest():
    node = Node(breaker_limit_bytes=8_000)
    rest = RestServer(node=node)
    status, _ = rest.dispatch(
        "PUT", "/b", {}, json.dumps({"mappings": MAPPINGS})
    )
    assert status == 200
    lines = []
    for i in range(60):
        lines.append(json.dumps({"index": {"_id": f"x{i}"}}))
        lines.append(json.dumps({"t": f"token{i} more words here", "n": i}))
    # Writes with ?refresh=true stay ACKED under HBM pressure (durably
    # applied; the refresh is skipped — a 429 after the ack would invite
    # duplicating retries). The explicit refresh API surfaces the breaker.
    status, resp = rest.dispatch(
        "POST", "/b/_bulk", {"refresh": "true"}, "\n".join(lines)
    )
    assert status == 200 and not resp["errors"]
    status, resp = rest.dispatch("POST", "/b/_refresh", {}, "")
    assert status == 429
    assert resp["error"]["type"] == "circuit_breaking_exception"
    status, resp = rest.dispatch(
        "PUT", "/b/_doc/solo", {"refresh": "true"}, json.dumps({"t": "hi"})
    )
    assert status in (200, 201)
    assert resp["forced_refresh"] is False
    status, stats = rest.dispatch("GET", "/_stats", {}, "")
    assert stats["breakers"]["hbm"]["tripped"] >= 1


def test_recovery_loads_despite_breaker(tmp_path):
    node = Node(data_path=str(tmp_path), breaker_limit_bytes=100 << 20)
    seed(node, index="r", n=40)
    node.flush("r")
    node.close()
    # Restart with a tiny budget: committed data must still load.
    node2 = Node(data_path=str(tmp_path), breaker_limit_bytes=1_000)
    assert node2.get_index("r").num_docs == 40
    r = node2.search("r", {"query": {"match_all": {}}, "size": 0})
    assert r["hits"]["total"]["value"] == 40
    assert node2.breaker.used > node2.breaker.limit  # accounted, not rejected
    node2.close()


def test_stats_expose_cache_and_memory():
    node = Node()
    seed(node)
    node.search("c", {"size": 0})
    node.search("c", {"size": 0})
    s = node.stats()
    assert s["_all"]["primaries"]["request_cache"]["hit_count"] == 1
    seg = s["indices"]["c"]["primaries"]["segments"]
    assert seg["count"] >= 1 and seg["device_memory_in_bytes"] > 0
    assert s["breakers"]["hbm"]["estimated_size_in_bytes"] > 0
