"""mapper-extras field types, multi_match, and the percolator.

Reference: RankFeatureFieldMapper/RankFeatureQueryBuilder,
RankFeaturesFieldMapper, TokenCountFieldMapper,
SearchAsYouTypeFieldMapper, MultiMatchQueryBuilder,
percolator module (PercolatorFieldMapper, PercolateQueryBuilder).
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.index.tiles import pack_segment
from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.ops import bm25_device
from elasticsearch_tpu.query.compile import Compiler
from elasticsearch_tpu.query.dsl import parse_query
from elasticsearch_tpu.search.oracle import OracleSearcher


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path))
    n.create_index(
        "docs",
        {
            "mappings": {
                "properties": {
                    "title": {
                        "type": "text",
                        "fields": {"length": {"type": "token_count"}},
                    },
                    "pagerank": {"type": "rank_feature"},
                    "features": {"type": "rank_features"},
                    "sayt": {"type": "search_as_you_type"},
                }
            }
        },
    )
    docs = [
        {"title": "quick brown fox", "pagerank": 8.0,
         "features": {"politics": 3.0}, "sayt": "quick brown fox"},
        {"title": "lazy dog", "pagerank": 2.0,
         "features": {"politics": 1.0, "sports": 9.0}, "sayt": "lazy dog"},
        {"title": "quick start guide for foxes and dogs", "pagerank": 5.0,
         "sayt": "quick start guide"},
    ]
    for i, d in enumerate(docs):
        n.index_doc("docs", d, str(i))
    n.refresh("docs")
    return n


def test_token_count_field(node):
    out = node.search(
        "docs", {"query": {"range": {"title.length": {"gte": 3}}}, "size": 10}
    )
    assert sorted(h["_id"] for h in out["hits"]["hits"]) == ["0", "2"]
    out = node.search(
        "docs",
        {"size": 0, "aggs": {"len": {"stats": {"field": "title.length"}}}},
    )
    assert out["aggregations"]["len"]["max"] == 7.0


def test_rank_feature_query(node):
    out = node.search(
        "docs",
        {
            "query": {
                "rank_feature": {
                    "field": "pagerank",
                    "saturation": {"pivot": 4.0},
                }
            },
            "size": 10,
        },
    )
    hits = out["hits"]["hits"]
    assert [h["_id"] for h in hits] == ["0", "2", "1"]
    # saturation: v/(v+pivot)
    assert abs(hits[0]["_score"] - 8.0 / 12.0) < 1e-6
    # log and sigmoid variants run too
    out = node.search(
        "docs",
        {
            "query": {
                "rank_feature": {
                    "field": "pagerank",
                    "log": {"scaling_factor": 1.0},
                }
            }
        },
    )
    assert out["hits"]["hits"][0]["_id"] == "0"
    with pytest.raises(ApiError):
        node.search(
            "docs",
            {"query": {"rank_feature": {"field": "pagerank"}}},
        )  # saturation without explicit pivot


def test_rank_features_flatten(node):
    out = node.search(
        "docs",
        {
            "query": {
                "rank_feature": {
                    "field": "features.sports",
                    "saturation": {"pivot": 1.0},
                }
            }
        },
    )
    assert [h["_id"] for h in out["hits"]["hits"]] == ["1"]


def test_search_as_you_type(node):
    # Trailing partial token matches via the _index_prefix subfield.
    out = node.search(
        "docs",
        {
            "query": {
                "multi_match": {
                    "query": "quick bro",
                    "type": "bool_prefix",
                    "fields": ["sayt", "sayt._index_prefix"],
                }
            }
        },
    )
    ids = [h["_id"] for h in out["hits"]["hits"]]
    assert ids[0] == "0"
    # 2-gram shingle field matches adjacent word pairs.
    out = node.search(
        "docs", {"query": {"match": {"sayt._2gram": "quick brown"}}}
    )
    assert [h["_id"] for h in out["hits"]["hits"]] == ["0"]


def test_multi_match_best_and_most_fields(node):
    out = node.search(
        "docs",
        {
            "query": {
                "multi_match": {
                    "query": "quick fox",
                    "fields": ["title^2", "sayt"],
                }
            }
        },
    )
    assert out["hits"]["hits"][0]["_id"] == "0"
    out = node.search(
        "docs",
        {
            "query": {
                "multi_match": {
                    "query": "quick",
                    "type": "most_fields",
                    "fields": ["title", "sayt"],
                }
            }
        },
    )
    assert {h["_id"] for h in out["hits"]["hits"]} == {"0", "2"}
    with pytest.raises(ApiError):
        node.search(
            "docs",
            {"query": {"multi_match": {"query": "x", "fields": [],}}},
        )


def test_match_bool_prefix_direct(node):
    out = node.search(
        "docs",
        {"query": {"match_bool_prefix": {"sayt._index_prefix": "qui"}}},
    )
    assert {h["_id"] for h in out["hits"]["hits"]} == {"0", "2"}


def test_rank_feature_device_oracle_parity():
    m = Mappings(properties={"f": {"type": "rank_feature"},
                             "t": {"type": "text"}})
    b = SegmentBuilder(m)
    rng = np.random.default_rng(3)
    for i in range(300):
        b.add({"t": "x", "f": float(rng.random() * 10)}, str(i))
    seg = b.build()
    dev = pack_segment(seg)
    tree = bm25_device.segment_tree(dev)
    for body in (
        {"rank_feature": {"field": "f", "saturation": {"pivot": 2.5}}},
        {"rank_feature": {"field": "f", "log": {"scaling_factor": 2.0}}},
        {"rank_feature": {"field": "f",
                          "sigmoid": {"pivot": 3.0, "exponent": 2.0}}},
    ):
        import jax

        q = parse_query(body)
        c = Compiler(dev.fields, dev.doc_values, m).compile(q)
        d_s, d_i, d_t = jax.device_get(
            bm25_device.execute(tree, c.spec, c.arrays, 10)
        )
        o_s, o_i, o_t = OracleSearcher(seg, m).search(q, 10)
        n = len(o_i)
        assert list(d_i[:n]) == list(o_i), body
        np.testing.assert_allclose(d_s[:n], o_s, rtol=2e-6)
        assert int(d_t) == o_t


@pytest.fixture()
def percolator_node(tmp_path):
    n = Node(data_path=str(tmp_path))
    n.create_index(
        "alerts",
        {
            "mappings": {
                "properties": {
                    "query": {"type": "percolator"},
                    "message": {"type": "text"},
                    "severity": {"type": "long"},
                }
            }
        },
    )
    n.index_doc("alerts", {"query": {"match": {"message": "fire"}}}, "q-fire")
    n.index_doc(
        "alerts",
        {"query": {"bool": {"must": [{"match": {"message": "flood"}}],
                            "filter": [{"range": {"severity": {"gte": 3}}}]}}},
        "q-flood",
    )
    n.index_doc("alerts", {"query": {"match_all": {}}}, "q-all")
    n.refresh("alerts")
    return n


def test_percolate(percolator_node):
    n = percolator_node
    out = n.search(
        "alerts",
        {
            "query": {
                "percolate": {
                    "field": "query",
                    "document": {"message": "fire in the server room"},
                }
            }
        },
    )
    assert sorted(h["_id"] for h in out["hits"]["hits"]) == ["q-all", "q-fire"]
    out = n.search(
        "alerts",
        {
            "query": {
                "percolate": {
                    "field": "query",
                    "document": {"message": "flood warning", "severity": 5},
                }
            }
        },
    )
    assert sorted(h["_id"] for h in out["hits"]["hits"]) == [
        "q-all", "q-flood",
    ]
    # severity below the stored filter: q-flood must not fire.
    out = n.search(
        "alerts",
        {
            "query": {
                "percolate": {
                    "field": "query",
                    "document": {"message": "flood warning", "severity": 1},
                }
            }
        },
    )
    assert sorted(h["_id"] for h in out["hits"]["hits"]) == ["q-all"]


def test_percolate_multiple_documents(percolator_node):
    out = percolator_node.search(
        "alerts",
        {
            "query": {
                "percolate": {
                    "field": "query",
                    "documents": [
                        {"message": "all quiet"},
                        {"message": "fire alarm"},
                    ],
                }
            }
        },
    )
    assert sorted(h["_id"] for h in out["hits"]["hits"]) == ["q-all", "q-fire"]


def test_percolator_validates_stored_queries(percolator_node):
    with pytest.raises(ApiError):
        percolator_node.index_doc(
            "alerts", {"query": {"not_a_query": {}}}, "bad"
        )
    with pytest.raises(ApiError):
        percolator_node.search(
            "alerts",
            {"query": {"percolate": {"field": "message",
                                     "document": {"x": 1}}}},
        )


def test_percolator_survives_restart(percolator_node, tmp_path):
    percolator_node.flush("alerts")
    n2 = Node(data_path=str(tmp_path))
    out = n2.search(
        "alerts",
        {
            "query": {
                "percolate": {
                    "field": "query",
                    "document": {"message": "fire drill"},
                }
            }
        },
    )
    assert sorted(h["_id"] for h in out["hits"]["hits"]) == ["q-all", "q-fire"]
