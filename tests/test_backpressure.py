"""Indexing backpressure + HTTP content limits + scroll stat pinning.

Reference: index/IndexingPressure.java (coordinating byte budget, 429),
http.max_content_length (413), search/SearchService reader contexts
(point-in-time statistics).
"""

import numpy as np
import pytest

from elasticsearch_tpu.common.indexing_pressure import (
    IndexingPressure,
    IndexingPressureRejected,
)
from elasticsearch_tpu.node import ApiError, Node


def test_indexing_pressure_acquire_release():
    p = IndexingPressure(limit_bytes=100)
    with p.acquire(60):
        assert p.current_bytes == 60
        with pytest.raises(IndexingPressureRejected):
            with p.acquire(50):
                pass
        with p.acquire(40):
            assert p.current_bytes == 100
    assert p.current_bytes == 0
    assert p.rejections == 1
    assert p.total_bytes == 100


def test_bulk_rejects_over_budget(tmp_path, monkeypatch):
    monkeypatch.setenv("ESTPU_INDEXING_PRESSURE_BYTES", "200")
    n = Node(data_path=str(tmp_path))
    small = '{"index": {"_index": "i", "_id": "1"}}\n{"a": "b"}\n'
    n.bulk(small)  # fits
    big = small * 50  # > 200 bytes
    with pytest.raises(ApiError) as e:
        n.bulk(big)
    assert e.value.status == 429
    assert "rejected execution" in e.value.reason
    # Budget released after the rejection and after success: small works.
    n.bulk(small)
    stats = n.nodes_info()["nodes"][n.node_name]["indexing_pressure"]
    assert stats["memory"]["total"]["coordinating_rejections"] == 1
    assert (
        stats["memory"]["current"][
            "combined_coordinating_and_primary_in_bytes"
        ]
        == 0
    )


def test_scroll_pins_statistics(tmp_path):
    """A pinned scroll's scores must not move when later writes shift
    shard-level avgdl enough to repack impacts in place."""
    n = Node(data_path=str(tmp_path))
    n.create_index("s", {"mappings": {"properties": {"t": {"type": "text"}}}})
    for i in range(20):
        n.index_doc("s", {"t": f"alpha beta word{i}"}, str(i))
    n.refresh("s")
    first = n.search(
        "s", {"query": {"match": {"t": "alpha"}}, "size": 5}, scroll="1m"
    )
    page1_scores = [h["_score"] for h in first["hits"]["hits"]]
    sid = first["_scroll_id"]
    # Massive avgdl shift: long documents, then refresh (repacks impacts).
    long_text = " ".join(f"filler{j}" for j in range(300))
    for i in range(30):
        n.index_doc("s", {"t": "alpha " + long_text}, f"big{i}")
    n.refresh("s")
    page2 = n.scroll({"scroll_id": sid, "scroll": "1m"})
    page2_scores = [h["_score"] for h in page2["hits"]["hits"]]
    # Same statistics scope as page 1: identical docs -> identical scores
    # (all 20 original docs share one shape, so every page's scores match
    # page 1's).
    assert page2_scores == page1_scores
