"""Index templates + dynamic templates (VERDICT r4 item 10).

Reference: cluster/metadata/MetadataIndexTemplateService.java:83
(composable templates applied at creation) and index/mapper/
DynamicTemplate.java (per-mapping dynamic field rules).
"""

import json

import pytest

from elasticsearch_tpu.rest.server import RestServer


@pytest.fixture
def rest():
    return RestServer()


def put_template(rest, name, body):
    return rest.dispatch(
        "PUT", f"/_index_template/{name}", {}, json.dumps(body)
    )


LOGS_TEMPLATE = {
    "index_patterns": ["logs-*"],
    "priority": 10,
    "template": {
        "settings": {"index": {"number_of_shards": 2}},
        "mappings": {
            "properties": {
                "message": {"type": "text"},
                "level": {"type": "keyword"},
                "ts": {"type": "date"},
            }
        },
    },
}


class TestTemplateCrud:
    def test_put_get_delete(self, rest):
        status, resp = put_template(rest, "logs", LOGS_TEMPLATE)
        assert status == 200 and resp["acknowledged"]
        status, resp = rest.dispatch("GET", "/_index_template/logs", {}, None)
        assert status == 200
        ((entry,),) = [resp["index_templates"]]
        assert entry["name"] == "logs"
        assert entry["index_template"]["index_patterns"] == ["logs-*"]
        status, resp = rest.dispatch("GET", "/_index_template", {}, None)
        assert status == 200 and len(resp["index_templates"]) == 1
        status, resp = rest.dispatch(
            "DELETE", "/_index_template/logs", {}, None
        )
        assert status == 200
        status, resp = rest.dispatch("GET", "/_index_template/logs", {}, None)
        assert status == 404

    def test_requires_patterns(self, rest):
        status, resp = put_template(rest, "bad", {"template": {}})
        assert status == 400

    def test_broken_mappings_rejected(self, rest):
        status, resp = put_template(
            rest,
            "bad",
            {
                "index_patterns": ["x-*"],
                "template": {
                    "mappings": {
                        "properties": {
                            "f": {"type": "text", "fields": {"a": {"fields": {"b": {}}}}}
                        }
                    }
                },
            },
        )
        assert status == 400


class TestTemplateApplication:
    def test_bulk_into_fresh_index_picks_up_template(self, rest):
        """The VERDICT acceptance: bulk into a fresh logs-* index gets the
        template's mappings and settings."""
        put_template(rest, "logs", LOGS_TEMPLATE)
        lines = [
            json.dumps({"index": {"_id": "1"}}),
            json.dumps({"message": "boot ok", "level": "info", "ts": 1000}),
        ]
        status, resp = rest.dispatch(
            "POST", "/logs-2026.07/_bulk", {"refresh": "true"}, "\n".join(lines)
        )
        assert status == 200 and not resp["errors"]
        status, mapping = rest.dispatch(
            "GET", "/logs-2026.07/_mapping", {}, None
        )
        assert status == 200
        props = mapping["logs-2026.07"]["mappings"]["properties"]
        assert props["level"]["type"] == "keyword"
        assert props["ts"]["type"] == "date"
        # Settings too: 2 shards from the template.
        svc = rest.node.get_index("logs-2026.07")
        assert svc.n_shards == 2
        # level is keyword -> term query matches exactly.
        status, resp = rest.dispatch(
            "POST",
            "/logs-2026.07/_search",
            {},
            json.dumps({"query": {"term": {"level": "info"}}}),
        )
        assert resp["hits"]["total"]["value"] == 1

    def test_priority_and_request_wins(self, rest):
        put_template(rest, "low", {
            "index_patterns": ["data-*"],
            "priority": 1,
            "template": {
                "mappings": {"properties": {"a": {"type": "keyword"}}},
            },
        })
        put_template(rest, "high", {
            "index_patterns": ["data-*"],
            "priority": 5,
            "template": {
                "mappings": {"properties": {"a": {"type": "text"}}},
                "settings": {"index": {"number_of_shards": 2}},
            },
        })
        # Request body overrides the template where they collide.
        status, _ = rest.dispatch(
            "PUT",
            "/data-1",
            {},
            json.dumps(
                {"settings": {"index": {"number_of_shards": 1}}}
            ),
        )
        assert status == 200
        svc = rest.node.get_index("data-1")
        assert svc.n_shards == 1  # request won
        assert svc.mappings.get("a").type == "text"  # high priority won

    def test_non_matching_name_untouched(self, rest):
        put_template(rest, "logs", LOGS_TEMPLATE)
        status, _ = rest.dispatch("PUT", "/metrics-1", {}, None)
        assert status == 200
        assert rest.node.get_index("metrics-1").n_shards == 1

    def test_template_aliases(self, rest):
        put_template(rest, "al", {
            "index_patterns": ["evt-*"],
            "template": {"aliases": {"events": {}}},
        })
        status, _ = rest.dispatch("PUT", "/evt-1", {}, None)
        assert status == 200
        status, resp = rest.dispatch(
            "PUT", "/evt-1/_doc/e1", {"refresh": "true"},
            json.dumps({"m": "x"}),
        )
        assert status in (200, 201)
        status, resp = rest.dispatch(
            "POST", "/events/_search", {}, json.dumps({})
        )
        assert status == 200 and resp["hits"]["total"]["value"] == 1


class TestDynamicTemplates:
    def test_strings_as_keyword_rule(self, rest):
        put_template(rest, "dt", {
            "index_patterns": ["k-*"],
            "template": {
                "mappings": {
                    "dynamic_templates": [
                        {
                            "strings_as_keyword": {
                                "match_mapping_type": "string",
                                "mapping": {"type": "keyword"},
                            }
                        }
                    ]
                }
            },
        })
        status, _ = rest.dispatch(
            "PUT", "/k-1/_doc/1", {"refresh": "true"},
            json.dumps({"label": "exact-value", "note": "another"}),
        )
        assert status in (200, 201)
        status, mapping = rest.dispatch("GET", "/k-1/_mapping", {}, None)
        props = mapping["k-1"]["mappings"]["properties"]
        assert props["label"]["type"] == "keyword"
        status, resp = rest.dispatch(
            "POST", "/k-1/_search", {},
            json.dumps({"query": {"term": {"label": "exact-value"}}}),
        )
        assert resp["hits"]["total"]["value"] == 1

    def test_match_and_unmatch_patterns(self, rest):
        status, _ = rest.dispatch(
            "PUT",
            "/dyn",
            {},
            json.dumps({
                "mappings": {
                    "dynamic_templates": [
                        {
                            "ids_as_keyword": {
                                "match": "*_id",
                                "unmatch": "raw_*",
                                "mapping": {"type": "keyword"},
                            }
                        }
                    ]
                }
            }),
        )
        assert status == 200
        rest.dispatch(
            "PUT", "/dyn/_doc/1", {"refresh": "true"},
            json.dumps({"user_id": "u17", "raw_id": "r1", "title": "hello"}),
        )
        status, mapping = rest.dispatch("GET", "/dyn/_mapping", {}, None)
        props = mapping["dyn"]["mappings"]["properties"]
        assert props["user_id"]["type"] == "keyword"
        assert props["raw_id"]["type"] == "text"  # unmatch excluded it
        assert props["title"]["type"] == "text"  # default dynamic rule

    def test_numeric_match_mapping_type(self, rest):
        status, _ = rest.dispatch(
            "PUT",
            "/num",
            {},
            json.dumps({
                "mappings": {
                    "dynamic_templates": [
                        {
                            "longs_as_double": {
                                "match_mapping_type": "long",
                                "mapping": {"type": "double"},
                            }
                        }
                    ]
                }
            }),
        )
        assert status == 200
        rest.dispatch(
            "PUT", "/num/_doc/1", {"refresh": "true"},
            json.dumps({"n": 7}),
        )
        status, mapping = rest.dispatch("GET", "/num/_mapping", {}, None)
        assert mapping["num"]["mappings"]["properties"]["n"]["type"] == "double"


class TestPersistence:
    def test_templates_survive_restart(self, tmp_path):
        data = str(tmp_path / "node")
        rest = RestServer(data_path=data)
        put_template(rest, "logs", LOGS_TEMPLATE)
        rest2 = RestServer(data_path=data)
        status, resp = rest2.dispatch("GET", "/_index_template/logs", {}, None)
        assert status == 200
        assert resp["index_templates"][0]["index_template"]["priority"] == 10
        # And it still applies after restart.
        status, _ = rest2.dispatch(
            "PUT", "/logs-after", {}, None
        )
        assert status == 200
        assert rest2.node.get_index("logs-after").n_shards == 2
