"""IVF-partitioned ANN: build invariants, the bit-exact re-rank parity
law, recall gates, filtered knn, invalidation, serving-path wiring, and
the dense_vector ingest/validation satellites (ISSUE 10)."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from elasticsearch_tpu.index.ann import (
    AnnCache,
    build_partitions,
    default_nprobe,
)
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.ops import ann_device
from elasticsearch_tpu.search.service import SearchRequest, SearchService

METRICS = ("cosine", "dot_product", "l2_norm")


def clustered(rng, n, d, n_centers=24, spread=3.0):
    """A mixture-of-gaussians corpus — the natural ANN workload shape
    (recall gates run on clustered data; pure-noise vectors have no
    structure for ANY approximate index to exploit)."""
    centers = rng.standard_normal((n_centers, d)).astype(np.float32) * spread
    assign = rng.integers(0, n_centers, n)
    return (
        centers[assign] + rng.standard_normal((n, d)).astype(np.float32)
    ).astype(np.float32), centers


def exact_top(dev_vectors, live, q, k, metric, mask=None):
    s, i, t = ann_device.knn_exact(dev_vectors, live, q, k, metric, mask)
    s, i = np.asarray(s), np.asarray(i)
    n = min(k, int(t))
    return s[:n], i[:n]


# --------------------------------------------------------------- kernels


class TestKernelParity:
    def test_rerank_bit_exact_fuzz(self):
        """The parity law: every candidate the IVF path returns carries a
        score BIT-EQUAL (fp32) to the exact brute-force kernel's score
        for that same doc — approximation may only choose candidates,
        never change scoring."""
        for metric in METRICS:
            for seed, n, d in ((1, 6000, 16), (2, 3000, 33), (3, 9000, 8)):
                rng = np.random.default_rng(seed)
                vecs, centers = clustered(rng, n, d)
                if metric == "dot_product":
                    vecs = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
                dev = jnp.asarray(vecs)
                parts = build_partitions(
                    "vec", vecs, dev, num_docs=n, metric=metric
                )
                live = jnp.ones(n, bool)
                nprobe = default_nprobe(parts.n_partitions)
                for qi in range(8):
                    q = (
                        centers[qi % len(centers)]
                        + rng.standard_normal(d).astype(np.float32)
                    ).astype(np.float32)
                    if metric == "dot_product":
                        q = q / np.linalg.norm(q)
                    s, ids, _t, _nc = ann_device.ann_ivf_search(
                        parts.tree(), live, q, 10, nprobe, metric
                    )
                    s, ids = np.asarray(s), np.asarray(ids)
                    exact_all = np.asarray(
                        ann_device.knn_exact(dev, live, q, n, metric)[0]
                    )
                    # knn_exact returns scores ranked; rebuild per-doc map
                    exact_ids = np.asarray(
                        ann_device.knn_exact(dev, live, q, n, metric)[1]
                    )
                    by_doc = dict(
                        zip(exact_ids.tolist(), exact_all.tolist())
                    )
                    for doc, score in zip(ids, s):
                        assert np.float32(score) == np.float32(
                            by_doc[int(doc)]
                        ), (metric, seed, int(doc))

    def test_full_probe_equals_exact(self):
        """nprobe == n_partitions reaches every candidate, so the IVF
        result must be IDENTICAL to brute force — ids, order (incl. the
        ascending-doc-id tie-break; the corpus repeats vectors to force
        ties), scores, totals."""
        rng = np.random.default_rng(5)
        n, d = 4000, 12
        base, _ = clustered(rng, n // 4, d)
        vecs = np.tile(base, (4, 1))  # every vector 4x -> guaranteed ties
        dev = jnp.asarray(vecs)
        live = jnp.ones(n, bool)
        for metric in METRICS:
            parts = build_partitions(
                "vec", vecs, dev, num_docs=n, metric=metric
            )
            for qi in range(6):
                q = vecs[rng.integers(0, n)] + 0.01 * rng.standard_normal(
                    d
                ).astype(np.float32)
                s, ids, tot, _nc = ann_device.ann_ivf_search(
                    parts.tree(), live, q, 20, parts.n_partitions, metric
                )
                es, ei = exact_top(dev, live, q, 20, metric)
                np.testing.assert_array_equal(np.asarray(ids)[: len(ei)], ei)
                np.testing.assert_array_equal(np.asarray(s)[: len(es)], es)
                assert int(tot) == n

    def test_recall_gate_default_nprobe(self):
        """recall@10 >= 0.95 at the DEFAULT nprobe on seeded clustered
        corpora — the fuzz gate the bench's cfg9 mirrors at scale."""
        hits = total = 0
        for seed in (11, 12, 13):
            rng = np.random.default_rng(seed)
            n, d = 8000, 24
            vecs, centers = clustered(rng, n, d)
            dev = jnp.asarray(vecs)
            parts = build_partitions(
                "vec", vecs, dev, num_docs=n, metric="cosine"
            )
            live = jnp.ones(n, bool)
            nprobe = default_nprobe(parts.n_partitions)
            for qi in range(16):
                q = (
                    centers[qi % len(centers)]
                    + rng.standard_normal(d).astype(np.float32)
                ).astype(np.float32)
                _s, ids, _t, _nc = ann_device.ann_ivf_search(
                    parts.tree(), live, q, 10, nprobe, "cosine"
                )
                _es, ei = exact_top(dev, live, q, 10, "cosine")
                hits += len(set(np.asarray(ids).tolist()) & set(ei.tolist()))
                total += len(ei)
        assert hits / total >= 0.95, f"recall@10 {hits / total:.3f}"

    def test_partition_layout_covers_every_doc_once(self):
        rng = np.random.default_rng(9)
        n, d = 5000, 10
        vecs, _ = clustered(rng, n, d)
        parts = build_partitions(
            "vec", vecs, jnp.asarray(vecs), num_docs=n, metric="l2_norm"
        )
        doc_map = np.asarray(parts.part_docs)
        real = doc_map[doc_map < n]
        assert sorted(real.tolist()) == list(range(n))
        # split clusters: every partition fits the uniform pmax, and the
        # padded layout stays bounded (the anti-skew guarantee).
        assert doc_map.shape[1] == parts.pmax
        assert doc_map.size <= 3 * n + parts.n_partitions * 0  # bounded
        # padding slots gather zero vectors, never another doc's.
        pv = np.asarray(parts.part_vectors)
        pad_rows = pv.reshape(-1, d)[(doc_map == n).reshape(-1)]
        assert not pad_rows.any()

    def test_batched_kernel_matches_solo(self):
        rng = np.random.default_rng(21)
        n, d = 6000, 16
        vecs, centers = clustered(rng, n, d)
        dev = jnp.asarray(vecs)
        live = jnp.ones(n, bool)
        parts = build_partitions(
            "vec", vecs, dev, num_docs=n, metric="cosine"
        )
        qs = np.stack(
            [
                (centers[i % len(centers)] + rng.standard_normal(d)).astype(
                    np.float32
                )
                for i in range(5)
            ]
        )
        s_b, i_b, t_b, nc_b = ann_device.ann_ivf_search_batch(
            parts.tree(), live, qs, 10, 6, "cosine"
        )
        for row in range(len(qs)):
            s, i, t, nc = ann_device.ann_ivf_search(
                parts.tree(), live, qs[row], 10, 6, "cosine"
            )
            np.testing.assert_array_equal(np.asarray(s_b)[row], np.asarray(s))
            np.testing.assert_array_equal(np.asarray(i_b)[row], np.asarray(i))
            assert int(np.asarray(t_b)[row]) == int(t)
            assert int(np.asarray(nc_b)[row]) == int(nc)


# ------------------------------------------------------------ service path


def vector_engine(n=1500, d=8, seed=4, extra_fields=False, n_centers=16):
    rng = np.random.default_rng(seed)
    props = {"vec": {"type": "dense_vector", "dims": d}}
    if extra_fields:
        props["tag"] = {"type": "keyword"}
        props["rank"] = {"type": "long"}
    engine = Engine(Mappings(properties=props))
    vecs, centers = clustered(rng, n, d, n_centers=n_centers)
    for i in range(n):
        doc = {"vec": vecs[i].tolist()}
        if extra_fields:
            doc["tag"] = "odd" if i % 2 else "even"
            doc["rank"] = i
        engine.index(doc, f"d{i}")
    engine.refresh()
    return engine, vecs, centers, rng


def knn_body(q, k=10, **kw):
    return {"knn": {"field": "vec", "query_vector": list(map(float, q)), "k": k, **kw}}


class TestServicePath:
    def test_ivf_engaged_and_scores_exact(self):
        engine, vecs, centers, rng = vector_engine()
        cache = AnnCache(min_docs=256)
        svc = SearchService(engine, "v", ann_cache=cache)
        q = (centers[0] + rng.standard_normal(8)).astype(np.float32)
        resp = svc.search(SearchRequest.from_json(knn_body(q, k=5)))
        assert len(resp.hits) == 5
        assert cache.stats()["builds"] == 1
        assert cache.stats()["searches"].get("ann_ivf", 0) >= 1
        dev = engine.segments[0].device.vectors["vec"]
        live = engine.segments[0].device.live
        es, ei = exact_top(dev, live, q, 1500, "cosine")
        by_doc = {f"d{int(doc)}": s for doc, s in zip(ei, es)}
        for h in resp.hits:
            assert np.float32(h.score) == np.float32(by_doc[h.doc_id])
        # recall vs exact top-5, recorded through the stats gate counter
        top5 = {f"d{int(doc)}" for doc in ei[:5]}
        recall = len({h.doc_id for h in resp.hits} & top5) / 5
        cache.note_recall_gate(recall >= 0.95)
        assert recall >= 0.95
        assert cache.stats()["recall_gate"] == {"pass": 1}

    def test_small_segment_falls_back_to_exact(self):
        engine, vecs, _centers, rng = vector_engine(n=300)
        cache = AnnCache(min_docs=4096)
        svc = SearchService(engine, "v", ann_cache=cache)
        q = rng.standard_normal(8).astype(np.float32)
        resp = svc.search(SearchRequest.from_json(knn_body(q, k=3)))
        assert cache.stats()["builds"] == 0
        assert cache.stats()["searches"] == {"device": 1}
        dev = engine.segments[0].device.vectors["vec"]
        es, ei = exact_top(dev, engine.segments[0].device.live, q, 3, "cosine")
        assert [h.doc_id for h in resp.hits] == [f"d{int(i)}" for i in ei]
        np.testing.assert_array_equal(
            np.asarray([h.score for h in resp.hits], np.float32), es
        )

    def test_filtered_knn_pre_rank_not_post_trim(self):
        """The filter applies BEFORE candidate ranking: k hits return
        even when the unfiltered top-k is entirely outside the filter (a
        post-trim would come back short)."""
        engine, vecs, centers, rng = vector_engine(extra_fields=True)
        cache = AnnCache(min_docs=256)
        svc = SearchService(engine, "v", ann_cache=cache)
        q = (centers[1] + 0.1 * rng.standard_normal(8)).astype(np.float32)
        resp = svc.search(
            SearchRequest.from_json(
                knn_body(q, k=8, filter={"term": {"tag": "odd"}})
            )
        )
        assert len(resp.hits) == 8
        assert all(int(h.doc_id[1:]) % 2 == 1 for h in resp.hits)
        # parity: full probe == exact filtered top-k (ids AND scores)
        resp_full = svc.search(
            SearchRequest.from_json(
                knn_body(
                    q, k=8, nprobe=4096, filter={"term": {"tag": "odd"}}
                )
            )
        )
        dev = engine.segments[0].device.vectors["vec"]
        live = engine.segments[0].device.live
        mask = jnp.asarray(
            np.array([i % 2 == 1 for i in range(len(vecs))])
        )
        es, ei = exact_top(dev, live, q, 8, "cosine", mask=mask)
        assert [h.doc_id for h in resp_full.hits] == [
            f"d{int(i)}" for i in ei
        ]
        np.testing.assert_array_equal(
            np.asarray([h.score for h in resp_full.hits], np.float32), es
        )
        # totals count the FILTERED eligible set, not the probe
        assert resp_full.total == len(vecs) // 2

    def test_refresh_new_segment_builds_merge_invalidates(self):
        engine, vecs, centers, rng = vector_engine(n=800)
        cache = AnnCache(min_docs=256)
        svc = SearchService(engine, "v", ann_cache=cache)
        q = (centers[0] + rng.standard_normal(8)).astype(np.float32)
        svc.search(SearchRequest.from_json(knn_body(q)))
        assert cache.stats()["builds"] == 1
        # A second segment arrives: its OWN partitions build; the first
        # segment's plane keeps serving (no rebuild for it).
        for i in range(800, 1400):
            engine.index(
                {"vec": (centers[i % 8] + rng.standard_normal(8)).tolist()},
                f"d{i}",
            )
        engine.refresh()
        svc.search(SearchRequest.from_json(knn_body(q)))
        assert cache.stats()["builds"] == 2
        assert cache.stats()["planes"] == 2
        uids_before = {k[1] for k in cache._entries}
        # Force a merge: merged-away handles mint fresh uids, their
        # planes are pruned on the next store, results stay correct.
        engine.force_merge(max_num_segments=1)
        svc.search(SearchRequest.from_json(knn_body(q)))
        uids_after = {k[1] for k in cache._entries}
        assert not (uids_before & uids_after)
        assert cache.stats()["planes"] == 1  # one merged segment
        resp = svc.search(SearchRequest.from_json(knn_body(q, k=5)))
        dev = engine.segments[0].device.vectors["vec"]
        es, ei = exact_top(
            dev, engine.segments[0].device.live, q, 1400, "cosine"
        )
        by_doc = {int(doc): s for doc, s in zip(ei, es)}
        for h in resp.hits:
            local = engine.segments[0].id_index[h.doc_id]
            assert np.float32(h.score) == np.float32(by_doc[local])

    def test_docs_without_vectors_never_surface(self):
        """A doc that omits the dense_vector field zero-fills its matrix
        row; it must never enter a kNN hit set (the reference only
        considers docs with an indexed vector — a zero row would score
        0.5 under cosine)."""
        rng = np.random.default_rng(14)
        engine = Engine(
            Mappings(
                properties={
                    "vec": {"type": "dense_vector", "dims": 6},
                    "title": {"type": "text"},
                }
            )
        )
        for i in range(40):
            doc = {"title": f"doc {i}"}
            if i % 3:  # a third of the docs carry NO vector
                doc["vec"] = (
                    rng.standard_normal(6) - 5.0  # negative cosine to q
                ).tolist()
            engine.index(doc, f"d{i}")
        engine.refresh()
        svc = SearchService(engine, "v", ann_cache=AnnCache(min_docs=8))
        q = np.full(6, 5.0, dtype=np.float32)
        body = knn_body(q, k=40)
        body["size"] = 40  # page size defaults to 10; expose all k hits
        resp = svc.search(SearchRequest.from_json(body))
        returned = {h.doc_id for h in resp.hits}
        vectorless = {f"d{i}" for i in range(40) if i % 3 == 0}
        assert not (returned & vectorless)
        assert len(resp.hits) == 40 - len(vectorless)
        # The exact brute-force path must agree (forced via a min_docs
        # the segment can't reach): same doc set, no -inf filler hits.
        exact_svc = SearchService(
            engine, "v", ann_cache=AnnCache(min_docs=1 << 20)
        )
        resp2 = exact_svc.search(SearchRequest.from_json(body))
        assert {h.doc_id for h in resp2.hits} == returned
        assert all(np.isfinite(h.score) for h in resp2.hits)

    def test_zero_vector_rejected_for_cosine_and_dot(self):
        for sim in ("cosine", "dot_product"):
            engine = Engine(
                Mappings(
                    properties={
                        "vec": {
                            "type": "dense_vector",
                            "dims": 3,
                            "similarity": sim,
                        }
                    }
                )
            )
            with pytest.raises(ValueError, match="zero magnitude"):
                engine.index({"vec": [0.0, 0.0, 0.0]}, "a")
        # l2_norm accepts it (distance from a zero point is well-defined)
        engine = Engine(
            Mappings(
                properties={
                    "vec": {
                        "type": "dense_vector",
                        "dims": 3,
                        "similarity": "l2_norm",
                    }
                }
            )
        )
        engine.index({"vec": [0.0, 0.0, 0.0]}, "a")

    def test_dense_vector_mapping_params_immutable(self):
        node = Node()
        try:
            node.create_index(
                "v",
                {
                    "mappings": {
                        "properties": {
                            "vec": {"type": "dense_vector", "dims": 4}
                        }
                    }
                },
            )
            for bad in (
                {"type": "dense_vector", "dims": 8},
                {"type": "dense_vector", "dims": 4, "similarity": "l2_norm"},
            ):
                with pytest.raises(ApiError) as err:
                    node.put_mapping("v", {"properties": {"vec": bad}})
                assert err.value.status == 400
                assert "Cannot update parameter" in str(err.value)
        finally:
            node.close()

    def test_deleted_docs_never_surface(self):
        engine, vecs, centers, rng = vector_engine(n=900)
        cache = AnnCache(min_docs=256)
        svc = SearchService(engine, "v", ann_cache=cache)
        q = (centers[2] + 0.05 * rng.standard_normal(8)).astype(np.float32)
        first = svc.search(SearchRequest.from_json(knn_body(q, k=3)))
        victim = first.hits[0].doc_id
        engine.delete(victim)
        engine.refresh()  # deletes become searchable-visible on refresh
        resp = svc.search(SearchRequest.from_json(knn_body(q, k=3)))
        assert victim not in {h.doc_id for h in resp.hits}

    def test_search_many_matches_solo(self):
        engine, vecs, centers, rng = vector_engine()
        cache = AnnCache(min_docs=256)
        svc = SearchService(engine, "v", ann_cache=cache)
        reqs = [
            SearchRequest.from_json(
                knn_body(
                    (centers[i] + rng.standard_normal(8)).astype(np.float32),
                    k=6,
                )
            )
            for i in range(4)
        ]
        batched = svc.search_many(list(reqs))
        for req, got in zip(reqs, batched):
            solo = svc.search(req)
            assert [h.doc_id for h in got.hits] == [
                h.doc_id for h in solo.hits
            ]
            np.testing.assert_array_equal(
                np.asarray([h.score for h in got.hits], np.float32),
                np.asarray([h.score for h in solo.hits], np.float32),
            )
            assert got.total == solo.total


# --------------------------------------------------------------- node path


class TestNodePath:
    def bulk_vectors(self, n, node, index, rng, d=8, centers=None):
        lines = []
        for i in range(n):
            base = centers[i % len(centers)] if centers is not None else 0.0
            lines.append(json.dumps({"index": {"_id": str(i)}}))
            lines.append(
                json.dumps(
                    {"vec": (base + rng.standard_normal(d)).tolist()}
                )
            )
        node.bulk("\n".join(lines) + "\n", default_index=index)
        node.refresh(index)

    def test_knn_section_end_to_end_sharded_global_topk(self):
        node = Node()
        try:
            node.ann_cache.min_docs = 512
            node.create_index(
                "v",
                {
                    "mappings": {
                        "properties": {
                            "vec": {"type": "dense_vector", "dims": 8}
                        }
                    },
                    "settings": {"index": {"number_of_shards": 2}},
                },
            )
            rng = np.random.default_rng(2)
            centers = rng.standard_normal((8, 8)).astype(np.float32) * 3
            self.bulk_vectors(3000, node, "v", rng, centers=centers)
            q = (centers[0] + rng.standard_normal(8)).tolist()
            out = node.search("v", knn_body(q, k=4, nprobe=4096))
            # GLOBAL top-k: 2 shards x k candidates merge to k hits.
            assert len(out["hits"]["hits"]) == 4
            assert out["_shards"]["successful"] == 2
            scores = [h["_score"] for h in out["hits"]["hits"]]
            assert scores == sorted(scores, reverse=True)
        finally:
            node.close()

    def test_rest_knn_search_endpoint_and_cache_clear(self):
        from elasticsearch_tpu.rest.server import RestServer

        node = Node()
        rest = RestServer(node=node)
        try:
            node.ann_cache.min_docs = 256
            node.create_index(
                "v",
                {
                    "mappings": {
                        "properties": {
                            "vec": {"type": "dense_vector", "dims": 8},
                            "tag": {"type": "keyword"},
                        }
                    }
                },
            )
            rng = np.random.default_rng(3)
            lines = []
            for i in range(800):
                lines.append(json.dumps({"index": {"_id": str(i)}}))
                lines.append(
                    json.dumps(
                        {
                            "vec": rng.standard_normal(8).tolist(),
                            "tag": "a" if i % 2 else "b",
                        }
                    )
                )
            node.bulk("\n".join(lines) + "\n", default_index="v")
            node.refresh("v")
            q = rng.standard_normal(8).tolist()
            status, body = rest.dispatch(
                "POST",
                "/v/_knn_search",
                {},
                json.dumps(
                    {
                        "knn": {
                            "field": "vec",
                            "query_vector": q,
                            "k": 3,
                            "num_candidates": 50,
                        },
                        "filter": {"term": {"tag": "a"}},
                        "_source": False,
                    }
                ),
            )
            assert status == 200, body
            assert len(body["hits"]["hits"]) == 3
            assert all(
                int(h["_id"]) % 2 == 1 for h in body["hits"]["hits"]
            )
            status, body = rest.dispatch(
                "POST", "/v/_knn_search", {}, json.dumps({})
            )
            assert status == 400
            # knn planes drop with _cache/clear and with index deletion
            assert node.ann_cache.stats()["planes"] == 1
            status, body = rest.dispatch(
                "POST", "/v/_cache/clear", {}, ""
            )
            assert status == 200 and body["cleared"]["ann"] == 1
            assert node.ann_cache.stats()["planes"] == 0
        finally:
            rest.close()

    def test_knn_rejected_with_scroll(self):
        node = Node()
        try:
            node.create_index(
                "v",
                {
                    "mappings": {
                        "properties": {
                            "vec": {"type": "dense_vector", "dims": 4}
                        }
                    }
                },
            )
            node.index_doc("v", {"vec": [1, 2, 3, 4]}, doc_id="a")
            node.refresh("v")
            with pytest.raises(ApiError) as err:
                node.search(
                    "v", knn_body([1, 2, 3, 4], k=1), scroll="1m"
                )
            assert err.value.status == 400
        finally:
            node.close()

    def test_ann_opt_out_still_serves_exact(self, monkeypatch):
        monkeypatch.setenv("ESTPU_ANN", "0")
        node = Node()
        try:
            assert node.ann_cache is None
            node.create_index(
                "v",
                {
                    "mappings": {
                        "properties": {
                            "vec": {"type": "dense_vector", "dims": 4}
                        }
                    }
                },
            )
            for i in range(20):
                node.index_doc(
                    "v", {"vec": [float(i), 0.0, 0.0, 1.0]}, doc_id=str(i)
                )
            node.refresh("v")
            out = node.search("v", knn_body([19.0, 0, 0, 1], k=2))
            assert len(out["hits"]["hits"]) == 2
            stats = node.nodes_stats()["nodes"][node.node_name]["search"][
                "ann"
            ]
            assert stats["enabled"] is False
        finally:
            node.close()

    def test_replicated_knn_serves_exact(self):
        from elasticsearch_tpu.rest.server import RestServer

        rest = RestServer(replication_nodes=3)
        try:
            rest.dispatch(
                "PUT",
                "/v",
                {},
                json.dumps(
                    {
                        "mappings": {
                            "properties": {
                                "vec": {"type": "dense_vector", "dims": 4}
                            }
                        },
                        "settings": {
                            "index": {
                                "number_of_shards": 2,
                                "number_of_replicas": 1,
                            }
                        },
                    }
                ),
            )
            rng = np.random.default_rng(6)
            for i in range(30):
                rest.dispatch(
                    "PUT",
                    f"/v/_doc/{i}",
                    {},
                    json.dumps({"vec": rng.standard_normal(4).tolist()}),
                )
            rest.dispatch("POST", "/v/_refresh", {}, "")
            status, body = rest.dispatch(
                "POST",
                "/v/_search",
                {},
                json.dumps(knn_body(rng.standard_normal(4).tolist(), k=3)),
            )
            assert status == 200, body
            assert len(body["hits"]["hits"]) == 3
        finally:
            rest.close()


# ----------------------------------------------- ingest validation satellite


class TestDenseVectorIngest:
    def make_node(self):
        node = Node()
        node.create_index(
            "v",
            {
                "mappings": {
                    "properties": {
                        "vec": {"type": "dense_vector", "dims": 3},
                        "body": {"type": "text"},
                    }
                }
            },
        )
        return node

    def test_dims_mismatch_400_at_index_time(self):
        node = self.make_node()
        try:
            with pytest.raises(ApiError) as err:
                node.index_doc("v", {"vec": [1.0, 2.0]}, doc_id="a")
            assert err.value.status == 400
            assert "dimensions" in str(err.value)
            # nothing half-indexed
            node.refresh("v")
            assert node.search("v", {"size": 0})["hits"]["total"]["value"] == 0
        finally:
            node.close()

    def test_bad_shapes_400(self):
        node = self.make_node()
        try:
            for bad in (
                [[1.0, 2.0, 3.0]],  # rank-2
                ["a", "b", "c"],  # strings
                {"x": 1},  # object
                [1.0, float("nan"), 2.0],  # NaN
            ):
                with pytest.raises(ApiError) as err:
                    node.index_doc("v", {"vec": bad})
                assert err.value.status == 400, bad
        finally:
            node.close()

    def test_bulk_reports_per_item_and_keeps_good_docs(self):
        node = self.make_node()
        try:
            lines = [
                json.dumps({"index": {"_id": "good1"}}),
                json.dumps({"vec": [1.0, 2.0, 3.0]}),
                json.dumps({"index": {"_id": "bad"}}),
                json.dumps({"vec": [1.0, 2.0]}),
                json.dumps({"index": {"_id": "good2"}}),
                json.dumps({"vec": [4.0, 5.0, 6.0]}),
            ]
            out = node.bulk("\n".join(lines) + "\n", default_index="v")
            assert out["errors"] is True
            statuses = [item["index"]["status"] for item in out["items"]]
            assert statuses == [201, 400, 201]
            err = out["items"][1]["index"]["error"]
            assert "dimensions" in err["reason"]
            node.refresh("v")
            assert node.search("v", {"size": 0})["hits"]["total"]["value"] == 2
        finally:
            node.close()

    def test_update_with_bad_vector_400_keeps_original(self):
        node = self.make_node()
        try:
            node.index_doc("v", {"vec": [1.0, 2.0, 3.0]}, doc_id="a")
            with pytest.raises(ApiError) as err:
                node.update_doc("v", "a", {"doc": {"vec": [9.0]}})
            assert err.value.status == 400
            assert "dimensions" in str(err.value)
            doc = node.get_doc("v", "a")
            assert doc["_source"]["vec"] == [1.0, 2.0, 3.0]
        finally:
            node.close()

    def test_mapping_requires_dims_and_valid_similarity(self):
        node = Node()
        try:
            with pytest.raises(ApiError) as err:
                node.create_index(
                    "nodims",
                    {
                        "mappings": {
                            "properties": {
                                "vec": {"type": "dense_vector"}
                            }
                        }
                    },
                )
            assert err.value.status == 400
            with pytest.raises(ApiError) as err:
                node.create_index(
                    "badsim",
                    {
                        "mappings": {
                            "properties": {
                                "vec": {
                                    "type": "dense_vector",
                                    "dims": 4,
                                    "similarity": "euclid",
                                }
                            }
                        }
                    },
                )
            assert err.value.status == 400
            # similarity round-trips through the mapping API
            node.create_index(
                "l2",
                {
                    "mappings": {
                        "properties": {
                            "vec": {
                                "type": "dense_vector",
                                "dims": 4,
                                "similarity": "l2_norm",
                            }
                        }
                    }
                },
            )
            got = node.get_mapping("l2")["l2"]["mappings"]["properties"]
            assert got["vec"]["similarity"] == "l2_norm"
        finally:
            node.close()


# ----------------------------------------- script_score stays byte-identical


class TestExactPathUnchanged:
    def test_script_score_never_routes_to_ann(self):
        """Exact kNN via script_score must not touch the ANN machinery:
        identical hits with the ann cache enabled, disabled, and after
        ANN planes exist for the same field."""
        engine, vecs, centers, rng = vector_engine()
        q = (centers[0] + rng.standard_normal(8)).astype(np.float32)
        body = {
            "query": {
                "script_score": {
                    "query": {"match_all": {}},
                    "script": {
                        "source": "cosineSimilarity(params.qv, 'vec') + 1.0",
                        "params": {"qv": q.tolist()},
                    },
                }
            },
            "size": 10,
        }
        plain = SearchService(engine, "v").search(
            SearchRequest.from_json(body)
        )
        cache = AnnCache(min_docs=256)
        svc = SearchService(engine, "v", ann_cache=cache)
        svc.search(SearchRequest.from_json(knn_body(q)))  # planes exist now
        with_ann = svc.search(SearchRequest.from_json(body))
        assert [h.doc_id for h in plain.hits] == [
            h.doc_id for h in with_ann.hits
        ]
        np.testing.assert_array_equal(
            np.asarray([h.score for h in plain.hits], np.float32),
            np.asarray([h.score for h in with_ann.hits], np.float32),
        )
        assert cache.stats()["searches"].get("ann_ivf", 0) == 1  # knn only


# ---------------------------------------------- _score asc host contract


class TestScoreAscContract:
    def docs_engine(self, refresh_every=None):
        mappings = Mappings(
            properties={"title": {"type": "text"}, "rank": {"type": "long"}}
        )
        engine = Engine(mappings)
        words = ["quick", "brown", "fox", "lazy", "dog", "bread"]
        rng = np.random.default_rng(8)
        for i in range(60):
            engine.index(
                {"title": " ".join(rng.choice(words, 5)), "rank": i},
                f"d{i}",
            )
            if refresh_every and (i + 1) % refresh_every == 0:
                engine.refresh()
        engine.refresh()
        return engine

    def oracle_bottom_k(self, engine, body, k):
        from elasticsearch_tpu.query.dsl import parse_query
        from elasticsearch_tpu.search.oracle import OracleSearcher

        rows = []
        for handle in engine.segments:
            oracle = OracleSearcher(
                handle.segment, engine.mappings, engine.params,
                stats=engine.field_stats(),
            )
            scores, matched = oracle._eval(parse_query(body["query"]))
            for local in np.flatnonzero(matched):
                rows.append(
                    (np.float32(scores[local]), handle.base + int(local),
                     handle.segment.ids[local])
                )
        rows.sort(key=lambda r: (r[0], r[1]))
        return rows[:k]

    def test_score_asc_solo_oracle_parity(self):
        engine = self.docs_engine()
        body = {
            "query": {"match": {"title": "quick fox"}},
            "sort": [{"_score": "asc"}],
            "size": 5,
        }
        resp = SearchService(engine, "t").search(
            SearchRequest.from_json(body)
        )
        want = self.oracle_bottom_k(engine, body, 5)
        assert [h.doc_id for h in resp.hits] == [w[2] for w in want]
        np.testing.assert_array_equal(
            np.asarray([h.score for h in resp.hits], np.float32),
            np.asarray([w[0] for w in want], np.float32),
        )

    def test_score_asc_multi_segment_oracle_parity(self):
        engine = self.docs_engine(refresh_every=17)
        assert len(engine.segments) > 1
        body = {
            "query": {"match": {"title": "lazy dog"}},
            "sort": [{"_score": "asc"}],
            "size": 7,
        }
        resp = SearchService(engine, "t").search(
            SearchRequest.from_json(body)
        )
        want = self.oracle_bottom_k(engine, body, 7)
        assert [h.doc_id for h in resp.hits] == [w[2] for w in want]

    def test_rescore_with_sort_is_a_clear_400(self):
        """PR-8 residue closed: rescore combined with ANY explicit sort —
        including {"_score": "asc"}, which used to silently DROP the
        rescore stage — is a parse-time error (reference behavior)."""
        for sort in ([{"_score": "asc"}], [{"_score": "desc"}], [{"rank": "asc"}]):
            with pytest.raises(ValueError, match="rescore"):
                SearchRequest.from_json(
                    {
                        "query": {"match": {"title": "quick"}},
                        "sort": sort,
                        "rescore": {
                            "window_size": 5,
                            "query": {
                                "rescore_query": {"match": {"title": "fox"}}
                            },
                        },
                    }
                )
