"""Engine concurrency safety + _version/seqno CAS semantics.

Mirrors the reference's InternalEngine version map + if_seq_no/if_primary_term
compare-and-set contract (action/index/IndexRequest.java:109) and the
multithreaded engine stress the round-2 verdict asked for (weak #6).
"""

import threading

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine, VersionConflictError
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.node import ApiError, Node


def _mappings():
    return Mappings(
        properties={"body": {"type": "text"}, "n": {"type": "long"}}
    )


class TestVersioning:
    def test_version_increments_on_reindex(self):
        eng = Engine(_mappings())
        r1 = eng.index({"body": "a"}, "d1")
        assert (r1["_version"], r1["result"]) == (1, "created")
        r2 = eng.index({"body": "b"}, "d1")
        assert (r2["_version"], r2["result"]) == (2, "updated")
        meta = eng.get_with_meta("d1")
        assert meta["_version"] == 2
        assert meta["_seq_no"] == r2["_seq_no"]

    def test_version_continues_after_delete(self):
        eng = Engine(_mappings())
        eng.index({"body": "a"}, "d1")
        rd = eng.delete("d1")
        assert rd["_version"] == 2
        r3 = eng.index({"body": "c"}, "d1")
        assert (r3["_version"], r3["result"]) == (3, "created")

    def test_version_survives_refresh_and_restart(self, tmp_path):
        eng = Engine(_mappings(), data_path=str(tmp_path))
        eng.index({"body": "a"}, "d1")
        eng.index({"body": "b"}, "d1")
        eng.refresh()
        eng.flush()
        eng.close()
        eng2 = Engine(_mappings(), data_path=str(tmp_path))
        meta = eng2.get_with_meta("d1")
        assert meta["_version"] == 2
        r = eng2.index({"body": "c"}, "d1")
        assert r["_version"] == 3
        eng2.close()

    def test_version_survives_translog_replay(self, tmp_path):
        eng = Engine(_mappings(), data_path=str(tmp_path))
        eng.index({"body": "a"}, "d1")
        eng.index({"body": "b"}, "d1")
        eng.sync_translog()
        eng.close()  # no flush: recovery must replay the translog
        eng2 = Engine(_mappings(), data_path=str(tmp_path))
        assert eng2.get_with_meta("d1")["_version"] == 2
        eng2.close()


class TestCas:
    def test_cas_success_and_conflict(self):
        eng = Engine(_mappings())
        r1 = eng.index({"body": "a"}, "d1")
        r2 = eng.index(
            {"body": "b"}, "d1", if_seq_no=r1["_seq_no"], if_primary_term=1
        )
        assert r2["_version"] == 2
        with pytest.raises(VersionConflictError):
            eng.index(
                {"body": "c"}, "d1",
                if_seq_no=r1["_seq_no"], if_primary_term=1,
            )
        with pytest.raises(VersionConflictError):
            eng.index(
                {"body": "c"}, "d1",
                if_seq_no=r2["_seq_no"], if_primary_term=99,
            )

    def test_cas_on_missing_doc_conflicts(self):
        eng = Engine(_mappings())
        with pytest.raises(VersionConflictError):
            eng.index({"body": "a"}, "ghost", if_seq_no=0, if_primary_term=1)
        with pytest.raises(VersionConflictError):
            eng.delete("ghost", if_seq_no=0, if_primary_term=1)

    def test_cas_delete(self):
        eng = Engine(_mappings())
        r1 = eng.index({"body": "a"}, "d1")
        with pytest.raises(VersionConflictError):
            eng.delete("d1", if_seq_no=r1["_seq_no"] + 5, if_primary_term=1)
        rd = eng.delete("d1", if_seq_no=r1["_seq_no"], if_primary_term=1)
        assert rd["result"] == "deleted"

    def test_node_cas_maps_to_409(self):
        node = Node()
        r = node.index_doc("idx", {"body": "a"}, "d1")
        with pytest.raises(ApiError) as ei:
            node.index_doc(
                "idx", {"body": "b"}, "d1",
                if_seq_no=r["_seq_no"] + 1, if_primary_term=1,
            )
        assert ei.value.status == 409
        ok = node.index_doc(
            "idx", {"body": "b"}, "d1",
            if_seq_no=r["_seq_no"], if_primary_term=1,
        )
        assert ok["_version"] == 2
        with pytest.raises(ApiError) as ei:
            node.update_doc(
                "idx", "d1", {"doc": {"n": 1}},
                if_seq_no=r["_seq_no"], if_primary_term=1,
            )
        assert ei.value.status == 409


class TestConcurrencyStress:
    def test_concurrent_bulk_search_refresh_flush(self, tmp_path):
        """Hammer one engine from writer/deleter/refresher/flusher/searcher
        threads; the engine must neither corrupt state nor drop acked writes."""
        from elasticsearch_tpu.query.dsl import parse_query
        from elasticsearch_tpu.search.service import SearchRequest, SearchService

        eng = Engine(_mappings(), data_path=str(tmp_path))
        svc = SearchService(eng)
        n_writers, per_writer = 4, 60
        errors: list[BaseException] = []
        stop = threading.Event()

        def writer(t):
            try:
                for i in range(per_writer):
                    eng.index(
                        {"body": f"doc tok{i % 7}", "n": i}, f"w{t}-{i}"
                    )
                    if i % 10 == 3:
                        eng.delete(f"w{t}-{i}")
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def refresher():
            try:
                while not stop.is_set():
                    eng.refresh()
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def flusher():
            try:
                while not stop.is_set():
                    eng.flush()
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def searcher():
            try:
                while not stop.is_set():
                    svc.search(
                        SearchRequest(
                            query=parse_query({"match": {"body": "tok1"}})
                        )
                    )
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(t,))
            for t in range(n_writers)
        ] + [
            threading.Thread(target=refresher),
            threading.Thread(target=flusher),
            threading.Thread(target=searcher),
        ]
        for th in threads:
            th.start()
        for th in threads[:n_writers]:
            th.join()
        stop.set()
        for th in threads[n_writers:]:
            th.join()
        assert not errors, errors

        eng.flush()
        # Every non-deleted acked write must be live and searchable.
        expected_live = {
            f"w{t}-{i}"
            for t in range(n_writers)
            for i in range(per_writer)
            if i % 10 != 3
        }
        assert {
            d for d in eng._live_ids
        } == expected_live
        # Seqnos must be unique (no duplicate assignment under contention).
        eng.close()
        eng2 = Engine(_mappings(), data_path=str(tmp_path))
        assert set(eng2._live_ids) == expected_live
        eng2.close()

    def test_concurrent_writes_unique_seqnos(self):
        eng = Engine(_mappings())
        seqnos: list[int] = []
        lock = threading.Lock()

        def writer(t):
            mine = [
                eng.index({"body": "x"}, f"t{t}-{i}")["_seq_no"]
                for i in range(200)
            ]
            with lock:
                seqnos.extend(mine)

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(8)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(seqnos) == len(set(seqnos)) == 1600
        assert sorted(seqnos) == list(range(1600))


class TestReviewFindings:
    """Round-3 inline review findings on the versioning/locking diff."""

    def test_rejected_write_leaves_doc_intact(self):
        """A mapper failure must not tombstone the existing doc or leave a
        partial ghost (atomic SegmentBuilder.add + no pre-tombstoning)."""
        m = Mappings(
            properties={
                "body": {"type": "text"},
                "v": {"type": "dense_vector", "dims": 4},
            }
        )
        eng = Engine(m)
        eng.index({"body": "good", "v": [1, 2, 3, 4]}, "d1")
        seq_before = eng.max_seqno
        with pytest.raises(ValueError):
            eng.index({"body": "bad", "v": [1, 2]}, "d1")  # dims mismatch
        assert eng.get("d1") == {"body": "good", "v": [1, 2, 3, 4]}
        assert eng.max_seqno == seq_before  # no seqno leaked
        eng.refresh()
        assert eng.num_docs == 1  # no ghost became searchable

    def test_op_type_create_put_if_absent(self):
        eng = Engine(_mappings())
        eng.index({"body": "a"}, "d1", op_type="create")
        with pytest.raises(VersionConflictError):
            eng.index({"body": "b"}, "d1", op_type="create")

    def test_concurrent_creates_exactly_one_wins(self):
        eng = Engine(_mappings())
        outcomes = []
        lock = threading.Lock()
        barrier = threading.Barrier(8)

        def creator(t):
            barrier.wait()
            try:
                eng.index({"body": f"from-{t}"}, "same", op_type="create")
                with lock:
                    outcomes.append("created")
            except VersionConflictError:
                with lock:
                    outcomes.append("conflict")

        threads = [
            threading.Thread(target=creator, args=(t,)) for t in range(8)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert outcomes.count("created") == 1
        assert outcomes.count("conflict") == 7

    def test_one_sided_cas_rejected(self):
        eng = Engine(_mappings())
        eng.index({"body": "a"}, "d1")
        with pytest.raises(ValueError):
            eng.index({"body": "b"}, "d1", if_seq_no=0)
        with pytest.raises(ValueError):
            eng.delete("d1", if_primary_term=1)

    def test_tombstone_version_survives_restart(self, tmp_path):
        eng = Engine(_mappings(), data_path=str(tmp_path))
        eng.index({"body": "a"}, "d1")
        eng.delete("d1")
        eng.flush()
        eng.close()
        eng2 = Engine(_mappings(), data_path=str(tmp_path))
        r = eng2.index({"body": "c"}, "d1")
        assert r["_version"] == 3  # 1 (index) + 2 (delete) -> 3
        eng2.close()

    def test_tombstones_gc_after_window(self, tmp_path):
        eng = Engine(_mappings(), data_path=str(tmp_path))
        eng.gc_deletes_s = 0.0  # expire immediately
        eng.index({"body": "a"}, "d1")
        eng.delete("d1")
        eng.flush()  # gc prunes the tombstone
        r = eng.index({"body": "c"}, "d1")
        assert r["_version"] == 1  # version line restarted after GC
        eng.close()
