"""Snapshot/restore: fs repository, incremental blobs, rename, GC.

Reference: repositories/blobstore/BlobStoreRepository.java:157,
repositories/fs/FsRepository.java, RestoreService.
"""

import json
import os

import pytest

from elasticsearch_tpu.node import ApiError, Node
from elasticsearch_tpu.rest.server import RestServer

MAPPINGS = {
    "properties": {
        "t": {"type": "text"},
        "k": {"type": "keyword"},
        "n": {"type": "long"},
    }
}


def seed(node, index, n=30, n_shards=1):
    node.create_index(
        index,
        {
            "settings": {"index": {"number_of_shards": n_shards}},
            "mappings": MAPPINGS,
        },
    )
    for i in range(n):
        node.index_doc(
            index, {"t": f"w{i % 4} text", "k": f"k{i % 3}", "n": i}, f"d{i}"
        )
    node.refresh(index)


def test_snapshot_restore_roundtrip(tmp_path):
    node = Node()
    seed(node, "src", n=40, n_shards=2)
    node.delete_doc("src", "d7", refresh=True)
    node.put_repository(
        "repo", {"type": "fs", "settings": {"location": str(tmp_path / "r")}}
    )
    out = node.create_snapshot("repo", "snap1", {})
    assert out["snapshot"]["state"] == "SUCCESS"
    assert out["snapshot"]["indices"] == ["src"]

    out = node.restore_snapshot(
        "repo",
        "snap1",
        {"rename_pattern": "src", "rename_replacement": "copy"},
    )
    assert out["snapshot"]["indices"] == ["copy"]
    r_src = node.search("src", {"query": {"match": {"t": "w2"}}, "size": 50})
    r_copy = node.search("copy", {"query": {"match": {"t": "w2"}}, "size": 50})
    assert r_copy["hits"]["total"]["value"] == r_src["hits"]["total"]["value"]
    assert {h["_id"] for h in r_copy["hits"]["hits"]} == {
        h["_id"] for h in r_src["hits"]["hits"]
    }
    assert node.get_doc("copy", "d7")["found"] is False  # delete survived
    # versions/seqnos preserved through restore
    a = node.get_doc("src", "d3")
    b = node.get_doc("copy", "d3")
    assert a["_version"] == b["_version"] and a["_seq_no"] == b["_seq_no"]
    # restored index accepts writes with seqno continuity
    resp = node.index_doc("copy", {"t": "new", "n": 99}, "d3")
    assert resp["_seq_no"] > b["_seq_no"]


def test_restore_collision_and_missing(tmp_path):
    node = Node()
    seed(node, "a", n=5)
    node.put_repository(
        "repo", {"type": "fs", "settings": {"location": str(tmp_path / "r")}}
    )
    node.create_snapshot("repo", "s1", {})
    with pytest.raises(ApiError):  # existing open index
        node.restore_snapshot("repo", "s1", {})
    with pytest.raises(ApiError):
        node.get_snapshot("repo", "nope")
    with pytest.raises(ApiError):
        node.create_snapshot("repo", "s1", {})  # duplicate name
    with pytest.raises(ApiError):
        node.create_snapshot("repo", "s2", {"indices": "missing_index"})


def test_incremental_blobs_and_gc(tmp_path):
    node = Node()
    seed(node, "inc", n=20)
    node.put_repository(
        "repo", {"type": "fs", "settings": {"location": str(tmp_path / "r")}}
    )
    node.create_snapshot("repo", "s1", {})
    blob_root = tmp_path / "r" / "blobs"
    blobs_after_s1 = set(os.listdir(blob_root))
    # second snapshot with no changes: shares every blob
    node.create_snapshot("repo", "s2", {})
    assert set(os.listdir(blob_root)) == blobs_after_s1
    # new segment -> exactly the new blobs are added
    node.index_doc("inc", {"t": "fresh", "n": 999}, "new", refresh=True)
    node.create_snapshot("repo", "s3", {})
    blobs_after_s3 = set(os.listdir(blob_root))
    assert blobs_after_s1 < blobs_after_s3
    # deleting s3 GCs only its unshared blobs
    node.delete_snapshot("repo", "s3")
    assert set(os.listdir(blob_root)) == blobs_after_s1
    node.delete_snapshot("repo", "s1")
    node.delete_snapshot("repo", "s2")
    assert set(os.listdir(blob_root)) == set()
    with pytest.raises(ApiError):
        node.delete_snapshot("repo", "s1")


def test_snapshot_rest_and_repo_persistence(tmp_path):
    node = Node(data_path=str(tmp_path / "data"))
    rest = RestServer(node=node)
    seed(node, "r1", n=10)
    status, resp = rest.dispatch(
        "PUT",
        "/_snapshot/backups",
        {},
        json.dumps(
            {"type": "fs", "settings": {"location": str(tmp_path / "repo")}}
        ),
    )
    assert status == 200 and resp["acknowledged"]
    status, resp = rest.dispatch(
        "PUT", "/_snapshot/backups/nightly", {}, json.dumps({"indices": "r1"})
    )
    assert status == 200
    status, resp = rest.dispatch("GET", "/_snapshot/backups/_all", {}, "")
    assert status == 200
    assert [s["snapshot"] for s in resp["snapshots"]] == ["nightly"]
    node.flush("r1")
    node.close()

    # repository registration survives restart; restore over REST works
    node2 = Node(data_path=str(tmp_path / "data"))
    rest2 = RestServer(node=node2)
    status, resp = rest2.dispatch("GET", "/_snapshot/backups", {}, "")
    assert status == 200 and "backups" in resp
    status, resp = rest2.dispatch(
        "POST",
        "/_snapshot/backups/nightly/_restore",
        {},
        json.dumps(
            {"rename_pattern": "r1", "rename_replacement": "r1_restored"}
        ),
    )
    assert status == 200
    r = node2.search("r1_restored", {"query": {"match_all": {}}, "size": 0})
    assert r["hits"]["total"]["value"] == 10
    # restored into a durable node: survives another restart
    node2.flush("r1_restored")
    node2.close()
    node3 = Node(data_path=str(tmp_path / "data"))
    assert node3.get_index("r1_restored").num_docs == 10
    node3.close()


def test_restore_preserves_tombstones_and_seqno_highwater(tmp_path):
    node = Node()
    node.create_index("s", {"mappings": MAPPINGS})
    node.index_doc("s", {"t": "x", "n": 1}, "doc1")  # seqno 0
    node.delete_doc("s", "doc1")  # seqno 1 (tombstone only)
    node.refresh("s")
    node.put_repository(
        "repo", {"type": "fs", "settings": {"location": str(tmp_path / "r")}}
    )
    node.create_snapshot("repo", "s1", {})
    node.restore_snapshot(
        "repo", "s1", {"rename_pattern": "^s$", "rename_replacement": "s2"}
    )
    # next write must take a FRESH seqno (the delete op's seqno lived only
    # in the op maps) and continue doc1's version line
    resp = node.index_doc("s2", {"t": "y", "n": 2}, "doc1")
    assert resp["_seq_no"] >= 2
    assert resp["_version"] == 3  # v1 index, v2 delete, v3 re-create


def test_restore_validates_all_targets_first(tmp_path):
    node = Node()
    seed(node, "a", n=4)
    seed(node, "b", n=4)
    node.put_repository(
        "repo", {"type": "fs", "settings": {"location": str(tmp_path / "r")}}
    )
    node.create_snapshot("repo", "s1", {})
    node.delete_index("a")  # "a" restorable, "b" collides
    with pytest.raises(ApiError):
        node.restore_snapshot("repo", "s1", {"indices": "a,b"})
    # nothing was partially restored
    assert "a" not in node.indices


def test_blob_dedup_survives_restart(tmp_path):
    node = Node(data_path=str(tmp_path / "data"))
    seed(node, "p", n=15)
    node.flush("p")
    node.put_repository(
        "repo", {"type": "fs", "settings": {"location": str(tmp_path / "r")}}
    )
    node.create_snapshot("repo", "s1", {})
    blobs1 = set(os.listdir(tmp_path / "r" / "blobs"))
    node.close()
    node2 = Node(data_path=str(tmp_path / "data"))
    node2.create_snapshot("repo", "s2", {})
    assert set(os.listdir(tmp_path / "r" / "blobs")) == blobs1
    node2.close()


def test_restore_rejects_duplicate_and_bad_rename_targets(tmp_path):
    node = Node()
    seed(node, "aa", n=3)
    seed(node, "bb", n=3)
    node.put_repository(
        "repo", {"type": "fs", "settings": {"location": str(tmp_path / "r")}}
    )
    node.create_snapshot("repo", "s1", {})
    with pytest.raises(ApiError):  # both rename to "same" — duplicate
        node.restore_snapshot(
            "repo", "s1",
            {"rename_pattern": "..", "rename_replacement": "same"},
        )
    assert "same" not in node.indices  # nothing partially restored
    with pytest.raises(ApiError):  # malformed regex -> 400, not 500
        node.restore_snapshot(
            "repo", "s1",
            {"rename_pattern": "[", "rename_replacement": "x"},
        )


def test_unsupported_repo_type_rejected():
    node = Node()
    with pytest.raises(ApiError):
        node.put_repository("s3repo", {"type": "s3", "settings": {}})
    with pytest.raises(ApiError):
        node.put_repository("bad", {"type": "fs", "settings": {}})
