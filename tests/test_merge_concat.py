"""Delta-scaled refresh (ISSUE 12): the posting-concatenation merge.

Three contracts gate the tokenization-free merge (index/merge.py):

1. **Structural parity** — the concat merge's output Segment is
   bit-identical, array by array and dtype by dtype, to what the old
   re-analysis path (SegmentBuilder re-adding every live doc) produces:
   term dictionaries, CSR postings, tf/position/ordinal planes, norms,
   presence, doc values, vectors, versions/seqnos, nested blocks,
   completion and percolator entries. Structural equality implies search
   bit-exactness on every path, which the search-parity fuzz re-asserts
   end to end (deletes purged, doc-value sorts, highlights).
2. **Zero re-tokenization** — hook-counted via
   `estpu_analysis_calls_total` (analysis/analyzers.py): a one-doc write
   + refresh on a populated shard performs analysis calls only for the
   delta doc; the merge and the mesh repack add none.
3. **Cache survival** — filter/ANN planes of untouched segments keep
   hitting across refresh + merge (uid-keyed, PR-9 scheme), and
   merged-away handle uids are pruned from both caches.
"""

import numpy as np
import pytest

from elasticsearch_tpu.analysis.analyzers import analysis_calls_total
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import Mappings
from elasticsearch_tpu.index.merge import (
    compact_segment,
    concat_segments,
    merged_live_segment,
)
from elasticsearch_tpu.index.segment import SegmentBuilder
from elasticsearch_tpu.search.service import SearchRequest, SearchService

MAPPINGS = Mappings.from_json(
    {
        "properties": {
            "body": {"type": "text"},
            "title": {"type": "text", "analyzer": "english"},
            "tag": {"type": "keyword"},
            "n": {"type": "long"},
            "vec": {"type": "dense_vector", "dims": 4},
            "sug": {"type": "completion"},
            "comments": {
                "type": "nested",
                "properties": {
                    "who": {"type": "keyword"},
                    "stars": {"type": "long"},
                },
            },
        }
    }
)

WORDS = ["one", "two", "three", "four", "five", "the", "and", "café", "naïve"]


def _make_doc(rng, i):
    doc = {"body": " ".join(rng.choice(WORDS, rng.integers(0, 7))), "n": int(i)}
    if rng.random() < 0.7:
        doc["title"] = " ".join(rng.choice(WORDS, rng.integers(1, 4)))
    if rng.random() < 0.6:
        doc["tag"] = str(rng.choice(["a", "b", "c"]))
    if rng.random() < 0.4:
        doc["vec"] = [float(x) for x in rng.normal(size=4)]
    if rng.random() < 0.3:
        doc["sug"] = {
            "input": [f"sug {i}", "shared"],
            "weight": int(rng.integers(1, 5)),
        }
    if rng.random() < 0.4:
        doc["comments"] = [
            {
                "who": str(rng.choice(["x", "y"])),
                "stars": int(rng.integers(0, 5)),
            }
            for _ in range(rng.integers(1, 3))
        ]
    return doc


def _random_segments(rng, n_segments=3, lo=5, hi=25):
    segs, lives = [], []
    counter = 0
    for _ in range(n_segments):
        builder = SegmentBuilder(MAPPINGS)
        for _ in range(rng.integers(lo, hi)):
            builder.add(
                _make_doc(rng, counter),
                f"d{counter}",
                version=int(rng.integers(1, 4)),
                seqno=counter,
            )
            counter += 1
        seg = builder.build()
        live = rng.random(seg.num_docs) > 0.3
        segs.append(seg)
        lives.append(live)
    return segs, lives


def _builder_merge(segs, lives):
    """The old re-analysis merge: re-add every live doc through the
    tokenizer — the oracle the concat merge must match bit-for-bit."""
    builder = SegmentBuilder(MAPPINGS)
    for seg, live in zip(segs, lives):
        for local in np.flatnonzero(live):
            local = int(local)
            builder.add(
                seg.sources[local],
                seg.ids[local],
                version=seg.doc_version(local),
                seqno=seg.doc_seqno(local),
            )
    return builder.build()


def _assert_fields_equal(a, b, name):
    assert a.terms == b.terms, name
    for attr in ("df", "offsets", "doc_ids", "tfs", "norm_bytes", "present"):
        x, y = getattr(a, attr), getattr(b, attr)
        assert x.dtype == y.dtype, (name, attr, x.dtype, y.dtype)
        assert np.array_equal(x, y), (name, attr)
    assert (a.pos_offsets is None) == (b.pos_offsets is None), name
    if a.pos_offsets is not None:
        assert np.array_equal(a.pos_offsets, b.pos_offsets), name
        assert np.array_equal(a.positions, b.positions), name
    assert a.doc_count == b.doc_count, name
    assert a.sum_total_tf == b.sum_total_tf, name
    assert a.has_norms == b.has_norms, name


def _assert_segments_equal(got, want, label=""):
    assert got.num_docs == want.num_docs, label
    assert sorted(got.fields) == sorted(want.fields), label
    for name in want.fields:
        _assert_fields_equal(got.fields[name], want.fields[name], label + name)
    assert sorted(got.doc_values) == sorted(want.doc_values), label
    for name in want.doc_values:
        assert got.doc_values[name].dtype == want.doc_values[name].dtype
        assert np.array_equal(
            got.doc_values[name], want.doc_values[name], equal_nan=True
        ), (label, name)
    assert sorted(got.vectors) == sorted(want.vectors), label
    for name in want.vectors:
        assert np.array_equal(got.vectors[name], want.vectors[name]), (
            label,
            name,
        )
    assert got.ids == want.ids, label
    assert got.sources == want.sources, label
    assert np.array_equal(got.versions, want.versions), label
    assert np.array_equal(got.seqnos, want.seqnos), label
    assert got.completion == want.completion, label
    assert got.percolator == want.percolator, label
    assert sorted(got.nested) == sorted(want.nested), label
    for path in want.nested:
        assert np.array_equal(
            got.nested[path].parent_of, want.nested[path].parent_of
        ), (label, path)
        _assert_segments_equal(
            got.nested[path].seg, want.nested[path].seg, label + path + "."
        )


# ----------------------------------------------------------- structural


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_concat_merge_structurally_equals_reanalysis(seed):
    rng = np.random.default_rng(seed)
    segs, lives = _random_segments(rng)
    merged = merged_live_segment(segs, lives)
    oracle = _builder_merge(segs, lives)
    _assert_segments_equal(merged, oracle)


def test_concat_merge_all_deleted_segment():
    rng = np.random.default_rng(11)
    segs, lives = _random_segments(rng, n_segments=3)
    lives[1][:] = False  # one segment entirely dead
    merged = merged_live_segment(segs, lives)
    oracle = _builder_merge(segs, lives)
    _assert_segments_equal(merged, oracle)


def test_concat_empty_input_builds_empty_segment():
    merged = concat_segments([])
    assert merged.num_docs == 0
    assert merged.fields == {} and merged.ids == []


def test_compact_all_live_is_identity():
    rng = np.random.default_rng(12)
    segs, _ = _random_segments(rng, n_segments=1)
    seg = segs[0]
    assert compact_segment(seg, np.ones(seg.num_docs, dtype=bool)) is seg


# ------------------------------------------------------- search parity


def _search_pairs(engine, body):
    resp = SearchService(engine).search(SearchRequest.from_json(body))
    return (
        [(h.doc_id, h.score, h.sort) for h in resp.hits],
        resp.total,
        resp,
    )


@pytest.mark.parametrize("seed", [7, 8])
def test_merged_engine_search_parity_fuzz(seed):
    """Merged engine == never-merged engine across match/bool/sort/
    highlight shapes, with deletes purged by the merge."""
    rng = np.random.default_rng(seed)
    merged = Engine(MAPPINGS, max_segments=3, merge_factor=3)
    flat = Engine(MAPPINGS)
    n = 90
    for i in range(n):
        doc = _make_doc(rng, i)
        merged.index(doc, f"d{i}")
        flat.index(doc, f"d{i}")
        if (i + 1) % 7 == 0:
            merged.refresh()
    for i in range(0, n, 5):  # deletes, purged by later merges
        merged.delete(f"d{i}")
        flat.delete(f"d{i}")
    merged.refresh()
    merged.force_merge(1)
    flat.refresh()
    assert len(merged.segments) == 1
    bodies = [
        {"query": {"match": {"body": "one two"}}, "size": n},
        {
            "query": {
                "bool": {
                    "must": [{"match": {"body": "three"}}],
                    "filter": [{"term": {"tag": "a"}}],
                }
            },
            "size": n,
        },
        {"query": {"match_all": {}}, "sort": [{"n": "desc"}], "size": 10},
        {"query": {"match_phrase": {"body": "one two"}}, "size": n},
        {
            "query": {"match": {"body": "four"}},
            "highlight": {"fields": {"body": {}}},
            "size": n,
        },
    ]
    for body in bodies:
        got, got_total, got_resp = _search_pairs(merged, body)
        want, want_total, want_resp = _search_pairs(flat, body)
        assert got_total == want_total, body
        assert [s for _, s, _ in got] == [s for _, s, _ in want], body
        # Same (score -> id set) membership; tie ORDER may differ because
        # merges renumber docs (Lucene merges do too).
        by_score_got: dict = {}
        by_score_want: dict = {}
        for h, s, srt in got:
            by_score_got.setdefault((s, tuple(srt or ())), set()).add(h)
        for h, s, srt in want:
            by_score_want.setdefault((s, tuple(srt or ())), set()).add(h)
        assert by_score_got == by_score_want, body
        if "highlight" in body:
            got_hl = {
                h.doc_id: h.highlight for h in got_resp.hits if h.highlight
            }
            want_hl = {
                h.doc_id: h.highlight for h in want_resp.hits if h.highlight
            }
            assert got_hl == want_hl


# ------------------------------------------------- analysis accounting


def test_merge_performs_zero_analysis_calls():
    rng = np.random.default_rng(21)
    engine = Engine(MAPPINGS, max_segments=100)
    for i in range(60):
        engine.index(_make_doc(rng, i), f"d{i}")
        if (i + 1) % 10 == 0:
            engine.refresh()
    engine.delete("d3")
    engine.refresh()
    before = analysis_calls_total()
    engine.force_merge(1)
    assert analysis_calls_total() == before  # the merge never tokenizes
    assert engine.merges_total >= 1
    assert engine.merge_docs_total >= 59
    assert len(engine.segments) == 1


def test_one_doc_write_refresh_analyzes_only_the_delta():
    """The ISSUE 12 acceptance shape on the host path: a one-doc write +
    refresh on a populated shard (small here; bench cfg10 runs 100k)
    performs analysis calls for the delta doc only, even when the
    refresh triggers a merge."""
    rng = np.random.default_rng(22)
    engine = Engine(MAPPINGS, max_segments=2, merge_factor=2)
    for i in range(50):
        engine.index(_make_doc(rng, i), f"d{i}")
        if (i + 1) % 10 == 0:
            engine.refresh()  # keeps merging down to <= 2 segments
    merges_before = engine.merges_total
    before = analysis_calls_total()
    engine.index({"body": "one two three", "n": 999}, "delta")
    after_write = analysis_calls_total()
    delta_calls = after_write - before
    assert delta_calls >= 1  # the delta doc itself analyzed
    engine.refresh()  # freezes the buffer AND merges (max_segments=2)
    assert engine.merges_total > merges_before  # a merge really ran
    assert analysis_calls_total() == after_write  # ...with zero analysis


# --------------------------------------------------- cache survival


def test_filter_planes_of_untouched_segments_survive_refresh_and_merge():
    from elasticsearch_tpu.index.filter_cache import FilterCache

    rng = np.random.default_rng(31)
    cache = FilterCache(min_freq=1)
    engine = Engine(MAPPINGS, max_segments=100)
    for i in range(40):
        engine.index(_make_doc(rng, i), f"d{i}")
        if (i + 1) % 10 == 0:
            engine.refresh()
    svc = SearchService(engine, filter_cache=cache)
    # Two filters: the compiler may drive candidates off one (the lead,
    # never masked); the other substitutes a cached plane.
    body = {
        "query": {
            "bool": {
                "must": [{"match": {"body": "one"}}],
                "filter": [
                    {"term": {"tag": "a"}},
                    {"range": {"n": {"lt": 1000000}}},
                ],
            }
        }
    }
    req = SearchRequest.from_json(body)
    svc.search(req)  # admission sighting
    svc.search(req)  # builds + stores planes per segment handle
    keys_before = set(cache.keys())
    assert keys_before, "planes should be resident"
    old_uids = {h.uid for h in engine.segments}
    # A refresh that only ADDS a segment leaves every old plane valid.
    engine.index({"body": "one", "tag": "a", "n": 1000}, "newdoc")
    engine.refresh()
    hits_before = cache.stats()["hit_count"]
    svc.search(req)
    assert keys_before <= set(cache.keys())  # untouched planes survived
    assert cache.stats()["hit_count"] > hits_before
    # A merge retires every merged handle: fresh uids, old planes pruned
    # on the next store/prune pass.
    engine.force_merge(1)
    live = frozenset(h.uid for h in engine.segments)
    assert not (live & old_uids)  # merge minted fresh handle uids
    cache.prune_dead(engine.uid, live)
    for key in cache.keys():
        if key[0] == engine.uid:
            assert key[2] in live  # no merged-away uid remains


def test_ann_planes_survive_refresh_and_prune_on_merge():
    from elasticsearch_tpu.index.ann import AnnCache

    rng = np.random.default_rng(32)
    cache = AnnCache(min_docs=8)
    engine = Engine(MAPPINGS, max_segments=100)
    for i in range(32):
        engine.index(
            {"vec": [float(x) for x in rng.normal(size=4)], "n": i}, f"v{i}"
        )
    engine.refresh()
    handle = engine.segments[0]
    parts = cache.get_or_build(engine, handle, "vec", "cosine")
    assert parts is not None
    key = (engine.uid, handle.uid, "vec")
    assert key in cache._entries
    # Refresh adding a new segment: the untouched handle's planes survive
    # and the SAME object is served (cache hit, no rebuild).
    engine.index(
        {"vec": [float(x) for x in rng.normal(size=4)], "n": 99}, "vnew"
    )
    engine.refresh()
    assert cache.get_or_build(engine, handle, "vec", "cosine") is parts
    assert int(cache._builds.value) == 1
    # Merge retires the handle; prune_dead drops its planes eagerly.
    engine.force_merge(1)
    dropped = cache.prune_dead(
        engine.uid, frozenset(h.uid for h in engine.segments)
    )
    assert dropped >= 1
    assert key not in cache._entries


def test_refresh_merge_stats_blocks_in_node_apis():
    from elasticsearch_tpu.node import Node

    node = Node()
    node.create_index(
        "rm", {"settings": {"index": {"merge": {"max_segment_count": 2,
                                                "merge_factor": 2}}}}
    )
    for i in range(30):
        node.index_doc("rm", {"body": f"w{i % 5} common"}, f"d{i}")
        if i % 5 == 4:
            node.refresh("rm")
    node.refresh("rm")
    stats = node.stats()
    blk = stats["indices"]["rm"]["primaries"]
    assert blk["refresh"]["total"] >= 6
    assert blk["merges"]["total"] >= 1
    assert blk["merges"]["total_docs"] > 0
    assert stats["_all"]["primaries"]["merges"]["total"] >= 1
    nstats = node.nodes_stats()
    nblk = nstats["nodes"][node.node_name]["indices"]
    assert nblk["refresh"]["total"] >= 6
    assert nblk["merges"]["total"] >= 1
    assert nblk["analysis"]["analysis_calls_total"] > 0
    # Prometheus exposition carries the analysis counter too.
    assert "estpu_analysis_calls_total" in node.metrics_text()
