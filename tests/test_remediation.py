"""The self-driving cluster (health-driven remediation loops).

Four layers of proof:

- **planners are pure** — plan_lifecycle/plan_allocation/plan_budget map
  a HealthContext to actions with no service state, so a dry-run plans
  exactly what live would;
- **flap damping** — an oscillating context (pressure on, pressure off,
  pressure on ...) executes at most ONE action per cooldown window and
  NEVER an action and its inverse within one window; the per-window cap
  bounds a pathological plan;
- **chaos** — an armed `remediate.<loop>` fault site makes actuation
  fail mid-flight: the loop retries with backoff, every attempt lands in
  `estpu_remediation_failures_total`, the loop degrades to ADVISORY
  instead of thrashing, and no acked write is lost;
- **the acceptance arc** — induced HBM pressure on a replicated node
  demotes the coldest unsearched index with zero operator actions, the
  executed action rides the published cluster state AND the health
  report's diagnosis, hits stay bit-identical through the demote /
  on-demand re-pack cycle, and the same arc under dry-run plans the
  identical action while executing none.
"""

import time

import pytest

from elasticsearch_tpu.cluster import LocalCluster
from elasticsearch_tpu.cluster.remediation import (
    ACTIONS,
    Action,
    RemediationService,
    next_rollover_name,
    plan_allocation,
    plan_budget,
    plan_lifecycle,
)
from elasticsearch_tpu.faults.registry import REGISTRY, FaultSpec
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.obs.health import HealthContext
from elasticsearch_tpu.obs.metrics import MetricsRegistry
from elasticsearch_tpu.rest.server import RestServer


class StubEngine:
    def __init__(self, demoted=False, n_segments=1):
        self.demoted = demoted
        self.segments = [None] * n_segments


class StubIndex:
    def __init__(self, num_docs=0, engines=None, created_at=0.0):
        self.num_docs = num_docs
        self.engines = engines if engines is not None else [StubEngine()]
        self.created_at = created_at


class StubNode:
    """Records every actuation; no device, no cluster."""

    def __init__(self):
        self.calls = []
        self.replication = None

    def force_merge(self, index):
        self.calls.append(("force_merge", index))

    def rollover_alias(self, alias, old, new):
        self.calls.append(("rollover", alias, old, new))

    def demote_index(self, index):
        self.calls.append(("demote_index", index))

    def promote_index(self, index):
        self.calls.append(("promote_index", index))

    def move_shard_replica(self, index, shard, src, dst):
        self.calls.append(("move_shard", index, shard, src, dst))

    def retune_cache_budgets(self, filter_bytes, ann_bytes, reason=""):
        self.calls.append(("retune_caches", filter_bytes, ann_bytes))

    def retune_packed_budget(self, max_plane_docs, reason=""):
        self.calls.append(("retune_packed", max_plane_docs))


def _pressure_ctx(
    used=95,
    limit=100,
    cold_demoted=False,
    searched=(),
    scrolls=0,
):
    """One coordinating front, one cold index, HBM fraction used/limit."""
    inputs = {
        "breaker": {
            "limit_size_in_bytes": limit,
            "estimated_size_in_bytes": used,
        },
        "hbm": {
            "by_label_index": [
                {"label": "segment", "index": "cold", "bytes": 1000}
            ]
        },
        "writes_recent": {},
    }
    return HealthContext(
        coordinator="n0",
        node_inputs={"n0": inputs},
        local_indices={
            "cold": StubIndex(
                engines=[StubEngine(demoted=cold_demoted)]
            )
        },
        recent_search_indices=searched,
        scrolls_active=scrolls,
        now=1000.0,
    )


def _svc(node=None, **cfg):
    svc = RemediationService(node or StubNode(), metrics=MetricsRegistry())
    for key, value in cfg.items():
        setattr(svc, key, value)
    return svc


# ------------------------------------------------------------- planners


class TestPlanners:
    def test_registry_names_match_planners(self):
        import elasticsearch_tpu.cluster.remediation as mod

        for name in ACTIONS:
            assert callable(getattr(mod, f"plan_{name}"))

    def test_next_rollover_name(self):
        assert next_rollover_name("logs-000001") == "logs-000002"
        assert next_rollover_name("logs-000009") == "logs-000010"
        assert next_rollover_name("logs") == "logs-000002"

    def test_pressure_plans_demote_of_coldest(self):
        acts = plan_lifecycle(_pressure_ctx())
        assert [(a.kind, a.target) for a in acts] == [
            ("demote_index", "cold")
        ]
        assert acts[0].inverse == "promote_index"

    def test_recently_searched_index_never_demoted(self):
        assert plan_lifecycle(_pressure_ctx(searched=("cold",))) == []

    def test_live_scrolls_block_demotion(self):
        # Scroll cursors pin frozen device planes; demotion under them
        # would invalidate what the cursor is paging through.
        assert plan_lifecycle(_pressure_ctx(scrolls=1)) == []

    def test_pressure_cleared_plans_promotion(self):
        acts = plan_lifecycle(_pressure_ctx(used=10, cold_demoted=True))
        assert [(a.kind, a.target) for a in acts] == [
            ("promote_index", "cold")
        ]

    def test_quiet_index_with_many_segments_force_merges(self):
        ctx = HealthContext(
            coordinator="n0",
            node_inputs={"n0": {"writes_recent": {"busy": 9}}},
            local_indices={
                "quiet": StubIndex(engines=[StubEngine(n_segments=10)]),
                "busy": StubIndex(engines=[StubEngine(n_segments=10)]),
            },
            now=1000.0,
        )
        acts = plan_lifecycle(ctx)
        # The hot index (writes in the trailing window) is left to the
        # ordinary merge policy; the quiet one compacts.
        assert [(a.kind, a.target) for a in acts] == [
            ("force_merge", "quiet")
        ]

    def test_rollover_past_doc_policy(self, monkeypatch):
        monkeypatch.setenv("ESTPU_REMEDIATION_ROLLOVER_DOCS", "100")
        ctx = HealthContext(
            coordinator="n0",
            node_inputs={"n0": {}},
            aliases={"logs": ("logs-000001",)},
            local_indices={"logs-000001": StubIndex(num_docs=150)},
            now=1000.0,
        )
        acts = plan_lifecycle(ctx)
        assert [(a.kind, a.target) for a in acts] == [("rollover", "logs")]
        assert acts[0].params["new_index"] == "logs-000002"

    def test_budget_shifts_toward_churning_filter_cache(self):
        ctx = HealthContext(
            coordinator="n0",
            node_inputs={
                "n0": {
                    "caches": {
                        "filter": {
                            "budget_bytes": 64 << 20,
                            "hit_count": 10,
                            "miss_count": 90,
                        },
                        "ann": {
                            "budget_bytes": 64 << 20,
                            "hit_count": 0,
                            "miss_count": 0,
                        },
                    },
                    "evictions_recent": {"filter": 200, "ann": 0},
                }
            },
        )
        acts = plan_budget(ctx)
        assert [a.kind for a in acts] == ["grow_filter_budget"]
        shift = acts[0].params["filter_bytes"] - (64 << 20)
        assert shift > 0
        assert acts[0].params["ann_bytes"] == (64 << 20) - shift

    def test_packed_budget_grows_at_occupancy(self):
        ctx = HealthContext(
            coordinator="n0",
            node_inputs={
                "n0": {
                    "caches": {
                        "packed": {
                            "plane_docs": 95,
                            "max_plane_docs": 100,
                            "default_plane_docs": 100,
                        }
                    }
                }
            },
        )
        acts = plan_budget(ctx)
        assert [a.kind for a in acts] == ["grow_packed_budget"]
        assert acts[0].params["max_plane_docs"] == 125

    def test_allocation_moves_replica_off_divergent_node(self):
        class Routing:
            primary = "n0"
            replicas = ["n1"]
            recovering = []

            def assigned(self):
                return ["n0", "n1"]

        class Meta:
            shards = {0: Routing()}

        class State:
            nodes = {"n0": None, "n1": None, "n2": None}
            voting_only = set()
            indices = {"idx": Meta()}

        ctx = HealthContext(
            coordinator="n0",
            state=State(),
            node_inputs={
                "n0": {"queue_wait_recent": {"p99": 2.0}},
                "n1": {"queue_wait_recent": {"p99": 900.0}},
                "n2": {"queue_wait_recent": {"p99": 2.0}},
            },
        )
        acts = plan_allocation(ctx)
        assert len(acts) == 1
        assert acts[0].kind == "move_shard"
        assert acts[0].params == {
            "index": "idx",
            "shard": 0,
            "from": "n1",
            "to": "n2",
        }

    def test_allocation_never_moves_primaries(self):
        class Routing:
            primary = "n1"  # the divergent node holds only the PRIMARY
            replicas = []
            recovering = []

            def assigned(self):
                return ["n1"]

        class Meta:
            shards = {0: Routing()}

        class State:
            nodes = {"n0": None, "n1": None, "n2": None}
            voting_only = set()
            indices = {"idx": Meta()}

        ctx = HealthContext(
            coordinator="n0",
            state=State(),
            node_inputs={
                "n0": {"queue_wait_recent": {"p99": 2.0}},
                "n1": {"queue_wait_recent": {"p99": 900.0}},
                "n2": {"queue_wait_recent": {"p99": 2.0}},
            },
        )
        assert plan_allocation(ctx) == []


# ---------------------------------------------------- damping & dry-run


class TestFlapDamping:
    def test_action_and_inverse_share_a_damping_key(self):
        demote = Action("lifecycle", "demote_index", "cold", "",
                        inverse="promote_index")
        promote = Action("lifecycle", "promote_index", "cold", "",
                         inverse="demote_index")
        assert demote.damping_key() == promote.damping_key()

    def test_oscillating_context_executes_once_per_window(self):
        node = StubNode()
        svc = _svc(node, cooldown_s=30.0)
        executed = []
        for round_no in range(6):
            ctx = (
                _pressure_ctx()
                if round_no % 2 == 0
                else _pressure_ctx(used=10, cold_demoted=True)
            )
            for record in svc.tick(ctx=ctx, force=True):
                if record["executed"]:
                    executed.append(record["kind"])
        # One cooldown window covers the whole loop: exactly one action
        # fired, and its inverse never did.
        assert executed == ["demote_index"]
        assert node.calls == [("demote_index", "cold")]
        suppressed = [
            r["suppressed"]
            for r in svc.status()["planned"]
            if "suppressed" in r
        ]
        assert suppressed and set(suppressed) == {"cooldown"}

    def test_window_cap_bounds_a_pathological_plan(self):
        node = StubNode()
        svc = _svc(node, max_actions=2)
        ctx = HealthContext(
            coordinator="n0",
            node_inputs={"n0": {}},
            local_indices={
                f"q{i}": StubIndex(engines=[StubEngine(n_segments=10)])
                for i in range(5)
            },
            now=1000.0,
        )
        records = svc.tick(ctx=ctx, force=True)
        assert len(records) == 5
        assert sum(r["executed"] for r in records) == 2
        assert [r["suppressed"] for r in records[2:]] == ["cap"] * 3
        assert len(node.calls) == 2

    def test_dry_run_plans_identically_and_executes_nothing(self):
        live_node, dry_node = StubNode(), StubNode()
        live = _svc(live_node)
        dry = _svc(dry_node, dry_run=True)
        ctx = _pressure_ctx()
        live_records = live.tick(ctx=ctx, force=True)
        dry_records = dry.tick(ctx=ctx, force=True)
        assert [(r["kind"], r["target"], r["reason"])
                for r in dry_records] == [
            (r["kind"], r["target"], r["reason"]) for r in live_records
        ]
        assert all(r["dry_run"] and not r["executed"]
                   for r in dry_records)
        assert dry_node.calls == []
        assert live_node.calls == [("demote_index", "cold")]
        # Dry-run claims the SAME damping slots, so toggling live after
        # a dry round cannot double-fire inside the window.
        repeat = dry.tick(ctx=ctx, force=True)
        assert [r["suppressed"] for r in repeat] == ["cooldown"]

    def test_disabled_service_plans_nothing(self):
        svc = _svc(enabled=False)
        assert svc.tick(ctx=_pressure_ctx(), force=True) == []


# --------------------------------------------------------------- chaos


class TestChaosAdvisory:
    def test_failed_actuation_retries_then_degrades_to_advisory(self):
        node = StubNode()
        svc = _svc(node, backoff_s=0.001)
        REGISTRY.put(
            FaultSpec(site="remediate.lifecycle", error_rate=1.0, seed=3)
        )
        try:
            records = svc.tick(ctx=_pressure_ctx(), force=True)
        finally:
            REGISTRY.clear()
        assert len(records) == 1
        record = records[0]
        assert record["executed"] is False
        assert record["attempts"] == svc.retries
        assert "injected fault" in record["error"]
        assert record["advisory"] is True
        # Every failed attempt is COUNTED.
        assert svc._failures.value == svc.retries
        assert node.calls == []
        # The loop is advisory now: the same plan is suppressed, not
        # retried into a thrash loop.
        repeat = svc.tick(ctx=_pressure_ctx(), force=True)
        assert [r["suppressed"] for r in repeat] == ["advisory"]
        assert "failed after" in repeat[0]["advisory_reason"]
        advisory = svc.status()["advisory"]
        assert "lifecycle" in advisory

    def test_cluster_chaos_arc_no_acked_write_loss(self):
        """Armed remediate.allocation faults + a planned replica move:
        retries, advisory degradation, counted failures — and every
        acked write still answers. After the fault clears, the same
        move executes through ordinary peer recovery."""
        n = Node(data_path=None, replication=LocalCluster(3))
        try:
            n.create_index(
                "chaos",
                {
                    "settings": {
                        "index": {
                            "number_of_shards": 2,
                            "number_of_replicas": 1,
                        }
                    },
                    "mappings": {"properties": {"b": {"type": "text"}}},
                },
            )
            for i in range(20):
                n.index_doc("chaos", {"b": f"payload {i}"}, str(i))
            n.refresh("chaos")
            svc = n.remediation
            svc.backoff_s = 0.001
            svc.cooldown_s = 0.05
            svc.advisory_s = 0.05
            state = n._coordinator_state()
            routing = state.indices["chaos"].shards[0]
            hot = routing.replicas[0]
            inputs = {
                nid: {"queue_wait_recent": {"p99": 1.0}}
                for nid in state.nodes
            }
            inputs[hot] = {"queue_wait_recent": {"p99": 900.0}}
            ctx = HealthContext(
                coordinator=n.node_name,
                standalone=False,
                state=state,
                node_inputs=inputs,
            )
            failures_before = svc._failures.value
            REGISTRY.put(
                FaultSpec(
                    site="remediate.allocation", error_rate=1.0, seed=5
                )
            )
            try:
                records = svc.tick(ctx=ctx, force=True)
            finally:
                REGISTRY.clear()
            assert len(records) == 1
            assert records[0]["executed"] is False
            assert records[0]["attempts"] == svc.retries
            assert svc._failures.value - failures_before == svc.retries
            # Zero acked-write loss through the chaos.
            out = n.search("chaos", {"query": {"match_all": {}},
                                     "size": 0})
            assert out["hits"]["total"]["value"] == 20
            # The instrument is live on the node registry (catalog ref).
            assert "estpu_remediation_failures_total" in n.metrics_text()
            # Fault cleared + advisory/cooldown expired: the SAME move
            # now executes as an observable cluster-state transition.
            time.sleep(0.1)
            records = svc.tick(ctx=ctx, force=True)
            assert [r["executed"] for r in records] == [True]
            new_state = n._coordinator_state()
            new_routing = new_state.indices["chaos"].shards[0]
            assert hot not in new_routing.replicas
            assert any(
                r["kind"] == "move_shard" for r in new_state.remediations
            )
            # The move completes through ordinary peer recovery.
            cluster = n.replication.cluster
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                routing = n._coordinator_state().indices["chaos"].shards[0]
                if not routing.recovering:
                    break
                cluster.step()
                time.sleep(0.02)
            assert not routing.recovering
            out = n.search("chaos", {"query": {"match_all": {}},
                                     "size": 0})
            assert out["hits"]["total"]["value"] == 20
        finally:
            n.close()


# ----------------------------------------------------- the acceptance arc


class TestAcceptanceArc:
    """Standalone topology: the front's LOCAL engines hold the segment
    HBM the lifecycle loop manages (in the replicated topology shard
    data lives on cluster members — the allocation chaos arc above
    covers the cluster-state publication surface there)."""

    @pytest.fixture()
    def rnode(self):
        n = Node()
        # Park the paced tick so the test's forced ticks are the only
        # rounds that plan.
        n.remediation.interval_s = 1e9
        n.remediation._last_tick = time.monotonic()
        n.create_index(
            "hot", {"mappings": {"properties": {"t": {"type": "text"}}}}
        )
        n.create_index(
            "cold", {"mappings": {"properties": {"t": {"type": "text"}}}}
        )
        for i in range(8):
            n.index_doc("hot", {"t": f"alpha {i}"}, str(i))
            n.index_doc("cold", {"t": f"omega term {i}"}, str(i))
        n.refresh("hot")
        n.refresh("cold")
        yield n
        n.close()

    def test_hot_spot_remediates_to_green_hands_off(
        self, rnode, monkeypatch
    ):
        n = rnode
        # Baseline hits BEFORE the arc (white-box: forget the baseline
        # search so "cold" still counts as unsearched for the planner).
        baseline = n.search("cold", {"query": {"match": {"t": "omega"}}})
        n._search_seen.clear()
        n.search("hot", {"query": {"match": {"t": "alpha"}}})
        # Induce the hot spot: ANY resident segment byte now counts as
        # pressure past the demotion fraction.
        monkeypatch.setenv("ESTPU_REMEDIATION_HBM_FRACTION", "1e-9")
        used_before = n.breaker.stats()["estimated_size_in_bytes"]
        assert used_before > 0
        records = n.remediation.tick(force=True)
        executed = [r for r in records if r["executed"]]
        assert [(r["kind"], r["target"]) for r in executed] == [
            ("demote_index", "cold")
        ]
        # ZERO operator actions: the hot spot cleared by itself.
        assert n.breaker.stats()["estimated_size_in_bytes"] < used_before
        assert all(e.demoted for e in n.indices["cold"].engines)
        # The action surfaces in GET /_remediation (the standalone
        # observable surface; clustered executions additionally ride
        # ClusterState.remediations — see the chaos arc above) ...
        status = n.get_remediation()
        assert any(
            r["kind"] == "demote_index" for r in status["executed"]
        )
        # ... and the health report's diagnosis NAMES it.
        monkeypatch.setenv("ESTPU_REMEDIATION_HBM_FRACTION", "0.9")
        report = n.health_report(verbose=True)
        assert report["status"] == "green"
        diagnosis = " ".join(
            d.get("cause", "") + " " + d.get("action", "")
            for d in report["indicators"]["device_memory"]["diagnosis"]
        )
        assert "remediation executed [demote_index] on [cold]" in diagnosis
        assert "no operator action needed" in diagnosis
        # Bit-identical hits through demotion + on-demand re-pack.
        after = n.search("cold", {"query": {"match": {"t": "omega"}}})
        assert [
            (h["_id"], h["_score"]) for h in after["hits"]["hits"]
        ] == [
            (h["_id"], h["_score"]) for h in baseline["hits"]["hits"]
        ]
        assert not n.indices["cold"].engines[0].demoted
        assert any(
            r["kind"] == "on_demand_repack"
            for r in n.get_remediation()["executed"]
        )

    def test_same_arc_under_dry_run_plans_identically(
        self, rnode, monkeypatch
    ):
        n = rnode
        n._search_seen.clear()
        n.search("hot", {"query": {"match": {"t": "alpha"}}})
        monkeypatch.setenv("ESTPU_REMEDIATION_HBM_FRACTION", "1e-9")
        used_before = n.breaker.stats()["estimated_size_in_bytes"]
        # A fresh service over the SAME node (no damping state shared
        # with other tests), in dry-run mode.
        dry = RemediationService(n, metrics=MetricsRegistry())
        dry.dry_run = True
        records = dry.tick(force=True)
        planned = [r for r in records if "suppressed" not in r]
        assert [(r["kind"], r["target"]) for r in planned] == [
            ("demote_index", "cold")
        ]
        # Identical plan, zero actuation: nothing demoted, no bytes
        # freed, the hot spot STAYS (non-green) until dry-run is lifted.
        assert all(not r["executed"] for r in records)
        assert not any(e.demoted for e in n.indices["cold"].engines)
        assert n.breaker.stats()["estimated_size_in_bytes"] == used_before
        # The dry-run plan narrates how to actuate it.
        view = dry.health_view()
        assert view["dry_run"] is True
        ctx = n._remediation_context()
        ctx = HealthContext(
            **{**ctx.__dict__, "remediation": dry.health_view()}
        )
        from elasticsearch_tpu.obs.health import _graft_remediation

        indicators = {
            "device_memory": {"diagnosis": [], "details": {}},
            "exec_saturation": {"diagnosis": [], "details": {}},
        }
        _graft_remediation(indicators, ctx)
        causes = " ".join(
            d.get("cause", "") + " " + d.get("action", "")
            for d in indicators["device_memory"]["diagnosis"]
        )
        assert "dry-run mode is on" in causes


# ------------------------------------------------- budgets & REST surface


class TestBudgetRetunes:
    def test_retune_recorded_on_cache_stats_and_health_inputs(self):
        n = Node()
        if n.filter_cache is None or n.ann_cache is None:
            pytest.skip("caches disabled in this environment")
        before_f = n.filter_cache.max_bytes
        before_a = n.ann_cache.max_bytes
        n.retune_cache_budgets(
            before_f + (1 << 20),
            before_a - (1 << 20),
            reason="test shift",
        )
        stats = n._health_inputs_local()["caches"]
        assert stats["filter"]["budget_bytes"] == before_f + (1 << 20)
        assert stats["ann"]["budget_bytes"] == before_a - (1 << 20)
        for side in ("filter", "ann"):
            events = stats[side]["retunes"]
            assert len(events) == 1
            assert events[0]["reason"] == "test shift"
            assert events[0]["from_bytes"] != events[0]["to_bytes"]

    def test_packed_retune_event_and_shrink_forces_readmission(self):
        n = Node()
        if n.packed_exec is None:
            pytest.skip("packed execution disabled")
        default = n.packed_exec.max_plane_docs
        n.retune_packed_budget(default * 2, reason="grow")
        n.retune_packed_budget(default, reason="shrink back")
        stats = n.packed_exec.stats()
        assert stats["max_plane_docs"] == default
        assert stats["default_plane_docs"] == default
        assert [e["reason"] for e in stats["retunes"]] == [
            "grow",
            "shrink back",
        ]


class TestRestSurface:
    def test_get_and_post_remediation(self):
        server = RestServer()
        try:
            status, out = server.dispatch("GET", "/_remediation", {}, "")
            assert status == 200
            assert out["loops"] == list(ACTIONS)
            assert {"enabled", "dry_run", "executed", "planned"} <= set(
                out
            )
            status, out = server.dispatch(
                "POST", "/_remediation", {}, '{"dry_run": true}'
            )
            assert status == 200
            assert out["dry_run"] is True
            status, out = server.dispatch(
                "POST", "/_remediation", {}, '{"dry_run": false}'
            )
            assert out["dry_run"] is False
            status, out = server.dispatch(
                "POST", "/_remediation", {}, '{"dry_run": "yes"}'
            )
            assert status == 400
        finally:
            server.close()
